"""MARWIL / BC: offline policy learning from logged JSONL data.

Reference analog: ``rllib/algorithms/marwil/marwil.py`` (MARWIL —
monotonic advantage re-weighted imitation learning; exponentially
advantage-weighted log-likelihood with a learned value baseline) and
``rllib/algorithms/bc/bc.py`` (behavior cloning = MARWIL with beta=0).
JAX re-design: the whole update (advantage estimate, weighting, policy +
value loss) is one jit program; data comes from the offline
``JsonReader`` (the output of ``JsonWriter`` collection runs).
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from .algorithm import Algorithm, AlgorithmConfig
from .offline import JsonReader
from .sample_batch import ACTIONS, DONES, OBS, REWARDS, SampleBatch


def _monte_carlo_returns(batch: SampleBatch, gamma: float) -> np.ndarray:
    """Discounted return-to-go per step; DONES bound episodes.

    Accepts flat episode-sequential [T] columns OR time-major [T, N]
    columns from vectorized rollout logs (each env column scanned
    independently — flattening [T, N] first would interleave episodes
    and corrupt every return). Returns match the column's shape."""
    rewards = np.asarray(batch[REWARDS], np.float32)
    dones = np.asarray(batch[DONES], bool)
    flat = rewards.ndim == 1
    if flat:
        rewards = rewards[:, None]
        dones = dones.reshape(-1)[:, None]
    out = np.zeros_like(rewards)
    acc = np.zeros(rewards.shape[1], np.float32)
    for t in range(rewards.shape[0] - 1, -1, -1):
        acc = np.where(dones[t], 0.0, acc)
        acc = rewards[t] + gamma * acc
        out[t] = acc
    return out[:, 0] if flat else out


class MARWILConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self._algo_class = MARWIL
        self.beta = 1.0  # 0.0 => pure behavior cloning
        self.vf_coeff = 1.0
        self.lr = 1e-3
        self.train_batch_size = 256
        self.num_updates_per_iter = 32
        self.input_path: str = ""
        self.moving_average_sqd_adv_norm_update_rate = 1e-2

    def offline_data(self, input_path: str) -> "MARWILConfig":
        self.input_path = input_path
        return self

    def training(self, **kwargs) -> "MARWILConfig":
        for k in ("beta", "vf_coeff", "num_updates_per_iter",
                  "moving_average_sqd_adv_norm_update_rate"):
            if k in kwargs:
                setattr(self, k, kwargs.pop(k))
        super().training(**kwargs)
        return self


class BCConfig(MARWILConfig):
    """Behavior cloning (reference: bc.py BC = MARWIL with beta=0)."""

    def __init__(self):
        super().__init__()
        self._algo_class = BC
        self.beta = 0.0


class MARWIL(Algorithm):
    """training_step: sample offline minibatch -> one jit update
    (advantage-weighted NLL + value regression). The WorkerSet's env is
    used only for EVALUATION (evaluate() rolls the learned policy out).
    """

    def setup(self, config: MARWILConfig) -> None:
        import optax

        super().setup(config)
        if not config.input_path:
            raise ValueError("MARWIL/BC needs config.offline_data(path)")
        data = JsonReader(config.input_path).read_all()
        # Returns are computed at the logged shape (flat [T] or
        # time-major [T, N]) BEFORE flattening — flattening first would
        # interleave the N envs' episodes.
        returns = _monte_carlo_returns(data, config.gamma).reshape(-1)
        obs = np.asarray(data[OBS], np.float32)
        self._data = {
            OBS: obs.reshape(len(returns), -1),
            ACTIONS: np.asarray(data[ACTIONS]).reshape(-1),
            "returns": returns,
        }
        self._rng_np = np.random.default_rng(config.seed)
        policy = self.workers.local_worker.policy
        self.params = policy.params
        apply_fn = policy.net.apply
        self.optimizer = optax.adam(config.lr)
        self.opt_state = self.optimizer.init(self.params)
        beta, vfc = config.beta, config.vf_coeff
        ma_rate = config.moving_average_sqd_adv_norm_update_rate

        def loss(params, batch, adv_norm):
            logits, values = apply_fn(params, batch[OBS])
            logp_all = jax.nn.log_softmax(logits)
            actions = batch[ACTIONS].astype(jnp.int32)
            logp = jnp.take_along_axis(logp_all, actions[:, None],
                                       axis=-1)[:, 0]
            adv = batch["returns"] - jax.lax.stop_gradient(values)
            if beta > 0:
                # Advantage-weighted imitation with a running norm
                # (reference: marwil_tf_policy explained_variance /
                # ma_adv_norm), clipped for stability.
                weights = jnp.exp(beta * jnp.clip(
                    adv / jnp.sqrt(adv_norm + 1e-8), -10.0, 10.0))
                weights = jnp.minimum(weights, 20.0)
            else:
                weights = jnp.ones_like(logp)
            policy_loss = -jnp.mean(
                jax.lax.stop_gradient(weights) * logp)
            vf_loss = jnp.mean((values - batch["returns"]) ** 2)
            total = policy_loss + vfc * vf_loss
            new_norm = adv_norm + ma_rate * (
                jnp.mean(adv ** 2) - adv_norm)
            return total, {"policy_loss": policy_loss,
                           "vf_loss": vf_loss,
                           "adv_norm": new_norm}

        optimizer = self.optimizer

        @jax.jit
        def update(params, opt_state, batch, adv_norm):
            (total, aux), grads = jax.value_and_grad(
                loss, has_aux=True)(params, batch, adv_norm)
            updates, opt_state = optimizer.update(grads, opt_state,
                                                  params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, total, aux

        self._update = update
        self._adv_norm = jnp.asarray(1.0)

    def training_step(self) -> Dict:
        cfg = self.config
        n = len(self._data["returns"])
        total = aux = None
        for _ in range(cfg.num_updates_per_iter):
            idx = self._rng_np.integers(0, n, cfg.train_batch_size)
            batch = {k: jnp.asarray(v[idx])
                     for k, v in self._data.items()}
            self.params, self.opt_state, total, aux = self._update(
                self.params, self.opt_state, batch, self._adv_norm)
            self._adv_norm = aux["adv_norm"]
        self._timesteps_total += (cfg.num_updates_per_iter
                                  * cfg.train_batch_size)
        weights = jax.tree.map(np.asarray, self.params)
        self.workers.local_worker.set_weights(weights)
        self.workers.sync_weights(weights)
        return {
            "timesteps_this_iter": (cfg.num_updates_per_iter
                                    * cfg.train_batch_size),
            "total_loss": float(total),
            "policy_loss": float(aux["policy_loss"]),
            "vf_loss": float(aux["vf_loss"]),
        }

    def evaluate(self, episodes: int = 5) -> Dict:
        """Roll the learned policy out through the worker's connector
        pipelines (eval mode: running stats frozen)."""
        worker = self.workers.local_worker
        env = worker.env
        rewards = []
        worker.agent_connectors.in_eval()
        worker.agent_connectors.reset()
        try:
            obs = worker.agent_connectors(
                env.vector_reset(seed=self.config.seed + 99))
            ep_rew = np.zeros(env.num_envs, np.float32)
            while len(rewards) < episodes:
                actions, _, _ = worker.policy.compute_actions(
                    obs, deterministic=True)
                nobs, r, dones, _ = env.vector_step(
                    worker.action_connectors(actions))
                worker.agent_connectors.on_episode_done(dones)
                obs = worker.agent_connectors(nobs)
                ep_rew += r
                for i in np.nonzero(dones)[0]:
                    rewards.append(float(ep_rew[i]))
                    ep_rew[i] = 0.0
        finally:
            worker.agent_connectors.in_training()
            worker.agent_connectors.reset()
            # Re-align the worker's stepping state with its env, which
            # this loop advanced out from under sample().
            worker._obs = worker.agent_connectors(
                env.vector_reset(seed=self.config.seed + 100))
        return {"episode_reward_mean": float(np.mean(rewards)),
                "episodes": len(rewards)}

    def get_state(self) -> Dict:
        state = super().get_state()
        state["params"] = jax.tree.map(np.asarray, self.params)
        state["adv_norm"] = float(self._adv_norm)
        state["opt_state"] = jax.tree.map(np.asarray, self.opt_state)
        return state

    def set_state(self, state: Dict) -> None:
        super().set_state(state)
        if "params" in state:
            self.params = jax.tree.map(jnp.asarray, state["params"])
            weights = jax.tree.map(np.asarray, self.params)
            self.workers.local_worker.set_weights(weights)
            self.workers.sync_weights(weights)
        if "adv_norm" in state:
            # A reset normalizer would inflate the exp advantage
            # weights after every resume (loss spike / policy lurch).
            self._adv_norm = jnp.asarray(state["adv_norm"])
        if "opt_state" in state:
            self.opt_state = jax.tree.map(jnp.asarray,
                                          state["opt_state"])


class BC(MARWIL):
    """Behavior cloning (reference: ``rllib/algorithms/bc``)."""
