"""RolloutWorker: env-sampling actor.

Reference analog: ``rllib/evaluation/rollout_worker.py:124`` with the
``SyncSampler`` env loop (``sampler.py:145,546``) — collects fixed-length
time-major rollout fragments from a vectorized env using the current policy
weights; weights are synced from the learner each iteration
(``WorkerSet.sync_weights``, worker_set.py:205).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from .connectors import ConnectorContext, create_connectors_for_policy
from .env import make_env
from .policy import JaxPolicy
from .sample_batch import (
    ACTIONS,
    DONES,
    LOGPS,
    OBS,
    REWARDS,
    STATE_IN,
    VF_PREDS,
    SampleBatch,
)


class RolloutWorker:
    """Actor body (also usable inline for num_workers=0 local mode)."""

    def __init__(self, env_spec: Any, num_envs: int = 1,
                 policy_config: Optional[Dict] = None, seed: int = 0,
                 worker_index: int = 0):
        import jax

        # Rollout workers always run CPU inference — the learner owns the
        # accelerator (reference: rollout workers are CPU actors).
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
        self.env = make_env(env_spec, num_envs, seed + worker_index * 1000)
        cfg = policy_config or {}
        # Connector pipelines sit between env and policy (reference:
        # connectors/util.py create_connectors_for_policy) — the policy
        # is built against the TRANSFORMED obs shape, and the batch
        # stores transformed observations (what the policy actually saw).
        ctx = ConnectorContext.from_env(self.env, cfg)
        self._policy_cfg = cfg
        self.agent_connectors, self.action_connectors = \
            create_connectors_for_policy(ctx, cfg.get("connectors"))
        raw = self.env.vector_reset(seed=seed + worker_index * 1000)
        self._obs = self.agent_connectors(raw)
        self._connected_obs_shape = tuple(np.asarray(self._obs).shape[1:])
        self.policy = self._make_policy(cfg, seed + worker_index)
        self._episode_rewards = np.zeros(self.env.num_envs, np.float32)
        self._completed: list = []
        self.worker_index = worker_index

    def _make_policy(self, cfg: Dict, seed: int):
        """Subclass hook: build the policy for this worker's env."""
        return JaxPolicy(
            self._connected_obs_shape, self.env.num_actions,
            hidden=cfg.get("hidden", (64, 64)), seed=seed,
            network=cfg.get("network", "auto"),
            model_config=cfg.get("model"),
        )

    def apply(self, fn) -> Any:
        """Run fn(self) in the worker (reference: RolloutWorker.apply)."""
        return fn(self)

    def _step_env(self, actions: np.ndarray):
        """One connected env step: action pipeline -> env.step -> agent
        pipeline on (obs, rewards) -> episode bookkeeping. Returns
        (transformed_next_obs, transformed_rewards, dones, infos)."""
        env_actions = self.action_connectors(actions)
        next_obs, rewards, dones, infos = self.env.vector_step(env_actions)
        self._episode_rewards += rewards
        for i in np.nonzero(dones)[0]:
            self._completed.append(float(self._episode_rewards[i]))
            self._episode_rewards[i] = 0.0
        self.agent_connectors.on_episode_done(dones)
        return (self.agent_connectors(next_obs),
                self.agent_connectors.transform_reward(rewards),
                dones, infos)

    def connector_state(self) -> Dict:
        """Serialized pipelines — Algorithm.get_state embeds this so a
        restored run (or a served policy) reconstructs the exact
        preprocessing, running statistics included (reference:
        connectors/util.py restore_connectors_for_policy).

        Non-serializable connectors (lambdas) are skipped with a warning
        rather than poisoning the whole checkpoint — losing a stateless
        lambda is recoverable; silently losing MeanStd statistics is not.
        """
        import warnings

        state: Dict = {"agent": [], "action": []}
        for key, pipe in (("agent", self.agent_connectors),
                          ("action", self.action_connectors)):
            for c in pipe.connectors:
                try:
                    state[key].append(c.to_state())
                except Exception:
                    warnings.warn(
                        f"connector {type(c).__name__} is not "
                        "serializable; omitted from checkpoint — "
                        "re-add it in the config on restore")
        return state

    def restore_connector_state(self, state: Dict) -> None:
        from .connectors import restore_connectors_for_policy

        ctx = ConnectorContext.from_env(self.env, self._policy_cfg)
        self.agent_connectors, self.action_connectors = \
            restore_connectors_for_policy(ctx, state)

    def set_weights(self, weights: Dict) -> None:
        self.policy.set_weights(weights)

    def get_weights(self) -> Dict:
        return self.policy.get_weights()

    def sample(self, rollout_length: int = 128) -> SampleBatch:
        """Collect a [T, N, ...] fragment; auto-resetting envs."""
        n = self.env.num_envs
        state_in = None
        if getattr(getattr(self.policy, "net", None), "is_recurrent",
                   False):
            # Ship the behavior policy's hidden state at fragment start
            # so the learner's sequence scan starts from the SAME state
            # (reference: state_in in rnn_sequencing.py) — zero-state
            # recompute would skew the importance ratio on fragments
            # starting mid-episode.
            state = self.policy.recurrent_state(n)
            state_in = np.stack([np.asarray(s) for s in state])
        # Preserve the env's obs dtype: forward_conv keys its /255
        # normalization on uint8, so coercing frames to float32 here would
        # make the training batch see a DIFFERENT function than the one
        # that sampled the actions (breaking the PPO importance ratio).
        obs_buf = np.empty((rollout_length, n) +
                           self._connected_obs_shape,
                           np.asarray(self._obs).dtype)
        act_buf = np.empty((rollout_length, n), np.int32)
        logp_buf = np.empty((rollout_length, n), np.float32)
        vf_buf = np.empty((rollout_length, n), np.float32)
        rew_buf = np.empty((rollout_length, n), np.float32)
        done_buf = np.empty((rollout_length, n), bool)
        for t in range(rollout_length):
            actions, logp, values = self.policy.compute_actions(self._obs)
            obs_buf[t] = self._obs
            act_buf[t] = actions
            logp_buf[t] = logp
            vf_buf[t] = values
            next_obs, rewards, dones, _ = self._step_env(actions)
            rew_buf[t] = rewards
            done_buf[t] = dones
            # Recurrent policies reset finished sub-envs' state slots.
            observe = getattr(self.policy, "observe_dones", None)
            if observe is not None:
                observe(dones)
            self._obs = next_obs
        # Bootstrap values for the final observation — side-effect-free
        # for recurrent policies: the next fragment will feed this same
        # observation again, so advancing the hidden state here would
        # make the LSTM see every fragment-boundary obs twice.
        saved_state = (self.policy.recurrent_state(n)
                       if state_in is not None else None)
        _, _, last_values = self.policy.compute_actions(self._obs)
        if saved_state is not None:
            self.policy.set_recurrent_state(n, saved_state)
        batch = SampleBatch({
            OBS: obs_buf, ACTIONS: act_buf, LOGPS: logp_buf,
            VF_PREDS: vf_buf, REWARDS: rew_buf, DONES: done_buf,
        })
        if state_in is not None:
            batch[STATE_IN] = state_in
        batch["last_values"] = np.asarray(last_values, np.float32)
        # Final observation [N, obs]: V-trace bootstraps V(x_T) under the
        # *learner's* policy (IMPALA), so ship the state, not just the
        # behavior-policy value estimate.
        batch["final_obs"] = np.asarray(self._obs)
        return batch

    def episode_stats(self, clear: bool = True) -> Dict:
        eps = list(self._completed)
        if clear:
            self._completed = []
        return {
            "episodes": len(eps),
            "episode_reward_mean": float(np.mean(eps)) if eps else None,
            "episode_reward_max": float(np.max(eps)) if eps else None,
        }
