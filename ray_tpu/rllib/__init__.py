"""RLlib-equivalent: reinforcement learning with JAX policies on TPU
learners + CPU rollout actors.

Reference analog: ``rllib/`` (Algorithm/AlgorithmConfig, PPO,
RolloutWorker/WorkerSet, SampleBatch, env abstractions).
"""

from .a2c import A2C, A2CConfig
from .algorithm import Algorithm, AlgorithmConfig, WorkerSet
from .appo import APPO, APPOConfig
from .bandit import BanditEnv, LinTS, LinUCB, run_bandit
from .cql import CQL, CQLConfig
from .es import ARS, ARSConfig, ES, ESConfig, SharedNoiseTable
from .dqn import DQN, DQNConfig
from .env import (
    AtariSim,
    FastCartPole,
    FastPendulum,
    GymVectorEnv,
    VectorEnv,
    make_env,
)
from .connectors import (
    ActionConnector,
    ActionConnectorPipeline,
    AgentConnector,
    AgentConnectorPipeline,
    ConnectorContext,
    create_connectors_for_policy,
    register_connector,
    restore_connectors_for_policy,
)
from .external import (
    ExternalDQNWorker,
    ExternalEnv,
    ExternalEnvWorker,
    PolicyClient,
    PolicyServerInput,
)
from .impala import Impala, ImpalaConfig, vtrace
from .multi_agent import MultiAgentEnv, make_multi_agent, sample_multi_agent
from .offline import (
    DirectMethod,
    DoublyRobust,
    ImportanceSampling,
    JsonReader,
    JsonWriter,
    WeightedImportanceSampling,
)
from .ondevice import JAX_ENVS, JaxEnv, OnDevicePPO, jax_atari_sim, \
    jax_cartpole
from .catalog import MODEL_DEFAULTS, get_network, register_custom_model
from .policy import JaxPolicy, Network, make_network
from .ppo import PPO, PPOConfig
from .replay_buffers import (
    MultiAgentReplayBuffer,
    PrioritizedReplayBuffer,
    ReplayBuffer,
    ReservoirReplayBuffer,
)
from .apex import ApexConfig, ApexDQN
from .marwil import BC, BCConfig, MARWIL, MARWILConfig
from .rollout_worker import RolloutWorker
from .sac import SAC, SACConfig
from .td3 import TD3, TD3Config
from .sample_batch import SampleBatch, compute_gae

__all__ = [
    "APPO",
    "APPOConfig",
    "MultiAgentEnv",
    "make_multi_agent",
    "sample_multi_agent",
    "DirectMethod", "DoublyRobust", "ImportanceSampling",
    "JsonReader",
    "JsonWriter",
    "WeightedImportanceSampling",
    "ActionConnector", "ActionConnectorPipeline", "AgentConnector",
    "AgentConnectorPipeline", "ConnectorContext",
    "create_connectors_for_policy", "register_connector",
    "restore_connectors_for_policy",
    "ExternalDQNWorker", "ExternalEnv", "ExternalEnvWorker",
    "PolicyClient", "PolicyServerInput",
    "A2C", "A2CConfig", "ARS", "ARSConfig", "BanditEnv", "CQL",
    "CQLConfig", "ES", "ESConfig", "LinTS", "LinUCB", "run_bandit",
    "SharedNoiseTable",
    "Algorithm", "AlgorithmConfig", "ApexConfig", "ApexDQN",
    "AtariSim", "DQN", "DQNConfig",
    "FastCartPole", "FastPendulum", "GymVectorEnv", "Impala",
    "BC", "BCConfig", "MARWIL", "MARWILConfig",
    "ImpalaConfig", "JAX_ENVS", "MODEL_DEFAULTS", "Network", "SAC",
    "SACConfig", "TD3", "TD3Config", "get_network",
    "register_custom_model",
    "JaxEnv", "JaxPolicy", "MultiAgentReplayBuffer", "OnDevicePPO", "PPO",
    "PPOConfig", "PrioritizedReplayBuffer", "ReplayBuffer",
    "ReservoirReplayBuffer", "RolloutWorker", "SampleBatch", "VectorEnv",
    "WorkerSet", "compute_gae", "jax_atari_sim", "jax_cartpole",
    "make_env", "make_network",
]
