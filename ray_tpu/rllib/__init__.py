"""RLlib-equivalent: reinforcement learning with JAX policies on TPU
learners + CPU rollout actors.

Reference analog: ``rllib/`` (Algorithm/AlgorithmConfig, PPO,
RolloutWorker/WorkerSet, SampleBatch, env abstractions).
"""

from .algorithm import Algorithm, AlgorithmConfig, WorkerSet
from .dqn import DQN, DQNConfig
from .env import FastCartPole, GymVectorEnv, VectorEnv, make_env
from .impala import Impala, ImpalaConfig, vtrace
from .policy import JaxPolicy
from .ppo import PPO, PPOConfig
from .replay_buffers import (
    MultiAgentReplayBuffer,
    PrioritizedReplayBuffer,
    ReplayBuffer,
    ReservoirReplayBuffer,
)
from .rollout_worker import RolloutWorker
from .sample_batch import SampleBatch, compute_gae

__all__ = [
    "Algorithm", "AlgorithmConfig", "DQN", "DQNConfig", "FastCartPole",
    "GymVectorEnv", "Impala", "ImpalaConfig", "JaxPolicy",
    "MultiAgentReplayBuffer", "PPO",
    "PPOConfig", "PrioritizedReplayBuffer", "ReplayBuffer",
    "ReservoirReplayBuffer", "RolloutWorker", "SampleBatch", "VectorEnv",
    "WorkerSet", "compute_gae", "make_env",
]
