"""RLlib-equivalent: reinforcement learning with JAX policies on TPU
learners + CPU rollout actors.

Reference analog: ``rllib/`` (Algorithm/AlgorithmConfig, PPO,
RolloutWorker/WorkerSet, SampleBatch, env abstractions).
"""

from .algorithm import Algorithm, AlgorithmConfig, WorkerSet
from .env import FastCartPole, GymVectorEnv, VectorEnv, make_env
from .policy import JaxPolicy
from .ppo import PPO, PPOConfig
from .rollout_worker import RolloutWorker
from .sample_batch import SampleBatch, compute_gae

__all__ = [
    "Algorithm", "AlgorithmConfig", "FastCartPole", "GymVectorEnv",
    "JaxPolicy", "PPO", "PPOConfig", "RolloutWorker", "SampleBatch",
    "VectorEnv", "WorkerSet", "compute_gae", "make_env",
]
