"""Environment abstractions: vectorized env over gymnasium + native envs.

Reference analog: ``rllib/env/`` (BaseEnv/VectorEnv wrapping gym). A
``VectorEnv`` steps N env copies with batched numpy IO — the rollout hot
loop's interface. ``FastCartPole`` is a pure-numpy vectorized CartPole used
for throughput benchmarking without per-env python loops (the env analog of
the reference's Atari throughput configs).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import numpy as np


class VectorEnv:
    """N synchronized env copies; batched reset/step."""

    num_envs: int
    observation_space_shape: Tuple[int, ...]
    num_actions: int

    def vector_reset(self, seed: Optional[int] = None) -> np.ndarray:
        raise NotImplementedError

    def vector_step(self, actions: np.ndarray
                    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, dict]:
        """-> (obs [N, ...], rewards [N], dones [N], info). Auto-resets
        done sub-envs (returned obs is the fresh reset obs)."""
        raise NotImplementedError


class GymVectorEnv(VectorEnv):
    """Wraps ``gymnasium.make_vec``-style env batches."""

    def __init__(self, env_id: str, num_envs: int = 1, **kwargs):
        import gymnasium as gym

        self._envs = [gym.make(env_id, **kwargs) for _ in range(num_envs)]
        self.num_envs = num_envs
        space = self._envs[0].observation_space
        self.observation_space_shape = tuple(space.shape)
        self.num_actions = int(self._envs[0].action_space.n)

    def vector_reset(self, seed: Optional[int] = None) -> np.ndarray:
        obs = []
        for i, e in enumerate(self._envs):
            o, _ = e.reset(seed=None if seed is None else seed + i)
            obs.append(o)
        return np.stack(obs)

    def vector_step(self, actions):
        obs, rewards, dones = [], [], []
        for e, a in zip(self._envs, actions):
            o, r, term, trunc, _ = e.step(int(a))
            done = bool(term or trunc)
            if done:
                o, _ = e.reset()
            obs.append(o)
            rewards.append(r)
            dones.append(done)
        return (np.stack(obs), np.asarray(rewards, np.float32),
                np.asarray(dones), {})


class FastCartPole(VectorEnv):
    """Vectorized numpy CartPole-v1 (identical dynamics/termination).

    One batched numpy update per step for all N envs — the high-throughput
    path for the env-steps/sec benchmark.
    """

    GRAVITY = 9.8
    MASS_CART = 1.0
    MASS_POLE = 0.1
    LENGTH = 0.5
    FORCE = 10.0
    TAU = 0.02
    THETA_LIMIT = 12 * 2 * np.pi / 360
    X_LIMIT = 2.4
    MAX_STEPS = 500

    def __init__(self, num_envs: int = 1, seed: int = 0):
        self.num_envs = num_envs
        self.observation_space_shape = (4,)
        self.num_actions = 2
        self._rng = np.random.default_rng(seed)
        self._state = np.zeros((num_envs, 4), np.float32)
        self._steps = np.zeros(num_envs, np.int32)

    def _reset_some(self, mask: np.ndarray) -> None:
        n = int(mask.sum())
        if n:
            self._state[mask] = self._rng.uniform(
                -0.05, 0.05, (n, 4)
            ).astype(np.float32)
            self._steps[mask] = 0

    def vector_reset(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._reset_some(np.ones(self.num_envs, bool))
        return self._state.copy()

    def vector_step(self, actions):
        x, x_dot, theta, theta_dot = self._state.T
        force = np.where(actions == 1, self.FORCE, -self.FORCE)
        costh, sinth = np.cos(theta), np.sin(theta)
        total_mass = self.MASS_CART + self.MASS_POLE
        polemass_length = self.MASS_POLE * self.LENGTH
        temp = (force + polemass_length * theta_dot**2 * sinth) / total_mass
        theta_acc = (self.GRAVITY * sinth - costh * temp) / (
            self.LENGTH * (4.0 / 3.0 - self.MASS_POLE * costh**2 / total_mass)
        )
        x_acc = temp - polemass_length * theta_acc * costh / total_mass
        x = x + self.TAU * x_dot
        x_dot = x_dot + self.TAU * x_acc
        theta = theta + self.TAU * theta_dot
        theta_dot = theta_dot + self.TAU * theta_acc
        self._state = np.stack([x, x_dot, theta, theta_dot], axis=1).astype(
            np.float32
        )
        self._steps += 1
        done = (
            (np.abs(x) > self.X_LIMIT)
            | (np.abs(theta) > self.THETA_LIMIT)
            | (self._steps >= self.MAX_STEPS)
        )
        rewards = np.ones(self.num_envs, np.float32)
        self._reset_some(done)
        return self._state.copy(), rewards, done, {}


class FastPendulum(VectorEnv):
    """Vectorized numpy Pendulum-v1 (identical dynamics/reward) — the
    continuous-action counterpart of FastCartPole; one batched numpy
    update per step for all N envs. Continuous envs expose
    ``action_dim`` + ``action_low/high`` instead of ``num_actions``."""

    G = 10.0
    M = 1.0
    L = 1.0
    DT = 0.05
    MAX_SPEED = 8.0
    MAX_TORQUE = 2.0
    MAX_STEPS = 200

    num_actions = 0  # continuous
    action_dim = 1
    action_low = -2.0
    action_high = 2.0

    def __init__(self, num_envs: int = 1, seed: int = 0):
        self.num_envs = num_envs
        self.observation_space_shape = (3,)
        self._rng = np.random.default_rng(seed)
        self._theta = np.zeros(num_envs, np.float32)
        self._thetadot = np.zeros(num_envs, np.float32)
        self._steps = np.zeros(num_envs, np.int32)

    def _obs(self) -> np.ndarray:
        return np.stack([np.cos(self._theta), np.sin(self._theta),
                         self._thetadot], axis=1).astype(np.float32)

    def _reset_some(self, mask: np.ndarray) -> None:
        n = int(mask.sum())
        if n:
            self._theta[mask] = self._rng.uniform(-np.pi, np.pi, n)
            self._thetadot[mask] = self._rng.uniform(-1.0, 1.0, n)
            self._steps[mask] = 0

    def vector_reset(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._reset_some(np.ones(self.num_envs, bool))
        return self._obs()

    def vector_step(self, actions):
        u = np.clip(np.asarray(actions, np.float32).reshape(self.num_envs),
                    -self.MAX_TORQUE, self.MAX_TORQUE)
        th, thdot = self._theta, self._thetadot
        norm_th = ((th + np.pi) % (2 * np.pi)) - np.pi
        costs = norm_th ** 2 + 0.1 * thdot ** 2 + 0.001 * u ** 2
        newthdot = thdot + (
            3.0 * self.G / (2.0 * self.L) * np.sin(th)
            + 3.0 / (self.M * self.L ** 2) * u) * self.DT
        newthdot = np.clip(newthdot, -self.MAX_SPEED, self.MAX_SPEED)
        self._theta = (th + newthdot * self.DT).astype(np.float32)
        self._thetadot = newthdot.astype(np.float32)
        self._steps += 1
        done = self._steps >= self.MAX_STEPS
        self._reset_some(done)
        return (self._obs(), (-costs).astype(np.float32), done, {})


class RepeatPrevObs(VectorEnv):
    """Memory probe env: the reward at step t is 1 iff the action
    equals the SIGNAL SHOWN AT t-1. A feedforward policy sees only the
    current signal — independent of the correct answer — so its best
    possible mean reward is chance (1/num_signals); any policy with one
    step of memory can score ~1 per step. Used to prove recurrent
    V-trace actually trains the recurrent pathway."""

    NUM_SIGNALS = 3
    MAX_STEPS = 32

    def __init__(self, num_envs: int = 1, seed: int = 0):
        self.num_envs = num_envs
        self.observation_space_shape = (self.NUM_SIGNALS,)
        self.num_actions = self.NUM_SIGNALS
        self._rng = np.random.default_rng(seed)
        self._signal = np.zeros(num_envs, np.int64)
        self._prev = np.zeros(num_envs, np.int64)
        self._steps = np.zeros(num_envs, np.int32)

    def _obs(self) -> np.ndarray:
        out = np.zeros((self.num_envs, self.NUM_SIGNALS), np.float32)
        out[np.arange(self.num_envs), self._signal] = 1.0
        return out

    def _reset_some(self, mask) -> None:
        n = int(np.sum(mask))
        if not n:
            return
        self._signal[mask] = self._rng.integers(0, self.NUM_SIGNALS, n)
        self._prev[mask] = 0  # the known start token
        self._steps[mask] = 0

    def vector_reset(self, seed=None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._reset_some(np.ones(self.num_envs, bool))
        return self._obs()

    def vector_step(self, actions):
        actions = np.asarray(actions).reshape(self.num_envs)
        rewards = (actions == self._prev).astype(np.float32)
        self._prev = self._signal.copy()
        self._signal = self._rng.integers(0, self.NUM_SIGNALS,
                                          self.num_envs)
        self._steps += 1
        done = self._steps >= self.MAX_STEPS
        self._reset_some(done)
        return self._obs(), rewards, done, {}


class AtariSim(VectorEnv):
    """Synthetic Atari-SHAPED env: 84x84x4 uint8 frame-stack observations,
    6 actions, pong-like ball/paddle dynamics rendered with vectorized
    numpy — the workload shape of the reference's Atari throughput configs
    (frame tensors, conv policy) without ALE ROMs, which this image lacks.
    Rewards: +1 when the paddle tracks the ball row at frame events.
    """

    H = W = 84
    STACK = 4
    MAX_STEPS = 1000

    def __init__(self, num_envs: int = 1, seed: int = 0):
        self.num_envs = num_envs
        self.observation_space_shape = (self.H, self.W, self.STACK)
        self.num_actions = 6
        self._rng = np.random.default_rng(seed)
        n = num_envs
        self._ball = np.zeros((n, 2), np.float32)    # (y, x)
        self._vel = np.zeros((n, 2), np.float32)
        self._paddle = np.zeros(n, np.float32)       # y position
        self._steps = np.zeros(n, np.int32)
        self._frames = np.zeros((n, self.H, self.W, self.STACK), np.uint8)

    def _reset_some(self, mask: np.ndarray) -> None:
        n = int(mask.sum())
        if not n:
            return
        self._ball[mask] = self._rng.uniform(20, 60, (n, 2))
        self._vel[mask] = self._rng.choice([-2.0, -1.0, 1.0, 2.0], (n, 2))
        self._paddle[mask] = self.H / 2
        self._steps[mask] = 0
        self._frames[mask] = 0

    def vector_reset(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._reset_some(np.ones(self.num_envs, bool))
        self._render()
        return self._frames.copy()

    def _render(self) -> None:
        # Shift the stack and draw ball + paddle into the newest frame.
        self._frames[..., :-1] = self._frames[..., 1:]
        new = np.zeros((self.num_envs, self.H, self.W), np.uint8)
        idx = np.arange(self.num_envs)
        by = np.clip(self._ball[:, 0].astype(int), 1, self.H - 2)
        bx = np.clip(self._ball[:, 1].astype(int), 1, self.W - 2)
        for dy in (-1, 0, 1):
            for dx in (-1, 0, 1):
                new[idx, by + dy, bx + dx] = 255
        py = np.clip(self._paddle.astype(int), 4, self.H - 5)
        for dy in range(-4, 5):
            new[idx, py + dy, self.W - 3] = 200
        self._frames[..., -1] = new

    def vector_step(self, actions):
        # 0/1: stay, 2/4: up, 3/5: down (Atari Pong action semantics-ish)
        move = np.where(np.isin(actions, (2, 4)), -2.0,
                        np.where(np.isin(actions, (3, 5)), 2.0, 0.0))
        self._paddle = np.clip(self._paddle + move, 4, self.H - 5)
        self._ball += self._vel
        for axis, lim in ((0, self.H - 2), (1, self.W - 2)):
            low = self._ball[:, axis] < 1
            high = self._ball[:, axis] > lim
            self._vel[low | high, axis] *= -1
            self._ball[:, axis] = np.clip(self._ball[:, axis], 1, lim)
        hit = (self._ball[:, 1] > self.W - 6) & (
            np.abs(self._ball[:, 0] - self._paddle) < 5)
        rewards = hit.astype(np.float32)
        self._steps += 1
        done = self._steps >= self.MAX_STEPS
        self._reset_some(done)
        self._render()
        return self._frames.copy(), rewards, done, {}


def make_env(env: Any, num_envs: int, seed: int = 0) -> VectorEnv:
    """Resolve an env spec: VectorEnv instance, factory, or gym id."""
    if isinstance(env, VectorEnv):
        return env
    if callable(env):
        made = env(num_envs)
        if isinstance(made, VectorEnv):
            return made
        raise TypeError("env factory must return a VectorEnv")
    if env == "FastCartPole":
        return FastCartPole(num_envs, seed)
    if env == "FastPendulum":
        return FastPendulum(num_envs, seed)
    if env == "AtariSim":
        return AtariSim(num_envs, seed)
    if env == "RepeatPrevObs":
        return RepeatPrevObs(num_envs, seed)
    return GymVectorEnv(env, num_envs)
