"""IMPALA: asynchronous actor-learner RL with V-trace correction.

Reference analog: ``rllib/algorithms/impala/`` — rollout actors sample
continuously and ship fragments to a central learner; the learner
corrects for policy lag with V-trace (Espeholt et al. 2018,
``vtrace_torch.py``) and streams updated weights back.

TPU-first shape: the learner update is one jit-compiled program (device
resident); rollout workers are CPU actors polled with ``wait`` so the
learner never blocks on the slowest worker — the async pipeline is the
point of IMPALA vs synchronous PPO.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .algorithm import Algorithm, AlgorithmConfig
from .policy import forward_mlp
from .sample_batch import (
    ACTIONS,
    DONES,
    LOGPS,
    OBS,
    REWARDS,
    STATE_IN,
    SampleBatch,
)


def vtrace(behavior_logp, target_logp, rewards, dones, values, bootstrap,
           gamma: float, rho_clip: float = 1.0, c_clip: float = 1.0
           ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """V-trace targets and policy-gradient advantages.

    All inputs time-major [T, B]; ``bootstrap`` [B] is V(x_T) under the
    *target* policy. Returns (vs, pg_advantages), both [T, B] and safe to
    ``stop_gradient`` (already detached here).
    """
    rho = jnp.exp(target_logp - behavior_logp)
    rho_c = jnp.minimum(rho, rho_clip)
    c = jnp.minimum(rho, c_clip)
    not_done = 1.0 - dones.astype(jnp.float32)
    next_values = jnp.concatenate([values[1:], bootstrap[None]], axis=0)
    deltas = rho_c * (rewards + gamma * not_done * next_values - values)

    def scan_fn(acc, inp):
        delta_t, c_t, nd_t = inp
        acc = delta_t + gamma * nd_t * c_t * acc
        return acc, acc

    _, vs_minus_v = jax.lax.scan(
        scan_fn, jnp.zeros_like(bootstrap),
        (deltas, c, not_done), reverse=True)
    vs = values + vs_minus_v
    vs_next = jnp.concatenate([vs[1:], bootstrap[None]], axis=0)
    pg_adv = rho_c * (rewards + gamma * not_done * vs_next - values)
    return jax.lax.stop_gradient(vs), jax.lax.stop_gradient(pg_adv)


def forward_feedforward(params, batch, apply_fn):
    """Feedforward target-policy forward over a time-major [T, B] batch:
    returns (logp_all [T,B,A], values [T,B], bootstrap [B])."""
    obs = batch[OBS]
    t_len, n = obs.shape[:2]
    flat_obs = obs.reshape((t_len * n,) + obs.shape[2:])
    logits, values = apply_fn(params, flat_obs)
    logits = logits.reshape(t_len, n, -1)
    values = values.reshape(t_len, n)
    _, bootstrap = apply_fn(params, batch["final_obs"])
    return jax.nn.log_softmax(logits), values, bootstrap


def forward_recurrent(params, batch, apply_state):
    """Recurrent target-policy forward (recurrent V-trace, reference:
    the LSTM-first IMPALA of ``rllib/algorithms/impala/``): scan the
    cell over T from STATE_IN — the BEHAVIOR policy's state at fragment
    start, shipped by the rollout worker — zeroing state at episode
    boundaries; the bootstrap value runs final_obs through the
    post-rollout state, exactly the state the behavior policy would
    carry into step T."""
    obs, dones = batch[OBS], batch[DONES]

    def step(state, xs):
        obs_t, done_t = xs
        logits, values, new_state = apply_state(params, obs_t, state)
        mask = (1.0 - done_t.astype(jnp.float32))[:, None]
        new_state = tuple(s * mask for s in new_state)
        return new_state, (logits, values)

    state0 = tuple(batch[STATE_IN][i]
                   for i in range(batch[STATE_IN].shape[0]))
    final_state, (logits, values) = jax.lax.scan(step, state0,
                                                 (obs, dones))
    _, bootstrap, _ = apply_state(params, batch["final_obs"],
                                  final_state)
    return jax.nn.log_softmax(logits), values, bootstrap


def impala_loss(params, batch, gamma, vf_coeff, ent_coeff,
                apply_fn=forward_mlp, forward=None):
    """batch: time-major [T, B] columns + final_obs [B, obs] (+ STATE_IN
    [S, B, cell] on the recurrent path)."""
    if forward is None:
        forward = functools.partial(forward_feedforward, apply_fn=apply_fn)
    logp_all, values, bootstrap = forward(params, batch)
    actions = batch[ACTIONS].astype(jnp.int32)
    target_logp = jnp.take_along_axis(
        logp_all, actions[..., None], axis=-1)[..., 0]

    vs, pg_adv = vtrace(batch[LOGPS], target_logp, batch[REWARDS],
                        batch[DONES], values, bootstrap, gamma)
    pg_loss = -jnp.mean(target_logp * pg_adv)
    vf_loss = 0.5 * jnp.mean((values - vs) ** 2)
    entropy = -jnp.mean(
        jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
    loss = pg_loss + vf_coeff * vf_loss - ent_coeff * entropy
    return loss, {"pg_loss": pg_loss, "vf_loss": vf_loss,
                  "entropy": entropy}


class ImpalaConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self._algo_class = Impala
        self.lr = 5e-4
        self.vf_coeff = 0.5
        self.entropy_coeff = 0.01
        self.rollout_fragment_length = 64
        self.num_batches_per_iter = 8  # learner updates per train() call
        self.grad_clip = 40.0

    def training(self, **kwargs) -> "ImpalaConfig":
        for k in ("vf_coeff", "entropy_coeff", "num_batches_per_iter",
                  "grad_clip"):
            if k in kwargs:
                setattr(self, k, kwargs.pop(k))
        super().training(**kwargs)
        return self


class Impala(Algorithm):
    """Async actor-learner loop.

    ``training_step``: keep one in-flight ``sample`` per remote worker;
    consume whichever finishes first (``wait(num_returns=1)``), update,
    push fresh weights to that worker only, resubmit. Synchronous
    fallback when num_rollout_workers == 0.
    """

    def setup(self, config: ImpalaConfig) -> None:
        import optax

        super().setup(config)
        self.params = self.workers.local_worker.policy.params
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(config.grad_clip),
            optax.adam(config.lr),
        )
        self.opt_state = self.optimizer.init(self.params)
        self._num_updates = 0
        self._in_flight: Dict = {}  # ref -> worker

        gamma = config.gamma
        vf_coeff, ent_coeff = config.vf_coeff, config.entropy_coeff
        forward = self._make_forward()

        @jax.jit
        def update(params, opt_state, batch):
            (loss, metrics), grads = jax.value_and_grad(
                impala_loss, has_aux=True)(params, batch, gamma,
                                           vf_coeff, ent_coeff,
                                           forward=forward)
            updates, opt_state = self.optimizer.update(grads, opt_state,
                                                       params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, metrics

        self._update = update

    def _make_forward(self):
        """Target-policy forward matched to the model: recurrent models
        get the scanning V-trace path (dropping the r4 guard)."""
        net = self.workers.local_worker.policy.net
        if net.is_recurrent:
            return functools.partial(forward_recurrent,
                                     apply_state=net.apply_state)
        return functools.partial(forward_feedforward, apply_fn=net.apply)

    def _learn_on(self, batch: SampleBatch) -> Tuple[float, Dict]:
        jbatch = {k: jnp.asarray(v) for k, v in batch.items()
                  if k != "last_values"}
        self.params, self.opt_state, loss, metrics = self._update(
            self.params, self.opt_state, jbatch)
        self._num_updates += 1
        return float(loss), metrics

    def training_step(self) -> Dict:
        from ..core import get, put, wait

        cfg = self.config
        new_steps = 0
        losses: List[float] = []

        if not self.workers.remote_workers:
            # Degenerate sync mode: still exercises the V-trace learner.
            for _ in range(cfg.num_batches_per_iter):
                batch = self.workers.local_worker.sample(
                    cfg.rollout_fragment_length)
                new_steps += batch[OBS].shape[0] * batch[OBS].shape[1]
                loss, _ = self._learn_on(batch)
                losses.append(loss)
                self.workers.local_worker.set_weights(
                    jax.tree.map(np.asarray, self.params))
        else:
            for w in self.workers.remote_workers:
                if not any(worker is w for worker in
                           self._in_flight.values()):
                    self._in_flight[w.sample.remote(
                        cfg.rollout_fragment_length)] = w
            for _ in range(cfg.num_batches_per_iter):
                ready, _ = wait(list(self._in_flight), num_returns=1,
                                timeout=60)
                if not ready:
                    break
                ref = ready[0]
                worker = self._in_flight.pop(ref)
                batch = get(ref)
                new_steps += batch[OBS].shape[0] * batch[OBS].shape[1]
                loss, _ = self._learn_on(batch)
                losses.append(loss)
                # Stream fresh weights to THIS worker only, then keep it
                # sampling (async: others never blocked on the update).
                weights_ref = put(jax.tree.map(np.asarray, self.params))
                worker.set_weights.remote(weights_ref)
                self._in_flight[worker.sample.remote(
                    cfg.rollout_fragment_length)] = worker
            self.workers.local_worker.set_weights(
                jax.tree.map(np.asarray, self.params))

        self._timesteps_total += new_steps
        return {
            "timesteps_this_iter": new_steps,
            "num_learner_updates": self._num_updates,
            "loss": float(np.mean(losses)) if losses else None,
        }

    def get_state(self) -> Dict:
        state = super().get_state()
        state.update({
            "params": jax.tree.map(np.asarray, self.params),
            "num_updates": self._num_updates,
        })
        return state

    def set_state(self, state: Dict) -> None:
        super().set_state(state)
        if "params" in state:
            self.params = jax.tree.map(jnp.asarray, state["params"])
            self._num_updates = state.get("num_updates", 0)
            weights = jax.tree.map(np.asarray, self.params)
            self.workers.local_worker.set_weights(weights)
            self.workers.sync_weights(weights)

    def stop(self) -> None:
        self._in_flight.clear()
        super().stop()
