"""DQN: double-DQN with prioritized replay on a JAX learner.

Reference analog: ``rllib/algorithms/dqn/`` (DQNConfig, DQN,
``dqn_torch_policy.py`` loss: double-Q bootstrapping, huber TD loss,
n-step targets, prioritized replay feedback) — re-founded on JAX: the
Q-network is a param pytree, the update is one jit-compiled program on
the learner device, and TD errors flow back to the sum-tree priorities.
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.common import truncated_normal
from .algorithm import Algorithm, AlgorithmConfig
from .replay_buffers import PrioritizedReplayBuffer, ReplayBuffer
from .rollout_worker import RolloutWorker
from .sample_batch import (
    ACTIONS,
    DONES,
    NEXT_OBS,
    OBS,
    REWARDS,
    SampleBatch,
)


def init_q_net(key, obs_dim: int, num_actions: int,
               hidden=(256, 256)) -> Dict:
    params = {}
    sizes = [obs_dim] + list(hidden)
    keys = jax.random.split(key, len(sizes) + 1)
    for i in range(len(sizes) - 1):
        std = float(np.sqrt(2.0 / sizes[i]))
        params[f"t{i}_w"] = truncated_normal(
            keys[i], (sizes[i], sizes[i + 1]), stddev=std)
        params[f"t{i}_b"] = jnp.zeros((sizes[i + 1],))
    params["q_w"] = truncated_normal(keys[-1], (sizes[-1], num_actions),
                                     stddev=0.01)
    params["q_b"] = jnp.zeros((num_actions,))
    return params


def q_values(params: Dict, obs: jnp.ndarray) -> jnp.ndarray:
    x = obs.astype(jnp.float32)
    i = 0
    while f"t{i}_w" in params:
        x = jax.nn.relu(x @ params[f"t{i}_w"] + params[f"t{i}_b"])
        i += 1
    return x @ params["q_w"] + params["q_b"]


@functools.partial(jax.jit, static_argnums=())
def _greedy_actions(params, obs):
    return jnp.argmax(q_values(params, obs), axis=-1)


class QPolicy:
    """Epsilon-greedy policy over a Q-MLP (CPU-jit on rollout workers)."""

    def __init__(self, obs_shape: Tuple[int, ...], num_actions: int,
                 hidden=(256, 256), seed: int = 0):
        self.obs_dim = int(np.prod(obs_shape))
        self.num_actions = num_actions
        self.params = init_q_net(jax.random.PRNGKey(seed), self.obs_dim,
                                 num_actions, hidden)
        self.epsilon = 1.0
        self._rng = np.random.default_rng(seed + 1)

    def compute_actions(self, obs: np.ndarray, deterministic: bool = False):
        obs = np.asarray(obs, np.float32).reshape(len(obs), -1)
        greedy = np.asarray(_greedy_actions(self.params, jnp.asarray(obs)))
        if deterministic or self.epsilon <= 0:
            actions = greedy
        else:
            explore = self._rng.random(len(obs)) < self.epsilon
            randoms = self._rng.integers(0, self.num_actions, len(obs))
            actions = np.where(explore, randoms, greedy)
        zeros = np.zeros(len(obs), np.float32)
        return actions.astype(np.int32), zeros, zeros

    def get_weights(self) -> Dict:
        return jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights: Dict) -> None:
        self.params = jax.tree.map(jnp.asarray, weights)


class DQNRolloutWorker(RolloutWorker):
    """Collects flat (s, a, r, s', done) transitions for replay.

    Unlike the on-policy fragment sampler, episode boundaries matter only
    through the ``dones`` mask, so rows are emitted [T*N] row-major.
    """

    def _make_policy(self, cfg: Dict, seed: int):
        return QPolicy(
            self._connected_obs_shape, self.env.num_actions,
            hidden=cfg.get("hidden", (256, 256)), seed=seed,
        )

    def set_epsilon(self, epsilon: float) -> None:
        self.policy.epsilon = float(epsilon)

    def sample(self, rollout_length: int = 64) -> SampleBatch:
        n = self.env.num_envs
        shape = self._connected_obs_shape
        obs_buf = np.empty((rollout_length, n) + shape, np.float32)
        nobs_buf = np.empty((rollout_length, n) + shape, np.float32)
        act_buf = np.empty((rollout_length, n), np.int32)
        rew_buf = np.empty((rollout_length, n), np.float32)
        done_buf = np.empty((rollout_length, n), bool)
        for t in range(rollout_length):
            actions, _, _ = self.policy.compute_actions(self._obs)
            obs_buf[t] = self._obs
            act_buf[t] = actions
            # next_obs at a done is the auto-reset obs; the (1 - done)
            # mask in the TD target makes the bootstrap ignore it.
            next_obs, rewards, dones, _ = self._step_env(actions)
            nobs_buf[t] = next_obs
            rew_buf[t] = rewards
            done_buf[t] = dones
            self._obs = next_obs
        flat = lambda a: a.reshape((-1,) + a.shape[2:])
        return SampleBatch({
            OBS: flat(obs_buf), ACTIONS: flat(act_buf),
            REWARDS: flat(rew_buf), DONES: flat(done_buf),
            NEXT_OBS: flat(nobs_buf),
        })


def dqn_loss(params, target_params, batch, gamma: float,
             double_q: bool = True):
    """(Double-)DQN huber TD loss; returns (loss, |td_error|)."""
    q = q_values(params, batch[OBS])
    q_taken = jnp.take_along_axis(
        q, batch[ACTIONS][:, None].astype(jnp.int32), axis=-1)[:, 0]
    next_target = q_values(target_params, batch[NEXT_OBS])
    if double_q:  # action chosen by the online net, valued by the target
        next_a = jnp.argmax(q_values(params, batch[NEXT_OBS]), axis=-1)
    else:  # vanilla DQN: target net picks and values
        next_a = jnp.argmax(next_target, axis=-1)
    next_q = jnp.take_along_axis(next_target, next_a[:, None], axis=-1)[:, 0]
    not_done = 1.0 - batch[DONES].astype(jnp.float32)
    target = batch[REWARDS] + gamma * not_done * next_q
    td = q_taken - jax.lax.stop_gradient(target)
    huber = jnp.where(jnp.abs(td) < 1.0, 0.5 * td ** 2,
                      jnp.abs(td) - 0.5)
    weights = batch.get("weights")
    if weights is None:
        loss = jnp.mean(huber)
    else:
        loss = jnp.mean(weights * huber)
    return loss, jnp.abs(td)


class DQNConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self._algo_class = DQN
        self.lr = 5e-4
        self.rollout_fragment_length = 32
        self.train_batch_size = 64
        self.buffer_capacity = 100_000
        self.prioritized_replay = True
        self.prioritized_alpha = 0.6
        self.prioritized_beta = 0.4
        self.learning_starts = 1_000
        self.target_network_update_freq = 500  # in learner updates
        self.num_updates_per_iter = 16
        self.epsilon_timesteps = 10_000  # linear 1.0 -> final_epsilon
        self.final_epsilon = 0.02
        self.double_q = True
        self.policy_hidden = (256, 256)

    def training(self, **kwargs) -> "DQNConfig":
        for k in ("buffer_capacity", "prioritized_replay",
                  "prioritized_alpha", "prioritized_beta", "learning_starts",
                  "target_network_update_freq", "num_updates_per_iter",
                  "epsilon_timesteps", "final_epsilon", "double_q"):
            if k in kwargs:
                setattr(self, k, kwargs.pop(k))
        super().training(**kwargs)
        return self


class DQN(Algorithm):
    """training_step: sample → replay add → K learner updates → sync.

    Reference: ``dqn.py DQN.training_step`` — sample, store, sample from
    buffer, train, update priorities, periodically update target net.
    """

    _worker_cls = DQNRolloutWorker

    def setup(self, config: DQNConfig) -> None:
        import optax

        super().setup(config)
        if config.prioritized_replay:
            self.buffer: ReplayBuffer = PrioritizedReplayBuffer(
                config.buffer_capacity, alpha=config.prioritized_alpha,
                seed=config.seed)
        else:
            self.buffer = ReplayBuffer(config.buffer_capacity,
                                       seed=config.seed)
        policy = self.workers.local_worker.policy
        self.params = policy.params
        self.target_params = jax.tree.map(jnp.copy, self.params)
        self.optimizer = optax.adam(config.lr)
        self.opt_state = self.optimizer.init(self.params)
        self._num_updates = 0

        gamma, double_q = config.gamma, config.double_q

        @jax.jit
        def update(params, target_params, opt_state, batch):
            (loss, td), grads = jax.value_and_grad(
                dqn_loss, has_aux=True)(params, target_params, batch, gamma,
                                        double_q)
            updates, opt_state = self.optimizer.update(grads, opt_state,
                                                       params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, td

        self._update = update

    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(1.0, self._timesteps_total / max(cfg.epsilon_timesteps, 1))
        return 1.0 + frac * (cfg.final_epsilon - 1.0)

    def training_step(self) -> Dict:
        cfg = self.config
        eps = self._epsilon()
        self.workers.foreach_worker(lambda w: w.set_epsilon(eps))
        batches = self.workers.sample(cfg.rollout_fragment_length)
        new_steps = 0
        for b in batches:
            self.buffer.add(b)
            new_steps += b.count
        self._timesteps_total += new_steps

        losses = []
        if len(self.buffer) >= cfg.learning_starts:
            for _ in range(cfg.num_updates_per_iter):
                if isinstance(self.buffer, PrioritizedReplayBuffer):
                    batch = self.buffer.sample(cfg.train_batch_size,
                                               beta=cfg.prioritized_beta)
                else:
                    batch = self.buffer.sample(cfg.train_batch_size)
                jbatch = {k: jnp.asarray(v) for k, v in batch.items()
                          if k != "batch_indexes"}
                self.params, self.opt_state, loss, td = self._update(
                    self.params, self.target_params, self.opt_state, jbatch)
                if isinstance(self.buffer, PrioritizedReplayBuffer):
                    self.buffer.update_priorities(
                        batch["batch_indexes"], np.asarray(td))
                self._num_updates += 1
                if self._num_updates % cfg.target_network_update_freq == 0:
                    self.target_params = jax.tree.map(jnp.copy, self.params)
                losses.append(float(loss))
            weights = jax.tree.map(np.asarray, self.params)
            self.workers.local_worker.set_weights(weights)
            self.workers.sync_weights(weights)

        return {
            "timesteps_this_iter": new_steps,
            "num_learner_updates": self._num_updates,
            "epsilon": eps,
            "replay_buffer_size": len(self.buffer),
            "loss": float(np.mean(losses)) if losses else None,
        }

    def get_state(self) -> Dict:
        state = super().get_state()
        state.update({
            "params": jax.tree.map(np.asarray, self.params),
            "target_params": jax.tree.map(np.asarray, self.target_params),
            "num_updates": self._num_updates,
        })
        return state

    def set_state(self, state: Dict) -> None:
        super().set_state(state)
        if "params" in state:
            self.params = jax.tree.map(jnp.asarray, state["params"])
            self.target_params = jax.tree.map(
                jnp.asarray, state["target_params"])
            self._num_updates = state.get("num_updates", 0)
            weights = jax.tree.map(np.asarray, self.params)
            self.workers.local_worker.set_weights(weights)
            self.workers.sync_weights(weights)
