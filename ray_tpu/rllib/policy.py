"""JAX policy: actor-critic network + jit-compiled action/update paths.

Reference analog: ``rllib/policy/policy.py:150`` (compute_actions :411,
learn_on_batch :542) with TorchPolicyV2 — re-founded on JAX: the policy is
a param pytree + pure functions; ``compute_actions`` is one jit program
(device-resident on the learner, CPU-jit on rollout workers);
``learn_on_batch`` is the PPO surrogate update compiled once per shape.
The reference's framework="jax" slot (models/jax/jax_modelv2.py) is
skeletal; this is the real implementation.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.common import truncated_normal


def init_mlp_policy(key, obs_dim: int, num_actions: int,
                    hidden: Sequence[int] = (64, 64)) -> Dict:
    """Separate actor and critic MLPs (shared trunks let large value
    targets swamp policy gradients — the standard PPO failure on
    high-return envs)."""
    params = {}
    sizes = [obs_dim] + list(hidden)
    keys = jax.random.split(key, 2 * len(sizes) + 2)
    for i in range(len(sizes) - 1):
        std = float(np.sqrt(2.0 / sizes[i]))
        params[f"pi_t{i}_w"] = truncated_normal(
            keys[2 * i], (sizes[i], sizes[i + 1]), stddev=std)
        params[f"pi_t{i}_b"] = jnp.zeros((sizes[i + 1],))
        params[f"vf_t{i}_w"] = truncated_normal(
            keys[2 * i + 1], (sizes[i], sizes[i + 1]), stddev=std)
        params[f"vf_t{i}_b"] = jnp.zeros((sizes[i + 1],))
    params["pi_w"] = truncated_normal(keys[-2], (sizes[-1], num_actions),
                                      stddev=0.01)
    params["pi_b"] = jnp.zeros((num_actions,))
    params["vf_w"] = truncated_normal(keys[-1], (sizes[-1], 1), stddev=1.0)
    params["vf_b"] = jnp.zeros((1,))
    return params


def forward_mlp(params: Dict, obs: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """-> (logits [B, A], values [B])."""
    x = obs.astype(jnp.float32)
    pi = vf = x
    i = 0
    while f"pi_t{i}_w" in params:
        pi = jnp.tanh(pi @ params[f"pi_t{i}_w"] + params[f"pi_t{i}_b"])
        vf = jnp.tanh(vf @ params[f"vf_t{i}_w"] + params[f"vf_t{i}_b"])
        i += 1
    logits = pi @ params["pi_w"] + params["pi_b"]
    values = (vf @ params["vf_w"] + params["vf_b"])[..., 0]
    return logits, values


# Nature-DQN conv trunk as (out_channels, kernel, stride) — single source
# for both init (shape math) and apply (stride schedule).
_CONV_SPEC = ((32, 8, 4), (64, 4, 2), (64, 3, 1))


def init_conv_policy(key, obs_shape: Tuple[int, ...], num_actions: int,
                     dense: int = 512) -> Dict:
    """Nature-CNN actor-critic for Atari-shaped [H, W, C] frames.

    Reference analog: the conv stacks ``rllib/models/catalog.py`` builds
    for image observations (Nature DQN filters 32x8x8/4, 64x4x4/2,
    64x3x3/1 -> dense 512), with separate policy/value heads off a shared
    conv trunk (the standard Atari PPO topology).
    """
    h, w, c = obs_shape
    keys = jax.random.split(key, 6)
    params: Dict = {}
    cin = c
    for i, (cout, k, stride) in enumerate(_CONV_SPEC):
        std = float(np.sqrt(2.0 / (k * k * cin)))
        params[f"conv{i}_w"] = truncated_normal(
            keys[i], (k, k, cin, cout), stddev=std)
        params[f"conv{i}_b"] = jnp.zeros((cout,))
        h = (h - k) // stride + 1
        w = (w - k) // stride + 1
        cin = cout
    flat = h * w * cin
    params["dense_w"] = truncated_normal(
        keys[3], (flat, dense), stddev=float(np.sqrt(2.0 / flat)))
    params["dense_b"] = jnp.zeros((dense,))
    params["pi_w"] = truncated_normal(keys[4], (dense, num_actions),
                                      stddev=0.01)
    params["pi_b"] = jnp.zeros((num_actions,))
    params["vf_w"] = truncated_normal(keys[5], (dense, 1), stddev=1.0)
    params["vf_b"] = jnp.zeros((1,))
    return params


def forward_conv(params: Dict, obs: jnp.ndarray
                 ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """[B, H, W, C] (uint8 or float) -> (logits [B, A], values [B]).

    The conv/dense trunk runs in bf16 (MXU native; fp32 convs are ~4-8x
    slower on TPU) with fp32 policy/value heads — logits precision is
    what matters for the categorical sample and the PPO ratio.
    """
    x = obs.astype(jnp.float32)
    if obs.dtype == jnp.uint8:
        x = x / 255.0
    x = x.astype(jnp.bfloat16)
    for i, (_cout, _k, stride) in enumerate(_CONV_SPEC):
        x = jax.lax.conv_general_dilated(
            x, params[f"conv{i}_w"].astype(x.dtype),
            window_strides=(stride, stride), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        ) + params[f"conv{i}_b"].astype(x.dtype)
        x = jax.nn.relu(x)
    x = x.reshape(x.shape[0], -1)
    x = jax.nn.relu(x @ params["dense_w"].astype(x.dtype)
                    + params["dense_b"].astype(x.dtype))
    x = x.astype(jnp.float32)
    logits = x @ params["pi_w"] + params["pi_b"]
    values = (x @ params["vf_w"] + params["vf_b"])[..., 0]
    return logits, values


@dataclass(frozen=True)
class Network:
    """A policy network: pure (init, apply) over a param pytree.

    Recurrent networks leave ``apply`` None and provide
    ``initial_state(batch)`` + ``apply_state(params, obs, state) ->
    (logits, values, new_state)`` instead (catalog use_lstm path)."""
    kind: str
    init: Callable[[Any], Dict]
    apply: Optional[Callable[
        [Dict, jnp.ndarray], Tuple[jnp.ndarray, jnp.ndarray]]] = None
    initial_state: Optional[Callable[[int], Any]] = None
    apply_state: Optional[Callable] = None

    @property
    def is_recurrent(self) -> bool:
        return self.apply_state is not None


def make_network(obs_shape: Tuple[int, ...], num_actions: int,
                 kind: str = "auto",
                 hidden: Sequence[int] = (64, 64)) -> Network:
    """'mlp' for vector obs, 'conv' (Nature CNN) for [H,W,C] frames;
    'auto' picks by observation rank."""
    if kind == "auto":
        kind = "conv" if len(obs_shape) == 3 else "mlp"
    if kind == "conv":
        return Network(
            kind="conv",
            init=lambda key: init_conv_policy(key, obs_shape, num_actions),
            apply=forward_conv,
        )
    obs_dim = int(np.prod(obs_shape))

    def apply_flat(params, obs):
        return forward_mlp(params, obs.reshape(obs.shape[0], -1))

    return Network(
        kind="mlp",
        init=lambda key: init_mlp_policy(key, obs_dim, num_actions, hidden),
        apply=apply_flat,
    )


def sample_actions(apply_fn, params, obs, key, deterministic: bool):
    """Pure sampling head shared by host policies and on-device rollout."""
    logits, values = apply_fn(params, obs)
    if deterministic:
        actions = jnp.argmax(logits, axis=-1)
    else:
        actions = jax.random.categorical(key, logits, axis=-1)
    logp = jax.nn.log_softmax(logits)[
        jnp.arange(actions.shape[0]), actions
    ]
    return actions, logp, values


class JaxPolicy:
    """Discrete-action actor-critic policy.

    ``model_config`` routes through the catalog (conv/mlp/lstm/custom,
    reference: ModelCatalog.get_model_v2); the legacy
    ``network``/``hidden`` args remain as shorthand. Recurrent nets keep
    their state here across ``compute_actions`` calls; rollout workers
    call ``observe_dones`` so finished sub-envs reset their slot."""

    def __init__(self, obs_shape: Tuple[int, ...], num_actions: int,
                 hidden: Sequence[int] = (64, 64), seed: int = 0,
                 network: str = "auto",
                 model_config: Optional[Dict] = None):
        self.obs_dim = int(np.prod(obs_shape))
        self.num_actions = num_actions
        if model_config is not None:
            from .catalog import get_network

            self.net = get_network(obs_shape, num_actions, model_config)
        else:
            self.net = make_network(obs_shape, num_actions, network,
                                    hidden)
        key = jax.random.PRNGKey(seed)
        self.params = self.net.init(key)
        self._key = jax.random.PRNGKey(seed + 1)
        # Recurrent state PER BATCH SIZE: the rollout loop (batch N) and
        # one-off eval calls (batch 1) each carry their own memory —
        # sharing one slot would either reset eval every step or let an
        # eval call corrupt the rollout state via shape broadcasting.
        self._states: Dict[int, Any] = {}
        if self.net.is_recurrent:
            apply_state = self.net.apply_state

            def sample_rec(params, obs, state, key, deterministic):
                logits, values, new_state = apply_state(params, obs,
                                                        state)
                if deterministic:
                    actions = jnp.argmax(logits, axis=-1)
                else:
                    actions = jax.random.categorical(key, logits, axis=-1)
                logp = jax.nn.log_softmax(logits)[
                    jnp.arange(actions.shape[0]), actions]
                return actions, logp, values, new_state

            self._sample_rec = jax.jit(sample_rec, static_argnums=(4,))
        else:
            self._sample = jax.jit(
                functools.partial(sample_actions, self.net.apply),
                static_argnums=(3,))

    def compute_actions(self, obs: np.ndarray, deterministic: bool = False):
        """Reference: Policy.compute_actions (:411)."""
        obs = np.asarray(obs)
        self._key, sub = jax.random.split(self._key)
        if self.net.is_recurrent:
            b = len(obs)
            state = self._states.get(b)
            if state is None:
                state = self.net.initial_state(b)
            actions, logp, values, new_state = self._sample_rec(
                self.params, jnp.asarray(obs), state, sub,
                deterministic)
            self._states[b] = new_state
            return (np.asarray(actions), np.asarray(logp),
                    np.asarray(values))
        actions, logp, values = self._sample(
            self.params, jnp.asarray(obs), sub, deterministic
        )
        return (np.asarray(actions), np.asarray(logp), np.asarray(values))

    def recurrent_state(self, batch: int):
        """The carried state for this batch size (zeros if fresh);
        None for feedforward nets."""
        if not self.net.is_recurrent:
            return None
        state = self._states.get(batch)
        return state if state is not None \
            else self.net.initial_state(batch)

    def set_recurrent_state(self, batch: int, state) -> None:
        if self.net.is_recurrent:
            self._states[batch] = state

    def observe_dones(self, dones: np.ndarray) -> None:
        """Reset recurrent state for finished sub-envs (no-op for
        feedforward nets)."""
        state = self._states.get(len(dones))
        if state is None or not np.any(dones):
            return
        mask = jnp.asarray(~np.asarray(dones, bool), jnp.float32)[:, None]
        self._states[len(dones)] = tuple(s * mask for s in state)

    def get_weights(self) -> Dict:
        return jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights: Dict) -> None:
        self.params = jax.tree.map(jnp.asarray, weights)
