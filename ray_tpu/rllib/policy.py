"""JAX policy: actor-critic network + jit-compiled action/update paths.

Reference analog: ``rllib/policy/policy.py:150`` (compute_actions :411,
learn_on_batch :542) with TorchPolicyV2 — re-founded on JAX: the policy is
a param pytree + pure functions; ``compute_actions`` is one jit program
(device-resident on the learner, CPU-jit on rollout workers);
``learn_on_batch`` is the PPO surrogate update compiled once per shape.
The reference's framework="jax" slot (models/jax/jax_modelv2.py) is
skeletal; this is the real implementation.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.common import truncated_normal


def init_mlp_policy(key, obs_dim: int, num_actions: int,
                    hidden: Sequence[int] = (64, 64)) -> Dict:
    """Separate actor and critic MLPs (shared trunks let large value
    targets swamp policy gradients — the standard PPO failure on
    high-return envs)."""
    params = {}
    sizes = [obs_dim] + list(hidden)
    keys = jax.random.split(key, 2 * len(sizes) + 2)
    for i in range(len(sizes) - 1):
        std = float(np.sqrt(2.0 / sizes[i]))
        params[f"pi_t{i}_w"] = truncated_normal(
            keys[2 * i], (sizes[i], sizes[i + 1]), stddev=std)
        params[f"pi_t{i}_b"] = jnp.zeros((sizes[i + 1],))
        params[f"vf_t{i}_w"] = truncated_normal(
            keys[2 * i + 1], (sizes[i], sizes[i + 1]), stddev=std)
        params[f"vf_t{i}_b"] = jnp.zeros((sizes[i + 1],))
    params["pi_w"] = truncated_normal(keys[-2], (sizes[-1], num_actions),
                                      stddev=0.01)
    params["pi_b"] = jnp.zeros((num_actions,))
    params["vf_w"] = truncated_normal(keys[-1], (sizes[-1], 1), stddev=1.0)
    params["vf_b"] = jnp.zeros((1,))
    return params


def forward_mlp(params: Dict, obs: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """-> (logits [B, A], values [B])."""
    x = obs.astype(jnp.float32)
    pi = vf = x
    i = 0
    while f"pi_t{i}_w" in params:
        pi = jnp.tanh(pi @ params[f"pi_t{i}_w"] + params[f"pi_t{i}_b"])
        vf = jnp.tanh(vf @ params[f"vf_t{i}_w"] + params[f"vf_t{i}_b"])
        i += 1
    logits = pi @ params["pi_w"] + params["pi_b"]
    values = (vf @ params["vf_w"] + params["vf_b"])[..., 0]
    return logits, values


@functools.partial(jax.jit, static_argnums=(3,))
def _sample_actions(params, obs, key, deterministic: bool):
    logits, values = forward_mlp(params, obs)
    if deterministic:
        actions = jnp.argmax(logits, axis=-1)
    else:
        actions = jax.random.categorical(key, logits, axis=-1)
    logp = jax.nn.log_softmax(logits)[
        jnp.arange(actions.shape[0]), actions
    ]
    return actions, logp, values


class JaxPolicy:
    """Discrete-action actor-critic policy."""

    def __init__(self, obs_shape: Tuple[int, ...], num_actions: int,
                 hidden: Sequence[int] = (64, 64), seed: int = 0):
        self.obs_dim = int(np.prod(obs_shape))
        self.num_actions = num_actions
        key = jax.random.PRNGKey(seed)
        self.params = init_mlp_policy(key, self.obs_dim, num_actions, hidden)
        self._key = jax.random.PRNGKey(seed + 1)

    def compute_actions(self, obs: np.ndarray, deterministic: bool = False):
        """Reference: Policy.compute_actions (:411)."""
        obs = np.asarray(obs, np.float32).reshape(len(obs), -1)
        self._key, sub = jax.random.split(self._key)
        actions, logp, values = _sample_actions(
            self.params, jnp.asarray(obs), sub, deterministic
        )
        return (np.asarray(actions), np.asarray(logp), np.asarray(values))

    def get_weights(self) -> Dict:
        return jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights: Dict) -> None:
        self.params = jax.tree.map(jnp.asarray, weights)
