"""A2C: synchronous advantage actor-critic.

Reference analog: ``rllib/algorithms/a2c/a2c.py`` — A2C is sync
parallel sampling + ONE on-policy gradient step per batch on the plain
policy-gradient surrogate (no ratio clipping, no SGD epochs; A3C's
microbatch path collapses to this in the synchronous setting). The whole
update is one jit program on the learner.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from .algorithm import Algorithm, AlgorithmConfig
from .sample_batch import (
    ACTIONS,
    ADVANTAGES,
    OBS,
    VALUE_TARGETS,
    SampleBatch,
    compute_gae,
    flatten_time_major,
)


class A2CConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self._algo_class = A2C
        self.lr = 1e-3
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.lambda_ = 1.0  # A2C default: plain n-step returns
        self.grad_clip = 0.5
        self.rollout_fragment_length = 20
        self.num_envs_per_worker = 16

    def training(self, vf_loss_coeff=None, entropy_coeff=None,
                 lambda_=None, grad_clip=None, **kwargs) -> "A2CConfig":
        super().training(**kwargs)
        for name, val in [("vf_loss_coeff", vf_loss_coeff),
                          ("entropy_coeff", entropy_coeff),
                          ("lambda_", lambda_), ("grad_clip", grad_clip)]:
            if val is not None:
                setattr(self, name, val)
        return self


def a2c_loss(params, batch, vf_coeff, ent_coeff, apply_fn):
    logits, values = apply_fn(params, batch[OBS])
    logp_all = jax.nn.log_softmax(logits)
    logp = jnp.take_along_axis(
        logp_all, batch[ACTIONS].astype(jnp.int32)[..., None],
        axis=-1)[..., 0]
    adv = batch[ADVANTAGES]
    adv = (adv - adv.mean()) / (adv.std() + 1e-8)
    # No importance ratio: the batch IS on-policy (single sync step).
    policy_loss = -jnp.mean(logp * adv)
    vf_loss = jnp.mean((values - batch[VALUE_TARGETS]) ** 2)
    entropy = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
    total = policy_loss + vf_coeff * vf_loss - ent_coeff * entropy
    return total, {"policy_loss": policy_loss, "vf_loss": vf_loss,
                   "entropy": entropy}


class A2C(Algorithm):
    def setup(self, config: A2CConfig) -> None:
        super().setup(config)
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(config.grad_clip),
            optax.adam(config.lr),
        )
        self.params = jax.tree.map(
            jnp.asarray, self.workers.local_worker.policy.params)
        self.opt_state = self.optimizer.init(self.params)
        apply_fn = self.workers.local_worker.policy.net.apply
        vfc, eco = config.vf_loss_coeff, config.entropy_coeff

        @jax.jit
        def update(params, opt_state, batch):
            (loss, aux), grads = jax.value_and_grad(
                a2c_loss, has_aux=True)(params, batch, vfc, eco, apply_fn)
            updates, opt_state = self.optimizer.update(grads, opt_state,
                                                       params)
            return optax.apply_updates(params, updates), opt_state, \
                {"total_loss": loss, **aux}

        self._update = update
        self.workers.sync_weights(jax.tree.map(np.asarray, self.params))

    def training_step(self) -> Dict:
        cfg: A2CConfig = self.config
        fragments = self.workers.sample(cfg.rollout_fragment_length)
        processed = []
        for frag in fragments:
            last_values = frag.pop("last_values")
            frag.pop("final_obs", None)
            frag = compute_gae(frag, last_values, cfg.gamma, cfg.lambda_)
            processed.append(flatten_time_major(frag))
        batch = SampleBatch.concat_samples(processed)
        steps = batch.count
        self._timesteps_total += steps
        device_batch = {k: jnp.asarray(v) for k, v in batch.items()
                       if k in (OBS, ACTIONS, ADVANTAGES, VALUE_TARGETS)}
        self.params, self.opt_state, metrics = self._update(
            self.params, self.opt_state, device_batch)
        weights = jax.tree.map(np.asarray, self.params)
        self.workers.local_worker.set_weights(weights)
        self.workers.sync_weights(weights)
        out = {k: float(v) for k, v in metrics.items()}
        out["timesteps_this_iter"] = steps
        return out

    def get_state(self) -> Dict:
        state = super().get_state()
        state["params"] = jax.tree.map(np.asarray, self.params)
        state["opt_state"] = jax.tree.map(np.asarray, self.opt_state)
        return state

    def set_state(self, state: Dict) -> None:
        super().set_state(state)
        if "params" in state:
            self.params = jax.tree.map(jnp.asarray, state["params"])
            weights = jax.tree.map(np.asarray, self.params)
            self.workers.local_worker.set_weights(weights)
            self.workers.sync_weights(weights)
        if "opt_state" in state:
            self.opt_state = jax.tree.map(jnp.asarray,
                                          state["opt_state"])
