"""Offline RL IO + off-policy estimation.

Reference analog: ``rllib/offline/`` — ``JsonWriter``/``JsonReader``
persist SampleBatches as JSONL for offline training/evaluation, and the
off-policy estimators (``offline/estimators/importance_sampling.py``,
``weighted_importance_sampling.py``) score a target policy on behavior
data without running it in the environment.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Any, Callable, Dict, Iterator, List, Optional

import numpy as np

from .sample_batch import (ACTIONS, DONES, LOGPS, NEXT_OBS, OBS, REWARDS,
                           SampleBatch)


class JsonWriter:
    """Appends SampleBatches to JSONL files (reference: JsonWriter)."""

    def __init__(self, path: str, max_file_size: int = 64 * 1024 * 1024):
        self.path = path
        os.makedirs(path, exist_ok=True)
        self._max = max_file_size
        self._index = 0
        self._file = None

    def _ensure_file(self):
        if self._file is None or self._file.tell() > self._max:
            if self._file is not None:
                self._file.close()
            self._index += 1
            self._file = open(os.path.join(
                self.path, f"output-{self._index:05d}.jsonl"), "a")
        return self._file

    def write(self, batch: SampleBatch) -> None:
        row = {k: np.asarray(v).tolist() for k, v in batch.items()}
        dtypes = {k: str(np.asarray(v).dtype) for k, v in batch.items()}
        f = self._ensure_file()
        f.write(json.dumps({"columns": row, "dtypes": dtypes}) + "\n")
        f.flush()

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None


class JsonReader:
    """Reads SampleBatches back from a JsonWriter directory (reference:
    JsonReader) — for offline training and off-policy evaluation."""

    def __init__(self, path: str):
        self.path = path

    def _files(self) -> List[str]:
        if os.path.isfile(self.path):
            return [self.path]
        return sorted(glob.glob(os.path.join(self.path, "*.jsonl")))

    def iter_batches(self) -> Iterator[SampleBatch]:
        for file in self._files():
            with open(file) as f:
                for line in f:
                    if not line.strip():
                        continue
                    entry = json.loads(line)
                    cols = entry["columns"]
                    dtypes = entry.get("dtypes", {})
                    yield SampleBatch({
                        k: np.asarray(v, dtype=dtypes.get(k))
                        for k, v in cols.items()
                    })

    def read_all(self) -> SampleBatch:
        batches = list(self.iter_batches())
        if not batches:
            raise ValueError(f"no batches under {self.path!r}")
        return SampleBatch.concat_samples(batches)


class OffPolicyEstimator:
    """Scores a TARGET policy on BEHAVIOR data (reference:
    ``offline/estimators/off_policy_estimator.py``).

    ``target_logp_fn(obs, actions) -> logp`` gives the target policy's
    log-prob of the logged actions; the batch's LOGPS column holds the
    behavior policy's. Batches are episode fragments: DONES splits
    episodes.
    """

    def __init__(self, target_logp_fn: Callable, gamma: float = 0.99):
        self._logp = target_logp_fn
        self.gamma = gamma

    def _episodes(self, batch: SampleBatch):
        """Split time-flat [T, ...] columns into per-episode slices
        (DONES marks episode ends)."""
        dones = np.asarray(batch[DONES]).reshape(-1)
        bounds = list(np.nonzero(dones)[0] + 1)
        if not bounds or bounds[-1] != len(dones):
            bounds.append(len(dones))
        start = 0
        for end in bounds:
            yield {k: np.asarray(v)[start:end] for k, v in batch.items()}
            start = end

    def _behavior_return(self, ep) -> float:
        rewards = np.asarray(ep[REWARDS], np.float64)
        return float(np.sum(self.gamma ** np.arange(len(rewards))
                            * rewards))

    def _episode_terms(self, ep) -> Dict[str, float]:
        rewards = ep[REWARDS].astype(np.float64)
        discounts = self.gamma ** np.arange(len(rewards))
        behavior_return = self._behavior_return(ep)
        target_logp = np.asarray(self._logp(ep[OBS], ep[ACTIONS]),
                                 np.float64)
        log_ratio = np.cumsum(target_logp - ep[LOGPS].astype(np.float64))
        weights = np.exp(np.clip(log_ratio, -30, 30))
        return {
            "behavior_return": behavior_return,
            "per_step_weights": weights,
            "discounted_rewards": discounts * rewards,
        }

    def estimate(self, batch: SampleBatch) -> Dict[str, float]:
        raise NotImplementedError


class ImportanceSampling(OffPolicyEstimator):
    """Ordinary per-decision IS (reference:
    ``offline/estimators/importance_sampling.py``): V_target =
    mean over episodes of sum_t w_t * gamma^t * r_t."""

    def estimate(self, batch: SampleBatch) -> Dict[str, float]:
        v_b, v_t, n = 0.0, 0.0, 0
        for ep in self._episodes(batch):
            terms = self._episode_terms(ep)
            v_b += terms["behavior_return"]
            v_t += float(np.sum(terms["per_step_weights"]
                                * terms["discounted_rewards"]))
            n += 1
        n = max(n, 1)
        v_b, v_t = v_b / n, v_t / n
        return {"v_behavior": v_b, "v_target": v_t,
                "v_gain": v_t / v_b if v_b else float("nan")}


class WeightedImportanceSampling(OffPolicyEstimator):
    """WIS (reference: ``weighted_importance_sampling.py``): per-step
    weights are normalized by their mean across episodes at each t —
    biased but far lower variance than ordinary IS."""

    def estimate(self, batch: SampleBatch) -> Dict[str, float]:
        episodes = [self._episode_terms(ep)
                    for ep in self._episodes(batch)]
        if not episodes:
            return {"v_behavior": 0.0, "v_target": 0.0,
                    "v_gain": float("nan")}
        max_t = max(len(e["per_step_weights"]) for e in episodes)
        # Mean weight per timestep across episodes (0-padded).
        sums = np.zeros(max_t)
        counts = np.zeros(max_t)
        for e in episodes:
            w = e["per_step_weights"]
            sums[:len(w)] += w
            counts[:len(w)] += 1
        mean_w = sums / np.maximum(counts, 1)
        v_b = v_t = 0.0
        for e in episodes:
            w = e["per_step_weights"]
            norm = w / np.maximum(mean_w[:len(w)], 1e-12)
            v_b += e["behavior_return"]
            v_t += float(np.sum(norm * e["discounted_rewards"]))
        n = len(episodes)
        v_b, v_t = v_b / n, v_t / n
        return {"v_behavior": v_b, "v_target": v_t,
                "v_gain": v_t / v_b if v_b else float("nan")}


class FittedQModel:
    """Fitted-Q evaluation (FQE): a small JAX Q-network trained by
    Bellman backups under the TARGET policy's action distribution —
    the model component of the direct-method and doubly-robust
    estimators (reference: ``offline/estimators/fqe_torch_model.py``,
    re-expressed as a jitted optax loop; discrete actions).
    """

    def __init__(self, obs_dim: int, num_actions: int,
                 hidden=(32, 32), lr: float = 5e-3, seed: int = 0):
        import jax
        import jax.numpy as jnp
        import optax

        self.num_actions = num_actions
        key = jax.random.PRNGKey(seed)
        sizes = (obs_dim, *hidden, num_actions)
        params = []
        for i in range(len(sizes) - 1):
            key, sub = jax.random.split(key)
            w = jax.random.normal(sub, (sizes[i], sizes[i + 1]),
                                  jnp.float32)
            w = w / np.sqrt(sizes[i])
            params.append({"w": w, "b": jnp.zeros((sizes[i + 1],))})
        self.params = params
        self._opt = optax.adam(lr)
        self._opt_state = self._opt.init(params)

        def q_fn(params, obs):
            x = obs
            for layer in params[:-1]:
                x = jnp.tanh(x @ layer["w"] + layer["b"])
            last = params[-1]
            return x @ last["w"] + last["b"]  # [T, A]

        opt = self._opt

        def sgd_step(params, opt_state, obs, act, y):
            def loss_fn(p):
                q = q_fn(p, obs)
                qa = jnp.take_along_axis(q, act[:, None], axis=1)[:, 0]
                return jnp.mean((qa - y) ** 2)

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        self._q = jax.jit(q_fn)
        self._sgd = jax.jit(sgd_step)

    def fit(self, obs, actions, rewards, next_obs, dones, next_probs,
            gamma: float, backups: int = 20, sgd_per_backup: int = 25
            ) -> float:
        """Iterate Bellman backups: y = r + gamma*(1-d)*E_{a'~pi}Q(s',a')
        with Q frozen per backup, then regress. Returns final loss."""
        import jax.numpy as jnp

        obs = jnp.asarray(obs, jnp.float32)
        actions = jnp.asarray(actions, jnp.int32)
        rewards = jnp.asarray(rewards, jnp.float32)
        next_obs = jnp.asarray(next_obs, jnp.float32)
        not_done = 1.0 - jnp.asarray(dones, jnp.float32)
        next_probs = jnp.asarray(next_probs, jnp.float32)
        loss = float("nan")
        for _ in range(backups):
            next_q = self._q(self.params, next_obs)
            next_v = jnp.sum(next_probs * next_q, axis=1)
            y = rewards + gamma * not_done * next_v
            for _ in range(sgd_per_backup):
                self.params, self._opt_state, loss = self._sgd(
                    self.params, self._opt_state, obs, actions, y)
        return float(loss)

    def q_values(self, obs) -> np.ndarray:
        import jax.numpy as jnp

        return np.asarray(self._q(self.params,
                                  jnp.asarray(obs, jnp.float32)))

    def v_values(self, obs, probs) -> np.ndarray:
        return np.sum(np.asarray(probs) * self.q_values(obs), axis=1)


class _ModelBasedEstimator(OffPolicyEstimator):
    """Shared FQE plumbing for DM/DR. ``target_probs_fn(obs) -> [T, A]``
    gives the target policy's full action distribution (needed both for
    Bellman backups and for E_{a~pi} Q(s, a))."""

    def __init__(self, target_logp_fn: Callable, target_probs_fn: Callable,
                 num_actions: int, gamma: float = 0.99,
                 q_hidden=(32, 32), q_lr: float = 5e-3,
                 q_backups: int = 20, seed: int = 0):
        super().__init__(target_logp_fn, gamma)
        self._probs = target_probs_fn
        self.num_actions = num_actions
        self._q_hidden = q_hidden
        self._q_lr = q_lr
        self._q_backups = q_backups
        self._seed = seed

    def _fit_q(self, batch: SampleBatch) -> FittedQModel:
        obs = np.asarray(batch[OBS], np.float32)
        next_obs = np.asarray(batch[NEXT_OBS], np.float32)
        model = FittedQModel(obs.shape[-1], self.num_actions,
                             hidden=self._q_hidden, lr=self._q_lr,
                             seed=self._seed)
        model.fit(obs, np.asarray(batch[ACTIONS]),
                  np.asarray(batch[REWARDS]), next_obs,
                  np.asarray(batch[DONES]),
                  np.asarray(self._probs(next_obs)), self.gamma,
                  backups=self._q_backups)
        return model


class DirectMethod(_ModelBasedEstimator):
    """DM (reference: ``offline/estimators/direct_method.py``):
    V_target = mean over episodes of E_{a~pi} Q_fqe(s0, a) — pure model
    extrapolation, zero variance from importance weights, biased by
    whatever the Q-model gets wrong."""

    def estimate(self, batch: SampleBatch) -> Dict[str, float]:
        model = self._fit_q(batch)
        v_b = v_t = 0.0
        n = 0
        for ep in self._episodes(batch):
            v_b += self._behavior_return(ep)
            s0 = np.asarray(ep[OBS][:1], np.float32)
            v_t += float(model.v_values(s0, self._probs(s0))[0])
            n += 1
        n = max(n, 1)
        v_b, v_t = v_b / n, v_t / n
        return {"v_behavior": v_b, "v_target": v_t,
                "v_gain": v_t / v_b if v_b else float("nan")}


class DoublyRobust(_ModelBasedEstimator):
    """DR (reference: ``offline/estimators/doubly_robust.py``; Jiang &
    Li 2016): the backward recursion
    ``v_t = V(s_t) + rho_t * (r_t + gamma * v_{t+1} - Q(s_t, a_t))``
    uses the FQE model as a control variate on importance sampling —
    unbiased when the behavior logps are correct, with variance bounded
    by the model's residuals instead of the raw returns."""

    def estimate(self, batch: SampleBatch) -> Dict[str, float]:
        model = self._fit_q(batch)
        v_b = v_t = 0.0
        n = 0
        for ep in self._episodes(batch):
            obs = np.asarray(ep[OBS], np.float32)
            acts = np.asarray(ep[ACTIONS]).astype(np.int64)
            rewards = np.asarray(ep[REWARDS], np.float64)
            probs = np.asarray(self._probs(obs), np.float64)
            q = model.q_values(obs).astype(np.float64)
            v_model = np.sum(probs * q, axis=1)
            q_taken = q[np.arange(len(acts)), acts]
            pi_a = probs[np.arange(len(acts)), acts]
            rho = pi_a / np.maximum(
                np.exp(np.asarray(ep[LOGPS], np.float64)), 1e-12)
            v = 0.0
            for t in range(len(rewards) - 1, -1, -1):
                v = v_model[t] + rho[t] * (
                    rewards[t] + self.gamma * v - q_taken[t])
            v_b += self._behavior_return(ep)
            v_t += float(v)
            n += 1
        n = max(n, 1)
        v_b, v_t = v_b / n, v_t / n
        return {"v_behavior": v_b, "v_target": v_t,
                "v_gain": v_t / v_b if v_b else float("nan")}
