"""Offline RL IO + off-policy estimation.

Reference analog: ``rllib/offline/`` — ``JsonWriter``/``JsonReader``
persist SampleBatches as JSONL for offline training/evaluation, and the
off-policy estimators (``offline/estimators/importance_sampling.py``,
``weighted_importance_sampling.py``) score a target policy on behavior
data without running it in the environment.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Any, Callable, Dict, Iterator, List, Optional

import numpy as np

from .sample_batch import ACTIONS, DONES, LOGPS, OBS, REWARDS, SampleBatch


class JsonWriter:
    """Appends SampleBatches to JSONL files (reference: JsonWriter)."""

    def __init__(self, path: str, max_file_size: int = 64 * 1024 * 1024):
        self.path = path
        os.makedirs(path, exist_ok=True)
        self._max = max_file_size
        self._index = 0
        self._file = None

    def _ensure_file(self):
        if self._file is None or self._file.tell() > self._max:
            if self._file is not None:
                self._file.close()
            self._index += 1
            self._file = open(os.path.join(
                self.path, f"output-{self._index:05d}.jsonl"), "a")
        return self._file

    def write(self, batch: SampleBatch) -> None:
        row = {k: np.asarray(v).tolist() for k, v in batch.items()}
        dtypes = {k: str(np.asarray(v).dtype) for k, v in batch.items()}
        f = self._ensure_file()
        f.write(json.dumps({"columns": row, "dtypes": dtypes}) + "\n")
        f.flush()

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None


class JsonReader:
    """Reads SampleBatches back from a JsonWriter directory (reference:
    JsonReader) — for offline training and off-policy evaluation."""

    def __init__(self, path: str):
        self.path = path

    def _files(self) -> List[str]:
        if os.path.isfile(self.path):
            return [self.path]
        return sorted(glob.glob(os.path.join(self.path, "*.jsonl")))

    def iter_batches(self) -> Iterator[SampleBatch]:
        for file in self._files():
            with open(file) as f:
                for line in f:
                    if not line.strip():
                        continue
                    entry = json.loads(line)
                    cols = entry["columns"]
                    dtypes = entry.get("dtypes", {})
                    yield SampleBatch({
                        k: np.asarray(v, dtype=dtypes.get(k))
                        for k, v in cols.items()
                    })

    def read_all(self) -> SampleBatch:
        batches = list(self.iter_batches())
        if not batches:
            raise ValueError(f"no batches under {self.path!r}")
        return SampleBatch.concat_samples(batches)


class OffPolicyEstimator:
    """Scores a TARGET policy on BEHAVIOR data (reference:
    ``offline/estimators/off_policy_estimator.py``).

    ``target_logp_fn(obs, actions) -> logp`` gives the target policy's
    log-prob of the logged actions; the batch's LOGPS column holds the
    behavior policy's. Batches are episode fragments: DONES splits
    episodes.
    """

    def __init__(self, target_logp_fn: Callable, gamma: float = 0.99):
        self._logp = target_logp_fn
        self.gamma = gamma

    def _episodes(self, batch: SampleBatch):
        """Split time-flat [T, ...] columns into per-episode slices
        (DONES marks episode ends)."""
        dones = np.asarray(batch[DONES]).reshape(-1)
        bounds = list(np.nonzero(dones)[0] + 1)
        if not bounds or bounds[-1] != len(dones):
            bounds.append(len(dones))
        start = 0
        for end in bounds:
            yield {k: np.asarray(v)[start:end] for k, v in batch.items()}
            start = end

    def _episode_terms(self, ep) -> Dict[str, float]:
        rewards = ep[REWARDS].astype(np.float64)
        discounts = self.gamma ** np.arange(len(rewards))
        behavior_return = float(np.sum(discounts * rewards))
        target_logp = np.asarray(self._logp(ep[OBS], ep[ACTIONS]),
                                 np.float64)
        log_ratio = np.cumsum(target_logp - ep[LOGPS].astype(np.float64))
        weights = np.exp(np.clip(log_ratio, -30, 30))
        return {
            "behavior_return": behavior_return,
            "per_step_weights": weights,
            "discounted_rewards": discounts * rewards,
        }

    def estimate(self, batch: SampleBatch) -> Dict[str, float]:
        raise NotImplementedError


class ImportanceSampling(OffPolicyEstimator):
    """Ordinary per-decision IS (reference:
    ``offline/estimators/importance_sampling.py``): V_target =
    mean over episodes of sum_t w_t * gamma^t * r_t."""

    def estimate(self, batch: SampleBatch) -> Dict[str, float]:
        v_b, v_t, n = 0.0, 0.0, 0
        for ep in self._episodes(batch):
            terms = self._episode_terms(ep)
            v_b += terms["behavior_return"]
            v_t += float(np.sum(terms["per_step_weights"]
                                * terms["discounted_rewards"]))
            n += 1
        n = max(n, 1)
        v_b, v_t = v_b / n, v_t / n
        return {"v_behavior": v_b, "v_target": v_t,
                "v_gain": v_t / v_b if v_b else float("nan")}


class WeightedImportanceSampling(OffPolicyEstimator):
    """WIS (reference: ``weighted_importance_sampling.py``): per-step
    weights are normalized by their mean across episodes at each t —
    biased but far lower variance than ordinary IS."""

    def estimate(self, batch: SampleBatch) -> Dict[str, float]:
        episodes = [self._episode_terms(ep)
                    for ep in self._episodes(batch)]
        if not episodes:
            return {"v_behavior": 0.0, "v_target": 0.0,
                    "v_gain": float("nan")}
        max_t = max(len(e["per_step_weights"]) for e in episodes)
        # Mean weight per timestep across episodes (0-padded).
        sums = np.zeros(max_t)
        counts = np.zeros(max_t)
        for e in episodes:
            w = e["per_step_weights"]
            sums[:len(w)] += w
            counts[:len(w)] += 1
        mean_w = sums / np.maximum(counts, 1)
        v_b = v_t = 0.0
        for e in episodes:
            w = e["per_step_weights"]
            norm = w / np.maximum(mean_w[:len(w)], 1e-12)
            v_b += e["behavior_return"]
            v_t += float(np.sum(norm * e["discounted_rewards"]))
        n = len(episodes)
        v_b, v_t = v_b / n, v_t / n
        return {"v_behavior": v_b, "v_target": v_t,
                "v_gain": v_t / v_b if v_b else float("nan")}
