"""Evolution Strategies + Augmented Random Search.

Reference analogs: ``rllib/algorithms/es/es.py`` (Salimans et al. 2017:
antithetic Gaussian perturbations, centered-rank fitness shaping, shared
noise table so only (index, return) pairs cross the wire) and
``rllib/algorithms/ars/ars.py`` (Mania et al. 2018: top-k directions,
reward-std step scaling).

The actor fan-out IS the algorithm here: N evaluation actors each hold
the env + a reconstruction of the shared noise table; the learner ships
one flat param vector per iteration and receives (noise_index, ret+,
ret-) triples — exactly the reference's communication pattern, on this
runtime's actor/object plane.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..core import get, kill, remote
from .algorithm import Algorithm, AlgorithmConfig
from .env import make_env
from .policy import JaxPolicy


class SharedNoiseTable:
    """Deterministic noise pool every process regenerates from one seed
    (reference: es.py create_shared_noise / SharedNoiseTable). Slices
    are perturbation vectors; only indices travel."""

    def __init__(self, size: int = 2_000_000, seed: int = 42):
        self.noise = np.random.default_rng(seed).standard_normal(
            size, dtype=np.float32)

    def get(self, idx: int, dim: int) -> np.ndarray:
        return self.noise[idx:idx + dim]

    def sample_index(self, rng: np.random.Generator, dim: int) -> int:
        return int(rng.integers(0, len(self.noise) - dim + 1))


def centered_ranks(x: np.ndarray) -> np.ndarray:
    """Fitness shaping: returns -> ranks in [-0.5, 0.5]
    (reference: es/utils.py compute_centered_ranks)."""
    ranks = np.empty(len(x), dtype=np.float32)
    ranks[x.argsort()] = np.arange(len(x), dtype=np.float32)
    if len(x) > 1:
        ranks = ranks / (len(x) - 1) - 0.5
    else:
        ranks[:] = 0.0
    return ranks


class ESEvalWorker:
    """Actor body: evaluates perturbed policies by full-episode rollout
    (reference: es.py Worker.do_rollouts)."""

    def __init__(self, env_spec, policy_config: Optional[Dict] = None,
                 seed: int = 0, worker_index: int = 0,
                 noise_size: int = 2_000_000, noise_seed: int = 42):
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
        from jax.flatten_util import ravel_pytree

        cfg = policy_config or {}
        self.env = make_env(env_spec, 1, seed + worker_index * 1000)
        self.policy = JaxPolicy(
            self.env.observation_space_shape, self.env.num_actions,
            hidden=cfg.get("hidden", (32, 32)), seed=seed)
        flat, self._unravel = ravel_pytree(self.policy.params)
        self.dim = int(flat.shape[0])
        self.noise = SharedNoiseTable(noise_size, noise_seed)
        self.rng = np.random.default_rng(seed + worker_index * 7919 + 1)
        self._max_steps = cfg.get("max_episode_steps", 500)

    def param_dim(self) -> int:
        return self.dim

    def _episode_return(self, flat: np.ndarray) -> Tuple[float, int]:
        self.policy.params = self._unravel(flat)
        obs = self.env.vector_reset(
            seed=int(self.rng.integers(0, 2 ** 31)))
        total, steps = 0.0, 0
        while steps < self._max_steps:
            a, _, _ = self.policy.compute_actions(obs, deterministic=True)
            obs, r, done, _ = self.env.vector_step(a)
            total += float(r[0])
            steps += 1
            if bool(done[0]):
                break
        return total, steps

    def do_rollouts(self, flat_params: np.ndarray, num_pairs: int,
                    sigma: float) -> Dict:
        """Antithetic pairs: evaluate theta +/- sigma*noise[idx]."""
        flat_params = np.asarray(flat_params, np.float32)
        indices, pos, neg, steps = [], [], [], 0
        for _ in range(num_pairs):
            idx = self.noise.sample_index(self.rng, self.dim)
            eps = self.noise.get(idx, self.dim)
            r_pos, s1 = self._episode_return(flat_params + sigma * eps)
            r_neg, s2 = self._episode_return(flat_params - sigma * eps)
            indices.append(idx)
            pos.append(r_pos)
            neg.append(r_neg)
            steps += s1 + s2
        return {"indices": indices, "pos": pos, "neg": neg,
                "steps": steps}

    def eval_policy(self, flat_params: np.ndarray,
                    episodes: int = 3) -> float:
        rets = [self._episode_return(np.asarray(flat_params,
                                                np.float32))[0]
                for _ in range(episodes)]
        return float(np.mean(rets))


class ESConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self._algo_class = ES
        self.num_rollout_workers = 2
        self.episodes_per_batch = 16  # antithetic pairs per iteration
        self.sigma = 0.05
        self.step_size = 0.02
        self.noise_size = 2_000_000
        self.policy_hidden = (32, 32)
        self.l2_coeff = 0.005

    def training(self, episodes_per_batch=None, sigma=None,
                 step_size=None, noise_size=None, l2_coeff=None,
                 **kwargs) -> "ESConfig":
        super().training(**kwargs)
        for name, val in [("episodes_per_batch", episodes_per_batch),
                          ("sigma", sigma), ("step_size", step_size),
                          ("noise_size", noise_size),
                          ("l2_coeff", l2_coeff)]:
            if val is not None:
                setattr(self, name, val)
        return self


class ES(Algorithm):
    """Learner: fan out rollout requests, combine centered-rank-weighted
    noise into one gradient, Adam step (reference: es.py _train)."""

    _is_ars = False

    def setup(self, config: ESConfig) -> None:
        # No WorkerSet: ES uses its own evaluation actors (the policy
        # weights here are a flat vector, not a JaxPolicy sync).
        policy_cfg = {"hidden": config.policy_hidden,
                      **config.policy_config_extra}
        self._local = ESEvalWorker(config.env, policy_cfg,
                                   seed=config.seed,
                                   noise_size=config.noise_size)
        self.dim = self._local.dim
        remote_cls = remote(ESEvalWorker)
        n = max(0, config.num_rollout_workers)
        self.eval_workers = [
            remote_cls.options(num_cpus=1).remote(
                config.env, policy_cfg, seed=config.seed,
                worker_index=i + 1, noise_size=config.noise_size)
            for i in range(n)
        ]
        from jax.flatten_util import ravel_pytree

        flat, _ = ravel_pytree(self._local.policy.params)
        self.flat_params = np.asarray(flat, np.float32)
        self.noise = self._local.noise
        # Adam moments (reference: es/optimizers.py Adam)
        self._m = np.zeros(self.dim, np.float32)
        self._v = np.zeros(self.dim, np.float32)
        self._t = 0

    def _adam_step(self, grad: np.ndarray, lr: float) -> None:
        b1, b2, eps = 0.9, 0.999, 1e-8
        self._t += 1
        self._m = b1 * self._m + (1 - b1) * grad
        self._v = b2 * self._v + (1 - b2) * grad * grad
        mhat = self._m / (1 - b1 ** self._t)
        vhat = self._v / (1 - b2 ** self._t)
        self.flat_params = self.flat_params - lr * mhat / (
            np.sqrt(vhat) + eps)

    def _collect(self, num_pairs: int) -> Dict:
        cfg = self.config
        if self.eval_workers:
            from ..core import put

            per = max(1, num_pairs // len(self.eval_workers))
            # One object-store copy, N readers (same pattern as
            # WorkerSet.sync_weights).
            ref = put(self.flat_params)
            results = get([
                w.do_rollouts.remote(ref, per, cfg.sigma)
                for w in self.eval_workers
            ])
        else:
            results = [self._local.do_rollouts(self.flat_params,
                                               num_pairs, cfg.sigma)]
        out = {"indices": [], "pos": [], "neg": [], "steps": 0}
        for r in results:
            out["indices"].extend(r["indices"])
            out["pos"].extend(r["pos"])
            out["neg"].extend(r["neg"])
            out["steps"] += r["steps"]
        return out

    def training_step(self) -> Dict:
        cfg: ESConfig = self.config
        res = self._collect(cfg.episodes_per_batch)
        pos = np.asarray(res["pos"], np.float32)
        neg = np.asarray(res["neg"], np.float32)
        n = len(pos)
        # Centered-rank shaping over ALL 2n returns, then the antithetic
        # difference per pair (reference: es.py batched_weighted_sum).
        shaped = centered_ranks(np.concatenate([pos, neg]))
        w = shaped[:n] - shaped[n:]
        grad = np.zeros(self.dim, np.float32)
        for wi, idx in zip(w, res["indices"]):
            grad += wi * self.noise.get(idx, self.dim)
        grad /= (n * cfg.sigma)
        grad -= cfg.l2_coeff * self.flat_params  # weight decay
        self._adam_step(-grad, cfg.step_size)  # ascend
        self._timesteps_total += res["steps"]
        return {
            "timesteps_this_iter": res["steps"],
            "episodes_this_iter": 2 * n,
            "episode_reward_mean": float(np.mean(
                np.concatenate([pos, neg]))),
            "grad_norm": float(np.linalg.norm(grad)),
        }

    def train(self) -> Dict:
        import time

        t0 = time.perf_counter()
        result = self.training_step()
        self.iteration += 1
        result.update({
            "training_iteration": self.iteration,
            "timesteps_total": self._timesteps_total,
            "time_this_iter_s": time.perf_counter() - t0,
        })
        return result

    def evaluate(self, episodes: int = 3) -> float:
        return self._local.eval_policy(self.flat_params, episodes)

    def get_state(self) -> Dict:
        return {"iteration": self.iteration,
                "timesteps_total": self._timesteps_total,
                "flat_params": self.flat_params,
                "m": self._m, "v": self._v, "t": self._t}

    def set_state(self, state: Dict) -> None:
        self.iteration = state.get("iteration", 0)
        self._timesteps_total = state.get("timesteps_total", 0)
        if "flat_params" in state:
            self.flat_params = np.asarray(state["flat_params"],
                                          np.float32)
        self._m = state.get("m", self._m)
        self._v = state.get("v", self._v)
        self._t = state.get("t", self._t)

    def stop(self) -> None:
        for w in self.eval_workers:
            try:
                kill(w)
            except Exception:
                pass


class ARSConfig(ESConfig):
    def __init__(self):
        super().__init__()
        self._algo_class = ARS
        self.top_k: Optional[int] = None  # default: use all directions
        self.sigma = 0.05
        self.step_size = 0.05

    def training(self, top_k=None, **kwargs) -> "ARSConfig":
        if top_k is not None:
            self.top_k = top_k
        super().training(**kwargs)
        return self


class ARS(ES):
    """ARS V1-t: keep only the top_k directions by max(r+, r-), weight
    by the raw return difference, scale the step by the std of the used
    returns (reference: ars.py; Mania et al. 2018 Alg. 2)."""

    _is_ars = True

    def training_step(self) -> Dict:
        cfg: ARSConfig = self.config
        res = self._collect(cfg.episodes_per_batch)
        pos = np.asarray(res["pos"], np.float32)
        neg = np.asarray(res["neg"], np.float32)
        n = len(pos)
        k = min(cfg.top_k or n, n)
        order = np.argsort(-np.maximum(pos, neg))[:k]
        used = np.concatenate([pos[order], neg[order]])
        sigma_r = float(used.std()) + 1e-8
        grad = np.zeros(self.dim, np.float32)
        for i in order:
            grad += (pos[i] - neg[i]) * self.noise.get(
                res["indices"][i], self.dim)
        grad /= (k * sigma_r)
        self._adam_step(-grad, cfg.step_size)
        self._timesteps_total += res["steps"]
        return {
            "timesteps_this_iter": res["steps"],
            "episodes_this_iter": 2 * n,
            "episode_reward_mean": float(np.mean(
                np.concatenate([pos, neg]))),
            "grad_norm": float(np.linalg.norm(grad)),
        }
