"""SampleBatch: columnar trajectory storage + GAE.

Reference analog: ``rllib/policy/sample_batch.py`` (SampleBatch,
concat_samples) and ``rllib/evaluation/postprocessing.py`` (GAE advantage
computation). Columns are numpy arrays host-side; the learner converts to
device arrays once per update.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional

import numpy as np

OBS = "obs"
ACTIONS = "actions"
REWARDS = "rewards"
DONES = "dones"
STATE_IN = "state_in"  # [S, N, cell]: recurrent state at fragment start
NEXT_OBS = "next_obs"
LOGPS = "action_logp"
VF_PREDS = "vf_preds"
ADVANTAGES = "advantages"
VALUE_TARGETS = "value_targets"


class SampleBatch(dict):
    """A dict of equal-length numpy columns."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        for k, v in list(self.items()):
            self[k] = np.asarray(v)

    @property
    def count(self) -> int:
        if not self:
            return 0
        return len(next(iter(self.values())))

    def slice(self, start: int, end: int) -> "SampleBatch":
        return SampleBatch({k: v[start:end] for k, v in self.items()})

    @staticmethod
    def concat_samples(batches: List["SampleBatch"]) -> "SampleBatch":
        if not batches:
            return SampleBatch()
        keys = batches[0].keys()
        return SampleBatch(
            {k: np.concatenate([b[k] for b in batches]) for k in keys}
        )

    def shuffle(self, seed: Optional[int] = None) -> "SampleBatch":
        rng = np.random.default_rng(seed)
        idx = rng.permutation(self.count)
        return SampleBatch({k: v[idx] for k, v in self.items()})

    def minibatches(self, size: int) -> Iterator["SampleBatch"]:
        n = self.count
        for start in range(0, n - size + 1, size):
            yield SampleBatch(
                {k: v[start:start + size] for k, v in self.items()}
            )

    def split(self, n: int) -> List["SampleBatch"]:
        bounds = np.linspace(0, self.count, n + 1).astype(int)
        return [
            SampleBatch({k: v[bounds[i]: bounds[i + 1]]
                         for k, v in self.items()})
            for i in range(n)
        ]


def compute_gae(batch: SampleBatch, last_values: np.ndarray,
                gamma: float = 0.99, lam: float = 0.95) -> SampleBatch:
    """Generalized advantage estimation over (possibly vectorized) rollouts.

    Expects columns shaped [T, N] (time-major over N parallel envs) for
    REWARDS/DONES/VF_PREDS; ``last_values`` [N] bootstraps the final step.
    Reference: postprocessing.py compute_advantages.
    """
    rewards = batch[REWARDS]
    dones = batch[DONES].astype(np.float32)
    values = batch[VF_PREDS]
    t_len = rewards.shape[0]
    next_values = np.concatenate([values[1:], last_values[None]], axis=0)
    adv = np.zeros_like(rewards, dtype=np.float32)
    last_gae = np.zeros_like(last_values, dtype=np.float32)
    for t in range(t_len - 1, -1, -1):
        nonterminal = 1.0 - dones[t]
        delta = rewards[t] + gamma * next_values[t] * nonterminal - values[t]
        last_gae = delta + gamma * lam * nonterminal * last_gae
        adv[t] = last_gae
    batch[ADVANTAGES] = adv
    batch[VALUE_TARGETS] = adv + values
    return batch


def flatten_time_major(batch: SampleBatch) -> SampleBatch:
    """[T, N, ...] -> [T*N, ...] for minibatch SGD."""
    out = {}
    for k, v in batch.items():
        out[k] = v.reshape((-1,) + v.shape[2:])
    return SampleBatch(out)
