"""Multi-agent environments + sampling.

Reference analog: ``rllib/env/multi_agent_env.py`` — dict-keyed
observations/actions per agent id, episode end via ``done["__all__"]``,
``make_multi_agent`` turning any single-agent env into an N-agent one,
and per-POLICY sample collection with a ``policy_mapping_fn``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .sample_batch import (
    ACTIONS,
    DONES,
    LOGPS,
    OBS,
    REWARDS,
    VF_PREDS,
    SampleBatch,
)


class MultiAgentEnv:
    """Agents step together; each carries its own obs/reward stream."""

    def reset(self, seed: Optional[int] = None) -> Dict[str, Any]:
        raise NotImplementedError

    def step(self, actions: Dict[str, Any]):
        """-> (obs, rewards, dones, infos) dicts; dones["__all__"] ends
        the episode."""
        raise NotImplementedError


def make_multi_agent(env_maker: Callable[[], Any], num_agents: int = 2):
    """Wrap independent copies of a single-agent env as one multi-agent
    env (reference: ``make_multi_agent``, multi_agent_env.py)."""

    class _IndependentAgents(MultiAgentEnv):
        def __init__(self):
            self.agents = {f"agent_{i}": env_maker()
                           for i in range(num_agents)}
            self._done = {aid: False for aid in self.agents}

        def reset(self, seed=None):
            self._done = {aid: False for aid in self.agents}
            out = {}
            for i, (aid, env) in enumerate(self.agents.items()):
                obs = env.reset(seed=None if seed is None else seed + i)
                out[aid] = obs[0] if isinstance(obs, tuple) else obs
            return out

        def step(self, actions):
            obs, rews, dones, infos = {}, {}, {}, {}
            for aid, act in actions.items():
                if self._done[aid]:
                    continue
                o, r, d, info = self._step_one(self.agents[aid], act)
                obs[aid], rews[aid], dones[aid], infos[aid] = o, r, d, info
                self._done[aid] = d
            dones["__all__"] = all(self._done.values())
            return obs, rews, dones, infos

        @staticmethod
        def _step_one(env, act):
            out = env.step(act)
            if len(out) == 5:  # gymnasium: obs, r, terminated, trunc, info
                o, r, term, trunc, info = out
                return o, r, bool(term or trunc), info
            return out

    return _IndependentAgents


def sample_multi_agent(env: MultiAgentEnv,
                       policies: Dict[str, Any],
                       policy_mapping_fn: Callable[[str], str],
                       num_steps: int = 128,
                       seed: Optional[int] = None
                       ) -> Dict[str, SampleBatch]:
    """Collect per-POLICY batches from a multi-agent episode stream.

    Each agent's transitions route to ``policies[policy_mapping_fn(
    agent_id)]`` (reference: MultiAgentSampleBatchBuilder); auto-resets
    when ``done["__all__"]``. Policies expose ``compute_actions(obs) ->
    (actions, logps, values)`` over a batch (JaxPolicy interface).
    """
    buffers: Dict[str, Dict[str, list]] = {
        pid: {OBS: [], ACTIONS: [], LOGPS: [], VF_PREDS: [], REWARDS: [],
              DONES: []}
        for pid in policies
    }
    obs = env.reset(seed=seed)
    for _ in range(num_steps):
        actions: Dict[str, Any] = {}
        step_meta: Dict[str, tuple] = {}
        # Group live agents by policy for one batched forward per policy.
        by_policy: Dict[str, List[str]] = {}
        for aid in obs:
            by_policy.setdefault(policy_mapping_fn(aid), []).append(aid)
        for pid, aids in by_policy.items():
            stacked = np.stack([np.asarray(obs[a]) for a in aids])
            acts, logps, values = policies[pid].compute_actions(stacked)
            for i, aid in enumerate(aids):
                actions[aid] = acts[i]
                step_meta[aid] = (pid, obs[aid], acts[i], logps[i],
                                  values[i])
        next_obs, rewards, dones, _ = env.step(actions)
        for aid, (pid, o, a, lp, v) in step_meta.items():
            if aid not in rewards:
                continue
            buf = buffers[pid]
            buf[OBS].append(np.asarray(o))
            buf[ACTIONS].append(a)
            buf[LOGPS].append(lp)
            buf[VF_PREDS].append(v)
            buf[REWARDS].append(rewards[aid])
            buf[DONES].append(dones.get(aid, False))
        if dones.get("__all__"):
            obs = env.reset()
        else:
            obs = {aid: o for aid, o in next_obs.items()
                   if not dones.get(aid, False)}
    return {
        pid: SampleBatch({k: np.asarray(v) for k, v in buf.items()})
        for pid, buf in buffers.items() if buf[OBS]
    }
