"""External environments: inverted-control envs + policy serving REST API.

Reference analogs:
- ``rllib/env/external_env.py:22`` — ``ExternalEnv``: the *environment*
  drives the loop and queries the policy (``start_episode`` /
  ``get_action`` / ``log_action`` / ``log_returns`` / ``end_episode``),
  instead of the algorithm calling ``env.step``.
- ``rllib/env/policy_server_input.py`` / ``policy_client.py`` — the same
  episode API over HTTP, so simulators living in another process (or
  another machine, behind a firewall) can drive training.

Design differences from the reference:
- The sampler batches *all* concurrently-waiting ``get_action`` requests
  into one jitted policy call (the reference answers them one at a time
  through the sampler's queue) — external episodes get the same batched
  inference path as vector envs.
- Transitions are emitted flat ``(obs, action, reward, next_obs, done)``
  rows — the replay-based algorithms (DQN/SAC/TD3) consume them natively;
  this is the reference's primary external-env use case (serving +
  off-policy training).
- The HTTP layer uses length-delimited pickle over POST (the reference
  pickles over HTTP too); ``PolicyClient`` only supports remote inference
  (every ``get_action`` is a round trip). Local-inference mode with
  weight sync is a non-goal: the server owns the single policy.
"""

from __future__ import annotations

import pickle
import queue
import threading
import time
import uuid
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .rollout_worker import RolloutWorker
from .sample_batch import ACTIONS, DONES, NEXT_OBS, OBS, REWARDS, SampleBatch


class _Episode:
    """Per-episode state: the obs->action handoff and the pending
    transition (reference: _ExternalEnvEpisode)."""

    def __init__(self, episode_id: str, training_enabled: bool = True):
        self.episode_id = episode_id
        self.training_enabled = training_enabled
        self.action_q: "queue.Queue" = queue.Queue(maxsize=1)
        self.prev_obs: Optional[np.ndarray] = None
        self.prev_action: Optional[Any] = None
        self.reward_accum = 0.0
        self.total_reward = 0.0


class ExternalEnv(threading.Thread):
    """Inverted-control environment.

    Subclass and override :meth:`run` with your loop::

        class MySim(ExternalEnv):
            def run(self):
                while True:
                    eid = self.start_episode()
                    obs = ...  # from your simulator
                    while not done:
                        action = self.get_action(eid, obs)
                        obs, reward, done = my_sim.step(action)
                        self.log_returns(eid, reward)
                    self.end_episode(eid, obs)

    Declare ``obs_shape`` / ``num_actions`` so the sampler can build the
    policy (the reference passes gym spaces; shapes are the JAX-native
    equivalent here).
    """

    def __init__(self, obs_shape: Tuple[int, ...], num_actions: int,
                 max_concurrent: int = 100):
        super().__init__(daemon=True)
        self.observation_space_shape = tuple(obs_shape)
        self.num_actions = int(num_actions)
        self.num_envs = 1  # batch dim is dynamic (concurrent episodes)
        self._max_concurrent = max_concurrent
        self._episodes: Dict[str, _Episode] = {}
        # Recent finished ids only (duplicate-end detection): unbounded
        # retention would leak one uuid per episode in a server that
        # runs for days.
        self._finished: "OrderedDict[str, None]" = OrderedDict()
        self._finished_cap = 10_000
        self._lock = threading.Lock()
        # (episode, obs) pairs waiting for an on-policy action.
        self._pending: "queue.Queue" = queue.Queue()
        # Completed transition rows, drained by the sampler.
        self._transitions: List[Tuple] = []
        self._completed_returns: List[float] = []

    # -- episode API (called from the external thread) ---------------------

    def start_episode(self, episode_id: Optional[str] = None,
                      training_enabled: bool = True) -> str:
        if episode_id is None:
            episode_id = uuid.uuid4().hex
        with self._lock:
            if episode_id in self._finished:
                raise ValueError(f"episode {episode_id} already completed")
            if episode_id in self._episodes:
                raise ValueError(f"episode {episode_id} already started")
            if len(self._episodes) >= self._max_concurrent:
                raise RuntimeError(
                    f"{len(self._episodes)} concurrent episodes exceed "
                    f"max_concurrent={self._max_concurrent}")
            self._episodes[episode_id] = _Episode(episode_id,
                                                  training_enabled)
        return episode_id

    def get_action(self, episode_id: str, observation) -> Any:
        """Record ``observation`` and block for the on-policy action."""
        ep = self._get(episode_id)
        obs = np.asarray(observation)
        self._emit_step(ep, obs, done=False)
        self._pending.put((ep, obs))
        action = ep.action_q.get()
        ep.prev_obs, ep.prev_action = obs, action
        return action

    def log_action(self, episode_id: str, observation, action) -> None:
        """Record an off-policy (externally chosen) action."""
        ep = self._get(episode_id)
        obs = np.asarray(observation)
        self._emit_step(ep, obs, done=False)
        ep.prev_obs, ep.prev_action = obs, action

    def log_returns(self, episode_id: str, reward: float,
                    info: Optional[Dict] = None) -> None:
        ep = self._get(episode_id)
        ep.reward_accum += float(reward)
        ep.total_reward += float(reward)

    def end_episode(self, episode_id: str, observation) -> None:
        ep = self._get(episode_id)
        self._emit_step(ep, np.asarray(observation), done=True)
        with self._lock:
            self._finished[episode_id] = None
            while len(self._finished) > self._finished_cap:
                self._finished.popitem(last=False)
            self._episodes.pop(episode_id, None)
            self._completed_returns.append(ep.total_reward)

    # -- internals ---------------------------------------------------------

    def _get(self, episode_id: str) -> _Episode:
        with self._lock:
            if episode_id in self._finished:
                raise ValueError(f"episode {episode_id} already completed")
            if episode_id not in self._episodes:
                raise ValueError(f"episode {episode_id} not found")
            return self._episodes[episode_id]

    def _emit_step(self, ep: _Episode, obs: np.ndarray, done: bool) -> None:
        """Complete the pending (prev_obs, prev_action) transition now
        that its next_obs (and accumulated reward) are known."""
        if ep.prev_obs is None:
            return
        if ep.training_enabled:
            with self._lock:
                self._transitions.append(
                    (ep.prev_obs, ep.prev_action, ep.reward_accum, obs,
                     done))
        ep.reward_accum = 0.0
        if done:
            ep.prev_obs = ep.prev_action = None

    def run(self):  # pragma: no cover - subclass hook
        raise NotImplementedError


class ExternalEnvWorker(RolloutWorker):
    """Rollout worker servicing an :class:`ExternalEnv`.

    ``sample(n)`` pumps the env's pending action requests — batching every
    concurrently-waiting episode into ONE policy call — until ``n``
    transition rows accumulate, then returns them as a flat SampleBatch
    (DQN/SAC layout). Plugs into any replay-based Algorithm via
    ``_worker_cls``.
    """

    #: subclasses override to pair with a different policy family
    #: (e.g. external DQN uses the QPolicy hook from DQNRolloutWorker).

    def __init__(self, env_spec: Any, num_envs: int = 1,
                 policy_config: Optional[Dict] = None, seed: int = 0,
                 worker_index: int = 0):
        from .connectors import ConnectorContext, \
            create_connectors_for_policy

        env = env_spec() if callable(env_spec) else env_spec
        if not isinstance(env, ExternalEnv):
            raise TypeError("ExternalEnvWorker needs an ExternalEnv "
                            "instance or factory")
        self.env = env
        cfg = policy_config or {}
        self._policy_cfg = cfg
        ctx = ConnectorContext.from_env(env, cfg)
        self.agent_connectors, self.action_connectors = \
            create_connectors_for_policy(ctx, cfg.get("connectors"))
        bad = [type(c).__name__ for c in self.agent_connectors.connectors
               if c.slot_stateful]
        if bad:
            raise ValueError(
                f"slot-stateful connectors {bad} cannot serve external "
                "envs: episodes interleave arbitrarily, so there is no "
                "stable slot layout to key per-slot state on. Apply "
                "frame stacking on the client side instead.")
        # Probe the TRANSFORMED obs shape with a throwaway pipeline so
        # the probe doesn't pollute running statistics (MeanStdObs).
        probe_agent, _ = create_connectors_for_policy(
            ctx, cfg.get("connectors"))
        probe = probe_agent(
            np.zeros((1,) + tuple(env.observation_space_shape),
                     np.float32))
        self._connected_obs_shape = tuple(probe.shape[1:])
        self.policy = self._make_policy(cfg, seed + worker_index)
        self._episode_rewards = np.zeros(1, np.float32)
        self._completed: List[float] = []
        self.worker_index = worker_index
        if not env.is_alive():
            env.start()

    def sample(self, rollout_length: int = 64,
               timeout_s: float = 30.0) -> SampleBatch:
        rows: List[Tuple] = []
        deadline = time.monotonic() + timeout_s
        env = self.env
        while len(rows) < rollout_length:
            if time.monotonic() > deadline:
                if rows:
                    break
                raise TimeoutError(
                    "external env produced no transitions within "
                    f"{timeout_s}s — is its run() loop alive?")
            # Drain every episode currently waiting on an action.
            waiting = []
            try:
                waiting.append(env._pending.get(timeout=0.05))
                while True:
                    waiting.append(env._pending.get_nowait())
            except queue.Empty:
                pass
            if waiting:
                obs = self.agent_connectors(
                    np.stack([o for _, o in waiting]))
                actions, _, _ = self.policy.compute_actions(obs)
                actions = self.action_connectors(actions)
                for (ep, _), a in zip(waiting, np.asarray(actions)):
                    ep.action_q.put(a.item() if a.shape == () else a)
            with env._lock:
                if env._transitions:
                    rows.extend(env._transitions)
                    env._transitions.clear()
                if env._completed_returns:
                    self._completed.extend(env._completed_returns)
                    env._completed_returns.clear()
        # Build the training batch in EVAL mode: the raw rows were each
        # already seen once at inference time (where running stats
        # update), so the batch pass must not count them again. The batch
        # obs are normalized with stats as-of-now rather than as-of-the-
        # action — the same mild skew the reference accepts when its
        # MeanStdFilter advances during sampling.
        self.agent_connectors.in_eval()
        try:
            obs = self.agent_connectors(
                np.stack([r[0] for r in rows]).astype(np.float32))
            next_obs = self.agent_connectors(
                np.stack([r[3] for r in rows]).astype(np.float32))
            rewards = self.agent_connectors.transform_reward(
                np.asarray([r[2] for r in rows], np.float32))
        finally:
            self.agent_connectors.in_training()
        return SampleBatch({
            OBS: obs,
            ACTIONS: np.asarray([r[1] for r in rows]),
            REWARDS: rewards,
            NEXT_OBS: next_obs,
            DONES: np.asarray([r[4] for r in rows], bool),
        })

    def episode_stats(self, clear: bool = True) -> Dict:
        with self.env._lock:
            self._completed.extend(self.env._completed_returns)
            self.env._completed_returns.clear()
        return super().episode_stats(clear)


class ExternalDQNWorker(ExternalEnvWorker):
    """External env paired with the DQN epsilon-greedy Q policy."""

    def _make_policy(self, cfg: Dict, seed: int):
        from .dqn import DQNRolloutWorker

        return DQNRolloutWorker._make_policy(self, cfg, seed)

    def set_epsilon(self, epsilon: float) -> None:
        self.policy.epsilon = float(epsilon)


# ---------------------------------------------------------------------------
# Policy server / client (reference: policy_server_input.py, policy_client.py)
# ---------------------------------------------------------------------------

_COMMANDS = ("START_EPISODE", "GET_ACTION", "LOG_ACTION", "LOG_RETURNS",
             "END_EPISODE")


class PolicyServerInput(ExternalEnv):
    """An ExternalEnv driven by HTTP clients instead of a local run loop.

    Start it as the env of an :class:`ExternalEnvWorker`-based algorithm;
    point any number of :class:`PolicyClient` processes at
    ``http://host:port``. Reference: ``PolicyServerInput``
    (policy_server_input.py:29) — same command protocol, minus the
    local-inference weight sync.

    .. warning:: Requests are **unpickled** (as in the reference), which
       is remote code execution for anyone who can reach the port. Bind
       to localhost (the default) or a trusted network only — never
       expose this port publicly.
    """

    def __init__(self, obs_shape: Tuple[int, ...], num_actions: int,
                 host: str = "127.0.0.1", port: int = 0,
                 max_concurrent: int = 100):
        super().__init__(obs_shape, num_actions, max_concurrent)
        env = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def do_POST(self):
                body = self.rfile.read(
                    int(self.headers.get("Content-Length", 0)))
                try:
                    req = pickle.loads(body)
                    out = env._handle(req)
                    payload = pickle.dumps({"ok": True, "result": out})
                    code = 200
                except Exception as e:  # noqa: BLE001 - ship to client
                    payload = pickle.dumps({"ok": False,
                                            "error": repr(e)})
                    code = 500
                self.send_response(code)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.address = (f"http://{self._httpd.server_address[0]}:"
                        f"{self._httpd.server_address[1]}")

    def _handle(self, req: Dict) -> Any:
        cmd = req["command"]
        if cmd == "START_EPISODE":
            return self.start_episode(req.get("episode_id"),
                                      req.get("training_enabled", True))
        if cmd == "GET_ACTION":
            return self.get_action(req["episode_id"], req["observation"])
        if cmd == "LOG_ACTION":
            return self.log_action(req["episode_id"], req["observation"],
                                   req["action"])
        if cmd == "LOG_RETURNS":
            return self.log_returns(req["episode_id"], req["reward"],
                                    req.get("info"))
        if cmd == "END_EPISODE":
            return self.end_episode(req["episode_id"], req["observation"])
        raise ValueError(f"unknown command {cmd!r} "
                         f"(expected one of {_COMMANDS})")

    def run(self):
        self._httpd.serve_forever(poll_interval=0.1)

    def shutdown(self):
        self._httpd.shutdown()
        self._httpd.server_close()


class PolicyClient:
    """Client-side episode API over HTTP (reference: PolicyClient,
    policy_client.py:59, remote inference mode)."""

    def __init__(self, address: str, timeout_s: float = 30.0):
        self.address = address.rstrip("/")
        self.timeout_s = timeout_s

    def _send(self, **req) -> Any:
        import urllib.error
        import urllib.request

        data = pickle.dumps(req)
        http_req = urllib.request.Request(
            self.address, data=data,
            headers={"Content-Type": "application/octet-stream"})
        try:
            with urllib.request.urlopen(http_req,
                                        timeout=self.timeout_s) as resp:
                out = pickle.loads(resp.read())
        except urllib.error.HTTPError as e:
            out = pickle.loads(e.read())
        if not out.get("ok"):
            raise RuntimeError(f"policy server error: {out.get('error')}")
        return out.get("result")

    def start_episode(self, episode_id: Optional[str] = None,
                      training_enabled: bool = True) -> str:
        return self._send(command="START_EPISODE", episode_id=episode_id,
                          training_enabled=training_enabled)

    def get_action(self, episode_id: str, observation) -> Any:
        return self._send(command="GET_ACTION", episode_id=episode_id,
                          observation=np.asarray(observation))

    def log_action(self, episode_id: str, observation, action) -> None:
        self._send(command="LOG_ACTION", episode_id=episode_id,
                   observation=np.asarray(observation), action=action)

    def log_returns(self, episode_id: str, reward: float,
                    info: Optional[Dict] = None) -> None:
        self._send(command="LOG_RETURNS", episode_id=episode_id,
                   reward=float(reward), info=info)

    def end_episode(self, episode_id: str, observation) -> None:
        self._send(command="END_EPISODE", episode_id=episode_id,
                   observation=np.asarray(observation))
