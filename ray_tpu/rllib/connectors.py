"""Connectors: composable env<->policy transformation pipelines.

Reference analog: ``rllib/connectors/connector.py`` (Connector,
ConnectorContext, ConnectorPipeline), ``connectors/agent/*`` (obs
preprocessing, reward clipping, state buffering, lambdas) and
``connectors/action/*`` (clip, normalize, immutable, lambdas).

Re-founded for the vectorized-rollout design of this framework: the
reference transforms *lists of per-agent items* (AgentConnectorDataType)
in Python loops; here a connector transforms the **whole [N, ...] batch**
of a vector env in one numpy op, which is what keeps the rollout loop off
the per-step Python floor and hands contiguous arrays to the jitted
policy. Connectors are serializable (``to_state``/``from_state``) so a
policy restored from a checkpoint — or served behind the policy server —
reconstructs the exact preprocessing it trained with, which is the whole
point of the reference's connector redesign (bring-your-own-env serving).

Stateful connectors (frame stacking, running obs normalization) key their
state on the env slot dimension and reset slots on episode ends via
``on_episode_done(mask)``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

# ---------------------------------------------------------------------------
# Registry (reference: register_connector / get_connector in connector.py,
# backed by the tune registry; plain dict here).
# ---------------------------------------------------------------------------

_CONNECTOR_REGISTRY: Dict[str, type] = {}


def register_connector(name: str, cls: type) -> None:
    """Register a connector class for name-based (de)serialization."""
    _CONNECTOR_REGISTRY[name] = cls


def get_connector(name: str, ctx: "ConnectorContext",
                  params: Any) -> "Connector":
    """Rebuild a connector from its serialized (name, params) state."""
    if name not in _CONNECTOR_REGISTRY:
        raise KeyError(
            f"Unknown connector {name!r}; registered: "
            f"{sorted(_CONNECTOR_REGISTRY)}")
    return _CONNECTOR_REGISTRY[name].from_state(ctx, params)


class ConnectorContext:
    """Env/policy facts a connector may need (reference:
    ConnectorContext, connector.py:27)."""

    def __init__(self, obs_shape: Optional[Tuple[int, ...]] = None,
                 num_actions: int = 0,
                 action_low: Optional[np.ndarray] = None,
                 action_high: Optional[np.ndarray] = None,
                 num_envs: int = 1,
                 config: Optional[Dict] = None):
        self.obs_shape = tuple(obs_shape) if obs_shape else None
        self.num_actions = num_actions
        self.action_low = action_low
        self.action_high = action_high
        self.num_envs = num_envs
        self.config = config or {}

    @staticmethod
    def from_env(env, config: Optional[Dict] = None) -> "ConnectorContext":
        return ConnectorContext(
            obs_shape=getattr(env, "observation_space_shape", None),
            num_actions=getattr(env, "num_actions", 0),
            action_low=getattr(env, "action_low", None),
            action_high=getattr(env, "action_high", None),
            num_envs=getattr(env, "num_envs", 1),
            config=config,
        )


class Connector:
    """Base: a named, serializable transformation step."""

    name = "Connector"

    def __init__(self, ctx: ConnectorContext):
        self._ctx = ctx
        self._is_training = True

    def in_training(self) -> None:
        self._is_training = True

    def in_eval(self) -> None:
        self._is_training = False

    # -- serialization ------------------------------------------------------
    def to_state(self) -> Tuple[str, Any]:
        """(name, json-able params). Stateless default."""
        return (self.name, None)

    @classmethod
    def from_state(cls, ctx: ConnectorContext, params: Any) -> "Connector":
        return cls(ctx)

    def __str__(self, indent: int = 0) -> str:
        return " " * indent + type(self).__name__


# ---------------------------------------------------------------------------
# Agent connectors: env data -> policy input
# ---------------------------------------------------------------------------


class AgentConnector(Connector):
    """Transforms the batched observation [N, ...] before the policy
    sees it (reference: AgentConnector, connector.py:137)."""

    #: True when the connector keys state on the batch's slot dimension
    #: (e.g. frame stacking). Such connectors require a stable vector-env
    #: slot layout and cannot serve flat interleaved-episode batches
    #: (external envs reject them).
    slot_stateful = False

    def __call__(self, obs: np.ndarray) -> np.ndarray:
        return self.transform(obs)

    def transform(self, obs: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def transform_reward(self, rewards: np.ndarray) -> np.ndarray:
        """Hook for reward-shaping connectors (identity default)."""
        return rewards

    def on_episode_done(self, done_mask: np.ndarray) -> None:
        """Reset per-slot state for finished sub-envs."""

    def reset(self) -> None:
        """Reset all state (new rollout worker / eval run)."""


class FlattenObsConnector(AgentConnector):
    """Flatten [N, ...] observations to [N, D] vectors.

    Reference: connectors/agent/obs_preproc.py (ObsPreprocessorConnector
    wrapping the catalog's flatten preprocessor)."""

    name = "FlattenObs"

    def transform(self, obs: np.ndarray) -> np.ndarray:
        obs = np.asarray(obs)
        return obs.reshape(obs.shape[0], -1)


class ClipRewardConnector(AgentConnector):
    """sign() or [-limit, limit] reward clipping.

    Reference: connectors/agent/clip_reward.py."""

    name = "ClipReward"

    def __init__(self, ctx: ConnectorContext, sign: bool = False,
                 limit: Optional[float] = None):
        super().__init__(ctx)
        self.sign = sign
        self.limit = limit

    def transform(self, obs):
        return obs

    def transform_reward(self, rewards: np.ndarray) -> np.ndarray:
        if self.sign:
            return np.sign(rewards).astype(np.float32)
        if self.limit is not None:
            return np.clip(rewards, -self.limit, self.limit)
        return rewards

    def to_state(self):
        return (self.name, {"sign": self.sign, "limit": self.limit})

    @classmethod
    def from_state(cls, ctx, params):
        return cls(ctx, **(params or {}))


class FrameStackConnector(AgentConnector):
    """Stack the last k observations along the final axis.

    The rolling buffer lives here (per env slot); finished slots refill
    with the reset frame so episodes never see cross-episode frames.
    Vector-obs envs get [N, D*k]; image envs [N, H, W, C*k]."""

    name = "FrameStack"
    slot_stateful = True

    def __init__(self, ctx: ConnectorContext, k: int = 4):
        super().__init__(ctx)
        self.k = int(k)
        self._buf: Optional[np.ndarray] = None  # [N, ..., C*k]
        self._reset_mask: Optional[np.ndarray] = None

    def transform(self, obs: np.ndarray) -> np.ndarray:
        obs = np.asarray(obs)
        if self._buf is None or self._buf.shape[0] != obs.shape[0]:
            self._buf = np.concatenate([obs] * self.k, axis=-1)
        else:
            c = obs.shape[-1]
            self._buf = np.concatenate([self._buf[..., c:], obs], axis=-1)
            if self._reset_mask is not None and np.any(self._reset_mask):
                # Done slots received a fresh reset obs this step: their
                # history must be k copies of it, not the dead episode's
                # trailing frames.
                m = self._reset_mask
                self._buf[m] = np.concatenate([obs[m]] * self.k, axis=-1)
        self._reset_mask = None
        return self._buf

    def on_episode_done(self, done_mask: np.ndarray) -> None:
        self._reset_mask = np.asarray(done_mask, bool)

    def reset(self) -> None:
        self._buf = None
        self._reset_mask = None

    def to_state(self):
        return (self.name, {"k": self.k})

    @classmethod
    def from_state(cls, ctx, params):
        return cls(ctx, **(params or {}))


class MeanStdObsConnector(AgentConnector):
    """Running mean/std observation normalization (Welford), frozen in
    eval mode.

    Reference: the MeanStdFilter observation filter
    (``rllib/utils/filter.py``) that ``config.observation_filter=
    "MeanStdFilter"`` installs — recast as a connector so the statistics
    serialize with the policy (the reference syncs filters separately
    through FilterManager)."""

    name = "MeanStdObs"

    def __init__(self, ctx: ConnectorContext, eps: float = 1e-8,
                 clip: float = 10.0):
        super().__init__(ctx)
        self.eps = eps
        self.clip = clip
        self.count = 0.0
        self.mean: Optional[np.ndarray] = None
        self.m2: Optional[np.ndarray] = None

    def transform(self, obs: np.ndarray) -> np.ndarray:
        obs = np.asarray(obs, np.float32)
        flat = obs.reshape(obs.shape[0], -1)
        if self.mean is None:
            self.mean = np.zeros(flat.shape[1], np.float64)
            self.m2 = np.zeros(flat.shape[1], np.float64)
        if self._is_training:
            # Chan parallel update with the batch as one group.
            bmean = flat.mean(axis=0)
            bm2 = ((flat - bmean) ** 2).sum(axis=0)
            n, bn = self.count, float(flat.shape[0])
            delta = bmean - self.mean
            tot = n + bn
            self.mean = self.mean + delta * (bn / tot)
            self.m2 = self.m2 + bm2 + delta ** 2 * (n * bn / tot)
            self.count = tot
        if self.count < 2:
            return obs
        std = np.sqrt(self.m2 / max(self.count - 1, 1.0)) + self.eps
        out = (flat - self.mean) / std
        return np.clip(out, -self.clip, self.clip).astype(
            np.float32).reshape(obs.shape)

    def to_state(self):
        return (self.name, {
            "eps": self.eps, "clip": self.clip, "count": self.count,
            "mean": None if self.mean is None else self.mean.tolist(),
            "m2": None if self.m2 is None else self.m2.tolist(),
        })

    @classmethod
    def from_state(cls, ctx, params):
        params = dict(params or {})
        count = params.pop("count", 0.0)
        mean = params.pop("mean", None)
        m2 = params.pop("m2", None)
        conn = cls(ctx, **params)
        conn.count = count
        conn.mean = None if mean is None else np.asarray(mean, np.float64)
        conn.m2 = None if m2 is None else np.asarray(m2, np.float64)
        return conn


class LambdaAgentConnector(AgentConnector):
    """Adapt a stateless fn into an agent connector (reference:
    register_lambda_agent_connector, connectors/agent/lambdas.py).
    Not serializable by name unless registered with a factory."""

    name = "LambdaAgent"

    def __init__(self, ctx: ConnectorContext,
                 fn: Callable[[np.ndarray], np.ndarray]):
        super().__init__(ctx)
        self.fn = fn

    def transform(self, obs):
        return self.fn(obs)

    def to_state(self):
        raise TypeError("LambdaAgentConnector is not serializable; "
                        "subclass AgentConnector and register it instead")


# ---------------------------------------------------------------------------
# Action connectors: policy output -> env actions
# ---------------------------------------------------------------------------


class ActionConnector(Connector):
    """Transforms the batched action array before env.step
    (reference: ActionConnector, connector.py:282)."""

    def __call__(self, actions: np.ndarray) -> np.ndarray:
        return self.transform(actions)

    def transform(self, actions: np.ndarray) -> np.ndarray:
        raise NotImplementedError


class ClipActionConnector(ActionConnector):
    """Clip continuous actions to the env's bounds
    (reference: connectors/action/clip.py)."""

    name = "ClipAction"

    def transform(self, actions: np.ndarray) -> np.ndarray:
        lo, hi = self._ctx.action_low, self._ctx.action_high
        if lo is None or hi is None:
            return actions
        return np.clip(actions, lo, hi)


class NormalizeActionConnector(ActionConnector):
    """Map squashed [-1, 1] policy outputs to the env's [low, high]
    (reference: connectors/action/normalize.py / unsquash_action)."""

    name = "NormalizeAction"

    def transform(self, actions: np.ndarray) -> np.ndarray:
        lo, hi = self._ctx.action_low, self._ctx.action_high
        if lo is None or hi is None:
            return actions
        lo = np.asarray(lo, np.float32)
        hi = np.asarray(hi, np.float32)
        return lo + (np.clip(actions, -1.0, 1.0) + 1.0) * 0.5 * (hi - lo)


class ImmutableActionConnector(ActionConnector):
    """Hand the env a write-protected copy so in-place env mutation can't
    corrupt the training batch (reference: connectors/action/immutable.py)."""

    name = "ImmutableAction"

    def transform(self, actions: np.ndarray) -> np.ndarray:
        out = np.array(actions, copy=True)
        out.setflags(write=False)
        return out


class LambdaActionConnector(ActionConnector):
    name = "LambdaAction"

    def __init__(self, ctx: ConnectorContext,
                 fn: Callable[[np.ndarray], np.ndarray]):
        super().__init__(ctx)
        self.fn = fn

    def transform(self, actions):
        return self.fn(actions)

    def to_state(self):
        raise TypeError("LambdaActionConnector is not serializable")


# ---------------------------------------------------------------------------
# Pipelines
# ---------------------------------------------------------------------------


class ConnectorPipeline:
    """Ordered connector chain with insert/remove by name
    (reference: ConnectorPipeline, connector.py:337)."""

    def __init__(self, ctx: ConnectorContext,
                 connectors: Sequence[Connector] = ()):
        self._ctx = ctx
        self.connectors: List[Connector] = list(connectors)

    def in_training(self):
        for c in self.connectors:
            c.in_training()

    def in_eval(self):
        for c in self.connectors:
            c.in_eval()

    def remove(self, name: str) -> None:
        self.connectors = [c for c in self.connectors
                           if type(c).__name__ != name and c.name != name]

    def insert_before(self, name: str, connector: Connector) -> None:
        idx = self._index(name)
        self.connectors.insert(idx, connector)

    def insert_after(self, name: str, connector: Connector) -> None:
        idx = self._index(name)
        self.connectors.insert(idx + 1, connector)

    def prepend(self, connector: Connector) -> None:
        self.connectors.insert(0, connector)

    def append(self, connector: Connector) -> None:
        self.connectors.append(connector)

    def _index(self, name: str) -> int:
        for i, c in enumerate(self.connectors):
            if type(c).__name__ == name or c.name == name:
                return i
        raise ValueError(f"No connector named {name!r} in pipeline")

    def to_state(self) -> List[Tuple[str, Any]]:
        return [c.to_state() for c in self.connectors]

    def __str__(self, indent: int = 0) -> str:
        lines = [" " * indent + type(self).__name__]
        lines += [c.__str__(indent + 4) for c in self.connectors]
        return "\n".join(lines)


class AgentConnectorPipeline(ConnectorPipeline):
    def __call__(self, obs: np.ndarray) -> np.ndarray:
        for c in self.connectors:
            obs = c(obs)
        return obs

    def transform_reward(self, rewards: np.ndarray) -> np.ndarray:
        for c in self.connectors:
            rewards = c.transform_reward(rewards)
        return rewards

    def on_episode_done(self, done_mask: np.ndarray) -> None:
        for c in self.connectors:
            c.on_episode_done(done_mask)

    def reset(self) -> None:
        for c in self.connectors:
            c.reset()

    @staticmethod
    def from_state(ctx: ConnectorContext,
                   state: List[Tuple[str, Any]]) -> "AgentConnectorPipeline":
        return AgentConnectorPipeline(
            ctx, [get_connector(name, ctx, params)
                  for name, params in state])


class ActionConnectorPipeline(ConnectorPipeline):
    def __call__(self, actions: np.ndarray) -> np.ndarray:
        for c in self.connectors:
            actions = c(actions)
        return actions

    @staticmethod
    def from_state(ctx: ConnectorContext,
                   state: List[Tuple[str, Any]]) -> "ActionConnectorPipeline":
        return ActionConnectorPipeline(
            ctx, [get_connector(name, ctx, params)
                  for name, params in state])


# ---------------------------------------------------------------------------
# Spec-driven construction (what algorithm configs carry)
# ---------------------------------------------------------------------------

#: connectors config spec:
#:   {"agent": [("FrameStack", {"k": 4}), "MeanStdObs"],
#:    "action": ["NormalizeAction", "ClipAction", "ImmutableAction"]}


def _build(ctx: ConnectorContext, spec: Sequence) -> List[Connector]:
    out = []
    for item in spec:
        if isinstance(item, Connector):
            out.append(item)
            continue
        if isinstance(item, str):
            name, params = item, None
        else:
            name, params = item
        out.append(get_connector(name, ctx, params))
    return out


def create_connectors_for_policy(
        ctx: ConnectorContext, spec: Optional[Dict] = None,
) -> Tuple[AgentConnectorPipeline, ActionConnectorPipeline]:
    """Build (agent_pipeline, action_pipeline) from a config spec
    (reference: create_connectors_for_policy, connectors/util.py)."""
    spec = spec or {}
    agent = AgentConnectorPipeline(ctx, _build(ctx, spec.get("agent", ())))
    action = ActionConnectorPipeline(
        ctx, _build(ctx, spec.get("action", ())))
    return agent, action


def restore_connectors_for_policy(
        ctx: ConnectorContext, state: Dict,
) -> Tuple[AgentConnectorPipeline, ActionConnectorPipeline]:
    """Rebuild pipelines from ``{"agent": [...], "action": [...]}`` state
    (reference: restore_connectors_for_policy, connectors/util.py)."""
    return (AgentConnectorPipeline.from_state(ctx, state.get("agent", [])),
            ActionConnectorPipeline.from_state(ctx,
                                               state.get("action", [])))


for _cls in (FlattenObsConnector, ClipRewardConnector, FrameStackConnector,
             MeanStdObsConnector, ClipActionConnector,
             NormalizeActionConnector, ImmutableActionConnector):
    register_connector(_cls.name, _cls)
