"""On-device PPO: env + rollout + GAE + SGD in ONE compiled TPU program.

Reference analog: none — the reference's PPO throughput path is CPU
rollout actors feeding a GPU learner (``rllib/evaluation/sampler.py:546``
per-env-step python loop). On TPU the idiomatic design (the "Anakin"
podracer architecture, Hessel et al. 2021) fuses the whole
sample→advantage→update cycle into a single ``jit``: a JAX-native
vectorized env steps entirely in HBM, the policy samples actions without
leaving the chip, and the PPO epochs run in the same program, so the only
host↔device traffic per iteration is metrics. This is what makes the
env-steps/s/chip north star reachable on hosts whose CPUs could never
feed a learner (the reference needs a rack of rollout CPUs for the same).

The actor-based path (``ppo.py`` + ``rollout_worker.py``) remains the
general answer for envs that only exist as host code; this module is the
TPU-native fast path for envs expressible as JAX functions.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from .policy import make_network


@dataclass(frozen=True)
class JaxEnv:
    """A vectorized env as pure functions over an env-state pytree.

    reset: key -> (state, obs[N, ...])
    step:  (state, actions[N], key) -> (state, obs, rewards[N], dones[N])
    """
    name: str
    num_envs: int
    obs_shape: Tuple[int, ...]
    num_actions: int
    reset: Callable
    step: Callable


def jax_cartpole(num_envs: int) -> JaxEnv:
    """CartPole-v1 dynamics as a JAX program (same physics/termination as
    ``env.FastCartPole``)."""
    lim_theta = 12 * 2 * np.pi / 360
    max_steps = 500

    def _fresh(key, n):
        return jax.random.uniform(key, (n, 4), jnp.float32, -0.05, 0.05)

    def reset(key):
        state = {"s": _fresh(key, num_envs),
                 "t": jnp.zeros(num_envs, jnp.int32),
                 "key": jax.random.fold_in(key, 1)}
        return state, state["s"]

    def step(state, actions, key):
        s = state["s"]
        x, x_dot, th, th_dot = s[:, 0], s[:, 1], s[:, 2], s[:, 3]
        force = jnp.where(actions == 1, 10.0, -10.0)
        costh, sinth = jnp.cos(th), jnp.sin(th)
        temp = (force + 0.05 * th_dot**2 * sinth) / 1.1
        th_acc = (9.8 * sinth - costh * temp) / (
            0.5 * (4.0 / 3.0 - 0.1 * costh**2 / 1.1))
        x_acc = temp - 0.05 * th_acc * costh / 1.1
        x = x + 0.02 * x_dot
        x_dot = x_dot + 0.02 * x_acc
        th = th + 0.02 * th_dot
        th_dot = th_dot + 0.02 * th_acc
        t = state["t"] + 1
        done = ((jnp.abs(x) > 2.4) | (jnp.abs(th) > lim_theta)
                | (t >= max_steps))
        fresh = _fresh(key, num_envs)
        s = jnp.stack([x, x_dot, th, th_dot], axis=1)
        s = jnp.where(done[:, None], fresh, s)
        t = jnp.where(done, 0, t)
        rewards = jnp.ones(num_envs, jnp.float32)
        return ({"s": s, "t": t, "key": key}, s, rewards, done)

    return JaxEnv("JaxCartPole", num_envs, (4,), 2, reset, step)


def jax_atari_sim(num_envs: int) -> JaxEnv:
    """Atari-SHAPED JAX env: 84x84x4 uint8 frame stacks, 6 actions,
    pong-like ball/paddle dynamics rendered on device (see
    ``env.AtariSim`` for the host twin). The observation tensor shape,
    dtype, and conv-policy workload match the reference's Atari
    throughput configs; the game itself is synthetic because this image
    has no ALE ROMs."""
    H = W = 84
    max_steps = 1000

    def render(ball, paddle, frames):
        by = jnp.clip(ball[:, 0].astype(jnp.int32), 1, H - 2)
        bx = jnp.clip(ball[:, 1].astype(jnp.int32), 1, W - 2)
        py = jnp.clip(paddle.astype(jnp.int32), 4, H - 5)
        rows = jnp.arange(H)[None, :, None]
        cols = jnp.arange(W)[None, None, :]
        ball_px = ((jnp.abs(rows - by[:, None, None]) <= 1)
                   & (jnp.abs(cols - bx[:, None, None]) <= 1))
        paddle_px = ((jnp.abs(rows - py[:, None, None]) <= 4)
                     & (cols == W - 3))
        new = jnp.where(ball_px, 255, jnp.where(paddle_px, 200, 0)
                        ).astype(jnp.uint8)
        return jnp.concatenate([frames[..., 1:], new[..., None]], axis=-1)

    def _fresh(key, n):
        kb, kv = jax.random.split(key)
        ball = jax.random.uniform(kb, (n, 2), jnp.float32, 20.0, 60.0)
        vel = jax.random.choice(kv, jnp.asarray([-2.0, -1.0, 1.0, 2.0]),
                                (n, 2))
        return ball, vel

    def reset(key):
        ball, vel = _fresh(key, num_envs)
        paddle = jnp.full(num_envs, H / 2, jnp.float32)
        frames = jnp.zeros((num_envs, H, W, 4), jnp.uint8)
        frames = render(ball, paddle, frames)
        state = {"ball": ball, "vel": vel, "paddle": paddle,
                 "t": jnp.zeros(num_envs, jnp.int32), "frames": frames}
        return state, frames

    def step(state, actions, key):
        move = jnp.where(jnp.isin(actions, jnp.asarray([2, 4])), -2.0,
                         jnp.where(jnp.isin(actions, jnp.asarray([3, 5])),
                                   2.0, 0.0))
        paddle = jnp.clip(state["paddle"] + move, 4, H - 5)
        ball = state["ball"] + state["vel"]
        vel = state["vel"]
        for axis, lim in ((0, H - 2), (1, W - 2)):
            oob = (ball[:, axis] < 1) | (ball[:, axis] > lim)
            vel = vel.at[:, axis].set(
                jnp.where(oob, -vel[:, axis], vel[:, axis]))
            ball = ball.at[:, axis].set(jnp.clip(ball[:, axis], 1, lim))
        hit = (ball[:, 1] > W - 6) & (jnp.abs(ball[:, 0] - paddle) < 5)
        rewards = hit.astype(jnp.float32)
        t = state["t"] + 1
        done = t >= max_steps
        fresh_ball, fresh_vel = _fresh(key, num_envs)
        ball = jnp.where(done[:, None], fresh_ball, ball)
        vel = jnp.where(done[:, None], fresh_vel, vel)
        paddle = jnp.where(done, H / 2, paddle)
        t = jnp.where(done, 0, t)
        frames = render(ball, paddle, state["frames"])
        frames = jnp.where(done[:, None, None, None],
                           render(ball, paddle,
                                  jnp.zeros_like(frames)), frames)
        state = {"ball": ball, "vel": vel, "paddle": paddle, "t": t,
                 "frames": frames}
        return state, frames, rewards, done

    return JaxEnv("JaxAtariSim", num_envs, (H, W, 4), 6, reset, step)


JAX_ENVS = {"JaxCartPole": jax_cartpole, "JaxAtariSim": jax_atari_sim}


class OnDevicePPO:
    """PPO whose entire iteration is one jit program on the accelerator.

    iterate(): rollout T steps (lax.scan: env.step + policy sample),
    GAE over the trajectory, then epochs x minibatches of the clipped
    surrogate — identical math to ``ppo.PPO`` (losses shared via
    ``ppo.ppo_loss``), different execution plan.
    """

    def __init__(self, env: JaxEnv, rollout_length: int = 128,
                 num_sgd_iter: int = 4, minibatches: int = 8,
                 lr: float = 3e-4, gamma: float = 0.99, lambda_: float = 0.95,
                 clip_param: float = 0.2, vf_loss_coeff: float = 0.5,
                 entropy_coeff: float = 0.01, grad_clip: float = 0.5,
                 network: str = "auto", seed: int = 0):
        from .ppo import ppo_loss

        self.env = env
        self.rollout_length = rollout_length
        net = make_network(env.obs_shape, env.num_actions, network)
        self.net = net
        key = jax.random.PRNGKey(seed)
        self.params = net.init(key)
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(grad_clip), optax.adam(lr))
        self.opt_state = self.optimizer.init(self.params)
        self._rng = jax.random.PRNGKey(seed + 1)
        reset_key, self._rng = jax.random.split(self._rng)
        self.env_state, self._obs = jax.jit(env.reset)(reset_key)

        T, N = rollout_length, env.num_envs
        mb_count = minibatches

        def rollout(params, env_state, obs, key):
            def step_fn(carry, step_key):
                env_state, obs = carry
                k_act, k_env = jax.random.split(step_key)
                logits, values = net.apply(params, obs)
                actions = jax.random.categorical(k_act, logits, axis=-1)
                logp = jnp.take_along_axis(
                    jax.nn.log_softmax(logits), actions[:, None],
                    axis=-1)[:, 0]
                env_state, next_obs, rewards, dones = env.step(
                    env_state, actions, k_env)
                traj = {"obs": obs, "actions": actions, "logp": logp,
                        "values": values, "rewards": rewards,
                        "dones": dones}
                return (env_state, next_obs), traj

            keys = jax.random.split(key, T)
            (env_state, obs), traj = jax.lax.scan(
                step_fn, (env_state, obs), keys)
            _, last_values = net.apply(params, obs)
            return env_state, obs, traj, last_values

        def gae(traj, last_values):
            def back(carry, xs):
                rewards, dones, values, next_values = xs
                not_done = 1.0 - dones.astype(jnp.float32)
                delta = rewards + gamma * next_values * not_done - values
                adv = delta + gamma * lambda_ * not_done * carry
                return adv, adv

            next_vals = jnp.concatenate(
                [traj["values"][1:], last_values[None]], axis=0)
            _, advs = jax.lax.scan(
                back, jnp.zeros(N, jnp.float32),
                (traj["rewards"], traj["dones"], traj["values"], next_vals),
                reverse=True)
            return advs, advs + traj["values"]

        def update(params, opt_state, flat, key):
            total = T * N
            mb_size = total // mb_count

            def epoch(carry, ekey):
                params, opt_state = carry
                perm = jax.random.permutation(ekey, total)[
                    : mb_size * mb_count]
                mbs = {k: v[perm].reshape((mb_count, mb_size) + v.shape[1:])
                       for k, v in flat.items()}

                def mb_body(carry, mb):
                    params, opt_state = carry
                    (loss, aux), grads = jax.value_and_grad(
                        ppo_loss, has_aux=True)(
                            params, mb, clip_param, 10.0, vf_loss_coeff,
                            entropy_coeff, net.apply)
                    updates, opt_state = self.optimizer.update(
                        grads, opt_state, params)
                    params = optax.apply_updates(params, updates)
                    return (params, opt_state), (loss, aux)

                (params, opt_state), (losses, auxs) = jax.lax.scan(
                    mb_body, (params, opt_state), mbs)
                return (params, opt_state), (losses[-1], jax.tree.map(
                    lambda a: a[-1], auxs))

            ekeys = jax.random.split(key, num_sgd_iter)
            (params, opt_state), (losses, auxs) = jax.lax.scan(
                epoch, (params, opt_state), ekeys)
            return params, opt_state, losses[-1], jax.tree.map(
                lambda a: a[-1], auxs)

        from .sample_batch import (ACTIONS, ADVANTAGES, LOGPS, OBS,
                                   VALUE_TARGETS)

        @jax.jit
        def iterate(params, opt_state, env_state, obs, key):
            k_roll, k_sgd = jax.random.split(key)
            env_state, obs, traj, last_values = rollout(
                params, env_state, obs, k_roll)
            advs, targets = gae(traj, last_values)
            flatten = lambda a: a.reshape((T * N,) + a.shape[2:])
            flat = {OBS: flatten(traj["obs"]),
                    ACTIONS: flatten(traj["actions"]),
                    LOGPS: flatten(traj["logp"]),
                    ADVANTAGES: flatten(advs),
                    VALUE_TARGETS: flatten(targets)}
            params, opt_state, loss, aux = update(
                params, opt_state, flat, k_sgd)
            dones_per_env = jnp.mean(
                traj["dones"].sum(0).astype(jnp.float32))
            metrics = {"total_loss": loss,
                       "mean_reward": jnp.mean(traj["rewards"]),
                       # episode terminations per env this rollout; the
                       # episode-length estimate divides T by it (clamped:
                       # 0 dones means episodes outlast the rollout).
                       "dones_per_env": dones_per_env,
                       "mean_episode_len": T / jnp.maximum(
                           dones_per_env, 1.0)}
            metrics.update(aux)
            return params, opt_state, env_state, obs, metrics

        self._iterate = iterate

    def train_iteration(self) -> Dict[str, float]:
        """One fused sample+learn cycle; returns host metrics."""
        self._rng, sub = jax.random.split(self._rng)
        self.params, self.opt_state, self.env_state, self._obs, metrics = (
            self._iterate(self.params, self.opt_state, self.env_state,
                          self._obs, sub))
        out = {k: float(v) for k, v in metrics.items()}
        out["timesteps_this_iter"] = self.rollout_length * self.env.num_envs
        return out
