"""CQL: conservative Q-learning for offline continuous control.

Reference analog: ``rllib/algorithms/cql/cql.py`` + ``cql_torch_policy.py``
(Kumar et al. 2020) — SAC's twin-Q learner trained purely from logged
data, with the CQL(H) conservative penalty pushing Q down on
out-of-distribution actions (logsumexp over random + policy actions)
and up on dataset actions, so the learned policy cannot exploit
erroneously optimistic Q estimates where the data has no coverage.

Reuses the SAC building blocks (`init_sac_params`, `sample_action`,
`_q`); the entire update (critics + penalty, actor with optional
behavior-cloning warmup, alpha, polyak) is one jit program.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from .algorithm import Algorithm, AlgorithmConfig
from .offline import JsonReader
from .sac import _q, actor_dist, init_sac_params, sample_action
from .sample_batch import ACTIONS, DONES, NEXT_OBS, OBS, REWARDS


class CQLConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self._algo_class = CQL
        self.input_path: Optional[str] = None  # JsonReader dir
        self.action_dim = 1
        self.action_low = -2.0
        self.action_high = 2.0
        self.lr = 3e-4
        self.train_batch_size = 256
        self.num_updates_per_iter = 64
        self.tau = 0.005
        self.min_q_weight = 5.0     # conservative penalty scale
        self.num_penalty_actions = 10
        self.bc_iters = 200         # actor warmup: pure behavior cloning
        self.initial_alpha = 0.2
        self.target_entropy: Optional[float] = None
        self.policy_hidden = (256, 256)

    def offline_data(self, input_path: str) -> "CQLConfig":
        self.input_path = input_path
        return self

    def training(self, **kwargs) -> "CQLConfig":
        for k in ("min_q_weight", "num_penalty_actions", "bc_iters",
                  "tau", "num_updates_per_iter", "initial_alpha",
                  "target_entropy"):
            if k in kwargs:
                setattr(self, k, kwargs.pop(k))
        super().training(**kwargs)
        return self


def cql_critic_loss(params, batch, key, cfg_static):
    """Twin-Q TD loss + CQL(H) penalty.

    penalty = logsumexp over {uniform-random, pi(s), pi(s')} actions of
    Q(s, a) (importance-corrected) minus Q(s, a_data); reference:
    cql_torch_policy.py cql_loss."""
    (adim, low, high, gamma, n_pen, min_q_w) = cfg_static
    obs, acts = batch[OBS], batch[ACTIONS]
    b = obs.shape[0]
    k_next, k_rand, k_pi, k_pin = jax.random.split(key, 4)

    # Standard SAC TD target from the polyak critics.
    next_a, next_logp = sample_action(params["actor"], batch[NEXT_OBS],
                                      k_next, adim, low, high)
    tq = jnp.minimum(
        _q(params["target_q1"], batch[NEXT_OBS], next_a),
        _q(params["target_q2"], batch[NEXT_OBS], next_a),
    )
    alpha = jnp.exp(params["log_alpha"])
    not_done = 1.0 - batch[DONES].astype(jnp.float32)
    target = batch[REWARDS] + gamma * not_done * (
        tq - alpha * next_logp)
    target = jax.lax.stop_gradient(target)
    q1_data = _q(params["q1"], obs, acts)
    q2_data = _q(params["q2"], obs, acts)
    td_loss = jnp.mean((q1_data - target) ** 2) + jnp.mean(
        (q2_data - target) ** 2)

    # --- CQL(H) penalty ---------------------------------------------------
    def tiled(o):
        return jnp.repeat(o, n_pen, axis=0)  # [B*N, d]

    rand_a = jax.random.uniform(k_rand, (b * n_pen, adim),
                                minval=low, maxval=high)
    # log density of the uniform proposal (importance correction)
    log_unif = -adim * jnp.log(high - low)
    pi_a, pi_logp = sample_action(params["actor"], tiled(obs), k_pi,
                                  adim, low, high)
    pin_a, pin_logp = sample_action(params["actor"],
                                    tiled(batch[NEXT_OBS]), k_pin,
                                    adim, low, high)
    pi_a = jax.lax.stop_gradient(pi_a)
    pin_a = jax.lax.stop_gradient(pin_a)

    def penalty(qp):
        q_rand = _q(qp, tiled(obs), rand_a).reshape(b, n_pen) - log_unif
        q_pi = (_q(qp, tiled(obs), pi_a).reshape(b, n_pen)
                - jax.lax.stop_gradient(pi_logp).reshape(b, n_pen))
        q_pin = (_q(qp, tiled(obs), pin_a).reshape(b, n_pen)
                 - jax.lax.stop_gradient(pin_logp).reshape(b, n_pen))
        cat = jnp.concatenate([q_rand, q_pi, q_pin], axis=1)
        return jnp.mean(jax.nn.logsumexp(cat, axis=1))

    cql1 = penalty(params["q1"]) - jnp.mean(q1_data)
    cql2 = penalty(params["q2"]) - jnp.mean(q2_data)
    total = td_loss + min_q_w * (cql1 + cql2)
    return total, {"td_loss": td_loss, "cql_penalty": cql1 + cql2,
                   "q_data_mean": jnp.mean(q1_data)}


def cql_actor_loss(actor, params, batch, key, bc_phase, cfg_static):
    """SAC actor objective after bc_iters; pure log-likelihood behavior
    cloning before (reference: cql.py bc_iters warmup)."""
    (adim, low, high, *_rest) = cfg_static
    a_pi, logp = sample_action(actor, batch[OBS], key, adim, low, high)
    alpha = jax.lax.stop_gradient(jnp.exp(params["log_alpha"]))
    q = jnp.minimum(_q(params["q1"], batch[OBS], a_pi),
                    _q(params["q2"], batch[OBS], a_pi))
    sac_obj = jnp.mean(alpha * logp - q)
    # BC: maximize the squashed-Gaussian mean's proximity to the data
    # action (an MSE surrogate for logp of the logged action).
    mean, _ = actor_dist(actor, batch[OBS], adim)
    scale = (high - low) / 2.0
    mean_act = low + (jnp.tanh(mean) + 1.0) * scale
    bc_obj = jnp.mean((mean_act - batch[ACTIONS]) ** 2)
    return jnp.where(bc_phase, bc_obj, sac_obj), logp


class CQL(Algorithm):
    """Fully offline: no rollout workers; data comes from JsonReader."""

    def __init__(self, config: CQLConfig):
        from ..core import runtime as runtime_mod

        runtime_mod.auto_init()
        self.config = config
        self.iteration = 0
        self._timesteps_total = 0
        self.setup(config)

    def setup(self, config: CQLConfig) -> None:
        if not config.input_path:
            raise ValueError("CQL needs config.offline_data(input_path)")
        batch = JsonReader(config.input_path).read_all()
        self._data = {
            OBS: np.asarray(batch[OBS], np.float32),
            ACTIONS: np.asarray(batch[ACTIONS], np.float32),
            REWARDS: np.asarray(batch[REWARDS], np.float32),
            NEXT_OBS: np.asarray(batch[NEXT_OBS], np.float32),
            DONES: np.asarray(batch[DONES]),
        }
        if self._data[ACTIONS].ndim == 1:
            self._data[ACTIONS] = self._data[ACTIONS][:, None]
        self._n = len(self._data[OBS])
        obs_dim = int(np.prod(self._data[OBS].shape[1:]))
        adim = config.action_dim
        self.params = init_sac_params(
            jax.random.PRNGKey(config.seed), obs_dim, adim,
            config.policy_hidden)
        self.params["log_alpha"] = jnp.asarray(
            np.log(config.initial_alpha))
        self._rng = jax.random.PRNGKey(config.seed + 1)
        self._np_rng = np.random.default_rng(config.seed + 2)
        self.critic_opt = optax.adam(config.lr)
        self.actor_opt = optax.adam(config.lr)
        self.alpha_opt = optax.adam(config.lr)
        critic_params = {k: self.params[k] for k in ("q1", "q2")}
        self.critic_state = self.critic_opt.init(critic_params)
        self.actor_state = self.actor_opt.init(self.params["actor"])
        self.alpha_state = self.alpha_opt.init(self.params["log_alpha"])
        target_entropy = (config.target_entropy
                          if config.target_entropy is not None
                          else -float(adim))
        cfg_static = (adim, config.action_low, config.action_high,
                      config.gamma, config.num_penalty_actions,
                      config.min_q_weight)
        tau = config.tau

        @jax.jit
        def update(params, copt, aopt, lopt, batch, key, bc_phase):
            k1, k2, k3 = jax.random.split(key, 3)
            critic_params = {"q1": params["q1"], "q2": params["q2"]}

            def critic_loss_fn(cp):
                p = dict(params)
                p.update(cp)
                return cql_critic_loss(p, batch, k1, cfg_static)

            (closs, caux), cgrads = jax.value_and_grad(
                critic_loss_fn, has_aux=True)(critic_params)
            cupd, copt = self.critic_opt.update(cgrads, copt,
                                                critic_params)
            critic_params = optax.apply_updates(critic_params, cupd)
            params = dict(params)
            params.update(critic_params)

            (aloss, logp), agrads = jax.value_and_grad(
                cql_actor_loss, has_aux=True)(
                params["actor"], params, batch, k2, bc_phase,
                cfg_static)
            aupd, aopt = self.actor_opt.update(agrads, aopt,
                                               params["actor"])
            params["actor"] = optax.apply_updates(params["actor"], aupd)

            def alpha_loss_fn(log_alpha):
                return -jnp.mean(jnp.exp(log_alpha) * jax.lax.
                                 stop_gradient(logp + target_entropy))

            lgrad = jax.grad(alpha_loss_fn)(params["log_alpha"])
            lupd, lopt = self.alpha_opt.update(lgrad, lopt,
                                               params["log_alpha"])
            params["log_alpha"] = optax.apply_updates(
                params["log_alpha"], lupd)

            for q in ("q1", "q2"):
                params[f"target_{q}"] = jax.tree.map(
                    lambda t, s: (1 - tau) * t + tau * s,
                    params[f"target_{q}"], params[q])
            return params, copt, aopt, lopt, {
                "critic_loss": closs, "actor_loss": aloss, **caux}

        self._update = update
        self._num_updates = 0

    def _sample_batch(self) -> Dict:
        idx = self._np_rng.integers(0, self._n,
                                    self.config.train_batch_size)
        return {k: jnp.asarray(v[idx]) for k, v in self._data.items()}

    def training_step(self) -> Dict:
        cfg: CQLConfig = self.config
        metrics = {}
        for _ in range(cfg.num_updates_per_iter):
            self._rng, sub = jax.random.split(self._rng)
            bc = jnp.asarray(self._num_updates < cfg.bc_iters)
            (self.params, self.critic_state, self.actor_state,
             self.alpha_state, metrics) = self._update(
                self.params, self.critic_state, self.actor_state,
                self.alpha_state, self._sample_batch(), sub, bc)
            self._num_updates += 1
        self._timesteps_total += (cfg.num_updates_per_iter
                                  * cfg.train_batch_size)
        return {k: float(v) for k, v in metrics.items()} | {
            "timesteps_this_iter": cfg.num_updates_per_iter
            * cfg.train_batch_size,
            "num_updates": self._num_updates,
        }

    def train(self) -> Dict:
        import time

        t0 = time.perf_counter()
        result = self.training_step()
        self.iteration += 1
        result.update({"training_iteration": self.iteration,
                       "timesteps_total": self._timesteps_total,
                       "time_this_iter_s": time.perf_counter() - t0})
        return result

    def q_values(self, obs: np.ndarray, actions: np.ndarray) -> np.ndarray:
        """min(Q1, Q2) — exposed for conservatism checks/eval."""
        obs = jnp.asarray(obs, jnp.float32)
        actions = jnp.asarray(actions, jnp.float32)
        return np.asarray(jnp.minimum(
            _q(self.params["q1"], obs, actions),
            _q(self.params["q2"], obs, actions)))

    def compute_single_action(self, obs: np.ndarray) -> np.ndarray:
        mean, _ = actor_dist(self.params["actor"],
                             jnp.asarray(obs, jnp.float32)[None],
                             self.config.action_dim)
        scale = (self.config.action_high - self.config.action_low) / 2.0
        act = self.config.action_low + (jnp.tanh(mean) + 1.0) * scale
        return np.asarray(act)[0]

    def get_state(self) -> Dict:
        return {"iteration": self.iteration,
                "timesteps_total": self._timesteps_total,
                "num_updates": self._num_updates,
                "params": jax.tree.map(np.asarray, self.params)}

    def set_state(self, state: Dict) -> None:
        self.iteration = state.get("iteration", 0)
        self._timesteps_total = state.get("timesteps_total", 0)
        self._num_updates = state.get("num_updates", 0)
        if "params" in state:
            self.params = jax.tree.map(jnp.asarray, state["params"])

    def stop(self) -> None:
        pass
