"""Algorithm base + AlgorithmConfig.

Reference analog: ``rllib/algorithms/algorithm.py:144`` (Algorithm extends
the Tune Trainable: ``setup`` :334 builds the WorkerSet, ``training_step``
:1161 is per-algorithm) and ``algorithm_config.py`` (fluent config).

The Algorithm here exposes the Trainable-style surface (train/save/restore)
and plugs into Tune via ``as_trainable``.
"""

from __future__ import annotations

import copy
import pickle
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ..core import get, kill, remote
from .rollout_worker import RolloutWorker
from .sample_batch import SampleBatch


class AlgorithmConfig:
    """Fluent config (reference: AlgorithmConfig.environment/rollouts/...)."""

    def __init__(self):
        self.env: Any = "FastCartPole"
        self.num_rollout_workers: int = 0
        self.num_envs_per_worker: int = 8
        self.rollout_fragment_length: int = 128
        self.gamma: float = 0.99
        self.lr: float = 3e-4
        self.train_batch_size: int = 2048
        self.seed: int = 0
        self.policy_hidden: tuple = (64, 64)
        # "auto" = conv (Nature CNN) for [H,W,C] frame obs, mlp otherwise
        self.policy_network: str = "auto"
        # Catalog model config (reference: config.model / MODEL_DEFAULTS):
        # fcnet_hiddens, use_lstm, lstm_cell_size, custom_model, ...
        self.model: Optional[Dict[str, Any]] = None
        # Algorithm-specific keys forwarded into every worker's
        # _make_policy cfg (e.g. TD3's explore_sigma).
        self.policy_config_extra: Dict[str, Any] = {}
        self.extra: Dict[str, Any] = {}

    def environment(self, env: Any = None, **kwargs) -> "AlgorithmConfig":
        if env is not None:
            self.env = env
        self.extra.update(kwargs)
        return self

    def rollouts(self, num_rollout_workers: Optional[int] = None,
                 num_envs_per_worker: Optional[int] = None,
                 rollout_fragment_length: Optional[int] = None
                 ) -> "AlgorithmConfig":
        if num_rollout_workers is not None:
            self.num_rollout_workers = num_rollout_workers
        if num_envs_per_worker is not None:
            self.num_envs_per_worker = num_envs_per_worker
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, lr: Optional[float] = None,
                 gamma: Optional[float] = None,
                 train_batch_size: Optional[int] = None,
                 model: Optional[Dict[str, Any]] = None,
                 **kwargs) -> "AlgorithmConfig":
        if lr is not None:
            self.lr = lr
        if gamma is not None:
            self.gamma = gamma
        if train_batch_size is not None:
            self.train_batch_size = train_batch_size
        if model is not None:
            self.model = model
        self.extra.update(kwargs)
        return self

    def debugging(self, seed: Optional[int] = None, **kwargs
                  ) -> "AlgorithmConfig":
        if seed is not None:
            self.seed = seed
        return self

    def copy(self) -> "AlgorithmConfig":
        return copy.deepcopy(self)

    def build(self) -> "Algorithm":
        algo_cls = getattr(self, "_algo_class", None)
        if algo_cls is None:
            raise ValueError("use a concrete config (e.g. PPOConfig)")
        return algo_cls(self)


class WorkerSet:
    """Learner-side view of the rollout actors.

    Reference: ``rllib/evaluation/worker_set.py`` — local worker +
    remote workers; ``sync_weights`` (:205) broadcasts learner weights.
    """

    def __init__(self, config: AlgorithmConfig, worker_cls=None):
        self.config = config
        worker_cls = worker_cls or RolloutWorker
        policy_cfg = {"hidden": config.policy_hidden,
                      "network": config.policy_network,
                      "model": config.model,
                      **config.policy_config_extra}
        self.local_worker = worker_cls(
            config.env, config.num_envs_per_worker,
            dict(policy_cfg), seed=config.seed,
        )
        self.remote_workers: List[Any] = []
        if config.num_rollout_workers > 0:
            remote_cls = remote(worker_cls)
            self.remote_workers = [
                remote_cls.options(num_cpus=1).remote(
                    config.env, config.num_envs_per_worker,
                    dict(policy_cfg),
                    seed=config.seed, worker_index=i + 1,
                )
                for i in range(config.num_rollout_workers)
            ]

    def foreach_worker(self, fn: Callable) -> List[Any]:
        """Apply fn to the local worker inline and to each remote worker
        via a __call__-style proxy method (reference:
        WorkerSet.foreach_worker)."""
        results = [fn(self.local_worker)]
        if self.remote_workers:
            results.extend(get([w.apply.remote(fn)
                                for w in self.remote_workers]))
        return results

    def sync_weights(self, weights: Dict) -> None:
        if self.remote_workers:
            from ..core import put

            ref = put(weights)  # one copy in the object store, N readers
            get([w.set_weights.remote(ref) for w in self.remote_workers])

    def sample(self, rollout_length: int) -> List[SampleBatch]:
        if self.remote_workers:
            return get([w.sample.remote(rollout_length)
                        for w in self.remote_workers])
        return [self.local_worker.sample(rollout_length)]

    def episode_stats(self) -> List[Dict]:
        if self.remote_workers:
            return get([w.episode_stats.remote()
                        for w in self.remote_workers])
        return [self.local_worker.episode_stats()]

    def stop(self) -> None:
        for w in self.remote_workers:
            try:
                kill(w)
            except Exception:
                pass


class Algorithm:
    """Trainable-style base (train/save/restore/stop)."""

    # Subclasses override to swap the rollout worker implementation
    # (e.g. DQN's transition-collecting worker).
    _worker_cls = RolloutWorker

    def __init__(self, config: AlgorithmConfig):
        from ..core import runtime as runtime_mod

        runtime_mod.auto_init()
        self.config = config
        self.iteration = 0
        self._timesteps_total = 0
        self.setup(config)

    def setup(self, config: AlgorithmConfig) -> None:
        self.workers = WorkerSet(config, worker_cls=type(self)._worker_cls)

    def training_step(self) -> Dict:
        raise NotImplementedError

    def train(self) -> Dict:
        """One training iteration (reference: Trainable.train -> step)."""
        t0 = time.perf_counter()
        result = self.training_step()
        self.iteration += 1
        elapsed = time.perf_counter() - t0
        stats = [s for s in self.workers.episode_stats()]
        rewards = [s["episode_reward_mean"] for s in stats
                   if s.get("episode_reward_mean") is not None]
        result.update({
            "training_iteration": self.iteration,
            "timesteps_total": self._timesteps_total,
            "time_this_iter_s": elapsed,
            "env_steps_per_sec": result.get("timesteps_this_iter", 0) / max(
                elapsed, 1e-9),
        })
        if rewards:
            result["episode_reward_mean"] = float(sum(rewards) / len(rewards))
        return result

    def save(self, path: str) -> str:
        import os

        os.makedirs(path, exist_ok=True)
        state = self.get_state()
        file = os.path.join(path, "algorithm_state.pkl")
        with open(file, "wb") as f:
            pickle.dump(state, f)
        return file

    def restore(self, path: str) -> None:
        import os

        file = (path if path.endswith(".pkl")
                else os.path.join(path, "algorithm_state.pkl"))
        with open(file, "rb") as f:
            state = pickle.load(f)
        self.set_state(state)

    def get_state(self) -> Dict:
        state = {"iteration": self.iteration,
                 "timesteps_total": self._timesteps_total}
        try:
            state["connectors"] = \
                self.workers.local_worker.connector_state()
        except Exception:
            # Lambda connectors are explicitly non-serializable; the
            # rest of the checkpoint still saves.
            pass
        return state

    def set_state(self, state: Dict) -> None:
        self.iteration = state.get("iteration", 0)
        self._timesteps_total = state.get("timesteps_total", 0)
        conn = state.get("connectors")
        if conn is not None:
            self.workers.foreach_worker(
                lambda w: w.restore_connector_state(conn))

    def stop(self) -> None:
        self.workers.stop()

    @classmethod
    def as_trainable(cls, base_config: AlgorithmConfig,
                     stop_iters: int = 10) -> Callable:
        """Adapt to the Tune layer (Algorithm IS a Trainable in the
        reference; here a function trainable wraps the step loop)."""

        def trainable(tune_config: Dict):
            from ..tune import report

            config = base_config.copy()
            for k, v in tune_config.items():
                setattr(config, k, v)
            algo = cls(config)
            try:
                for _ in range(stop_iters):
                    report(algo.train())
            finally:
                algo.stop()

        return trainable
