"""APPO: asynchronous PPO — IMPALA's actor-learner pipeline with PPO's
clipped surrogate objective over V-trace-corrected advantages.

Reference analog: ``rllib/algorithms/appo/`` — APPO extends IMPALA
(``appo.py`` subclasses Impala) replacing the plain policy-gradient term
with the clipped surrogate so stale (lagged) rollouts can't push the
policy arbitrarily far.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

import functools

from .impala import Impala, ImpalaConfig, forward_feedforward, vtrace
from .policy import forward_mlp
from .sample_batch import ACTIONS, DONES, LOGPS, REWARDS


def appo_loss(params, batch, gamma, vf_coeff, ent_coeff, clip_param,
              apply_fn=forward_mlp, forward=None):
    """IMPALA loss with the PPO clipped surrogate on V-trace advantages."""
    if forward is None:
        forward = functools.partial(forward_feedforward, apply_fn=apply_fn)
    logp_all, values, bootstrap = forward(params, batch)
    actions = batch[ACTIONS].astype(jnp.int32)
    target_logp = jnp.take_along_axis(
        logp_all, actions[..., None], axis=-1)[..., 0]

    vs, pg_adv = vtrace(batch[LOGPS], target_logp, batch[REWARDS],
                        batch[DONES], values, bootstrap, gamma)
    ratio = jnp.exp(target_logp - batch[LOGPS])
    surr = jnp.minimum(
        ratio * pg_adv,
        jnp.clip(ratio, 1.0 - clip_param, 1.0 + clip_param) * pg_adv)
    pg_loss = -jnp.mean(surr)
    vf_loss = 0.5 * jnp.mean((values - vs) ** 2)
    entropy = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
    loss = pg_loss + vf_coeff * vf_loss - ent_coeff * entropy
    return loss, {"pg_loss": pg_loss, "vf_loss": vf_loss,
                  "entropy": entropy}


class APPOConfig(ImpalaConfig):
    def __init__(self):
        super().__init__()
        self._algo_class = APPO
        self.clip_param = 0.2

    def training(self, **kwargs) -> "APPOConfig":
        if "clip_param" in kwargs:
            self.clip_param = kwargs.pop("clip_param")
        super().training(**kwargs)
        return self


class APPO(Impala):
    """Same async pipeline as Impala; only the jitted update differs."""

    def setup(self, config: APPOConfig) -> None:
        import optax

        super().setup(config)
        gamma = config.gamma
        vf_coeff, ent_coeff = config.vf_coeff, config.entropy_coeff
        clip_param = config.clip_param
        forward = self._make_forward()  # recurrent-aware (Impala)

        @jax.jit
        def update(params, opt_state, batch):
            (loss, metrics), grads = jax.value_and_grad(
                appo_loss, has_aux=True)(
                    params, batch, gamma, vf_coeff, ent_coeff,
                    clip_param, forward=forward)
            updates, opt_state = self.optimizer.update(grads, opt_state,
                                                       params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, metrics

        self._update = update
