"""Contextual bandits: LinUCB and linear Thompson sampling.

Reference analog: ``rllib/algorithms/bandit/bandit.py`` +
``bandit_torch_model.py`` (DiscreteLinearModelUCB /
DiscreteLinearModelThompsonSampling) — per-arm ridge regression
posteriors updated online; exploration via UCB bonus or posterior
sampling. Pure closed-form linear algebra (Sherman-Morrison rank-1
precision updates), no gradient loop.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np


class _LinearArm:
    """Ridge posterior for one arm: A = lam*I + sum(x x^T),
    b = sum(r x); theta = A^-1 b. A_inv maintained by Sherman-Morrison
    (reference: bandit_torch_model.py OnlineLinearRegression)."""

    def __init__(self, dim: int, lam: float = 1.0):
        self.dim = dim
        self.a_inv = np.eye(dim, dtype=np.float64) / lam
        self.b = np.zeros(dim, np.float64)
        self.theta = np.zeros(dim, np.float64)
        self.count = 0

    def update(self, x: np.ndarray, reward: float) -> None:
        x = np.asarray(x, np.float64)
        av = self.a_inv @ x
        self.a_inv -= np.outer(av, av) / (1.0 + x @ av)
        self.b += reward * x
        self.theta = self.a_inv @ self.b
        self.count += 1

    def ucb(self, x: np.ndarray, alpha: float) -> float:
        x = np.asarray(x, np.float64)
        return float(self.theta @ x
                     + alpha * np.sqrt(max(x @ self.a_inv @ x, 0.0)))

    def sample(self, x: np.ndarray, rng: np.random.Generator,
               nu: float) -> float:
        x = np.asarray(x, np.float64)
        theta_s = rng.multivariate_normal(
            self.theta, nu ** 2 * self.a_inv, method="cholesky")
        return float(theta_s @ x)


class LinUCB:
    """Disjoint LinUCB (Li et al. 2010): pick the arm maximizing
    theta_a^T x + alpha * sqrt(x^T A_a^-1 x)."""

    def __init__(self, num_arms: int, context_dim: int,
                 alpha: float = 1.0, lam: float = 1.0):
        self.arms = [_LinearArm(context_dim, lam)
                     for _ in range(num_arms)]
        self.alpha = alpha

    def select_arm(self, context: np.ndarray) -> int:
        scores = [arm.ucb(context, self.alpha) for arm in self.arms]
        return int(np.argmax(scores))

    def update(self, context: np.ndarray, arm: int,
               reward: float) -> None:
        self.arms[arm].update(context, reward)


class LinTS:
    """Linear Thompson sampling: sample theta_a ~ N(theta_a, nu^2
    A_a^-1), pick argmax theta_s^T x (Agrawal & Goyal 2013)."""

    def __init__(self, num_arms: int, context_dim: int, nu: float = 0.5,
                 lam: float = 1.0, seed: Optional[int] = None):
        self.arms = [_LinearArm(context_dim, lam)
                     for _ in range(num_arms)]
        self.nu = nu
        self.rng = np.random.default_rng(seed)

    def select_arm(self, context: np.ndarray) -> int:
        scores = [arm.sample(context, self.rng, self.nu)
                  for arm in self.arms]
        return int(np.argmax(scores))

    def update(self, context: np.ndarray, arm: int,
               reward: float) -> None:
        self.arms[arm].update(context, reward)


class BanditEnv:
    """Linear contextual bandit environment for tests/benchmarks
    (reference: rllib/examples/env/bandit_envs_discrete.py)."""

    def __init__(self, num_arms: int = 4, context_dim: int = 8,
                 noise: float = 0.1, seed: int = 0):
        self.rng = np.random.default_rng(seed)
        self.theta = self.rng.normal(size=(num_arms, context_dim))
        self.theta /= np.linalg.norm(self.theta, axis=1, keepdims=True)
        self.noise = noise
        self.context_dim = context_dim
        self.num_arms = num_arms

    def observe(self) -> np.ndarray:
        x = self.rng.normal(size=self.context_dim)
        return x / np.linalg.norm(x)

    def pull(self, context: np.ndarray, arm: int) -> Tuple[float, float]:
        """-> (reward, regret vs best arm)."""
        means = self.theta @ context
        r = float(means[arm] + self.rng.normal() * self.noise)
        return r, float(means.max() - means[arm])


def run_bandit(policy, env: BanditEnv, steps: int) -> Dict:
    """Online loop: observe -> select -> reward -> update; returns
    cumulative regret curve (the bandit figure of merit)."""
    regrets = np.zeros(steps)
    for t in range(steps):
        x = env.observe()
        arm = policy.select_arm(x)
        r, regret = env.pull(x, arm)
        policy.update(x, arm, r)
        regrets[t] = regret
    return {"cumulative_regret": float(regrets.sum()),
            "regret_curve": np.cumsum(regrets),
            "final_window_regret": float(regrets[-steps // 10:].mean())}
