"""SAC: soft actor-critic for continuous action spaces on a JAX learner.

Reference analog: ``rllib/algorithms/sac/sac.py:23,280`` (SACConfig/SAC)
and ``sac_torch_policy.py`` (twin Q networks, tanh-squashed Gaussian
actor, entropy temperature autotuning) — re-founded on JAX: the actor,
both critics, their polyak targets, and log_alpha live in one param
pytree, and the whole update (critic step, actor step, alpha step,
target polyak) is a single jit-compiled program on the learner device.
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.common import truncated_normal
from .algorithm import Algorithm, AlgorithmConfig
from .replay_buffers import ReplayBuffer
from .rollout_worker import RolloutWorker
from .sample_batch import (
    ACTIONS,
    DONES,
    NEXT_OBS,
    OBS,
    REWARDS,
    SampleBatch,
)

LOG_STD_MIN, LOG_STD_MAX = -20.0, 2.0


def _init_mlp(key, sizes, out_dim: int, out_std: float = 0.01) -> Dict:
    params = {}
    keys = jax.random.split(key, len(sizes) + 1)
    for i in range(len(sizes) - 1):
        std = float(np.sqrt(2.0 / sizes[i]))
        params[f"t{i}_w"] = truncated_normal(
            keys[i], (sizes[i], sizes[i + 1]), stddev=std)
        params[f"t{i}_b"] = jnp.zeros((sizes[i + 1],))
    params["out_w"] = truncated_normal(keys[-1], (sizes[-1], out_dim),
                                       stddev=out_std)
    params["out_b"] = jnp.zeros((out_dim,))
    return params


def _mlp(params: Dict, x: jnp.ndarray) -> jnp.ndarray:
    i = 0
    while f"t{i}_w" in params:
        x = jax.nn.relu(x @ params[f"t{i}_w"] + params[f"t{i}_b"])
        i += 1
    return x @ params["out_w"] + params["out_b"]


def init_sac_params(key, obs_dim: int, action_dim: int,
                    hidden=(256, 256)) -> Dict:
    """Actor + twin critics + their polyak targets + log_alpha."""
    ka, k1, k2 = jax.random.split(key, 3)
    sizes = [obs_dim] + list(hidden)
    qsizes = [obs_dim + action_dim] + list(hidden)
    q1 = _init_mlp(k1, qsizes, 1, out_std=0.1)
    q2 = _init_mlp(k2, qsizes, 1, out_std=0.1)
    return {
        "actor": _init_mlp(ka, sizes, 2 * action_dim),
        "q1": q1, "q2": q2,
        "target_q1": jax.tree.map(jnp.copy, q1),
        "target_q2": jax.tree.map(jnp.copy, q2),
        "log_alpha": jnp.zeros(()),
    }


def actor_dist(actor: Dict, obs: jnp.ndarray, action_dim: int):
    out = _mlp(actor, obs.astype(jnp.float32))
    mean, log_std = out[..., :action_dim], out[..., action_dim:]
    return mean, jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX)


def sample_action(actor: Dict, obs, key, action_dim: int, low, high):
    """Reparameterized tanh-squashed Gaussian sample -> (action, logp).

    logp includes the tanh change-of-variables correction
    (sac_torch_policy: SquashedGaussian.logp).
    """
    mean, log_std = actor_dist(actor, obs, action_dim)
    std = jnp.exp(log_std)
    eps = jax.random.normal(key, mean.shape)
    pre_tanh = mean + std * eps
    tanh_a = jnp.tanh(pre_tanh)
    # N(mean, std) log-density of pre_tanh
    logp = -0.5 * (eps ** 2 + 2 * log_std + jnp.log(2 * jnp.pi))
    # d tanh / dx correction, numerically stable form
    logp = logp - 2.0 * (jnp.log(2.0) - pre_tanh
                         - jax.nn.softplus(-2.0 * pre_tanh))
    logp = jnp.sum(logp, axis=-1)
    scale = (high - low) / 2.0
    action = low + (tanh_a + 1.0) * scale
    # affine rescale: logp -= sum(log scale)
    logp = logp - jnp.sum(jnp.log(scale) * jnp.ones_like(tanh_a), axis=-1)
    return action, logp


def _q(params: Dict, obs, act) -> jnp.ndarray:
    x = jnp.concatenate([obs.astype(jnp.float32),
                         act.astype(jnp.float32)], axis=-1)
    return _mlp(params, x)[..., 0]


class SACPolicy:
    """Stochastic tanh-Gaussian policy for rollouts (CPU-jit)."""

    def __init__(self, obs_shape: Tuple[int, ...], action_dim: int,
                 low: float, high: float, hidden=(256, 256), seed: int = 0):
        self.obs_dim = int(np.prod(obs_shape))
        self.action_dim = action_dim
        self.low, self.high = float(low), float(high)
        self.params = init_sac_params(
            jax.random.PRNGKey(seed), self.obs_dim, action_dim, hidden)
        self._key = jax.random.PRNGKey(seed + 1)
        adim = action_dim

        @jax.jit
        def _sample(actor, obs, key):
            return sample_action(actor, obs, key, adim,
                                 self.low, self.high)

        @jax.jit
        def _mean_act(actor, obs):
            mean, _ = actor_dist(actor, obs, adim)
            scale = (self.high - self.low) / 2.0
            return self.low + (jnp.tanh(mean) + 1.0) * scale

        self._sample = _sample
        self._mean_act = _mean_act

    def compute_actions(self, obs: np.ndarray, deterministic: bool = False):
        obs = np.asarray(obs, np.float32).reshape(len(obs), -1)
        if deterministic:
            actions = np.asarray(self._mean_act(
                self.params["actor"], jnp.asarray(obs)))
            logp = np.zeros(len(obs), np.float32)
        else:
            self._key, sub = jax.random.split(self._key)
            a, lp = self._sample(self.params["actor"], jnp.asarray(obs), sub)
            actions, logp = np.asarray(a), np.asarray(lp, np.float32)
        zeros = np.zeros(len(obs), np.float32)
        return actions.astype(np.float32), logp, zeros

    def get_weights(self) -> Dict:
        return jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights: Dict) -> None:
        self.params = jax.tree.map(jnp.asarray, weights)


class SACRolloutWorker(RolloutWorker):
    """Collects flat (s, a, r, s', done) transitions with FLOAT actions
    (the DQN worker's layout, continuous actions)."""

    def _make_policy(self, cfg: Dict, seed: int):
        return SACPolicy(
            self._connected_obs_shape, self.env.action_dim,
            self.env.action_low, self.env.action_high,
            hidden=cfg.get("hidden", (256, 256)), seed=seed,
        )

    def sample(self, rollout_length: int = 64) -> SampleBatch:
        n = self.env.num_envs
        shape = self._connected_obs_shape
        adim = self.env.action_dim
        obs_buf = np.empty((rollout_length, n) + shape, np.float32)
        nobs_buf = np.empty((rollout_length, n) + shape, np.float32)
        act_buf = np.empty((rollout_length, n, adim), np.float32)
        rew_buf = np.empty((rollout_length, n), np.float32)
        done_buf = np.empty((rollout_length, n), bool)
        for t in range(rollout_length):
            actions, _, _ = self.policy.compute_actions(self._obs)
            obs_buf[t] = self._obs
            act_buf[t] = actions.reshape(n, adim)
            next_obs, rewards, dones, _ = self._step_env(actions)
            nobs_buf[t] = next_obs
            rew_buf[t] = rewards
            done_buf[t] = dones
            self._obs = next_obs
        flat = lambda a: a.reshape((-1,) + a.shape[2:])
        return SampleBatch({
            OBS: flat(obs_buf), ACTIONS: flat(act_buf),
            REWARDS: flat(rew_buf), DONES: flat(done_buf),
            NEXT_OBS: flat(nobs_buf),
        })


class SACConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self._algo_class = SAC
        self.env = "FastPendulum"
        self.lr = 3e-4
        self.rollout_fragment_length = 8
        self.train_batch_size = 256
        self.buffer_capacity = 100_000
        self.learning_starts = 1_000
        self.tau = 0.005  # polyak target rate
        self.num_updates_per_iter = 32
        self.initial_alpha = 1.0
        self.target_entropy: float = None  # default: -action_dim
        self.policy_hidden = (256, 256)

    def training(self, **kwargs) -> "SACConfig":
        for k in ("buffer_capacity", "learning_starts", "tau",
                  "num_updates_per_iter", "initial_alpha",
                  "target_entropy"):
            if k in kwargs:
                setattr(self, k, kwargs.pop(k))
        super().training(**kwargs)
        return self


class SAC(Algorithm):
    """training_step: sample -> replay add -> K jit updates -> sync.

    One jit program per update: critic step (twin-Q TD toward the soft
    target), actor step (reparameterized, maximizing Q - alpha*logp),
    alpha step (toward target entropy), polyak target update.
    Reference: ``sac.py SAC.training_step`` (:280).
    """

    _worker_cls = SACRolloutWorker

    def setup(self, config: SACConfig) -> None:
        import optax

        super().setup(config)
        env = self.workers.local_worker.env
        self.action_dim = env.action_dim
        low, high = float(env.action_low), float(env.action_high)
        self.buffer = ReplayBuffer(config.buffer_capacity, seed=config.seed)
        self.params = self.workers.local_worker.policy.params
        if config.initial_alpha != 1.0:
            self.params["log_alpha"] = jnp.asarray(
                np.log(config.initial_alpha), jnp.float32)
        target_entropy = (config.target_entropy
                          if config.target_entropy is not None
                          else -float(self.action_dim))
        self.optimizer = optax.adam(config.lr)
        self.opt_state = self.optimizer.init(
            {"actor": self.params["actor"], "q1": self.params["q1"],
             "q2": self.params["q2"],
             "log_alpha": self.params["log_alpha"]})
        self._num_updates = 0
        gamma, tau, adim = config.gamma, config.tau, self.action_dim
        optimizer = self.optimizer

        def losses(train_params, target_q1, target_q2, batch, key):
            actor = train_params["actor"]
            alpha = jax.lax.stop_gradient(
                jnp.exp(train_params["log_alpha"]))
            k1, k2 = jax.random.split(key)
            # -- critic loss: soft Bellman target from the CURRENT actor
            next_a, next_logp = sample_action(
                jax.lax.stop_gradient(actor), batch[NEXT_OBS], k1, adim,
                low, high)
            tq = jnp.minimum(_q(target_q1, batch[NEXT_OBS], next_a),
                             _q(target_q2, batch[NEXT_OBS], next_a))
            not_done = 1.0 - batch[DONES].astype(jnp.float32)
            target = batch[REWARDS] + gamma * not_done * (
                tq - alpha * next_logp)
            target = jax.lax.stop_gradient(target)
            q1 = _q(train_params["q1"], batch[OBS], batch[ACTIONS])
            q2 = _q(train_params["q2"], batch[OBS], batch[ACTIONS])
            critic_loss = jnp.mean((q1 - target) ** 2) + jnp.mean(
                (q2 - target) ** 2)
            # -- actor loss: maximize E[min Q - alpha logp] (reparam)
            a, logp = sample_action(actor, batch[OBS], k2, adim, low, high)
            q_pi = jnp.minimum(
                _q(jax.lax.stop_gradient(train_params["q1"]),
                   batch[OBS], a),
                _q(jax.lax.stop_gradient(train_params["q2"]),
                   batch[OBS], a))
            actor_loss = jnp.mean(alpha * logp - q_pi)
            # -- temperature loss: autotune toward target entropy
            alpha_loss = -jnp.mean(
                train_params["log_alpha"]
                * jax.lax.stop_gradient(logp + target_entropy))
            total = critic_loss + actor_loss + alpha_loss
            return total, {"critic_loss": critic_loss,
                           "actor_loss": actor_loss,
                           "alpha": alpha,
                           "entropy": -jnp.mean(logp)}

        @jax.jit
        def update(params, opt_state, batch, key):
            train = {"actor": params["actor"], "q1": params["q1"],
                     "q2": params["q2"], "log_alpha": params["log_alpha"]}
            grads, aux = jax.grad(losses, has_aux=True)(
                train, params["target_q1"], params["target_q2"], batch,
                key)
            updates, opt_state = optimizer.update(grads, opt_state, train)
            train = optax.apply_updates(train, updates)
            new = dict(train)
            polyak = lambda t, o: jax.tree.map(
                lambda a, b: (1 - tau) * a + tau * b, t, o)
            new["target_q1"] = polyak(params["target_q1"], train["q1"])
            new["target_q2"] = polyak(params["target_q2"], train["q2"])
            return new, opt_state, aux

        self._update = update
        self._key = jax.random.PRNGKey(config.seed + 17)

    def training_step(self) -> Dict:
        cfg = self.config
        batches = self.workers.sample(cfg.rollout_fragment_length)
        new_steps = 0
        for b in batches:
            self.buffer.add(b)
            new_steps += b.count
        self._timesteps_total += new_steps

        aux_out = {}
        if len(self.buffer) >= cfg.learning_starts:
            for _ in range(cfg.num_updates_per_iter):
                batch = self.buffer.sample(cfg.train_batch_size)
                jbatch = {k: jnp.asarray(v) for k, v in batch.items()
                          if k != "batch_indexes"}
                self._key, sub = jax.random.split(self._key)
                self.params, self.opt_state, aux = self._update(
                    self.params, self.opt_state, jbatch, sub)
                self._num_updates += 1
            aux_out = {k: float(v) for k, v in aux.items()}
            weights = jax.tree.map(np.asarray, self.params)
            self.workers.local_worker.set_weights(weights)
            self.workers.sync_weights(weights)

        return {
            "timesteps_this_iter": new_steps,
            "num_learner_updates": self._num_updates,
            "replay_buffer_size": len(self.buffer),
            **aux_out,
        }

    def get_state(self) -> Dict:
        state = super().get_state()
        state.update({
            "params": jax.tree.map(np.asarray, self.params),
            "num_updates": self._num_updates,
            "opt_state": jax.tree.map(np.asarray, self.opt_state),
            "rng_key": np.asarray(self._key),
        })
        return state

    def set_state(self, state: Dict) -> None:
        super().set_state(state)
        if "params" in state:
            self.params = jax.tree.map(jnp.asarray, state["params"])
            self._num_updates = state.get("num_updates", 0)
            weights = jax.tree.map(np.asarray, self.params)
            self.workers.local_worker.set_weights(weights)
            self.workers.sync_weights(weights)
        if "opt_state" in state:
            # A zeroed Adam state after resume causes a loss spike.
            self.opt_state = jax.tree.map(jnp.asarray, state["opt_state"])
        if "rng_key" in state:
            self._key = jnp.asarray(state["rng_key"])
