"""PPO: clipped-surrogate policy optimization with a JAX learner.

Reference analog: ``rllib/algorithms/ppo/ppo.py:47,289,401`` —
``training_step`` = synchronous_parallel_sample → train_one_step →
sync_weights (SURVEY §3.6). TPU re-design: the whole SGD phase (epochs x
minibatches of the clipped surrogate + value + entropy loss) is ONE
jit-compiled program (``lax.scan`` over minibatches inside ``lax.scan``
over epochs) running on the accelerator; rollouts stay on CPU actors.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from .algorithm import Algorithm, AlgorithmConfig
from .policy import forward_mlp
from .sample_batch import (
    ACTIONS,
    ADVANTAGES,
    DONES,
    LOGPS,
    OBS,
    STATE_IN,
    VALUE_TARGETS,
    SampleBatch,
    compute_gae,
    flatten_time_major,
)


class PPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self._algo_class = PPO
        self.clip_param = 0.2
        self.vf_clip_param = 10.0
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.num_sgd_iter = 8
        self.sgd_minibatch_size = 256
        self.lambda_ = 0.95
        self.grad_clip = 0.5

    def training(self, clip_param=None, vf_loss_coeff=None,
                 entropy_coeff=None, num_sgd_iter=None,
                 sgd_minibatch_size=None, lambda_=None, **kwargs
                 ) -> "PPOConfig":
        super().training(**kwargs)
        for name, val in [("clip_param", clip_param),
                          ("vf_loss_coeff", vf_loss_coeff),
                          ("entropy_coeff", entropy_coeff),
                          ("num_sgd_iter", num_sgd_iter),
                          ("sgd_minibatch_size", sgd_minibatch_size),
                          ("lambda_", lambda_)]:
            if val is not None:
                setattr(self, name, val)
        return self


def ppo_loss(params, batch, clip_param, vf_clip, vf_coeff, ent_coeff,
             apply_fn=forward_mlp, batch_apply=None):
    """``batch_apply(params, batch) -> (logits, values)`` supersedes
    ``apply_fn`` when set (recurrent nets need DONES from the batch to
    reset state mid-sequence); arrays may carry any leading dims
    ([B] flat or [T, B_seq] sequence-major)."""
    if batch_apply is not None:
        logits, values = batch_apply(params, batch)
    else:
        logits, values = apply_fn(params, batch[OBS])
    logp_all = jax.nn.log_softmax(logits)
    actions = batch[ACTIONS].astype(jnp.int32)
    logp = jnp.take_along_axis(logp_all, actions[..., None],
                               axis=-1)[..., 0]
    ratio = jnp.exp(logp - batch[LOGPS])
    adv = batch[ADVANTAGES]
    adv = (adv - adv.mean()) / (adv.std() + 1e-8)
    surrogate = jnp.minimum(
        ratio * adv,
        jnp.clip(ratio, 1 - clip_param, 1 + clip_param) * adv,
    )
    policy_loss = -jnp.mean(surrogate)
    vf_err = jnp.clip(values - batch[VALUE_TARGETS], -vf_clip, vf_clip)
    vf_loss = jnp.mean(vf_err ** 2)
    entropy = -jnp.mean(
        jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1)
    )
    total = policy_loss + vf_coeff * vf_loss - ent_coeff * entropy
    return total, {
        "policy_loss": policy_loss, "vf_loss": vf_loss, "entropy": entropy,
        "kl": jnp.mean(batch[LOGPS] - logp),
    }


def _build_sgd_scan(config: PPOConfig, optimizer, make_minibatches,
                    num_items, loss_kwargs_fn):
    """Shared SGD driver: epochs x minibatches as nested ``lax.scan`` —
    no per-minibatch dispatch from the host. The flat and recurrent
    updates differ only in how a permutation slices the batch into
    minibatches (``make_minibatches``) and how the loss applies the
    network (``loss_kwargs_fn``)."""
    clip, vfc, vco, eco = (config.clip_param, config.vf_clip_param,
                           config.vf_loss_coeff, config.entropy_coeff)
    epochs = config.num_sgd_iter

    @jax.jit
    def update(params, opt_state, batch, rng):
        n = num_items(batch)

        def epoch_body(carry, epoch_rng):
            params, opt_state = carry
            perm = jax.random.permutation(epoch_rng, n)
            mbs = make_minibatches(batch, perm)

            def mb_body(carry, mb):
                params, opt_state = carry
                (loss, aux), grads = jax.value_and_grad(
                    ppo_loss, has_aux=True
                )(params, mb, clip, vfc, vco, eco, **loss_kwargs_fn())
                updates, opt_state = optimizer.update(grads, opt_state,
                                                      params)
                params = optax.apply_updates(params, updates)
                return (params, opt_state), (loss, aux)

            (params, opt_state), (losses, auxs) = jax.lax.scan(
                mb_body, (params, opt_state), mbs
            )
            return (params, opt_state), (losses[-1], jax.tree.map(
                lambda a: a[-1], auxs))

        rngs = jax.random.split(rng, epochs)
        (params, opt_state), (losses, auxs) = jax.lax.scan(
            epoch_body, (params, opt_state), rngs
        )
        metrics = {"total_loss": losses[-1]}
        metrics.update({k: v[-1] for k, v in auxs.items()})
        return params, opt_state, metrics

    return update


def build_ppo_update(config: PPOConfig, optimizer, apply_fn=forward_mlp):
    """Flat-batch PPO update: minibatches are row slices of [B, ...]."""
    mb_size = config.sgd_minibatch_size

    def make_minibatches(batch, perm):
        n = batch[OBS].shape[0]
        num_mb = max(1, n // mb_size)
        usable = num_mb * mb_size
        shuffled = {k: v[perm[:usable]] for k, v in batch.items()}
        return {
            k: v.reshape((num_mb, mb_size) + v.shape[1:])
            for k, v in shuffled.items()
        }

    return _build_sgd_scan(
        config, optimizer, make_minibatches,
        num_items=lambda batch: batch[OBS].shape[0],
        loss_kwargs_fn=lambda: {"apply_fn": apply_fn})


def build_ppo_update_recurrent(config: PPOConfig, optimizer, net):
    """Recurrent PPO: batch arrays are SEQUENCE-MAJOR [T, N, ...] plus
    STATE_IN [S, N, cell]; minibatches are whole sequences (N axis), and
    the loss recomputes logits by scanning the recurrent cell over T
    from the SAME state the behavior policy had at fragment start
    (shipped by the rollout worker), resetting at episode boundaries
    (reference: state_in handling in
    ``rllib/policy/rnn_sequencing.py``)."""
    apply_state = net.apply_state
    mb_size = config.sgd_minibatch_size

    def seq_apply(params, batch):
        obs, dones = batch[OBS], batch[DONES]

        def step(state, xs):
            obs_t, done_t = xs
            logits, values, new_state = apply_state(params, obs_t, state)
            mask = (1.0 - done_t.astype(jnp.float32))[:, None]
            new_state = tuple(s * mask for s in new_state)
            return new_state, (logits, values)

        state0 = tuple(batch[STATE_IN][i]
                       for i in range(batch[STATE_IN].shape[0]))
        _, (logits, values) = jax.lax.scan(step, state0, (obs, dones))
        return logits, values  # [T, n_seq, A], [T, n_seq]

    def make_minibatches(batch, perm):
        t = batch[OBS].shape[0]
        n = batch[OBS].shape[1]
        mb = max(1, min(max(1, mb_size // t), n))
        num_mb = max(1, n // mb)
        usable = num_mb * mb
        out = {}
        for k, v in batch.items():
            # Sequence axis: 1 for [T, N, ...] arrays AND [S, N, cell]
            # state; reshape the seq axis into (num_mb, mb) and move
            # num_mb to the front for the scan.
            sliced = v[:, perm[:usable]]
            lead = sliced.shape[0]
            out[k] = jnp.moveaxis(
                sliced.reshape((lead, num_mb, mb) + sliced.shape[2:]),
                1, 0)
        return out

    return _build_sgd_scan(
        config, optimizer, make_minibatches,
        num_items=lambda batch: batch[OBS].shape[1],
        loss_kwargs_fn=lambda: {"apply_fn": None,
                                "batch_apply": seq_apply})


class PPO(Algorithm):
    def setup(self, config: PPOConfig) -> None:
        super().setup(config)
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(config.grad_clip),
            optax.adam(config.lr),
        )
        # Learner-side copy of the policy params lives on the accelerator.
        self.params = jax.tree.map(
            jnp.asarray, self.workers.local_worker.policy.params
        )
        self.opt_state = self.optimizer.init(self.params)
        net = self.workers.local_worker.policy.net
        self._recurrent = net.is_recurrent
        if self._recurrent:
            self._update = build_ppo_update_recurrent(
                config, self.optimizer, net)
        else:
            self._update = build_ppo_update(config, self.optimizer,
                                            net.apply)
        self._rng = jax.random.PRNGKey(config.seed)
        self.workers.sync_weights(jax.tree.map(np.asarray, self.params))

    def training_step(self) -> Dict:
        """sample -> GAE -> compiled SGD -> weight broadcast (SURVEY §3.6)."""
        cfg: PPOConfig = self.config
        fragments = self.workers.sample(cfg.rollout_fragment_length)
        processed = []
        for frag in fragments:
            last_values = frag.pop("last_values")
            frag.pop("final_obs", None)  # IMPALA-only bootstrap column
            frag = compute_gae(frag, last_values, cfg.gamma, cfg.lambda_)
            if not self._recurrent:
                frag = flatten_time_major(frag)
            processed.append(frag)
        if self._recurrent:
            # Sequence-major [T, N] (+ STATE_IN [S, N, cell]): concat
            # fragments along the env axis.
            keys = (OBS, ACTIONS, LOGPS, ADVANTAGES, VALUE_TARGETS,
                    DONES, STATE_IN)
            device_batch = {
                k: jnp.asarray(np.concatenate(
                    [np.asarray(f[k]) for f in processed], axis=1))
                for k in keys
            }
            steps = int(device_batch[OBS].shape[0]
                        * device_batch[OBS].shape[1])
        else:
            train_batch = SampleBatch.concat_samples(processed)
            steps = train_batch.count
            device_batch = {
                k: jnp.asarray(v) for k, v in train_batch.items()
                if k in (OBS, ACTIONS, LOGPS, ADVANTAGES, VALUE_TARGETS)
            }
        self._timesteps_total += steps
        self._rng, sub = jax.random.split(self._rng)
        self.params, self.opt_state, metrics = self._update(
            self.params, self.opt_state, device_batch, sub
        )
        weights = jax.tree.map(np.asarray, self.params)
        self.workers.local_worker.set_weights(weights)
        self.workers.sync_weights(weights)
        out = {k: float(v) for k, v in metrics.items()}
        out["timesteps_this_iter"] = steps
        return out

    def get_state(self) -> Dict:
        state = super().get_state()
        state["params"] = jax.tree.map(np.asarray, self.params)
        return state

    def set_state(self, state: Dict) -> None:
        super().set_state(state)
        if "params" in state:
            self.params = jax.tree.map(jnp.asarray, state["params"])
            weights = jax.tree.map(np.asarray, self.params)
            self.workers.local_worker.set_weights(weights)
            self.workers.sync_weights(weights)

    def compute_single_action(self, obs, deterministic: bool = True):
        actions, _, _ = self.workers.local_worker.policy.compute_actions(
            np.asarray(obs)[None], deterministic=deterministic
        )
        return int(actions[0])
