"""PPO: clipped-surrogate policy optimization with a JAX learner.

Reference analog: ``rllib/algorithms/ppo/ppo.py:47,289,401`` —
``training_step`` = synchronous_parallel_sample → train_one_step →
sync_weights (SURVEY §3.6). TPU re-design: the whole SGD phase (epochs x
minibatches of the clipped surrogate + value + entropy loss) is ONE
jit-compiled program (``lax.scan`` over minibatches inside ``lax.scan``
over epochs) running on the accelerator; rollouts stay on CPU actors.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from .algorithm import Algorithm, AlgorithmConfig
from .policy import forward_mlp
from .sample_batch import (
    ACTIONS,
    ADVANTAGES,
    LOGPS,
    OBS,
    VALUE_TARGETS,
    SampleBatch,
    compute_gae,
    flatten_time_major,
)


class PPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self._algo_class = PPO
        self.clip_param = 0.2
        self.vf_clip_param = 10.0
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.num_sgd_iter = 8
        self.sgd_minibatch_size = 256
        self.lambda_ = 0.95
        self.grad_clip = 0.5

    def training(self, clip_param=None, vf_loss_coeff=None,
                 entropy_coeff=None, num_sgd_iter=None,
                 sgd_minibatch_size=None, lambda_=None, **kwargs
                 ) -> "PPOConfig":
        super().training(**kwargs)
        for name, val in [("clip_param", clip_param),
                          ("vf_loss_coeff", vf_loss_coeff),
                          ("entropy_coeff", entropy_coeff),
                          ("num_sgd_iter", num_sgd_iter),
                          ("sgd_minibatch_size", sgd_minibatch_size),
                          ("lambda_", lambda_)]:
            if val is not None:
                setattr(self, name, val)
        return self


def ppo_loss(params, batch, clip_param, vf_clip, vf_coeff, ent_coeff,
             apply_fn=forward_mlp):
    logits, values = apply_fn(params, batch[OBS])
    logp_all = jax.nn.log_softmax(logits)
    actions = batch[ACTIONS].astype(jnp.int32)
    logp = jnp.take_along_axis(logp_all, actions[:, None], axis=-1)[:, 0]
    ratio = jnp.exp(logp - batch[LOGPS])
    adv = batch[ADVANTAGES]
    adv = (adv - adv.mean()) / (adv.std() + 1e-8)
    surrogate = jnp.minimum(
        ratio * adv,
        jnp.clip(ratio, 1 - clip_param, 1 + clip_param) * adv,
    )
    policy_loss = -jnp.mean(surrogate)
    vf_err = jnp.clip(values - batch[VALUE_TARGETS], -vf_clip, vf_clip)
    vf_loss = jnp.mean(vf_err ** 2)
    entropy = -jnp.mean(
        jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1)
    )
    total = policy_loss + vf_coeff * vf_loss - ent_coeff * entropy
    return total, {
        "policy_loss": policy_loss, "vf_loss": vf_loss, "entropy": entropy,
        "kl": jnp.mean(batch[LOGPS] - logp),
    }


def build_ppo_update(config: PPOConfig, optimizer, apply_fn=forward_mlp):
    """One compiled program: epochs x minibatches of SGD.

    The minibatch schedule is a static reshape + permutation consumed by
    nested ``lax.scan`` — no per-minibatch dispatch from the host.
    """
    clip, vfc, vco, eco = (config.clip_param, config.vf_clip_param,
                           config.vf_loss_coeff, config.entropy_coeff)
    mb_size = config.sgd_minibatch_size
    epochs = config.num_sgd_iter

    @jax.jit
    def update(params, opt_state, batch, rng):
        n = batch[OBS].shape[0]
        num_mb = max(1, n // mb_size)
        usable = num_mb * mb_size

        def epoch_body(carry, epoch_rng):
            params, opt_state = carry
            perm = jax.random.permutation(epoch_rng, n)[:usable]
            shuffled = {k: v[perm] for k, v in batch.items()}
            mbs = {
                k: v.reshape((num_mb, mb_size) + v.shape[1:])
                for k, v in shuffled.items()
            }

            def mb_body(carry, mb):
                params, opt_state = carry
                (loss, aux), grads = jax.value_and_grad(
                    ppo_loss, has_aux=True
                )(params, mb, clip, vfc, vco, eco, apply_fn)
                updates, opt_state = optimizer.update(grads, opt_state,
                                                      params)
                params = optax.apply_updates(params, updates)
                return (params, opt_state), (loss, aux)

            (params, opt_state), (losses, auxs) = jax.lax.scan(
                mb_body, (params, opt_state), mbs
            )
            return (params, opt_state), (losses[-1], jax.tree.map(
                lambda a: a[-1], auxs))

        rngs = jax.random.split(rng, epochs)
        (params, opt_state), (losses, auxs) = jax.lax.scan(
            epoch_body, (params, opt_state), rngs
        )
        metrics = {"total_loss": losses[-1]}
        metrics.update({k: v[-1] for k, v in auxs.items()})
        return params, opt_state, metrics

    return update


class PPO(Algorithm):
    def setup(self, config: PPOConfig) -> None:
        super().setup(config)
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(config.grad_clip),
            optax.adam(config.lr),
        )
        # Learner-side copy of the policy params lives on the accelerator.
        self.params = jax.tree.map(
            jnp.asarray, self.workers.local_worker.policy.params
        )
        self.opt_state = self.optimizer.init(self.params)
        self._update = build_ppo_update(
            config, self.optimizer,
            self.workers.local_worker.policy.net.apply)
        self._rng = jax.random.PRNGKey(config.seed)
        self.workers.sync_weights(jax.tree.map(np.asarray, self.params))

    def training_step(self) -> Dict:
        """sample -> GAE -> compiled SGD -> weight broadcast (SURVEY §3.6)."""
        cfg: PPOConfig = self.config
        fragments = self.workers.sample(cfg.rollout_fragment_length)
        processed = []
        for frag in fragments:
            last_values = frag.pop("last_values")
            frag.pop("final_obs", None)  # IMPALA-only bootstrap column
            frag = compute_gae(frag, last_values, cfg.gamma, cfg.lambda_)
            processed.append(flatten_time_major(frag))
        train_batch = SampleBatch.concat_samples(processed)
        steps = train_batch.count
        self._timesteps_total += steps

        device_batch = {
            k: jnp.asarray(v) for k, v in train_batch.items()
            if k in (OBS, ACTIONS, LOGPS, ADVANTAGES, VALUE_TARGETS)
        }
        self._rng, sub = jax.random.split(self._rng)
        self.params, self.opt_state, metrics = self._update(
            self.params, self.opt_state, device_batch, sub
        )
        weights = jax.tree.map(np.asarray, self.params)
        self.workers.local_worker.set_weights(weights)
        self.workers.sync_weights(weights)
        out = {k: float(v) for k, v in metrics.items()}
        out["timesteps_this_iter"] = steps
        return out

    def get_state(self) -> Dict:
        state = super().get_state()
        state["params"] = jax.tree.map(np.asarray, self.params)
        return state

    def set_state(self, state: Dict) -> None:
        super().set_state(state)
        if "params" in state:
            self.params = jax.tree.map(jnp.asarray, state["params"])
            weights = jax.tree.map(np.asarray, self.params)
            self.workers.local_worker.set_weights(weights)
            self.workers.sync_weights(weights)

    def compute_single_action(self, obs, deterministic: bool = True):
        actions, _, _ = self.workers.local_worker.policy.compute_actions(
            np.asarray(obs)[None], deterministic=deterministic
        )
        return int(actions[0])
