"""Model catalog: pick/build policy networks by observation space and
model config.

Reference analog: ``rllib/models/catalog.py`` (``ModelCatalog``) — the
component that turns (obs space, action space, model config) into a
network: conv stacks for image observations, MLPs for vectors, an LSTM
wrapper when ``use_lstm`` is set, and a custom-model registry
(``register_custom_model`` + ``model_config["custom_model"]``).
JAX re-design: networks are pure ``(init, apply)`` pairs over param
pytrees (``policy.Network``); recurrent networks add
``initial_state``/``apply_state``.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.common import truncated_normal
from .policy import (
    Network,
    forward_mlp,
    init_conv_policy,
    init_mlp_policy,
    make_network,
)

# Reference: models/catalog.py MODEL_DEFAULTS (subset that applies here).
MODEL_DEFAULTS: Dict = {
    "custom_model": None,
    "fcnet_hiddens": (64, 64),
    "use_lstm": False,
    "lstm_cell_size": 64,
    # None -> Nature-CNN for rank-3 obs; "mlp"/"conv" force a family.
    "network": "auto",
}

_CUSTOM_MODELS: Dict[str, Callable] = {}


def register_custom_model(name: str, factory: Callable) -> None:
    """``factory(obs_shape, num_actions, model_config) -> Network``
    (reference: ModelCatalog.register_custom_model)."""
    _CUSTOM_MODELS[name] = factory


def init_lstm_policy(key, obs_dim: int, num_actions: int,
                     hidden: Sequence[int] = (64,),
                     cell: int = 64) -> Dict:
    """MLP trunk -> LSTM cell -> separate pi/vf heads (reference:
    catalog.py use_lstm wrapping, models/torch/recurrent_net.py)."""
    params = {}
    sizes = [obs_dim] + list(hidden)
    keys = jax.random.split(key, len(sizes) + 3)
    for i in range(len(sizes) - 1):
        std = float(np.sqrt(2.0 / sizes[i]))
        params[f"t{i}_w"] = truncated_normal(
            keys[i], (sizes[i], sizes[i + 1]), stddev=std)
        params[f"t{i}_b"] = jnp.zeros((sizes[i + 1],))
    feat = sizes[-1]
    std = float(np.sqrt(1.0 / (feat + cell)))
    # One fused kernel for the 4 gates (i, f, g, o).
    params["lstm_w"] = truncated_normal(
        keys[-3], (feat + cell, 4 * cell), stddev=std)
    params["lstm_b"] = jnp.zeros((4 * cell,))
    params["pi_w"] = truncated_normal(keys[-2], (cell, num_actions),
                                      stddev=0.01)
    params["pi_b"] = jnp.zeros((num_actions,))
    params["vf_w"] = truncated_normal(keys[-1], (cell, 1), stddev=1.0)
    params["vf_b"] = jnp.zeros((1,))
    return params


def lstm_initial_state(batch: int, cell: int) -> Tuple[jnp.ndarray, ...]:
    return (jnp.zeros((batch, cell)), jnp.zeros((batch, cell)))


def forward_lstm(params: Dict, obs: jnp.ndarray, state):
    """-> (logits [B, A], values [B], new_state)."""
    x = obs.astype(jnp.float32).reshape(obs.shape[0], -1)
    i = 0
    while f"t{i}_w" in params:
        x = jnp.tanh(x @ params[f"t{i}_w"] + params[f"t{i}_b"])
        i += 1
    h, c = state
    gates = jnp.concatenate([x, h], axis=-1) @ params["lstm_w"] + \
        params["lstm_b"]
    gi, gf, gg, go = jnp.split(gates, 4, axis=-1)
    c = jax.nn.sigmoid(gf + 1.0) * c + jax.nn.sigmoid(gi) * jnp.tanh(gg)
    h = jax.nn.sigmoid(go) * jnp.tanh(c)
    logits = h @ params["pi_w"] + params["pi_b"]
    values = (h @ params["vf_w"] + params["vf_b"])[..., 0]
    return logits, values, (h, c)


def init_conv_lstm_policy(key, obs_shape: Tuple[int, ...],
                          num_actions: int, cell: int = 64,
                          dense: int = 256) -> Dict:
    """Nature-CNN trunk -> dense -> LSTM cell -> pi/vf heads (the
    catalog's vision+LSTM wrapping for image observations)."""
    from .policy import _CONV_SPEC

    h, w, c = obs_shape
    keys = jax.random.split(key, 8)
    params: Dict = {}
    cin = c
    for i, (cout, k, stride) in enumerate(_CONV_SPEC):
        std = float(np.sqrt(2.0 / (k * k * cin)))
        params[f"conv{i}_w"] = truncated_normal(
            keys[i], (k, k, cin, cout), stddev=std)
        params[f"conv{i}_b"] = jnp.zeros((cout,))
        h = (h - k) // stride + 1
        w = (w - k) // stride + 1
        cin = cout
    flat = h * w * cin
    params["dense_w"] = truncated_normal(
        keys[3], (flat, dense), stddev=float(np.sqrt(2.0 / flat)))
    params["dense_b"] = jnp.zeros((dense,))
    std = float(np.sqrt(1.0 / (dense + cell)))
    params["lstm_w"] = truncated_normal(
        keys[4], (dense + cell, 4 * cell), stddev=std)
    params["lstm_b"] = jnp.zeros((4 * cell,))
    params["pi_w"] = truncated_normal(keys[5], (cell, num_actions),
                                      stddev=0.01)
    params["pi_b"] = jnp.zeros((num_actions,))
    params["vf_w"] = truncated_normal(keys[6], (cell, 1), stddev=1.0)
    params["vf_b"] = jnp.zeros((1,))
    return params


def forward_conv_lstm(params: Dict, obs: jnp.ndarray, state):
    """[B, H, W, C] frames (uint8 normalized like forward_conv) ->
    (logits, values, new_state)."""
    from .policy import _CONV_SPEC

    x = obs.astype(jnp.float32)
    if obs.dtype == jnp.uint8:
        x = x / 255.0
    x = x.astype(jnp.bfloat16)
    for i, (_cout, _k, stride) in enumerate(_CONV_SPEC):
        x = jax.lax.conv_general_dilated(
            x, params[f"conv{i}_w"].astype(x.dtype),
            window_strides=(stride, stride), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        ) + params[f"conv{i}_b"].astype(x.dtype)
        x = jax.nn.relu(x)
    x = x.reshape(x.shape[0], -1)
    x = jax.nn.relu(x @ params["dense_w"].astype(x.dtype)
                    + params["dense_b"].astype(x.dtype))
    x = x.astype(jnp.float32)
    h, c = state
    gates = jnp.concatenate([x, h], axis=-1) @ params["lstm_w"] + \
        params["lstm_b"]
    gi, gf, gg, go = jnp.split(gates, 4, axis=-1)
    c = jax.nn.sigmoid(gf + 1.0) * c + jax.nn.sigmoid(gi) * jnp.tanh(gg)
    h = jax.nn.sigmoid(go) * jnp.tanh(c)
    logits = h @ params["pi_w"] + params["pi_b"]
    values = (h @ params["vf_w"] + params["vf_b"])[..., 0]
    return logits, values, (h, c)


def get_network(obs_shape: Tuple[int, ...], num_actions: int,
                model_config: Optional[Dict] = None) -> Network:
    """The catalog entry point (reference: ModelCatalog.get_model_v2):
    custom registry first, then LSTM wrapper, then conv-vs-mlp by
    observation rank."""
    cfg = dict(MODEL_DEFAULTS)
    cfg.update(model_config or {})
    custom = cfg.get("custom_model")
    if custom is not None:
        if callable(custom):
            # A factory passed directly survives pickling into remote
            # rollout workers (the NAME registry is process-local:
            # remote actors never ran the driver's register calls).
            return custom(obs_shape, num_actions, cfg)
        if custom not in _CUSTOM_MODELS:
            raise ValueError(
                f"custom model {custom!r} is not registered "
                f"(known: {sorted(_CUSTOM_MODELS)}). With remote "
                "rollout workers pass the factory CALLABLE as "
                "custom_model — string registration is per-process")
        return _CUSTOM_MODELS[custom](obs_shape, num_actions, cfg)
    if cfg.get("use_lstm"):
        cell = int(cfg["lstm_cell_size"])
        if len(obs_shape) == 3:
            # Image observations: conv trunk feeding the LSTM cell
            # (reference: ModelCatalog wraps the vision network with
            # the LSTM; a flattened-MLP trunk over raw [0,255] frames
            # would saturate immediately).
            return Network(
                kind="conv_lstm",
                init=lambda key: init_conv_lstm_policy(
                    key, obs_shape, num_actions, cell),
                apply=None,
                initial_state=lambda batch: lstm_initial_state(batch,
                                                               cell),
                apply_state=forward_conv_lstm,
            )
        obs_dim = int(np.prod(obs_shape))
        hidden = tuple(cfg["fcnet_hiddens"])
        return Network(
            kind="lstm",
            init=lambda key: init_lstm_policy(
                key, obs_dim, num_actions, hidden, cell),
            apply=None,
            initial_state=lambda batch: lstm_initial_state(batch, cell),
            apply_state=forward_lstm,
        )
    return make_network(obs_shape, num_actions, cfg.get("network", "auto"),
                        tuple(cfg["fcnet_hiddens"]))


__all__ = [
    "MODEL_DEFAULTS",
    "get_network",
    "init_conv_policy",
    "init_lstm_policy",
    "init_mlp_policy",
    "forward_lstm",
    "forward_mlp",
    "register_custom_model",
]
