"""TD3: twin-delayed deterministic policy gradients (continuous control).

Reference analog: ``rllib/algorithms/ddpg/`` family with the TD3 flags
(``twin_q``, ``policy_delay``, ``smooth_target_policy`` — td3.py
presets): deterministic tanh actor, twin Q critics, clipped Gaussian
TARGET-policy smoothing, delayed actor updates, polyak targets. Shares
the MLP/critic machinery with SAC (``sac.py``); one jit program per
update step.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .algorithm import Algorithm, AlgorithmConfig
from .replay_buffers import ReplayBuffer
from .sac import SACRolloutWorker, _init_mlp, _mlp, _q
from .sample_batch import ACTIONS, DONES, NEXT_OBS, OBS, REWARDS


def init_td3_params(key, obs_dim: int, action_dim: int,
                    hidden=(256, 256)) -> Dict:
    ka, k1, k2 = jax.random.split(key, 3)
    sizes = [obs_dim] + list(hidden)
    qsizes = [obs_dim + action_dim] + list(hidden)
    actor = _init_mlp(ka, sizes, action_dim, out_std=0.01)
    q1 = _init_mlp(k1, qsizes, 1, out_std=0.1)
    q2 = _init_mlp(k2, qsizes, 1, out_std=0.1)
    return {
        "actor": actor, "q1": q1, "q2": q2,
        "target_actor": jax.tree.map(jnp.copy, actor),
        "target_q1": jax.tree.map(jnp.copy, q1),
        "target_q2": jax.tree.map(jnp.copy, q2),
    }


def deterministic_action(actor: Dict, obs, low: float, high: float):
    scale = (high - low) / 2.0
    return low + (jnp.tanh(_mlp(actor, obs.astype(jnp.float32)))
                  + 1.0) * scale


class TD3Policy:
    """Deterministic actor + Gaussian EXPLORATION noise for rollouts
    (reference: ddpg GaussianNoise exploration)."""

    def __init__(self, obs_shape: Tuple[int, ...], action_dim: int,
                 low: float, high: float, hidden=(256, 256),
                 seed: int = 0, explore_sigma: float = 0.1):
        self.obs_dim = int(np.prod(obs_shape))
        self.action_dim = action_dim
        self.low, self.high = float(low), float(high)
        self.explore_sigma = explore_sigma
        # Uniform-random warmup (reference: ddpg random_timesteps /
        # TD3's start_steps): an untrained tanh actor emits ~zero
        # actions and never explores; the learner flips this off once
        # the buffer holds learning_starts transitions.
        self.random_phase = True
        self.params = init_td3_params(
            jax.random.PRNGKey(seed), self.obs_dim, action_dim, hidden)
        self._rng = np.random.default_rng(seed + 1)

        @jax.jit
        def _act(actor, obs):
            return deterministic_action(actor, obs, self.low, self.high)

        self._act = _act

    def compute_actions(self, obs: np.ndarray, deterministic: bool = False):
        obs = np.asarray(obs, np.float32).reshape(len(obs), -1)
        if self.random_phase and not deterministic:
            actions = self._rng.uniform(
                self.low, self.high, (len(obs), self.action_dim))
            zeros = np.zeros(len(obs), np.float32)
            return actions.astype(np.float32), zeros, zeros
        actions = np.asarray(self._act(self.params["actor"],
                                       jnp.asarray(obs)))
        if not deterministic:
            scale = (self.high - self.low) / 2.0
            noise = self._rng.normal(
                0.0, self.explore_sigma * scale, actions.shape)
            actions = np.clip(actions + noise, self.low, self.high)
        zeros = np.zeros(len(obs), np.float32)
        return actions.astype(np.float32), zeros, zeros

    def get_weights(self) -> Dict:
        return jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights: Dict) -> None:
        # MERGE: the learner syncs only the subtree workers need (the
        # actor — critics/targets are learner-side), but a full tree
        # from checkpoint restore also lands correctly.
        self.params = {**self.params,
                       **jax.tree.map(jnp.asarray, weights)}


class TD3RolloutWorker(SACRolloutWorker):
    def _make_policy(self, cfg: Dict, seed: int):
        return TD3Policy(
            self._connected_obs_shape, self.env.action_dim,
            self.env.action_low, self.env.action_high,
            hidden=cfg.get("hidden", (256, 256)), seed=seed,
            explore_sigma=cfg.get("explore_sigma", 0.1),
        )


class TD3Config(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self._algo_class = TD3
        self.env = "FastPendulum"
        self.lr = 1e-3
        self.rollout_fragment_length = 8
        self.train_batch_size = 128
        self.buffer_capacity = 100_000
        self.learning_starts = 500
        self.tau = 0.005
        self.num_updates_per_iter = 32
        self.policy_delay = 2  # delayed actor updates (the "TD" in TD3)
        self.target_noise = 0.2  # target-policy smoothing sigma
        self.target_noise_clip = 0.5
        self.explore_sigma = 0.1
        self.policy_config_extra["explore_sigma"] = self.explore_sigma
        self.policy_hidden = (256, 256)

    def training(self, **kwargs) -> "TD3Config":
        for k in ("buffer_capacity", "learning_starts", "tau",
                  "num_updates_per_iter", "policy_delay", "target_noise",
                  "target_noise_clip", "explore_sigma"):
            if k in kwargs:
                setattr(self, k, kwargs.pop(k))
        # Rollout policies need the exploration sigma at construction
        # (WorkerSet forwards policy_config_extra into _make_policy).
        self.policy_config_extra["explore_sigma"] = self.explore_sigma
        super().training(**kwargs)
        return self


class TD3(Algorithm):
    """training_step: sample -> replay add -> K jit updates (critic every
    step; actor + targets every policy_delay steps) -> sync."""

    _worker_cls = TD3RolloutWorker

    def setup(self, config: TD3Config) -> None:
        import optax

        # Authoritative at build time: the attribute may have been set
        # directly (config.explore_sigma = ...) after __init__/.training
        # snapshotted it into policy_config_extra.
        config.policy_config_extra["explore_sigma"] = config.explore_sigma
        super().setup(config)
        env = self.workers.local_worker.env
        adim = env.action_dim
        low, high = float(env.action_low), float(env.action_high)
        scale = (high - low) / 2.0
        self.buffer = ReplayBuffer(config.buffer_capacity,
                                   seed=config.seed)
        self.params = self.workers.local_worker.policy.params
        # SEPARATE optimizers: the actor's must only advance on actor
        # steps — a shared optimizer fed zero actor-grads on critic-only
        # steps still moves the actor via Adam momentum, silently
        # defeating the delayed-update schedule.
        self.critic_opt = optax.adam(config.lr)
        self.actor_opt = optax.adam(config.lr)
        self.opt_state = {
            "critic": self.critic_opt.init(
                {"q1": self.params["q1"], "q2": self.params["q2"]}),
            "actor": self.actor_opt.init(self.params["actor"]),
        }
        self._num_updates = 0
        self._warmup_done = False
        gamma, tau = config.gamma, config.tau
        tn = config.target_noise * scale
        tn_clip = config.target_noise_clip * scale
        def critic_loss(train, params, batch, key):
            # Target-policy smoothing: noisy clipped target action.
            target_a = deterministic_action(
                params["target_actor"], batch[NEXT_OBS], low, high)
            noise = jnp.clip(
                tn * jax.random.normal(key, target_a.shape),
                -tn_clip, tn_clip)
            target_a = jnp.clip(target_a + noise, low, high)
            tq = jnp.minimum(
                _q(params["target_q1"], batch[NEXT_OBS], target_a),
                _q(params["target_q2"], batch[NEXT_OBS], target_a))
            not_done = 1.0 - batch[DONES].astype(jnp.float32)
            target = jax.lax.stop_gradient(
                batch[REWARDS] + gamma * not_done * tq)
            q1 = _q(train["q1"], batch[OBS], batch[ACTIONS])
            q2 = _q(train["q2"], batch[OBS], batch[ACTIONS])
            return (jnp.mean((q1 - target) ** 2)
                    + jnp.mean((q2 - target) ** 2))

        def actor_loss(actor, critics, batch):
            a = deterministic_action(actor, batch[OBS], low, high)
            return -jnp.mean(_q(jax.lax.stop_gradient(critics["q1"]),
                                batch[OBS], a))

        critic_opt, actor_opt = self.critic_opt, self.actor_opt

        @jax.jit
        def update(params, opt_state, batch, key, do_actor):
            critics = {"q1": params["q1"], "q2": params["q2"]}
            c_loss, c_grads = jax.value_and_grad(critic_loss)(
                critics, params, batch, key)
            c_updates, critic_state = critic_opt.update(
                c_grads, opt_state["critic"], critics)
            critics = optax.apply_updates(critics, c_updates)

            def with_actor(_):
                a_loss, a_grads = jax.value_and_grad(actor_loss)(
                    params["actor"], critics, batch)
                a_updates, actor_state = actor_opt.update(
                    a_grads, opt_state["actor"], params["actor"])
                actor = optax.apply_updates(params["actor"], a_updates)

                def polyak(t, o):
                    return jax.tree.map(
                        lambda a, b: (1 - tau) * a + tau * b, t, o)

                return (actor, actor_state, a_loss,
                        polyak(params["target_q1"], critics["q1"]),
                        polyak(params["target_q2"], critics["q2"]),
                        polyak(params["target_actor"], actor))

            def without_actor(_):
                # Critic-only step: actor, its optimizer state, and ALL
                # targets stay frozen (the "delayed" in TD3).
                return (params["actor"], opt_state["actor"],
                        jnp.asarray(0.0), params["target_q1"],
                        params["target_q2"], params["target_actor"])

            (actor, actor_state, a_loss, tq1, tq2, ta) = jax.lax.cond(
                do_actor, with_actor, without_actor, None)
            new = dict(params)
            new.update({"actor": actor, "q1": critics["q1"],
                        "q2": critics["q2"], "target_q1": tq1,
                        "target_q2": tq2, "target_actor": ta})
            return (new, {"critic": critic_state, "actor": actor_state},
                    {"critic_loss": c_loss, "actor_loss": a_loss})

        self._update = update
        self._key = jax.random.PRNGKey(config.seed + 23)

    def training_step(self) -> Dict:
        cfg = self.config
        batches = self.workers.sample(cfg.rollout_fragment_length)
        new_steps = 0
        for b in batches:
            self.buffer.add(b)
            new_steps += b.count
        self._timesteps_total += new_steps
        aux_out = {}
        if len(self.buffer) >= cfg.learning_starts:
            if not self._warmup_done:
                self._warmup_done = True
                self.workers.foreach_worker(
                    lambda w: setattr(w.policy, "random_phase", False))
            actor_loss = None
            for _ in range(cfg.num_updates_per_iter):
                batch = self.buffer.sample(cfg.train_batch_size)
                jbatch = {k: jnp.asarray(v) for k, v in batch.items()
                          if k != "batch_indexes"}
                self._key, sub = jax.random.split(self._key)
                is_actor_step = (
                    self._num_updates % cfg.policy_delay == 0)
                self.params, self.opt_state, aux = self._update(
                    self.params, self.opt_state, jbatch, sub,
                    jnp.asarray(is_actor_step))
                if is_actor_step:
                    actor_loss = aux["actor_loss"]
                self._num_updates += 1
            aux_out = {"critic_loss": float(aux["critic_loss"])}
            if actor_loss is not None:
                aux_out["actor_loss"] = float(actor_loss)
            # Workers only evaluate the actor; shipping critics+targets
            # too would 6x the per-iteration broadcast for nothing.
            weights = {"actor": jax.tree.map(np.asarray,
                                             self.params["actor"])}
            self.workers.local_worker.set_weights(weights)
            self.workers.sync_weights(weights)
        return {
            "timesteps_this_iter": new_steps,
            "num_learner_updates": self._num_updates,
            "replay_buffer_size": len(self.buffer),
            **aux_out,
        }

    def get_state(self) -> Dict:
        state = super().get_state()
        state.update({
            "params": jax.tree.map(np.asarray, self.params),
            "num_updates": self._num_updates,
            "opt_state": jax.tree.map(np.asarray, self.opt_state),
            "warmup_done": self._warmup_done,
            "rng_key": np.asarray(self._key),
        })
        return state

    def set_state(self, state: Dict) -> None:
        super().set_state(state)
        if "params" in state:
            self.params = jax.tree.map(jnp.asarray, state["params"])
            self._num_updates = state.get("num_updates", 0)
            weights = jax.tree.map(np.asarray, self.params)
            self.workers.local_worker.set_weights(weights)
            self.workers.sync_weights(weights)
        if "opt_state" in state:
            # A zeroed Adam state after resume causes a loss spike.
            self.opt_state = jax.tree.map(jnp.asarray,
                                          state["opt_state"])
        if "rng_key" in state:
            self._key = jnp.asarray(state["rng_key"])
        if state.get("warmup_done"):
            # Do NOT re-enter uniform-random warmup with a trained
            # policy — reward would collapse after every resume.
            self._warmup_done = True
            self.workers.foreach_worker(
                lambda w: setattr(w.policy, "random_phase", False))
