"""Replay buffers: FIFO, prioritized (sum-tree), reservoir.

Reference analog: ``rllib/utils/replay_buffers/`` — ``ReplayBuffer``
(FIFO ring), ``PrioritizedReplayBuffer`` (proportional prioritization,
Schaul et al. 2015), ``ReservoirReplayBuffer`` (uniform-over-stream).

TPU-first design notes: buffers live in host RAM as preallocated numpy
ring arrays (structure-of-arrays, one array per SampleBatch column), so
``sample`` produces a contiguous batch the learner can ship to HBM in a
single transfer.  The sum-tree is a flat numpy array updated vectorised —
no per-element Python tree nodes.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from .sample_batch import SampleBatch


class ReplayBuffer:
    """FIFO ring buffer over SampleBatch rows.

    Columns are preallocated on the first ``add`` from the batch's own
    dtypes/shapes; adds and samples are vectorised slices.
    """

    def __init__(self, capacity: int = 100_000, seed: int = 0):
        self.capacity = int(capacity)
        self._cols: Dict[str, np.ndarray] = {}
        self._size = 0
        self._next = 0
        self._rng = np.random.default_rng(seed)
        self._added = 0

    def __len__(self) -> int:
        return self._size

    @property
    def added_count(self) -> int:
        return self._added

    def _ensure_cols(self, batch: SampleBatch) -> None:
        for k, v in batch.items():
            if k not in self._cols:
                v = np.asarray(v)
                self._cols[k] = np.zeros((self.capacity,) + v.shape[1:],
                                         v.dtype)

    def _write(self, batch: SampleBatch) -> np.ndarray:
        """Write rows into the ring; returns the written indices."""
        self._ensure_cols(batch)
        n = batch.count
        if n > self.capacity:  # keep only the newest rows
            batch = batch.slice(n - self.capacity, n)
            n = self.capacity
        idx = (self._next + np.arange(n)) % self.capacity
        for k, v in batch.items():
            self._cols[k][idx] = np.asarray(v)
        self._next = int((self._next + n) % self.capacity)
        self._size = min(self._size + n, self.capacity)
        self._added += n
        return idx

    def add(self, batch: SampleBatch) -> None:
        self._write(batch)

    def sample(self, num_items: int) -> SampleBatch:
        if self._size == 0:
            raise ValueError("empty replay buffer")
        idx = self._rng.integers(0, self._size, num_items)
        return SampleBatch({k: v[idx] for k, v in self._cols.items()})

    def stats(self) -> Dict:
        return {"size": self._size, "capacity": self.capacity,
                "added_count": self._added}


class SumSegmentTree:
    """Flat-array sum tree supporting O(log n) prefix-sum sampling and
    vectorised priority updates (reference: ``utils/segment_tree.py``)."""

    def __init__(self, capacity: int):
        self.capacity = 1
        while self.capacity < capacity:
            self.capacity *= 2
        self._tree = np.zeros(2 * self.capacity, np.float64)

    def __setitem__(self, idx, val) -> None:
        idx = np.atleast_1d(np.asarray(idx, np.int64)) + self.capacity
        self._tree[idx] = np.atleast_1d(val)
        # propagate up level by level (vectorised over the index set)
        while idx[0] > 1:
            idx = np.unique(idx // 2)
            self._tree[idx] = self._tree[2 * idx] + self._tree[2 * idx + 1]

    def __getitem__(self, idx):
        return self._tree[np.asarray(idx) + self.capacity]

    def sum(self) -> float:
        return float(self._tree[1])

    def find_prefixsum_idx(self, prefixsum: np.ndarray) -> np.ndarray:
        """Vectorised descent: for each target mass, the leaf where the
        running prefix sum crosses it."""
        prefixsum = np.asarray(prefixsum, np.float64).copy()
        idx = np.ones(len(prefixsum), np.int64)
        while idx[0] < self.capacity:
            left = self._tree[2 * idx]
            go_right = prefixsum > left
            prefixsum -= np.where(go_right, left, 0.0)
            idx = 2 * idx + go_right
        return idx - self.capacity


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritized replay (alpha/beta schedule, IS weights).

    ``sample`` returns the batch plus ``weights`` (importance-sampling
    correction) and ``batch_indexes`` for ``update_priorities``.
    """

    def __init__(self, capacity: int = 100_000, alpha: float = 0.6,
                 seed: int = 0):
        super().__init__(capacity, seed)
        assert alpha > 0
        self._alpha = alpha
        self._tree = SumSegmentTree(self.capacity)
        self._max_priority = 1.0

    def add(self, batch: SampleBatch, priorities=None) -> None:
        """``priorities`` (|td| per row) lets distributed producers ship
        INITIAL priorities with the data instead of defaulting to max —
        the Ape-X insight that keeps fresh-but-boring transitions from
        flooding the sample distribution (reference: apex_dqn.py)."""
        idx = self._write(batch)
        if priorities is None:
            self._tree[idx] = self._max_priority ** self._alpha
        else:
            priorities = np.abs(np.asarray(priorities, np.float64)) + 1e-6
            # _write keeps only the NEWEST `capacity` rows of an
            # oversized batch; keep the matching tail of priorities.
            if len(priorities) > len(idx):
                priorities = priorities[-len(idx):]
            self._tree[idx] = priorities ** self._alpha
            self._max_priority = max(self._max_priority,
                                     float(priorities.max()))

    def sample(self, num_items: int, beta: float = 0.4) -> SampleBatch:
        if self._size == 0:
            raise ValueError("empty replay buffer")
        mass = self._rng.random(num_items) * self._tree.sum()
        idx = np.minimum(self._tree.find_prefixsum_idx(mass), self._size - 1)
        p = self._tree[idx] / max(self._tree.sum(), 1e-12)
        weights = (p * self._size) ** (-beta)
        weights /= weights.max() + 1e-12
        out = SampleBatch({k: v[idx] for k, v in self._cols.items()})
        out["weights"] = weights.astype(np.float32)
        out["batch_indexes"] = idx.astype(np.int64)
        return out

    def update_priorities(self, idx: np.ndarray, priorities: np.ndarray
                          ) -> None:
        priorities = np.abs(np.asarray(priorities, np.float64)) + 1e-6
        self._tree[np.asarray(idx)] = priorities ** self._alpha
        self._max_priority = max(self._max_priority,
                                 float(priorities.max()))


class ReservoirReplayBuffer(ReplayBuffer):
    """Uniform sample over the whole stream (Vitter's algorithm R);
    used by league-style algorithms (reference: reservoir buffer in
    ``utils/replay_buffers/reservoir_replay_buffer.py``)."""

    def add(self, batch: SampleBatch) -> None:
        self._ensure_cols(batch)
        n = batch.count
        for row in range(n):
            self._added += 1
            if self._size < self.capacity:
                slot = self._size
                self._size += 1
            else:
                slot = int(self._rng.integers(0, self._added))
                if slot >= self.capacity:
                    continue
            for k, v in batch.items():
                self._cols[k][slot] = np.asarray(v[row])


class MultiAgentReplayBuffer:
    """Per-policy-id buffers behind one facade (reference:
    ``multi_agent_replay_buffer.py``)."""

    def __init__(self, capacity: int = 100_000, prioritized: bool = False,
                 seed: int = 0, **kwargs):
        self._capacity = capacity
        self._prioritized = prioritized
        self._seed = seed
        self._kwargs = kwargs
        self.buffers: Dict[str, ReplayBuffer] = {}

    def _buffer(self, policy_id: str) -> ReplayBuffer:
        if policy_id not in self.buffers:
            cls = PrioritizedReplayBuffer if self._prioritized else ReplayBuffer
            self.buffers[policy_id] = cls(
                self._capacity, seed=self._seed + len(self.buffers),
                **self._kwargs)
        return self.buffers[policy_id]

    def add(self, batch: SampleBatch, policy_id: str = "default_policy"
            ) -> None:
        self._buffer(policy_id).add(batch)

    def sample(self, num_items: int, policy_id: str = "default_policy",
               **kwargs) -> SampleBatch:
        return self._buffer(policy_id).sample(num_items, **kwargs)

    def stats(self) -> Dict:
        return {pid: b.stats() for pid, b in self.buffers.items()}
