"""Ape-X DQN: distributed prioritized experience replay.

Reference analog: ``rllib/algorithms/apex_dqn/apex_dqn.py`` (Horgan et
al. 2018) — the three Ape-X separations, each mapped onto this
framework's actor substrate:

- ROLLOUT workers compute INITIAL priorities (|td| under their current
  weights) locally and ship (batch, priorities) to the replay tier, so
  the learner never touches raw transitions it won't sample;
- the REPLAY tier is a set of sharded ``PrioritizedReplayBuffer``
  actors — adds, prioritized samples, and priority updates all run as
  actor RPCs over the object plane (this algorithm deliberately
  stresses the core runtime, not just another loss);
- the LEARNER keeps one in-flight sample per rollout worker (the
  IMPALA-style ``wait`` pump), trains from round-robin shard samples,
  pushes priority corrections back to the owning shard, and broadcasts
  weights on a period instead of every update.

Per-worker exploration follows the Ape-X schedule
``eps_i = base ** (1 + i/(N-1) * alpha)`` — a fleet of differently
greedy explorers instead of one annealed epsilon.
"""

from __future__ import annotations

from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from ..core import get, kill, remote, wait
from .dqn import DQN, DQNConfig, DQNRolloutWorker, q_values
from .replay_buffers import PrioritizedReplayBuffer
from .sample_batch import ACTIONS, DONES, NEXT_OBS, OBS, REWARDS, SampleBatch


class ApexRolloutWorker(DQNRolloutWorker):
    """DQN rollout worker that ships initial priorities with its data."""

    def sample_with_priorities(self, rollout_length: int, gamma: float):
        batch = self.sample(rollout_length)
        params = self.policy.params
        q = np.asarray(q_values(params, jnp.asarray(batch[OBS])))
        q_taken = q[np.arange(batch.count),
                    np.asarray(batch[ACTIONS]).astype(np.int64)]
        next_q_online = np.asarray(
            q_values(params, jnp.asarray(batch[NEXT_OBS])))
        # Workers hold no target net; the online net both picks and
        # values for the INITIAL priority — it only seeds the sampling
        # distribution, the learner's updates use the real target net.
        next_a = np.argmax(next_q_online, axis=-1)
        next_q = next_q_online[np.arange(batch.count), next_a]
        not_done = 1.0 - np.asarray(batch[DONES], np.float32)
        target = np.asarray(batch[REWARDS]) + gamma * not_done * next_q
        prios = np.abs(q_taken - target).astype(np.float32)
        return dict(batch), prios


class ReplayShard:
    """Actor hosting one prioritized replay shard (reference: the
    ``ReplayActor`` of apex_dqn.py)."""

    def __init__(self, capacity: int, alpha: float, seed: int):
        self.buffer = PrioritizedReplayBuffer(capacity, alpha=alpha,
                                              seed=seed)
        self.adds = 0
        self.samples = 0

    def add(self, batch: Dict, priorities) -> int:
        self.buffer.add(SampleBatch(batch), priorities)
        self.adds += 1
        return len(self.buffer)

    def sample(self, num_items: int, beta: float):
        if len(self.buffer) < num_items:
            return None
        self.samples += 1
        return dict(self.buffer.sample(num_items, beta=beta))

    def update_priorities(self, idx, priorities) -> bool:
        self.buffer.update_priorities(np.asarray(idx),
                                      np.asarray(priorities))
        return True

    def stats(self) -> Dict:
        return {"size": len(self.buffer), "adds": self.adds,
                "samples": self.samples}


class ApexConfig(DQNConfig):
    def __init__(self):
        super().__init__()
        self._algo_class = ApexDQN
        self.num_rollout_workers = 2
        self.num_replay_shards = 2
        self.worker_epsilon_base = 0.4
        self.worker_epsilon_alpha = 7.0
        self.weight_sync_period = 16  # learner updates between broadcasts
        self.sample_wait_timeout = 10.0

    def training(self, **kwargs) -> "ApexConfig":
        for k in ("num_replay_shards", "worker_epsilon_base",
                  "worker_epsilon_alpha", "weight_sync_period"):
            if k in kwargs:
                setattr(self, k, kwargs.pop(k))
        super().training(**kwargs)
        return self


class ApexDQN(DQN):
    """Distributed replay on the actor substrate; learner math is DQN's."""

    _worker_cls = ApexRolloutWorker

    def setup(self, config: ApexConfig) -> None:
        super().setup(config)
        self.buffer = None  # replaced by the sharded replay tier
        shard_cls = remote(ReplayShard)
        per_shard = max(1, config.buffer_capacity
                        // max(config.num_replay_shards, 1))
        self.shards = [
            shard_cls.options(num_cpus=0).remote(
                per_shard, config.prioritized_alpha, config.seed + i)
            for i in range(config.num_replay_shards)
        ]
        # which shard a learner batch came from, keyed by shard index
        self._add_rr = 0
        self._sample_rr = 0
        self._replay_size = 0
        self._in_flight: Dict = {}
        # Ape-X per-worker epsilon ladder (constant, not annealed).
        n = max(len(self.workers.remote_workers), 1)
        base, alpha = (config.worker_epsilon_base,
                       config.worker_epsilon_alpha)
        self._epsilons = [
            float(base ** (1.0 + (i / max(n - 1, 1)) * alpha))
            for i in range(n)
        ]
        for i, w in enumerate(self.workers.remote_workers):
            eps = self._epsilons[i]
            get(w.apply.remote(
                lambda wk, e=eps: wk.set_epsilon(e)), timeout=60)
        self.workers.local_worker.set_epsilon(self._epsilons[0])

    def _push_to_shard(self, batch: Dict, prios) -> None:
        shard = self.shards[self._add_rr % len(self.shards)]
        self._add_rr += 1
        # fire-and-forget: the learner never blocks on replay ingestion
        shard.add.remote(batch, prios)

    def _pump_workers(self) -> int:
        """Keep one in-flight sample per remote worker; drain finished
        ones into the replay tier. Returns new env-steps observed."""
        cfg = self.config
        new_steps = 0
        for w in self.workers.remote_workers:
            if w not in self._in_flight.values():
                ref = w.sample_with_priorities.remote(
                    cfg.rollout_fragment_length, cfg.gamma)
                self._in_flight[ref] = w
        if self._in_flight:
            ready, _ = wait(list(self._in_flight),
                            num_returns=1,
                            timeout=cfg.sample_wait_timeout)
            for ref in ready:
                self._in_flight.pop(ref)
                batch, prios = get(ref)
                new_steps += len(prios)
                self._push_to_shard(batch, prios)
        return new_steps

    def training_step(self) -> Dict:
        cfg = self.config
        if self.workers.remote_workers:
            new_steps = self._pump_workers()
        else:  # synchronous fallback (tests / single-core debug)
            batch, prios = self.workers.local_worker \
                .sample_with_priorities(cfg.rollout_fragment_length,
                                        cfg.gamma)
            self._push_to_shard(batch, prios)
            new_steps = len(prios)
        self._timesteps_total += new_steps

        losses = []
        # Gate on learning_starts like DQN: correlated warm-up data must
        # not drive the first updates. _replay_size is last tick's shard
        # total (refreshing it costs one RPC fan-out per step anyway).
        updates_allowed = (cfg.num_updates_per_iter
                           if self._replay_size >= cfg.learning_starts
                           else 0)
        for _ in range(updates_allowed):
            shard_i = self._sample_rr % len(self.shards)
            self._sample_rr += 1
            shard = self.shards[shard_i]
            sampled = get(shard.sample.remote(
                cfg.train_batch_size, cfg.prioritized_beta), timeout=60)
            if sampled is None:
                continue  # shard still warming up
            jbatch = {k: jnp.asarray(v) for k, v in sampled.items()
                      if k != "batch_indexes"}
            self.params, self.opt_state, loss, td = self._update(
                self.params, self.target_params, self.opt_state, jbatch)
            shard.update_priorities.remote(
                sampled["batch_indexes"], np.asarray(td))
            self._num_updates += 1
            if self._num_updates % cfg.target_network_update_freq == 0:
                self.target_params = jax.tree.map(jnp.copy, self.params)
            if self._num_updates % cfg.weight_sync_period == 0:
                weights = jax.tree.map(np.asarray, self.params)
                self.workers.local_worker.set_weights(weights)
                self.workers.sync_weights(weights)
            losses.append(float(loss))

        shard_stats = get([s.stats.remote() for s in self.shards],
                          timeout=60)
        self._replay_size = int(sum(s["size"] for s in shard_stats))
        return {
            "timesteps_this_iter": new_steps,
            "num_learner_updates": self._num_updates,
            "replay_shards": shard_stats,
            "replay_buffer_size": int(sum(s["size"]
                                          for s in shard_stats)),
            "loss": float(np.mean(losses)) if losses else None,
        }

    def stop(self) -> None:
        for ref in list(self._in_flight):
            self._in_flight.pop(ref)
        for s in getattr(self, "shards", []):
            try:
                kill(s)
            except Exception:  # noqa: BLE001 — already dead
                pass
        super().stop()
