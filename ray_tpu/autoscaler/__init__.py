"""Autoscaler: demand-driven cluster resizing with pluggable providers.

Reference analog: ``python/ray/autoscaler/_private/`` —
``StandardAutoscaler.update`` (autoscaler.py:162,353),
``ResourceDemandScheduler.get_nodes_to_launch`` bin-packing
(resource_demand_scheduler.py:43,102), ``LoadMetrics``, ``NodeProvider``
plugin API (node_provider.py) with the fake multi-node provider for tests
(fake_multi_node/node_provider.py:237).

TPU-native: node types describe pod slices (``tpu_slice: v5e-8`` with chip
counts and ICI shape labels), so demands expressed as mesh claims lower to
slice-typed node launches.
"""

from .autoscaler import (
    AutoscalerConfig,
    LoadMetrics,
    NodeType,
    ResourceDemandScheduler,
    StandardAutoscaler,
)
from .command_runner import (
    CommandRunner,
    CommandRunnerError,
    NodeUpdater,
    SSHCommandRunner,
    SubprocessCommandRunner,
)
from .kube_operator import (
    KubeRayNodeProvider,
    KubectlAPI,
    MockKubeAPI,
    RayClusterOperator,
    RayClusterSpec,
    WorkerGroupSpec,
)
from .providers import FakeNodeProvider, LocalNodeProvider, NodeProvider

__all__ = [
    "AutoscalerConfig", "CommandRunner", "CommandRunnerError",
    "FakeNodeProvider", "KubeRayNodeProvider", "KubectlAPI",
    "LoadMetrics",
    "LocalNodeProvider", "MockKubeAPI", "NodeProvider", "NodeType",
    "NodeUpdater", "RayClusterOperator", "RayClusterSpec",
    "ResourceDemandScheduler", "SSHCommandRunner", "StandardAutoscaler",
    "SubprocessCommandRunner", "WorkerGroupSpec",
]
