"""Kubernetes operator: RayCluster-style custom resources reconciled
into pods.

Reference analog: the KubeRay operator shipped with the reference
ecosystem (``python/ray/autoscaler/_private/kuberay/`` — node provider
speaking to the operator's RayCluster CRD, plus the operator's own
reconcile loop): a declarative cluster spec (head + worker groups) is
continuously reconciled against observed pod state — create missing
pods, delete surplus, replace crashed heads, surface status.

The Kubernetes API itself is abstracted behind :class:`KubeAPI`:
``MockKubeAPI`` (in-memory pods with optional chaos) drives tests and
the autoscaler-style E2E; ``KubectlAPI`` shells out to ``kubectl`` when
present and fails with an actionable error here (no cluster in this
environment). The operator also exposes a :class:`NodeProvider` facade
so the StandardAutoscaler can scale worker groups through the same CRD
path (the KubeRay arrangement: autoscaler edits replicas, operator
reconciles pods).
"""

from __future__ import annotations

import copy
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from .providers import NodeInstance, NodeProvider


@dataclass
class WorkerGroupSpec:
    """One homogeneous worker group (KubeRay workerGroupSpecs entry)."""

    group_name: str
    replicas: int = 1
    min_replicas: int = 0
    max_replicas: int = 10
    resources: Dict[str, float] = field(default_factory=dict)
    labels: Dict[str, str] = field(default_factory=dict)


@dataclass
class RayClusterSpec:
    """The RayCluster custom resource (KubeRay CRD shape, trimmed)."""

    name: str
    head_resources: Dict[str, float] = field(
        default_factory=lambda: {"CPU": 1.0})
    worker_groups: List[WorkerGroupSpec] = field(default_factory=list)

    @staticmethod
    def from_dict(doc: Dict) -> "RayClusterSpec":
        """Parse the YAML/JSON document shape KubeRay uses::

            apiVersion: ray.io/v1
            kind: RayCluster
            metadata: {name: demo}
            spec:
              headGroupSpec: {resources: {CPU: 2}}
              workerGroupSpecs:
                - groupName: cpu
                  replicas: 2
                  minReplicas: 0
                  maxReplicas: 8
                  resources: {CPU: 4}
        """
        if doc.get("kind") != "RayCluster":
            raise ValueError(
                f"expected kind: RayCluster, got {doc.get('kind')!r}")
        spec = doc.get("spec", {})
        groups = []
        for g in spec.get("workerGroupSpecs", []):
            groups.append(WorkerGroupSpec(
                group_name=g["groupName"],
                replicas=int(g.get("replicas", 1)),
                min_replicas=int(g.get("minReplicas", 0)),
                max_replicas=int(g.get("maxReplicas", 10)),
                resources=dict(g.get("resources", {})),
                labels=dict(g.get("labels", {})),
            ))
        return RayClusterSpec(
            name=doc.get("metadata", {}).get("name", "raycluster"),
            head_resources=dict(
                spec.get("headGroupSpec", {}).get("resources",
                                                  {"CPU": 1.0})),
            worker_groups=groups,
        )


@dataclass
class Pod:
    name: str
    role: str                  # "head" | "worker"
    group: Optional[str]
    phase: str = "Pending"     # Pending | Running | Failed | Terminating
    labels: Dict[str, str] = field(default_factory=dict)
    resources: Dict[str, float] = field(default_factory=dict)
    created_at: float = field(default_factory=time.time)


class KubeAPI:
    """The 4 pod verbs the operator needs (CoreV1 subset)."""

    def list_pods(self, selector: Dict[str, str]) -> List[Pod]:
        raise NotImplementedError

    def create_pod(self, pod: Pod) -> Pod:
        raise NotImplementedError

    def delete_pod(self, name: str) -> None:
        raise NotImplementedError

    def pod_phase(self, name: str) -> Optional[str]:
        raise NotImplementedError


class MockKubeAPI(KubeAPI):
    """In-memory pod store: created pods turn Running after
    ``ready_after`` polls (scheduling latency); test chaos via
    :meth:`fail_pod`."""

    def __init__(self, ready_after: int = 0):
        self._pods: Dict[str, Pod] = {}
        self._polls: Dict[str, int] = {}
        self.ready_after = ready_after
        self._lock = threading.Lock()

    def list_pods(self, selector: Dict[str, str]) -> List[Pod]:
        with self._lock:
            out = []
            for pod in self._pods.values():
                if all(pod.labels.get(k) == v
                       for k, v in selector.items()):
                    self._advance(pod)
                    out.append(copy.deepcopy(pod))
            return out

    def _advance(self, pod: Pod) -> None:
        if pod.phase == "Pending":
            n = self._polls.get(pod.name, 0) + 1
            self._polls[pod.name] = n
            if n > self.ready_after:
                pod.phase = "Running"

    def create_pod(self, pod: Pod) -> Pod:
        with self._lock:
            if pod.name in self._pods:
                raise ValueError(f"pod {pod.name} exists")
            self._pods[pod.name] = copy.deepcopy(pod)
            return pod

    def delete_pod(self, name: str) -> None:
        with self._lock:
            self._pods.pop(name, None)
            self._polls.pop(name, None)

    def pod_phase(self, name: str) -> Optional[str]:
        with self._lock:
            pod = self._pods.get(name)
            return pod.phase if pod else None

    def fail_pod(self, name: str) -> None:
        with self._lock:
            if name in self._pods:
                self._pods[name].phase = "Failed"


class KubectlAPI(KubeAPI):
    """Real-cluster path via kubectl; declared-but-gated here
    (no Kubernetes control plane in this environment)."""

    def __init__(self, namespace: str = "default"):
        import shutil

        if shutil.which("kubectl") is None:
            raise RuntimeError(
                "KubectlAPI needs kubectl on PATH; none found in this "
                "environment — use MockKubeAPI for tests or run the "
                "operator inside a cluster")
        self.namespace = namespace  # pragma: no cover - needs a cluster


class RayClusterOperator:
    """The reconcile loop (KubeRay raycluster_controller logic):

    observe pods -> compare against spec -> converge:
      * no Running/Pending head  -> create head pod (crash replacement)
      * group below replicas     -> create worker pods
      * group above replicas     -> delete newest surplus pods
      * Failed pods              -> delete (next pass recreates)
    One reconcile() call is one idempotent pass; run() loops it.
    """

    def __init__(self, api: KubeAPI, spec: RayClusterSpec,
                 poll_interval_s: float = 1.0):
        self.api = api
        self.spec = spec
        self.poll_interval_s = poll_interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.events: List[str] = []

    # -- selectors ---------------------------------------------------------
    def _selector(self) -> Dict[str, str]:
        return {"ray.io/cluster": self.spec.name}

    def _base_labels(self, role: str, group: Optional[str]
                     ) -> Dict[str, str]:
        labels = {"ray.io/cluster": self.spec.name, "ray.io/role": role}
        if group:
            labels["ray.io/group"] = group
        return labels

    def _log(self, msg: str) -> None:
        self.events.append(msg)

    # -- reconcile ---------------------------------------------------------
    def reconcile(self) -> Dict[str, Any]:
        pods = self.api.list_pods(self._selector())
        # Failed pods are deleted this pass; replacements appear next
        # pass (KubeRay does the same two-phase replacement).
        for pod in [p for p in pods if p.phase == "Failed"]:
            self._log(f"delete failed pod {pod.name}")
            self.api.delete_pod(pod.name)
        pods = [p for p in pods if p.phase != "Failed"]

        heads = [p for p in pods if p.role == "head"]
        if not heads:
            name = f"{self.spec.name}-head-{uuid.uuid4().hex[:6]}"
            self._log(f"create head pod {name}")
            self.api.create_pod(Pod(
                name=name, role="head", group=None,
                labels=self._base_labels("head", None),
                resources=dict(self.spec.head_resources)))

        for group in self.spec.worker_groups:
            members = sorted(
                (p for p in pods
                 if p.role == "worker" and p.group == group.group_name),
                key=lambda p: p.created_at)
            want = max(group.min_replicas,
                       min(group.replicas, group.max_replicas))
            for _ in range(want - len(members)):
                name = (f"{self.spec.name}-{group.group_name}-"
                        f"{uuid.uuid4().hex[:6]}")
                self._log(f"create worker pod {name}")
                self.api.create_pod(Pod(
                    name=name, role="worker", group=group.group_name,
                    labels=self._base_labels("worker", group.group_name),
                    resources=dict(group.resources)))
            for pod in members[want:] if want < len(members) else []:
                self._log(f"scale down: delete {pod.name}")
                self.api.delete_pod(pod.name)
        return self.status()

    def status(self) -> Dict[str, Any]:
        """The CRD's status subresource (KubeRay state/ready counts)."""
        pods = self.api.list_pods(self._selector())
        heads = [p for p in pods if p.role == "head"]
        groups = {}
        for g in self.spec.worker_groups:
            members = [p for p in pods if p.group == g.group_name]
            groups[g.group_name] = {
                "desired": g.replicas,
                "ready": sum(1 for p in members
                             if p.phase == "Running"),
                "pending": sum(1 for p in members
                               if p.phase == "Pending"),
            }
        head_ready = any(p.phase == "Running" for p in heads)
        all_ready = head_ready and all(
            v["ready"] >= min(g.replicas, g.max_replicas)
            for g, v in zip(self.spec.worker_groups, groups.values()))
        return {
            "state": "ready" if all_ready else "reconciling",
            "head": {"ready": head_ready},
            "worker_groups": groups,
            "num_pods": len(pods),
        }

    def scale_group(self, group_name: str, replicas: int) -> None:
        """Edit the CRD's replicas (what the autoscaler patches)."""
        for g in self.spec.worker_groups:
            if g.group_name == group_name:
                g.replicas = max(g.min_replicas,
                                 min(replicas, g.max_replicas))
                return
        raise KeyError(f"no worker group {group_name!r}")

    # -- background loop ---------------------------------------------------
    def run(self) -> "RayClusterOperator":
        def loop():
            while not self._stop.is_set():
                try:
                    self.reconcile()
                except Exception as e:  # noqa: BLE001 - keep looping
                    self._log(f"reconcile error: {e!r}")
                self._stop.wait(self.poll_interval_s)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="rt-kube-operator")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)


class KubeRayNodeProvider(NodeProvider):
    """Autoscaler-facing facade: nodes are worker pods; create/terminate
    become CRD replica edits that the operator reconciles (the KubeRay
    node provider pattern — the autoscaler never touches pods
    directly)."""

    def __init__(self, operator: RayClusterOperator):
        self.operator = operator

    def _group(self, node_type: str) -> WorkerGroupSpec:
        for g in self.operator.spec.worker_groups:
            if g.group_name == node_type:
                return g
        raise KeyError(f"no worker group {node_type!r}")

    def non_terminated_nodes(self) -> List[NodeInstance]:
        pods = self.operator.api.list_pods(self.operator._selector())
        return [
            NodeInstance(node_id=p.name, node_type=p.group or "head",
                         tags=dict(p.labels),
                         running=(p.phase == "Running"))
            for p in pods if p.role == "worker"
        ]

    def create_node(self, node_type: str, count: int = 1) -> List[str]:
        g = self._group(node_type)
        self.operator.scale_group(node_type, g.replicas + count)
        self.operator.reconcile()
        pods = self.operator.api.list_pods(self.operator._selector())
        members = sorted((p for p in pods if p.group == node_type),
                         key=lambda p: p.created_at)
        return [p.name for p in members[-count:]]

    def terminate_node(self, node_id: str) -> None:
        pods = self.operator.api.list_pods(self.operator._selector())
        for p in pods:
            if p.name == node_id and p.group:
                g = self._group(p.group)
                self.operator.scale_group(p.group, g.replicas - 1)
                self.operator.api.delete_pod(node_id)
                return
