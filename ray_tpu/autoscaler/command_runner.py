"""Command runners + node updater: the bring-up path for launched hosts.

Reference analog: ``python/ray/autoscaler/_private/command_runner.py``
(``SSHCommandRunner``: run/run_rsync_up with retries and ssh options)
and ``updater.py`` (``NodeUpdater``: wait-for-ready, sync files, run
setup commands, start the node process). Without this layer a provider
can launch a host but nothing can configure it — the gap that left the
TPU-pod provider mock-only in round 3.

Two runners: ``SSHCommandRunner`` for real remote hosts and
``SubprocessCommandRunner`` (localhost exec) so the updater lifecycle is
fully testable without sshd — the same split as the reference's
``SSHCommandRunner`` vs ``FakeCommandRunner``/local node provider.
"""

from __future__ import annotations

import os
import shlex
import subprocess
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional


class CommandRunnerError(RuntimeError):
    def __init__(self, cmd: str, returncode: int, output: str):
        super().__init__(
            f"command failed (rc={returncode}): {cmd}\n{output[-2000:]}")
        self.cmd = cmd
        self.returncode = returncode
        self.output = output


class CommandRunner:
    """Run commands / sync files on one node."""

    def run(self, cmd: str, timeout: float = 120.0,
            env: Optional[Dict[str, str]] = None) -> str:
        raise NotImplementedError

    def run_detached(self, cmd: str,
                     env: Optional[Dict[str, str]] = None) -> None:
        """Launch a long-running process that survives this runner."""
        raise NotImplementedError

    def sync_up(self, local_path: str, remote_path: str) -> None:
        raise NotImplementedError

    def ready(self, timeout: float = 60.0) -> bool:
        """Node reachable and able to execute commands."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                self.run("true", timeout=10)
                return True
            except Exception:  # noqa: BLE001 — keep probing
                time.sleep(1.0)
        return False


class SubprocessCommandRunner(CommandRunner):
    """Localhost execution — the testable updater path (reference:
    the local/fake command runner used by the local node provider)."""

    def __init__(self, cwd: Optional[str] = None):
        self.cwd = cwd

    def run(self, cmd: str, timeout: float = 120.0,
            env: Optional[Dict[str, str]] = None) -> str:
        full_env = dict(os.environ)
        full_env.update(env or {})
        proc = subprocess.run(
            ["/bin/sh", "-c", cmd], cwd=self.cwd, env=full_env,
            capture_output=True, text=True, timeout=timeout)
        if proc.returncode != 0:
            raise CommandRunnerError(cmd, proc.returncode,
                                     proc.stdout + proc.stderr)
        return proc.stdout

    def run_detached(self, cmd: str,
                     env: Optional[Dict[str, str]] = None) -> None:
        full_env = dict(os.environ)
        full_env.update(env or {})
        subprocess.Popen(
            ["/bin/sh", "-c", cmd], cwd=self.cwd, env=full_env,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            start_new_session=True)

    def sync_up(self, local_path: str, remote_path: str) -> None:
        os.makedirs(os.path.dirname(remote_path) or ".", exist_ok=True)
        subprocess.run(["cp", "-r", local_path, remote_path], check=True)


class SSHCommandRunner(CommandRunner):
    """SSH execution (reference: command_runner.py SSHCommandRunner —
    same ssh option set: batch mode, no host-key prompts, connection
    timeout; rsync for file sync)."""

    SSH_OPTS = [
        "-o", "ConnectTimeout=10s",
        "-o", "StrictHostKeyChecking=no",
        "-o", "UserKnownHostsFile=/dev/null",
        "-o", "BatchMode=yes",
        "-o", "LogLevel=ERROR",
    ]

    def __init__(self, host: str, user: Optional[str] = None,
                 ssh_key: Optional[str] = None, port: int = 22):
        self.host = host
        self.user = user
        self.ssh_key = ssh_key
        self.port = port

    def _target(self) -> str:
        return f"{self.user}@{self.host}" if self.user else self.host

    def _ssh_base(self) -> List[str]:
        base = ["ssh"] + list(self.SSH_OPTS) + ["-p", str(self.port)]
        if self.ssh_key:
            base += ["-i", self.ssh_key]
        return base

    def run(self, cmd: str, timeout: float = 120.0,
            env: Optional[Dict[str, str]] = None) -> str:
        exports = "".join(
            f"export {k}={shlex.quote(v)}; " for k, v in (env or {}).items())
        argv = self._ssh_base() + [self._target(), exports + cmd]
        proc = subprocess.run(argv, capture_output=True, text=True,
                              timeout=timeout)
        if proc.returncode != 0:
            raise CommandRunnerError(cmd, proc.returncode,
                                     proc.stdout + proc.stderr)
        return proc.stdout

    def run_detached(self, cmd: str,
                     env: Optional[Dict[str, str]] = None) -> None:
        exports = "".join(
            f"export {k}={shlex.quote(v)}; " for k, v in (env or {}).items())
        # nohup + setsid so the process survives the ssh session.
        self.run(f"setsid nohup sh -c {shlex.quote(exports + cmd)} "
                 f">/tmp/rt_node.log 2>&1 & echo started", timeout=30)

    def sync_up(self, local_path: str, remote_path: str) -> None:
        ssh_cmd = " ".join(self._ssh_base())
        subprocess.run(
            ["rsync", "-az", "-e", ssh_cmd, local_path,
             f"{self._target()}:{remote_path}"],
            check=True, timeout=300)


@dataclass
class NodeUpdater:
    """Drive a launched host from bare to cluster member (reference:
    updater.py NodeUpdater lifecycle: wait_ready → sync → setup →
    start): waits for the runner, syncs ``file_mounts``, runs
    ``setup_commands``, then launches ``rt start --address=<head>``
    detached."""

    runner: CommandRunner
    head_address: str
    file_mounts: Dict[str, str] = field(default_factory=dict)
    setup_commands: List[str] = field(default_factory=list)
    start_command: Optional[str] = None
    env: Dict[str, str] = field(default_factory=dict)
    num_workers: int = 2

    def update(self, ready_timeout: float = 120.0) -> None:
        if not self.runner.ready(timeout=ready_timeout):
            raise TimeoutError("node never became reachable")
        for local, remote in self.file_mounts.items():
            self.runner.sync_up(local, remote)
        for cmd in self.setup_commands:
            self.runner.run(cmd, timeout=600)
        start = self.start_command or (
            f"python -m ray_tpu.scripts.cli start "
            f"--address={self.head_address} "
            f"--num-workers={self.num_workers}")
        self.runner.run_detached(start, env=self.env)
