"""Autoscaler core: load metrics, bin-packing, scale up/down decisions.

Reference analog:
  - ``autoscaler/_private/load_metrics.py`` — per-node utilization +
    pending demand aggregation
  - ``autoscaler/_private/resource_demand_scheduler.py:43,102`` —
    ``get_nodes_to_launch``: first-fit bin-packing of pending demands over
    existing + launchable node types, respecting max workers
  - ``autoscaler/_private/autoscaler.py:162,353`` — ``StandardAutoscaler.
    update``: terminate idle nodes past timeout, launch to fit demand.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from .providers import NodeProvider


@dataclass
class NodeType:
    """Launchable node shape (reference: available_node_types yaml entries).

    ``topology`` labels TPU slices (e.g. {"tpu_slice": "v5e-8", "chips": 8})
    so mesh claims can demand them.
    """

    name: str
    resources: Dict[str, float]
    min_workers: int = 0
    max_workers: int = 10
    topology: Dict[str, object] = field(default_factory=dict)


@dataclass
class AutoscalerConfig:
    node_types: Dict[str, NodeType] = field(default_factory=dict)
    max_workers: int = 20
    idle_timeout_s: float = 60.0
    upscaling_speed: float = 1.0


class LoadMetrics:
    """Demand + utilization snapshot (reference: load_metrics.py)."""

    def __init__(self):
        self.pending_demands: List[Dict[str, float]] = []
        self.node_usage: Dict[str, Tuple[Dict[str, float], Dict[str, float]]] = {}
        self.last_active: Dict[str, float] = {}

    def update_node(self, node_id: str, total: Dict[str, float],
                    available: Dict[str, float]) -> None:
        self.node_usage[node_id] = (dict(total), dict(available))
        busy = any(available.get(k, 0) < v for k, v in total.items())
        if busy or node_id not in self.last_active:
            self.last_active[node_id] = time.monotonic()

    def set_pending_demands(self, demands: List[Dict[str, float]]) -> None:
        self.pending_demands = [dict(d) for d in demands]

    def idle_seconds(self, node_id: str) -> float:
        return time.monotonic() - self.last_active.get(node_id,
                                                       time.monotonic())

    @classmethod
    def from_runtime(cls, runtime) -> "LoadMetrics":
        """Snapshot a live runtime (the monitor's GCS poll equivalent).

        Demands include queued/infeasible task leases AND the bundles of
        pending placement groups — mesh claims lower to PG bundles of
        TPU chips (``MeshClaim.to_bundles``), so a pending claim surfaces
        as {"TPU": n} demands that bin-pack onto TPU-pod node types."""
        lm = cls()
        for node in runtime.scheduler.nodes():
            lm.update_node(node.node_id.hex(), node.ledger.total,
                           node.ledger.available)
        with runtime.scheduler._lock:
            demands = [dict(l.spec.resources)
                       for l in runtime.scheduler._queue]
            demands += [dict(l.spec.resources)
                        for l in runtime.scheduler._infeasible]
        pgm = getattr(runtime, "placement_group_manager", None)
        if pgm is not None:
            with pgm._lock:
                for pg in pgm._groups.values():
                    if pg.state in ("PENDING", "UNSCHEDULABLE"):
                        demands += [dict(b) for b in pg.bundles]
        lm.set_pending_demands([d for d in demands if d])
        return lm


class ResourceDemandScheduler:
    """Bin-pack pending demands -> node launches.

    Reference: resource_demand_scheduler.py get_nodes_to_launch — fit each
    demand onto existing free capacity first, then onto hypothetical new
    nodes of each type (first type that fits), respecting per-type and
    global caps.
    """

    def __init__(self, config: AutoscalerConfig):
        self.config = config

    def get_nodes_to_launch(
        self, metrics: LoadMetrics,
        existing_by_type: Dict[str, int],
    ) -> Dict[str, int]:
        free: List[Dict[str, float]] = [
            dict(avail) for _, avail in metrics.node_usage.values()
        ]
        to_launch: Dict[str, int] = {}
        planned: List[Tuple[str, Dict[str, float]]] = []

        def fits(pool: Dict[str, float], demand: Dict[str, float]) -> bool:
            return all(pool.get(k, 0.0) >= v for k, v in demand.items())

        def consume(pool: Dict[str, float], demand: Dict[str, float]):
            for k, v in demand.items():
                pool[k] = pool.get(k, 0.0) - v

        total_existing = sum(existing_by_type.values())
        for demand in sorted(metrics.pending_demands,
                             key=lambda d: -sum(d.values())):
            placed = False
            for pool in free:
                if fits(pool, demand):
                    consume(pool, demand)
                    placed = True
                    break
            if placed:
                continue
            for _, pool in planned:
                if fits(pool, demand):
                    consume(pool, demand)
                    placed = True
                    break
            if placed:
                continue
            for nt in self.config.node_types.values():
                count = (existing_by_type.get(nt.name, 0)
                         + to_launch.get(nt.name, 0))
                if count >= nt.max_workers:
                    continue
                if (total_existing + sum(to_launch.values())
                        >= self.config.max_workers):
                    break
                if fits(dict(nt.resources), demand):
                    pool = dict(nt.resources)
                    consume(pool, demand)
                    planned.append((nt.name, pool))
                    to_launch[nt.name] = to_launch.get(nt.name, 0) + 1
                    placed = True
                    break
        # min_workers floors.
        for nt in self.config.node_types.values():
            have = existing_by_type.get(nt.name, 0) + to_launch.get(nt.name, 0)
            if have < nt.min_workers:
                to_launch[nt.name] = (to_launch.get(nt.name, 0)
                                      + nt.min_workers - have)
        return to_launch


class StandardAutoscaler:
    """The update loop (reference: autoscaler.py:162 StandardAutoscaler).

    ``updater_factory(instance) -> NodeUpdater`` (optional) is the
    bring-up path: every node the provider launches is configured and
    joined to the cluster by its updater on a background thread
    (reference: the NodeUpdater threads spawned by
    ``autoscaler.py update_if_needed``)."""

    def __init__(self, provider: NodeProvider, config: AutoscalerConfig,
                 updater_factory=None):
        self.provider = provider
        self.config = config
        self.scheduler = ResourceDemandScheduler(config)
        self.updater_factory = updater_factory
        self.max_bringup_failures = 3
        self._updated: set = set()
        self._updater_threads: Dict[str, Any] = {}
        self._bringup_failures: Dict[str, int] = {}
        self.updater_errors: Dict[str, str] = {}

    def _maybe_update_nodes(self, nodes) -> None:
        if self.updater_factory is None:
            return
        import threading

        for inst in nodes:
            if inst.node_id in self._updated:
                continue
            if getattr(inst, "tags", None) and \
                    inst.tags.get("rt-configured"):
                # Provider-persisted marker: survives autoscaler
                # restarts, so already-joined hosts are not re-setup
                # (providers without label persistence re-run bring-up
                # after a restart — start commands must be idempotent).
                self._updated.add(inst.node_id)
                continue
            self._updated.add(inst.node_id)
            updater = self.updater_factory(inst)
            if updater is None:
                continue

            def run(node_id=inst.node_id, updater=updater):
                try:
                    updater.update()
                except Exception as e:  # noqa: BLE001 — recorded, visible
                    self.updater_errors[node_id] = repr(e)
                    n = self._bringup_failures.get(node_id, 0) + 1
                    self._bringup_failures[node_id] = n
                    if n >= self.max_bringup_failures:
                        # Give up: a phantom node that never joined
                        # satisfies demand counts without capacity.
                        try:
                            self.provider.terminate_node(node_id)
                        except Exception:  # noqa: BLE001
                            pass
                    else:
                        # Retry on the next tick.
                        self._updated.discard(node_id)
                    return
                self.updater_errors.pop(node_id, None)
                label = getattr(self.provider, "label_node", None)
                if label is not None:
                    try:
                        label(node_id, {"rt-configured": "1"})
                    except Exception:  # noqa: BLE001
                        pass

            t = threading.Thread(target=run, daemon=True,
                                 name=f"rt-updater-{inst.node_id[:8]}")
            self._updater_threads[inst.node_id] = t
            t.start()

    def update(self, metrics: LoadMetrics) -> Dict[str, int]:
        """One reconcile tick: terminate idle, launch for demand."""
        nodes = self.provider.non_terminated_nodes()
        by_type: Dict[str, int] = {}
        for n in nodes:
            by_type[n.node_type] = by_type.get(n.node_type, 0) + 1
        # Scale down: idle past timeout, above min_workers.
        for n in nodes:
            nt = self.config.node_types.get(n.node_type)
            if nt is None:
                continue
            if by_type.get(n.node_type, 0) <= nt.min_workers:
                continue
            # Provider ids and runtime ids may differ; match by suffix.
            idle = min(
                (metrics.idle_seconds(rid) for rid in metrics.node_usage
                 if n.node_id.endswith(rid[:8]) or rid.startswith(
                     n.node_id.split("-")[-1])),
                default=metrics.idle_seconds(n.node_id),
            )
            if idle > self.config.idle_timeout_s:
                self.provider.terminate_node(n.node_id)
                by_type[n.node_type] -= 1
        # Scale up.
        to_launch = self.scheduler.get_nodes_to_launch(metrics, by_type)
        for node_type, count in to_launch.items():
            self.provider.create_node(node_type, count)
        # Bring-up: configure + join any launched-but-unconfigured node.
        self._maybe_update_nodes(self.provider.non_terminated_nodes())
        return to_launch
