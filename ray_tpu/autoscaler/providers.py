"""Node providers: the cloud abstraction behind the autoscaler.

Reference analog: ``autoscaler/node_provider.py`` (NodeProvider plugin API)
+ ``_private/fake_multi_node/node_provider.py:237`` (fake provider driving
the in-process Cluster for tests — how autoscaler e2e runs without a
cloud). A GCP-TPU-style provider would map node types to pod-slice
acceleratorTypes (reference: ``_private/gcp/node.py:187`` GCPTPUNode).
"""

from __future__ import annotations

import threading
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class NodeInstance:
    node_id: str
    node_type: str
    tags: Dict[str, str] = field(default_factory=dict)
    running: bool = True


class NodeProvider:
    """Plugin API: subclass per cloud."""

    def non_terminated_nodes(self) -> List[NodeInstance]:
        raise NotImplementedError

    def create_node(self, node_type: str, count: int = 1) -> List[str]:
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError


class FakeNodeProvider(NodeProvider):
    """In-memory provider for pure-logic autoscaler tests."""

    def __init__(self):
        self._nodes: Dict[str, NodeInstance] = {}
        self._lock = threading.Lock()
        self.create_calls: List[tuple] = []
        self.terminate_calls: List[str] = []

    def non_terminated_nodes(self) -> List[NodeInstance]:
        with self._lock:
            return [n for n in self._nodes.values() if n.running]

    def create_node(self, node_type: str, count: int = 1) -> List[str]:
        ids = []
        with self._lock:
            for _ in range(count):
                nid = f"{node_type}-{uuid.uuid4().hex[:8]}"
                self._nodes[nid] = NodeInstance(nid, node_type)
                ids.append(nid)
            self.create_calls.append((node_type, count))
        return ids

    def label_node(self, node_id: str, tags: Dict[str, str]) -> None:
        """Persist bring-up markers on the instance (reference: the
        node status tags the autoscaler sets via the provider)."""
        with self._lock:
            node = self._nodes.get(node_id)
            if node is not None:
                node.tags.update(tags)

    def terminate_node(self, node_id: str) -> None:
        with self._lock:
            node = self._nodes.get(node_id)
            if node:
                node.running = False
            self.terminate_calls.append(node_id)


class TPUPodAPI:
    """Client surface of a TPU-VM pod-slice API (reference: the ``GCPTPU``
    resource client, ``autoscaler/_private/gcp/node.py:547`` — create /
    delete / list TPU nodes by acceleratorType). Subclass per cloud; the
    mock below serves autoscaler logic and tests, matching how the
    reference tests autoscaler e2e with a fake provider."""

    def create_tpu(self, name: str, accelerator_type: str,
                   labels: Optional[Dict[str, str]] = None) -> dict:
        raise NotImplementedError

    def delete_tpu(self, name: str) -> None:
        raise NotImplementedError

    def list_tpus(self) -> List[dict]:
        raise NotImplementedError


class MockTPUPodAPI(TPUPodAPI):
    """In-memory TPU API: slices come up READY after ``ready_after``
    polls (CREATING first, like real slice provisioning)."""

    def __init__(self, ready_after: int = 0):
        self._slices: Dict[str, dict] = {}
        self._lock = threading.Lock()
        self._ready_after = ready_after
        self.create_calls: List[tuple] = []
        self.delete_calls: List[str] = []

    def create_tpu(self, name, accelerator_type, labels=None) -> dict:
        with self._lock:
            entry = {"name": name, "acceleratorType": accelerator_type,
                     "state": "CREATING" if self._ready_after else "READY",
                     "labels": dict(labels or {}), "polls": 0}
            self._slices[name] = entry
            self.create_calls.append((name, accelerator_type))
            return dict(entry)

    def delete_tpu(self, name) -> None:
        with self._lock:
            self._slices.pop(name, None)
            self.delete_calls.append(name)

    def list_tpus(self) -> List[dict]:
        with self._lock:
            out = []
            for entry in self._slices.values():
                if entry["state"] == "CREATING":
                    entry["polls"] += 1
                    if entry["polls"] >= self._ready_after:
                        entry["state"] = "READY"
                out.append(dict(entry))
            return out


class TPUPodProvider(NodeProvider):
    """Maps autoscaler node types to TPU pod slices: one provider node =
    one slice of the node type's ``topology["accelerator_type"]``
    (reference: ``GCPTPUNode``, ``gcp/node.py:187`` + the ``tpu.yaml``
    node type with ``acceleratorType: v2-8``). A pending mesh claim's
    {"TPU": n} demand bin-packs onto these types, so claims trigger
    slice scale-up."""

    def __init__(self, api: TPUPodAPI, node_types: Dict[str, Any],
                 name_prefix: str = "rt-tpu"):
        self._api = api
        self._types = node_types
        self._prefix = name_prefix
        self._counter = 0
        self._lock = threading.Lock()

    def accelerator_type_for(self, node_type: str) -> str:
        nt = self._types[node_type]
        topo = getattr(nt, "topology", None) or {}
        acc = topo.get("accelerator_type") or topo.get("tpu_slice")
        if not acc:
            raise ValueError(
                f"node type {node_type!r} has no "
                f"topology['accelerator_type'] (e.g. 'v5e-8')")
        return str(acc)

    def non_terminated_nodes(self) -> List[NodeInstance]:
        out = []
        for s in self._api.list_tpus():
            labels = s.get("labels", {})
            out.append(NodeInstance(
                s["name"], labels.get("rt-node-type", s["acceleratorType"]),
                tags={"state": s["state"],
                      "acceleratorType": s["acceleratorType"]},
                running=s["state"] in ("CREATING", "READY"),
            ))
        return out

    def create_node(self, node_type: str, count: int = 1) -> List[str]:
        acc = self.accelerator_type_for(node_type)
        ids = []
        for _ in range(count):
            with self._lock:
                self._counter += 1
                name = f"{self._prefix}-{node_type}-{self._counter}"
            self._api.create_tpu(name, acc,
                                 labels={"rt-node-type": node_type})
            ids.append(name)
        return ids

    def terminate_node(self, node_id: str) -> None:
        self._api.delete_tpu(node_id)


class LocalNodeProvider(NodeProvider):
    """Backs provider nodes with real simulated cluster nodes.

    The e2e analog of the fake multi-node provider: ``create_node`` adds a
    NodeManager to the live runtime, ``terminate_node`` removes it.
    """

    def __init__(self, cluster, node_types: Dict[str, "NodeType"]):
        self._cluster = cluster
        self._types = node_types
        self._nodes: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def non_terminated_nodes(self) -> List[NodeInstance]:
        with self._lock:
            return [NodeInstance(nid, t) for nid, (t, _) in self._nodes.items()]

    def create_node(self, node_type: str, count: int = 1) -> List[str]:
        nt = self._types[node_type]
        out = []
        for _ in range(count):
            runtime_node_id = self._cluster.add_node(
                num_cpus=nt.resources.get("CPU", 1),
                resources={k: v for k, v in nt.resources.items()
                           if k != "CPU"},
            )
            nid = f"{node_type}-{runtime_node_id.hex()[:8]}"
            with self._lock:
                self._nodes[nid] = (node_type, runtime_node_id)
            out.append(nid)
        return out

    def terminate_node(self, node_id: str) -> None:
        with self._lock:
            entry = self._nodes.pop(node_id, None)
        if entry is not None:
            self._cluster.remove_node(entry[1])
