"""Node providers: the cloud abstraction behind the autoscaler.

Reference analog: ``autoscaler/node_provider.py`` (NodeProvider plugin API)
+ ``_private/fake_multi_node/node_provider.py:237`` (fake provider driving
the in-process Cluster for tests — how autoscaler e2e runs without a
cloud). A GCP-TPU-style provider would map node types to pod-slice
acceleratorTypes (reference: ``_private/gcp/node.py:187`` GCPTPUNode).
"""

from __future__ import annotations

import threading
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class NodeInstance:
    node_id: str
    node_type: str
    tags: Dict[str, str] = field(default_factory=dict)
    running: bool = True


class NodeProvider:
    """Plugin API: subclass per cloud."""

    def non_terminated_nodes(self) -> List[NodeInstance]:
        raise NotImplementedError

    def create_node(self, node_type: str, count: int = 1) -> List[str]:
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError


class FakeNodeProvider(NodeProvider):
    """In-memory provider for pure-logic autoscaler tests."""

    def __init__(self):
        self._nodes: Dict[str, NodeInstance] = {}
        self._lock = threading.Lock()
        self.create_calls: List[tuple] = []
        self.terminate_calls: List[str] = []

    def non_terminated_nodes(self) -> List[NodeInstance]:
        with self._lock:
            return [n for n in self._nodes.values() if n.running]

    def create_node(self, node_type: str, count: int = 1) -> List[str]:
        ids = []
        with self._lock:
            for _ in range(count):
                nid = f"{node_type}-{uuid.uuid4().hex[:8]}"
                self._nodes[nid] = NodeInstance(nid, node_type)
                ids.append(nid)
            self.create_calls.append((node_type, count))
        return ids

    def terminate_node(self, node_id: str) -> None:
        with self._lock:
            node = self._nodes.get(node_id)
            if node:
                node.running = False
            self.terminate_calls.append(node_id)


class LocalNodeProvider(NodeProvider):
    """Backs provider nodes with real simulated cluster nodes.

    The e2e analog of the fake multi-node provider: ``create_node`` adds a
    NodeManager to the live runtime, ``terminate_node`` removes it.
    """

    def __init__(self, cluster, node_types: Dict[str, "NodeType"]):
        self._cluster = cluster
        self._types = node_types
        self._nodes: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def non_terminated_nodes(self) -> List[NodeInstance]:
        with self._lock:
            return [NodeInstance(nid, t) for nid, (t, _) in self._nodes.items()]

    def create_node(self, node_type: str, count: int = 1) -> List[str]:
        nt = self._types[node_type]
        out = []
        for _ in range(count):
            runtime_node_id = self._cluster.add_node(
                num_cpus=nt.resources.get("CPU", 1),
                resources={k: v for k, v in nt.resources.items()
                           if k != "CPU"},
            )
            nid = f"{node_type}-{runtime_node_id.hex()[:8]}"
            with self._lock:
                self._nodes[nid] = (node_type, runtime_node_id)
            out.append(nid)
        return out

    def terminate_node(self, node_id: str) -> None:
        with self._lock:
            entry = self._nodes.pop(node_id, None)
        if entry is not None:
            self._cluster.remove_node(entry[1])
