// Native control-store daemon — the cluster metadata authority.
//
// Reference analog: src/ray/gcs/gcs_server/ (GcsServer hosting the node
// table + health checker, internal KV, pubsub) and src/ray/pubsub/.  The
// reference serves these over gRPC; here the wire is a minimal
// length-prefixed binary protocol over TCP (loopback for single-host,
// routable for multi-host DCN control traffic).  Payload schemas (node
// info, published messages) are opaque bytes to the daemon — language
// frontends pick the encoding, mirroring how the reference's KV stores
// serialized protobufs it never inspects.
//
// Build: part of the `make -C ray_tpu/_native` default target
// (control_store binary).  Driven from Python by
// ray_tpu/core/gcs_socket.py.
//
// Protocol (all integers little-endian u32 unless noted):
//   request  := u32 frame_len | u8 op | fields...
//   response := u32 frame_len | u8 status | fields...
//   bytes field := u32 len | raw
//   status: 0 = OK, 1 = ERR (payload = message), 2 = NIL (KV miss)
//   Subscribed connections additionally receive push frames:
//     u32 frame_len | u8 0xFE | channel | payload

#include <arpa/inet.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <signal.h>
#include <sys/file.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

enum Op : uint8_t {
  OP_PING = 1,
  OP_KV_PUT = 2,
  OP_KV_GET = 3,
  OP_KV_DEL = 4,
  OP_KV_KEYS = 5,
  OP_NODE_REGISTER = 10,
  OP_NODE_HEARTBEAT = 11,
  OP_NODE_LIST = 12,
  OP_NODE_MARK_DEAD = 13,
  OP_PUBLISH = 20,
  OP_SUBSCRIBE = 21,
  OP_HEALTH_START = 30,
  OP_STATS = 31,
  // Durable control-plane tables (reference: gcs_table_storage.h — one
  // storage table per FSM: actors, jobs, placement groups). Values are
  // opaque frontend-encoded records; SCAN returns a full table so a
  // restarted head can reload every FSM in one round trip per table.
  OP_TABLE_PUT = 40,
  OP_TABLE_DEL = 41,
  OP_TABLE_SCAN = 42,
  OP_SHUTDOWN = 99,
  OP_PUSH = 0xFE,
};

enum Status : uint8_t { ST_OK = 0, ST_ERR = 1, ST_NIL = 2 };

double MonotonicSeconds() {
  return std::chrono::duration<double>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// ---------------------------------------------------------------------------
// Wire helpers
// ---------------------------------------------------------------------------

bool ReadAll(int fd, void* buf, size_t n) {
  auto* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::read(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool WriteAll(int fd, const void* buf, size_t n) {
  const auto* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t r = ::write(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

class Reader {  // cursor over a received frame
 public:
  Reader(const std::vector<char>& buf) : buf_(buf) {}
  bool U8(uint8_t* v) {
    if (pos_ + 1 > buf_.size()) return false;
    *v = static_cast<uint8_t>(buf_[pos_++]);
    return true;
  }
  bool U32(uint32_t* v) {
    if (pos_ + 4 > buf_.size()) return false;
    std::memcpy(v, buf_.data() + pos_, 4);
    pos_ += 4;
    return true;
  }
  bool F64(double* v) {
    if (pos_ + 8 > buf_.size()) return false;
    std::memcpy(v, buf_.data() + pos_, 8);
    pos_ += 8;
    return true;
  }
  bool Bytes(std::string* out) {
    uint32_t n;
    if (!U32(&n) || pos_ + n > buf_.size()) return false;
    out->assign(buf_.data() + pos_, n);
    pos_ += n;
    return true;
  }

 private:
  const std::vector<char>& buf_;
  size_t pos_ = 0;
};

struct Connection {
  int fd;
  std::mutex write_mu;  // responses and pushes interleave
  bool closed = false;  // guarded by write_mu; set before ::close(fd)
  explicit Connection(int f) : fd(f) {}
};

class Writer {  // builds a frame body (status/op byte first)
 public:
  explicit Writer(uint8_t first) { buf_.push_back(static_cast<char>(first)); }
  void U8(uint8_t v) { buf_.push_back(static_cast<char>(v)); }
  void U32(uint32_t v) { Append(&v, 4); }
  void F64(double v) { Append(&v, 8); }
  void Bytes(const std::string& s) {
    U32(static_cast<uint32_t>(s.size()));
    Append(s.data(), s.size());
  }
  bool Send(Connection* conn) {
    uint32_t len = static_cast<uint32_t>(buf_.size());
    // Serialized with close: a publish must never write into an fd the
    // handler already closed (the number could be reused by a new accept).
    std::lock_guard<std::mutex> lk(conn->write_mu);
    if (conn->closed) return false;
    return WriteAll(conn->fd, &len, 4) &&
           WriteAll(conn->fd, buf_.data(), buf_.size());
  }

 private:
  void Append(const void* p, size_t n) {
    const auto* c = static_cast<const char*>(p);
    buf_.insert(buf_.end(), c, c + n);
  }
  std::vector<char> buf_;
};

// ---------------------------------------------------------------------------
// Store state
// ---------------------------------------------------------------------------

struct NodeEntry {
  std::string info;  // opaque frontend-encoded payload
  bool alive = true;
  double last_heartbeat = 0;
};

class ControlStore {
 public:
  // KV ------------------------------------------------------------------
  bool KvPut(const std::string& ns, const std::string& key,
             const std::string& val, bool overwrite) {
    std::lock_guard<std::mutex> lk(mu_);
    auto& m = kv_[ns];
    if (!overwrite && m.count(key)) return false;
    m[key] = val;
    return true;
  }
  bool KvGet(const std::string& ns, const std::string& key,
             std::string* out) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = kv_.find(ns);
    if (it == kv_.end()) return false;
    auto jt = it->second.find(key);
    if (jt == it->second.end()) return false;
    *out = jt->second;
    return true;
  }
  bool KvDel(const std::string& ns, const std::string& key) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = kv_.find(ns);
    return it != kv_.end() && it->second.erase(key) > 0;
  }
  std::vector<std::string> KvKeys(const std::string& ns,
                                  const std::string& prefix) {
    std::lock_guard<std::mutex> lk(mu_);
    std::vector<std::string> out;
    auto it = kv_.find(ns);
    if (it == kv_.end()) return out;
    for (const auto& [k, _] : it->second)
      if (k.rfind(prefix, 0) == 0) out.push_back(k);
    return out;
  }

  // Node table -----------------------------------------------------------
  void NodeRegister(const std::string& id, const std::string& info) {
    {
      std::lock_guard<std::mutex> lk(mu_);
      auto& e = nodes_[id];
      e.info = info;
      e.alive = true;
      e.last_heartbeat = MonotonicSeconds();
    }
    Publish("NODE", "ALIVE:" + id);
  }
  void NodeHeartbeat(const std::string& id) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = nodes_.find(id);
    if (it != nodes_.end()) it->second.last_heartbeat = MonotonicSeconds();
  }
  bool NodeMarkDead(const std::string& id) {
    {
      std::lock_guard<std::mutex> lk(mu_);
      auto it = nodes_.find(id);
      if (it == nodes_.end() || !it->second.alive) return false;
      it->second.alive = false;
    }
    Publish("NODE", "DEAD:" + id);
    return true;
  }
  std::vector<std::tuple<std::string, bool, double, std::string>> NodeList() {
    std::lock_guard<std::mutex> lk(mu_);
    std::vector<std::tuple<std::string, bool, double, std::string>> out;
    double now = MonotonicSeconds();
    for (const auto& [id, e] : nodes_)
      out.emplace_back(id, e.alive, now - e.last_heartbeat, e.info);
    return out;
  }

  // Control-plane tables (actor/job/PG records) --------------------------
  void TablePut(const std::string& table, const std::string& key,
                const std::string& val) {
    std::lock_guard<std::mutex> lk(mu_);
    tables_[table][key] = val;
  }
  bool TableDel(const std::string& table, const std::string& key) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = tables_.find(table);
    return it != tables_.end() && it->second.erase(key) > 0;
  }
  std::vector<std::pair<std::string, std::string>> TableScan(
      const std::string& table) {
    std::lock_guard<std::mutex> lk(mu_);
    std::vector<std::pair<std::string, std::string>> out;
    auto it = tables_.find(table);
    if (it == tables_.end()) return out;
    out.reserve(it->second.size());
    for (const auto& [k, v] : it->second) out.emplace_back(k, v);
    return out;
  }

  // Pubsub ---------------------------------------------------------------
  void Subscribe(const std::string& channel,
                 std::shared_ptr<Connection> conn) {
    std::lock_guard<std::mutex> lk(mu_);
    auto& vec = subs_[channel];
    // Dedup per connection: a client's resubscribe handshake can race
    // its own concurrent subscribe() — double registration would push
    // every message twice for the connection's lifetime.
    for (const auto& c : vec)
      if (c.get() == conn.get()) return;
    vec.push_back(conn);
  }
  uint32_t Publish(const std::string& channel, const std::string& payload) {
    std::vector<std::shared_ptr<Connection>> targets;
    {
      std::lock_guard<std::mutex> lk(mu_);
      auto it = subs_.find(channel);
      if (it == subs_.end()) return 0;
      targets = it->second;
    }
    uint32_t delivered = 0;
    std::set<int> dead;
    for (auto& conn : targets) {
      Writer push(OP_PUSH);
      push.Bytes(channel);
      push.Bytes(payload);
      if (push.Send(conn.get())) {
        delivered++;
      } else {
        dead.insert(conn->fd);
      }
    }
    if (!dead.empty()) {
      std::lock_guard<std::mutex> lk(mu_);
      for (auto& [ch, vec] : subs_) {
        vec.erase(std::remove_if(vec.begin(), vec.end(),
                                 [&](const std::shared_ptr<Connection>& c) {
                                   return dead.count(c->fd) > 0;
                                 }),
                  vec.end());
      }
    }
    return delivered;
  }
  void DropConnection(int fd) {
    std::lock_guard<std::mutex> lk(mu_);
    for (auto& [ch, vec] : subs_)
      vec.erase(std::remove_if(vec.begin(), vec.end(),
                               [&](const std::shared_ptr<Connection>& c) {
                                 return c->fd == fd;
                               }),
                vec.end());
  }

  // Health checker (GcsHeartbeatManager equivalent) ----------------------
  void StartHealth(double period_s, uint32_t timeout_beats) {
    std::lock_guard<std::mutex> lk(health_mu_);
    health_period_ = period_s;
    health_beats_ = timeout_beats;
    if (health_running_) return;
    health_running_ = true;
    health_thread_ = std::thread([this] { HealthLoop(); });
  }
  void HealthLoop() {
    std::unique_lock<std::mutex> lk(health_mu_);
    while (!stopping_) {
      health_cv_.wait_for(lk, std::chrono::duration<double>(health_period_));
      if (stopping_) break;
      double deadline = MonotonicSeconds() - health_period_ * health_beats_;
      std::vector<std::string> expired;
      {
        std::lock_guard<std::mutex> slk(mu_);
        for (const auto& [id, e] : nodes_)
          if (e.alive && e.last_heartbeat < deadline) expired.push_back(id);
      }
      for (const auto& id : expired) NodeMarkDead(id);
    }
  }

  void Stats(uint32_t* n_nodes, uint32_t* n_kv, uint32_t* n_subs) {
    std::lock_guard<std::mutex> lk(mu_);
    *n_nodes = static_cast<uint32_t>(nodes_.size());
    uint32_t kv = 0;
    for (const auto& [_, m] : kv_) kv += static_cast<uint32_t>(m.size());
    *n_kv = kv;
    uint32_t s = 0;
    for (const auto& [_, v] : subs_) s += static_cast<uint32_t>(v.size());
    *n_subs = s;
  }

  void Shutdown() {
    {
      std::lock_guard<std::mutex> lk(health_mu_);
      stopping_ = true;
    }
    health_cv_.notify_all();
    if (health_thread_.joinable()) health_thread_.join();
  }

 private:
  std::mutex mu_;
  std::unordered_map<std::string, std::unordered_map<std::string, std::string>>
      kv_;
  // table name -> key -> opaque record (std::map: deterministic scans)
  std::unordered_map<std::string, std::map<std::string, std::string>> tables_;
  std::map<std::string, NodeEntry> nodes_;
  std::unordered_map<std::string, std::vector<std::shared_ptr<Connection>>>
      subs_;

  std::mutex health_mu_;
  std::condition_variable health_cv_;
  std::thread health_thread_;
  double health_period_ = 1.0;
  uint32_t health_beats_ = 5;
  bool health_running_ = false;
  bool stopping_ = false;
};

std::atomic<bool> g_shutdown{false};
std::atomic<int> g_listen_fd{-1};

// ---------------------------------------------------------------------------
// Persistence: append-only mutation log, replayed on startup.
// Reference analog: GcsTableStorage over RedisStoreClient — restartable
// control-plane state. Only durable mutations are logged (KV put/del,
// node register/mark-dead); heartbeats and pubsub are runtime-only.
// Record format: u32 len | raw request frame (op byte + fields).
// ---------------------------------------------------------------------------

std::FILE* g_persist = nullptr;
std::mutex g_persist_mu;

bool IsDurableOp(uint8_t op) {
  return op == OP_KV_PUT || op == OP_KV_DEL || op == OP_NODE_REGISTER ||
         op == OP_NODE_MARK_DEAD || op == OP_TABLE_PUT || op == OP_TABLE_DEL;
}

// Caller must hold g_persist_mu (the durable-op apply lock): log order
// MUST equal apply order or replay reconstructs a different state than
// the live store had (e.g. a lost no-overwrite race flips winners).
void PersistFrameLocked(const std::vector<char>& frame) {
  if (g_persist == nullptr) return;
  uint32_t len = static_cast<uint32_t>(frame.size());
  std::fwrite(&len, 4, 1, g_persist);
  std::fwrite(frame.data(), 1, frame.size(), g_persist);
  std::fflush(g_persist);
}

// Parse one durable-mutation frame, applying it to `store` when non-null
// (validate-only pass when null). Returns false when the frame is not a
// complete durable mutation. Used both by WAL replay (apply) and by the
// connection handler BEFORE persisting (validate) — a malformed frame
// must never reach the log, because replay treats an unparseable record
// as a torn tail and truncates everything after it.
bool ParseDurableFrame(ControlStore* store, const std::vector<char>& frame) {
  Reader r(frame);
  uint8_t op;
  if (!r.U8(&op)) return false;
  switch (op) {
    case OP_KV_PUT: {
      std::string ns, key, val;
      uint8_t overwrite;
      if (!r.Bytes(&ns) || !r.Bytes(&key) || !r.Bytes(&val) ||
          !r.U8(&overwrite))
        return false;
      if (store) store->KvPut(ns, key, val, overwrite != 0);
      return true;
    }
    case OP_KV_DEL: {
      std::string ns, key;
      if (!r.Bytes(&ns) || !r.Bytes(&key)) return false;
      if (store) store->KvDel(ns, key);
      return true;
    }
    case OP_NODE_REGISTER: {
      std::string id, info;
      if (!r.Bytes(&id) || !r.Bytes(&info)) return false;
      if (store) store->NodeRegister(id, info);
      return true;
    }
    case OP_NODE_MARK_DEAD: {
      std::string id;
      if (!r.Bytes(&id)) return false;
      if (store) store->NodeMarkDead(id);
      return true;
    }
    case OP_TABLE_PUT: {
      std::string table, key, val;
      if (!r.Bytes(&table) || !r.Bytes(&key) || !r.Bytes(&val)) return false;
      if (store) store->TablePut(table, key, val);
      return true;
    }
    case OP_TABLE_DEL: {
      std::string table, key;
      if (!r.Bytes(&table) || !r.Bytes(&key)) return false;
      if (store) store->TableDel(table, key);
      return true;
    }
    default:
      // Only durable ops are ever logged; anything else is garbage bytes
      // that happened to parse as a length-prefixed frame.
      return false;
  }
}

void ReplayLog(ControlStore* store, const char* path) {
  std::FILE* f = std::fopen(path, "rb");
  if (f == nullptr) return;  // first start: nothing to replay
  size_t replayed = 0;
  // Byte offset just past the last fully-valid record: a SIGKILL
  // mid-append leaves a truncated/garbage final record, which must be
  // DROPPED (truncate below) — appending new mutations after the torn
  // bytes would hide them from every future replay.
  long valid_end = 0;
  for (;;) {
    uint32_t len;
    if (std::fread(&len, 4, 1, f) != 1) break;          // clean EOF or torn len
    if (len > (64u << 20)) break;                       // corrupt length
    std::vector<char> frame(len);
    if (std::fread(frame.data(), 1, len, f) != len) break;  // torn body
    if (!ParseDurableFrame(store, frame)) break;        // garbage record
    replayed++;
    valid_end = std::ftell(f);
  }
  std::fseek(f, 0, SEEK_END);
  long file_end = std::ftell(f);
  std::fclose(f);
  if (file_end > valid_end) {
    if (::truncate(path, valid_end) == 0) {
      std::fprintf(stderr,
                   "control_store: dropped torn log tail (%ld bytes at "
                   "offset %ld) in %s\n",
                   file_end - valid_end, valid_end, path);
    } else {
      std::perror("control_store: truncate torn tail");
    }
  }
  std::fprintf(stderr, "control_store: replayed %zu mutations from %s\n",
               replayed, path);
}

void HandleConnection(ControlStore* store, std::shared_ptr<Connection> conn) {
  for (;;) {
    uint32_t frame_len;
    if (!ReadAll(conn->fd, &frame_len, 4)) break;
    if (frame_len > (64u << 20)) break;  // sanity cap: 64 MiB control frames
    std::vector<char> frame(frame_len);
    if (!ReadAll(conn->fd, frame.data(), frame_len)) break;
    Reader r(frame);
    uint8_t op;
    if (!r.U8(&op)) break;
    // Durable ops serialize log+apply under one lock so the mutation log
    // replays in exactly the order mutations took effect; the log write
    // happens BEFORE the case sends its ack (write-ahead: an acked
    // mutation is never lost to a crash between ack and append) but only
    // AFTER the body validates — a malformed frame in the log would read
    // as a torn tail on replay and truncate every record after it.
    std::unique_lock<std::mutex> durable_lk;
    if (IsDurableOp(op)) {
      if (!ParseDurableFrame(nullptr, frame)) goto malformed;
      durable_lk = std::unique_lock<std::mutex>(g_persist_mu);
      PersistFrameLocked(frame);
    }

    switch (op) {
      case OP_PING: {
        Writer w(ST_OK);
        w.Send(conn.get());
        break;
      }
      case OP_KV_PUT: {
        std::string ns, key, val;
        uint8_t overwrite;
        if (!r.Bytes(&ns) || !r.Bytes(&key) || !r.Bytes(&val) ||
            !r.U8(&overwrite))
          goto malformed;
        Writer w(ST_OK);
        w.U8(store->KvPut(ns, key, val, overwrite != 0) ? 1 : 0);
        w.Send(conn.get());
        break;
      }
      case OP_KV_GET: {
        std::string ns, key, val;
        if (!r.Bytes(&ns) || !r.Bytes(&key)) goto malformed;
        if (store->KvGet(ns, key, &val)) {
          Writer w(ST_OK);
          w.Bytes(val);
          w.Send(conn.get());
        } else {
          Writer w(ST_NIL);
          w.Send(conn.get());
        }
        break;
      }
      case OP_KV_DEL: {
        std::string ns, key;
        if (!r.Bytes(&ns) || !r.Bytes(&key)) goto malformed;
        Writer w(ST_OK);
        w.U8(store->KvDel(ns, key) ? 1 : 0);
        w.Send(conn.get());
        break;
      }
      case OP_KV_KEYS: {
        std::string ns, prefix;
        if (!r.Bytes(&ns) || !r.Bytes(&prefix)) goto malformed;
        auto keys = store->KvKeys(ns, prefix);
        Writer w(ST_OK);
        w.U32(static_cast<uint32_t>(keys.size()));
        for (const auto& k : keys) w.Bytes(k);
        w.Send(conn.get());
        break;
      }
      case OP_NODE_REGISTER: {
        std::string id, info;
        if (!r.Bytes(&id) || !r.Bytes(&info)) goto malformed;
        store->NodeRegister(id, info);
        Writer w(ST_OK);
        w.Send(conn.get());
        break;
      }
      case OP_NODE_HEARTBEAT: {
        std::string id;
        if (!r.Bytes(&id)) goto malformed;
        store->NodeHeartbeat(id);
        Writer w(ST_OK);
        w.Send(conn.get());
        break;
      }
      case OP_NODE_LIST: {
        auto nodes = store->NodeList();
        Writer w(ST_OK);
        w.U32(static_cast<uint32_t>(nodes.size()));
        for (const auto& [id, alive, age, info] : nodes) {
          w.Bytes(id);
          w.U8(alive ? 1 : 0);
          w.F64(age);
          w.Bytes(info);
        }
        w.Send(conn.get());
        break;
      }
      case OP_NODE_MARK_DEAD: {
        std::string id;
        if (!r.Bytes(&id)) goto malformed;
        Writer w(ST_OK);
        w.U8(store->NodeMarkDead(id) ? 1 : 0);
        w.Send(conn.get());
        break;
      }
      case OP_PUBLISH: {
        std::string channel, payload;
        if (!r.Bytes(&channel) || !r.Bytes(&payload)) goto malformed;
        uint32_t n = store->Publish(channel, payload);
        Writer w(ST_OK);
        w.U32(n);
        w.Send(conn.get());
        break;
      }
      case OP_SUBSCRIBE: {
        std::string channel;
        if (!r.Bytes(&channel)) goto malformed;
        store->Subscribe(channel, conn);
        Writer w(ST_OK);
        w.Send(conn.get());
        break;
      }
      case OP_TABLE_PUT: {
        std::string table, key, val;
        if (!r.Bytes(&table) || !r.Bytes(&key) || !r.Bytes(&val))
          goto malformed;
        store->TablePut(table, key, val);
        Writer w(ST_OK);
        w.Send(conn.get());
        break;
      }
      case OP_TABLE_DEL: {
        std::string table, key;
        if (!r.Bytes(&table) || !r.Bytes(&key)) goto malformed;
        Writer w(ST_OK);
        w.U8(store->TableDel(table, key) ? 1 : 0);
        w.Send(conn.get());
        break;
      }
      case OP_TABLE_SCAN: {
        std::string table;
        if (!r.Bytes(&table)) goto malformed;
        auto entries = store->TableScan(table);
        Writer w(ST_OK);
        w.U32(static_cast<uint32_t>(entries.size()));
        for (const auto& [k, v] : entries) {
          w.Bytes(k);
          w.Bytes(v);
        }
        w.Send(conn.get());
        break;
      }
      case OP_HEALTH_START: {
        double period;
        uint32_t beats;
        if (!r.F64(&period) || !r.U32(&beats)) goto malformed;
        store->StartHealth(period, beats);
        Writer w(ST_OK);
        w.Send(conn.get());
        break;
      }
      case OP_STATS: {
        uint32_t n_nodes, n_kv, n_subs;
        store->Stats(&n_nodes, &n_kv, &n_subs);
        Writer w(ST_OK);
        w.U32(n_nodes);
        w.U32(n_kv);
        w.U32(n_subs);
        w.Send(conn.get());
        break;
      }
      case OP_SHUTDOWN: {
        Writer w(ST_OK);
        w.Send(conn.get());
        g_shutdown = true;
        // Kick the accept loop out of its blocking accept().
        ::shutdown(g_listen_fd.load(), SHUT_RDWR);
        goto done;
      }
      default: {
        Writer w(ST_ERR);
        w.Bytes("unknown op");
        w.Send(conn.get());
        break;
      }
    }
    continue;
  malformed : {
    Writer w(ST_ERR);
    w.Bytes("malformed frame");
    w.Send(conn.get());
    goto done;
  }
  }
done:
  store->DropConnection(conn->fd);
  {
    std::lock_guard<std::mutex> lk(conn->write_mu);
    conn->closed = true;
    ::close(conn->fd);
  }
}

}  // namespace

int main(int argc, char** argv) {
  int port = 0;  // 0 = ephemeral; actual port printed to stdout
  const char* host = "127.0.0.1";
  const char* persist = nullptr;
  bool die_with_parent = false;
  for (int i = 1; i < argc; i++) {
    if (!std::strcmp(argv[i], "--die-with-parent")) die_with_parent = true;
    if (i >= argc - 1) continue;
    if (!std::strcmp(argv[i], "--port")) port = std::atoi(argv[i + 1]);
    if (!std::strcmp(argv[i], "--host")) host = argv[i + 1];
    if (!std::strcmp(argv[i], "--persist")) persist = argv[i + 1];
  }
  ::signal(SIGPIPE, SIG_IGN);
  if (die_with_parent) {
    // Die with the spawning head process (head-failover chaos: a
    // SIGKILLed head must not leave an orphan daemon appending to the
    // WAL that the replacement head is about to replay and reopen).
    // A ppid poll, NOT PR_SET_PDEATHSIG: the prctl signal fires when
    // the spawning THREAD exits, which would falsely kill the daemon
    // under a head that called init() from a short-lived thread.
    // Exit on ppid CHANGE, not on ppid==1 — the head may legitimately
    // BE pid 1 (container entrypoint), and its death then tears the
    // whole pid namespace down anyway.
    pid_t parent = ::getppid();
    std::thread([parent] {
      for (;;) {
        ::usleep(500 * 1000);
        if (::getppid() != parent) ::_exit(0);  // reparented: head died
      }
    }).detach();
  }

  int listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (listen_fd < 0) {
    std::perror("socket");
    return 1;
  }
  int one = 1;
  ::setsockopt(listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  ::inet_pton(AF_INET, host, &addr.sin_addr);
  if (::bind(listen_fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) <
      0) {
    std::perror("bind");
    return 1;
  }
  socklen_t alen = sizeof(addr);
  ::getsockname(listen_fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  if (::listen(listen_fd, 128) < 0) {
    std::perror("listen");
    return 1;
  }
  g_listen_fd = listen_fd;

  ControlStore store;
  if (persist != nullptr) {
    // Single-writer guard BEFORE replay: a lingering predecessor daemon
    // still appending would corrupt the log under us (and our replay
    // would miss its in-flight mutations). Bounded wait, then fail
    // loudly before the port handshake.
    int lock_fd = ::open(persist, O_RDWR | O_CREAT, 0644);
    if (lock_fd < 0) {
      std::perror("persist open");
      return 1;
    }
    bool locked = false;
    for (int i = 0; i < 100; i++) {  // ~5s
      if (::flock(lock_fd, LOCK_EX | LOCK_NB) == 0) {
        locked = true;
        break;
      }
      ::usleep(50 * 1000);
    }
    if (!locked) {
      std::fprintf(stderr,
                   "control_store: %s is locked by another daemon\n",
                   persist);
      return 1;
    }
    ReplayLog(&store, persist);
    g_persist = std::fopen(persist, "ab");
    if (g_persist == nullptr) {
      // Exit BEFORE the port handshake: the launcher then fails loudly
      // instead of running a daemon that silently isn't durable.
      std::perror("persist open");
      return 1;
    }
    // lock_fd stays open (and locked) for the daemon's lifetime.
  }
  // Startup handshake: the launcher reads the bound port from stdout.
  std::printf("CONTROL_STORE_PORT %d\n", ntohs(addr.sin_port));
  std::fflush(stdout);
  std::vector<std::thread> workers;
  while (!g_shutdown) {
    int fd = ::accept(listen_fd, nullptr, nullptr);
    if (fd < 0) break;
    if (g_shutdown) {
      ::close(fd);
      break;
    }
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    auto conn = std::make_shared<Connection>(fd);
    workers.emplace_back(
        [&store, conn] { HandleConnection(&store, conn); });
  }
  ::close(listen_fd);
  store.Shutdown();
  // Daemon exit: worker threads die with the process (detached semantics).
  for (auto& t : workers) t.detach();
  return 0;
}
