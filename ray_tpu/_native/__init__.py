"""ctypes bindings for the native shared-memory arena store.

The C++ store (``shm_store.cc``) is the plasma-equivalent data plane; this
module builds it on first use (g++, cached in ``build/``) and exposes
:class:`NativeStore`. Callers fall back to the pure-Python per-object
segment store when the toolchain is unavailable.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
import weakref
from typing import Optional

import numpy as _np

_HERE = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_HERE, "build", "libshmstore.so")
_lib = None
_lib_lock = threading.Lock()


def _stale(artifact: str, *sources: str) -> bool:
    """True if the artifact is missing or older than any of its sources."""
    if not os.path.exists(artifact):
        return True
    mtime = os.path.getmtime(artifact)
    return any(
        os.path.exists(src) and os.path.getmtime(src) > mtime
        for src in sources
    )


def _load_lib():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if _stale(_LIB_PATH, os.path.join(_HERE, "shm_store.cc")):
            try:
                subprocess.run(
                    ["make", "-C", _HERE], check=True,
                    capture_output=True, timeout=120,
                )
            except Exception as e:
                raise RuntimeError(f"native store build failed: {e}") from e
        lib = ctypes.CDLL(_LIB_PATH)
        lib.rt_store_create.restype = ctypes.c_void_p
        lib.rt_store_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.rt_store_attach.restype = ctypes.c_void_p
        lib.rt_store_attach.argtypes = [ctypes.c_char_p]
        lib.rt_store_put.restype = ctypes.c_int
        lib.rt_store_put.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_uint64,
        ]
        lib.rt_store_create_object.restype = ctypes.c_void_p
        lib.rt_store_create_object.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_int32),
        ]
        lib.rt_store_seal.restype = ctypes.c_int
        lib.rt_store_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rt_store_put_frame.restype = ctypes.c_int
        lib.rt_store_put_frame.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            ctypes.c_char_p, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_uint32,
        ]
        lib.rt_store_abort.restype = ctypes.c_int
        lib.rt_store_abort.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rt_store_get.restype = ctypes.POINTER(ctypes.c_ubyte)
        lib.rt_store_get.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.rt_store_release.restype = ctypes.c_int
        lib.rt_store_release.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rt_store_contains.restype = ctypes.c_int
        lib.rt_store_contains.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rt_store_delete.restype = ctypes.c_int
        lib.rt_store_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rt_store_stats.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.rt_store_close.argtypes = [ctypes.c_void_p, ctypes.c_int]
        _lib = lib
        return lib


def available() -> bool:
    try:
        _load_lib()
        return True
    except Exception:
        return False


class NativeStoreError(Exception):
    pass


class NativeStoreFull(NativeStoreError):
    pass


class NativeStorePendingDelete(NativeStoreError):
    """Key was deleted while readers still pin the old extent; a new put
    for the same key must wait until the last reader releases."""


class NativeStoreExists(NativeStoreError):
    """Object already SEALED under this key — puts are idempotent, so
    callers usually treat this as success."""


class NativeStoreUnsealed(NativeStoreError):
    """An unsealed reservation exists for this key (a prior writer died
    between create and seal). The owner serializes same-key writes, so
    it may abort() the wedged reservation and retry."""


def _pinned_view(store: "NativeStore", key: bytes, ptr: int,
                 size: int) -> memoryview:
    """Read-only view over a pinned arena extent whose pin is released
    when the LAST derived view is garbage-collected.

    The ctypes array is the buffer exporter: every derived slice —
    including numpy arrays rebuilt from out-of-band pickle buffers —
    keeps it alive through the buffer protocol, and ``weakref.finalize``
    fires the release exactly once when the exporter is collected.
    (A ``__buffer__``-based exporter class would need PEP 688, py3.12+;
    the finalize pin works on every supported interpreter.) Deferred-free
    in the store (``SLOT_PENDING_DELETE``) guarantees the extent is not
    reused while pinned, so zero-copy values safely outlive deletion."""
    arr = (ctypes.c_ubyte * max(size, 1)).from_address(ptr)
    key = bytes(key)
    lib, handle = store._lib, store._handle

    def _release():
        if not store._closed:
            try:
                lib.rt_store_release(handle, key)
            except Exception:
                pass

    weakref.finalize(arr, _release)
    # ctypes exports format "<B"; cast to "B" so consumers (pickle
    # buffer loads, numpy frombuffer) accept it.
    return memoryview(arr).cast("B").toreadonly()[:size]


class NativeStore:
    """One arena per node; create in the node manager, attach in workers."""

    def __init__(self, handle, name: str, owner: bool):
        self._lib = _load_lib()
        self._handle = ctypes.c_void_p(handle)
        self.name = name
        self._owner = owner
        self._closed = False

    @classmethod
    def create(cls, name: str, capacity: int) -> "NativeStore":
        lib = _load_lib()
        handle = lib.rt_store_create(name.encode(), capacity)
        if not handle:
            raise NativeStoreError(f"failed to create shm arena {name!r}")
        return cls(handle, name, owner=True)

    @classmethod
    def attach(cls, name: str) -> "NativeStore":
        lib = _load_lib()
        handle = lib.rt_store_attach(name.encode())
        if not handle:
            raise NativeStoreError(f"failed to attach shm arena {name!r}")
        return cls(handle, name, owner=False)

    def put(self, key: bytes, data: bytes) -> None:
        rc = self._lib.rt_store_put(self._handle, key, data, len(data))
        if rc == -1:
            return  # already sealed: idempotent put
        if rc == -2:
            raise NativeStoreFull("arena full")
        if rc == -3:
            raise NativeStoreError("object table full")
        if rc == -5:
            raise NativeStorePendingDelete(key.hex())
        if rc != 0:
            raise NativeStoreError(f"put failed rc={rc}")

    def get(self, key: bytes) -> Optional[memoryview]:
        """Zero-copy view into the arena; release() when done with it."""
        size = ctypes.c_uint64()
        ptr = self._lib.rt_store_get(self._handle, key, ctypes.byref(size))
        if not ptr:
            return None
        return memoryview(
            ctypes.cast(
                ptr, ctypes.POINTER(ctypes.c_ubyte * size.value)
            ).contents
        )

    def get_pinned(self, key: bytes) -> Optional[memoryview]:
        """Zero-copy READ-ONLY view whose pin is released automatically
        when the last derived view (e.g. a numpy array deserialized out
        of band) is garbage-collected — plasma-client buffer semantics.
        """
        size = ctypes.c_uint64()
        ptr = self._lib.rt_store_get(self._handle, key, ctypes.byref(size))
        if not ptr:
            return None
        addr = ctypes.cast(ptr, ctypes.c_void_p).value
        return _pinned_view(self, key, addr, size.value)

    def create_object(self, key: bytes, size: int) -> memoryview:
        """Reserve an extent and return a WRITABLE view into the arena;
        call seal() after filling it (abort() on failure). This is the
        zero-copy write path (reference: plasma Create/Seal)."""
        err = ctypes.c_int32()
        ptr = self._lib.rt_store_create_object(
            self._handle, key, size, ctypes.byref(err))
        if not ptr:
            if err.value == -2:
                raise NativeStoreFull("arena full")
            if err.value == -3:
                raise NativeStoreError("object table full")
            if err.value == -5:
                raise NativeStorePendingDelete(key.hex())
            if err.value == -1:
                raise NativeStoreExists(key.hex())
            if err.value == -6:
                raise NativeStoreUnsealed(key.hex())
            raise NativeStoreError(f"create_object failed err={err.value}")
        arr = (ctypes.c_ubyte * max(size, 1)).from_address(ptr)
        return memoryview(arr).cast("B")[:size]

    def seal(self, key: bytes) -> None:
        rc = self._lib.rt_store_seal(self._handle, key)
        if rc != 0:
            raise NativeStoreError(f"seal failed rc={rc}")

    def put_frame(self, key: bytes, inband: bytes, buffers) -> None:
        """One-call owner put of a serialized frame (reserve → C-side
        copy with the lock released → seal); layout identical to
        ``serialization.SerializedObject.write_into`` (the C side owns
        the only other copy of the offset math — callers wanting the
        frame size use ``SerializedObject.frame_bytes()``). ``buffers``
        is a sequence of PickleBuffers. Raises the same exceptions as
        create_object."""
        n = len(buffers)
        ptrs = (ctypes.c_void_p * n)()
        lens = (ctypes.c_uint64 * n)()
        raws = []  # keep buffer views alive across the C call
        for i, b in enumerate(buffers):
            raw = b.raw()
            # np.frombuffer yields a pointer for read-only exporters
            # too (ctypes.from_buffer insists on writable).
            arr = _np.frombuffer(raw, dtype=_np.uint8)
            raws.append((raw, arr))
            ptrs[i] = arr.ctypes.data
            lens[i] = raw.nbytes
        rc = self._lib.rt_store_put_frame(
            self._handle, key, inband, len(inband), ptrs, lens, n)
        if rc == 0:
            return
        if rc == -1:
            raise NativeStoreExists(key.hex())
        if rc == -2:
            raise NativeStoreFull("arena full")
        if rc == -3:
            raise NativeStoreError("object table full")
        if rc == -5:
            raise NativeStorePendingDelete(key.hex())
        if rc == -6:
            raise NativeStoreUnsealed(key.hex())
        raise NativeStoreError(f"put_frame failed rc={rc}")

    def abort(self, key: bytes) -> None:
        self._lib.rt_store_abort(self._handle, key)

    def release(self, key: bytes) -> None:
        self._lib.rt_store_release(self._handle, key)

    def contains(self, key: bytes) -> bool:
        return bool(self._lib.rt_store_contains(self._handle, key))

    def delete(self, key: bytes) -> bool:
        """True when the object existed. The extent free may be deferred
        until the last pinned reader releases (rc 1); either way the key
        stops being gettable immediately."""
        return self._lib.rt_store_delete(self._handle, key) >= 0

    def stats(self) -> dict:
        cap = ctypes.c_uint64()
        used = ctypes.c_uint64()
        n = ctypes.c_uint64()
        self._lib.rt_store_stats(self._handle, ctypes.byref(cap),
                                 ctypes.byref(used), ctypes.byref(n))
        return {"capacity_bytes": cap.value, "used_bytes": used.value,
                "num_objects": n.value}

    def close(self, unlink: Optional[bool] = None) -> None:
        if self._closed:
            return
        self._closed = True
        self._lib.rt_store_close(
            self._handle, int(self._owner if unlink is None else unlink)
        )

    def __del__(self):
        try:
            self.close(unlink=False)
        except Exception:
            pass
