// Shared-memory arena object store — the native data plane.
//
// Reference analog: src/ray/object_manager/plasma/ (PlasmaStore,
// plasma/store.h:55; dlmalloc arena over mmap/shm, plasma/dlmalloc.cc;
// object lifecycle table, object_lifecycle_manager.h) — re-designed as a
// single POSIX shm arena per node that ALL worker processes map directly:
//
//   [ StoreHeader | object table (open addressing) | data arena ]
//
// The allocator (first-fit free list with coalescing) and the object table
// live inside the mapping and are guarded by one process-shared pthread
// mutex, so creation/sealing/lookup need no server round-trip at all —
// strictly less IPC than the reference's unix-socket protocol. Objects are
// immutable after seal (plasma semantics); freeing returns extents to the
// free list.
//
// Exposed as a C ABI consumed via ctypes (ray_tpu/_native/__init__.py).

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

// Layout version is part of the magic: bump the last byte whenever
// StoreHeader changes so a new binary refuses a stale /dev/shm segment
// instead of misreading the mutex offset ("RT_SHMA2" = v2: reserved
// ranges added before the mutex).
constexpr uint64_t kMagic = 0x52545f53484d4132ull;  // "RT_SHMA2"
constexpr uint32_t kKeySize = 20;                   // ObjectID bytes
constexpr uint32_t kTableSize = 1 << 16;            // object table slots
constexpr uint64_t kAlign = 64;                     // allocation alignment

enum SlotState : uint32_t {
  SLOT_FREE = 0,
  SLOT_CREATED = 1,  // allocated, being written
  SLOT_SEALED = 2,   // immutable, readable
  SLOT_TOMBSTONE = 3,
  SLOT_PENDING_DELETE = 4,  // deleted while pinned; freed on last release
};

struct Slot {
  uint8_t key[kKeySize];
  uint32_t state;
  uint64_t offset;      // into data arena
  uint64_t size;        // logical object size
  uint64_t alloc_size;  // actual extent charged by arena_alloc (>= size)
  int64_t refcount;     // pin count from readers
};

struct FreeBlock {
  uint64_t size;
  uint64_t next;  // offset of next free block (0 = end)
};

constexpr uint64_t kMaxReserved = 64;  // crash-repair reservations

struct StoreHeader {
  uint64_t magic;
  uint64_t capacity;       // data arena bytes
  uint64_t data_start;     // offset of arena from mapping base
  uint64_t free_head;      // offset of first free block (arena-relative+1; 0=none)
  uint64_t used_bytes;
  uint64_t num_objects;
  // Byte ranges permanently withheld from the allocator: repair found a
  // pinned slot losing an overlap conflict, so a surviving reader still
  // maps these bytes while another (winning) slot may own a subrange.
  // arena_free clips every freed extent against this list — even the
  // winner's own later delete cannot recycle a reserved byte.
  uint64_t reserved_count;
  uint64_t reserved_off[kMaxReserved];
  uint64_t reserved_size[kMaxReserved];
  pthread_mutex_t mutex;
};

struct Store {
  void* base;
  uint64_t map_size;
  int fd;
  char name[256];
  bool owner;
};

inline StoreHeader* header(Store* s) {
  return reinterpret_cast<StoreHeader*>(s->base);
}

inline Slot* table(Store* s) {
  return reinterpret_cast<Slot*>(
      static_cast<char*>(s->base) + sizeof(StoreHeader));
}

inline char* arena(Store* s) {
  return static_cast<char*>(s->base) + header(s)->data_start;
}

inline uint64_t align_up(uint64_t v) { return (v + kAlign - 1) & ~(kAlign - 1); }

uint64_t hash_key(const uint8_t* key) {
  // FNV-1a over the 20-byte id.
  uint64_t h = 1469598103934665603ull;
  for (uint32_t i = 0; i < kKeySize; i++) {
    h ^= key[i];
    h *= 1099511628211ull;
  }
  return h;
}

Slot* find_slot(Store* s, const uint8_t* key, bool for_insert) {
  Slot* t = table(s);
  uint64_t idx = hash_key(key) & (kTableSize - 1);
  Slot* first_tomb = nullptr;
  for (uint32_t probe = 0; probe < kTableSize; probe++) {
    Slot* slot = &t[(idx + probe) & (kTableSize - 1)];
    if (slot->state == SLOT_FREE) {
      if (for_insert) return first_tomb ? first_tomb : slot;
      return nullptr;
    }
    if (slot->state == SLOT_TOMBSTONE) {
      if (for_insert && !first_tomb) first_tomb = slot;
      continue;
    }
    if (memcmp(slot->key, key, kKeySize) == 0) return slot;
  }
  return for_insert ? first_tomb : nullptr;
}

// First-fit allocation from the in-arena free list. Returns arena-relative
// offset or UINT64_MAX; *actual_out receives the extent actually charged
// (aligned size, possibly grown by an absorbed sliver) — the caller must
// pass exactly this value back to arena_free. Caller holds the mutex.
uint64_t arena_alloc(Store* s, uint64_t size, uint64_t* actual_out) {
  StoreHeader* h = header(s);
  size = align_up(size);
  uint64_t prev_off = 0;  // 0 = head pointer itself
  uint64_t cur = h->free_head;
  while (cur != 0) {
    FreeBlock* blk = reinterpret_cast<FreeBlock*>(arena(s) + (cur - 1));
    if (blk->size >= size) {
      uint64_t remaining = blk->size - size;
      uint64_t next = blk->next;
      if (remaining >= sizeof(FreeBlock) + kAlign) {
        uint64_t new_off = (cur - 1) + size + 1;
        FreeBlock* rest = reinterpret_cast<FreeBlock*>(arena(s) + (new_off - 1));
        rest->size = remaining;
        rest->next = next;
        next = new_off;
      } else {
        size = blk->size;  // absorb the sliver
      }
      if (prev_off == 0) {
        h->free_head = next;
      } else {
        reinterpret_cast<FreeBlock*>(arena(s) + (prev_off - 1))->next = next;
      }
      h->used_bytes += size;
      *actual_out = size;
      return cur - 1;
    }
    prev_off = cur;
    cur = blk->next;
  }
  return UINT64_MAX;
}

// Link one extent into the free list, coalescing with neighbors. Callers
// outside repair go through arena_free (which clips reservations first).
// Caller holds the mutex.
void arena_free_raw(Store* s, uint64_t offset, uint64_t size) {
  StoreHeader* h = header(s);
  h->used_bytes -= size;
  // Insert sorted by offset, then coalesce.
  uint64_t prev_off = 0;
  uint64_t cur = h->free_head;
  while (cur != 0 && (cur - 1) < offset) {
    prev_off = cur;
    cur = reinterpret_cast<FreeBlock*>(arena(s) + (cur - 1))->next;
  }
  FreeBlock* blk = reinterpret_cast<FreeBlock*>(arena(s) + offset);
  blk->size = size;
  blk->next = cur;
  if (prev_off == 0) {
    h->free_head = offset + 1;
  } else {
    FreeBlock* prev = reinterpret_cast<FreeBlock*>(arena(s) + (prev_off - 1));
    prev->next = offset + 1;
    // Coalesce prev + blk.
    if ((prev_off - 1) + prev->size == offset) {
      prev->size += blk->size;
      prev->next = blk->next;
      blk = prev;
      offset = prev_off - 1;
    }
  }
  // Coalesce blk + next.
  if (blk->next != 0 && offset + blk->size == blk->next - 1) {
    FreeBlock* nxt = reinterpret_cast<FreeBlock*>(arena(s) + (blk->next - 1));
    blk->size += nxt->size;
    blk->next = nxt->next;
  }
}

// Return an extent to the free list, withholding any subrange on the
// crash-repair reservation list: a reserved byte is still mapped by a
// surviving reader of a conflict-losing slot, so even the legitimate
// owner's delete must not let the allocator recycle it. Reserved slivers
// stay counted in used_bytes (a bounded leak until the arena is
// recreated). Caller holds the mutex.
void arena_free(Store* s, uint64_t offset, uint64_t size) {
  StoreHeader* h = header(s);
  if (h->reserved_count == 0) {
    arena_free_raw(s, offset, size);
    return;
  }
  // Subtract each reserved range from the piece set, then free what is
  // left. Piece count is bounded by reservations + 1.
  uint64_t ps[kMaxReserved + 1];
  uint64_t pe[kMaxReserved + 1];
  uint64_t np = 1;
  ps[0] = offset;
  pe[0] = offset + size;
  for (uint64_t i = 0; i < h->reserved_count && np <= kMaxReserved; i++) {
    uint64_t ro = h->reserved_off[i];
    uint64_t re = ro + h->reserved_size[i];
    uint64_t cur_np = np;
    for (uint64_t j = 0; j < cur_np; j++) {
      if (pe[j] <= ro || ps[j] >= re) continue;  // disjoint
      uint64_t a0 = ps[j], a1 = pe[j];
      if (a0 < ro) {
        pe[j] = ro;  // keep the left remainder in place
      } else {
        ps[j] = pe[j] = 0;  // fully covered on the left side
      }
      if (a1 > re && np <= kMaxReserved) {  // right remainder
        ps[np] = re;
        pe[np] = a1;
        np++;
      }
    }
  }
  for (uint64_t j = 0; j < np; j++) {
    if (pe[j] > ps[j] && pe[j] - ps[j] >= sizeof(FreeBlock)) {
      arena_free_raw(s, ps[j], pe[j] - ps[j]);
    }
  }
  // Clipped bytes intentionally remain counted in used_bytes.
}

}  // namespace

extern "C" {

// Create a new store of `capacity` data bytes. Returns handle or null.
void* rt_store_create(const char* name, uint64_t capacity) {
  uint64_t table_bytes = sizeof(Slot) * kTableSize;
  uint64_t data_start = align_up(sizeof(StoreHeader) + table_bytes);
  uint64_t total = data_start + capacity;

  shm_unlink(name);
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, (off_t)total) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* base = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
#ifdef MADV_HUGEPAGE
  // Best-effort: where shmem THP is enabled, 2MB mappings cut the TLB
  // cost of bulk copies into the arena (a 10MB put touches 2560 4K
  // pages; heap destinations already get THP, so without this the put
  // medium starts ~15-20% behind a heap memcpy). Ignored elsewhere.
  madvise(base, total, MADV_HUGEPAGE);
#endif
  memset(base, 0, data_start);
  StoreHeader* h = reinterpret_cast<StoreHeader*>(base);
  h->capacity = capacity;
  h->data_start = data_start;
  h->used_bytes = 0;
  h->num_objects = 0;
  pthread_mutexattr_t attr;
  pthread_mutexattr_init(&attr);
  pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&h->mutex, &attr);
  // One giant free block spans the arena.
  FreeBlock* blk = reinterpret_cast<FreeBlock*>(
      static_cast<char*>(base) + data_start);
  blk->size = capacity;
  blk->next = 0;
  h->free_head = 1;  // arena offset 0, +1 encoding
  h->magic = kMagic;

  Store* s = new Store{base, total, fd, {0}, true};
  strncpy(s->name, name, sizeof(s->name) - 1);
  return s;
}

void* rt_store_attach(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  void* base =
      mmap(nullptr, st.st_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
#ifdef MADV_HUGEPAGE
  madvise(base, st.st_size, MADV_HUGEPAGE);  // see rt_store_create
#endif
  StoreHeader* h = reinterpret_cast<StoreHeader*>(base);
  if (h->magic != kMagic) {
    munmap(base, st.st_size);
    close(fd);
    return nullptr;
  }
  Store* s = new Store{base, (uint64_t)st.st_size, fd, {0}, false};
  strncpy(s->name, name, sizeof(s->name) - 1);
  return s;
}

// Rebuild allocator + table invariants after a lock owner died inside a
// critical section (EOWNERDEAD): the dead process may have left slot
// fields half-written or the free-list splice mid-update. The object
// table is the source of truth — every structurally valid allocated
// slot keeps its extent; half-written slots are tombstoned; the free
// list is rebuilt as the sorted, coalesced complement of the kept
// extents. Caller holds the (just-made-consistent) mutex.
// Reference concern: plasma's server-mediated design never exposes
// clients to each other's locks (plasma/store.h:55); the direct-mapped
// arena earns the same safety here.
static void repair_store(Store* s) {
  StoreHeader* h = header(s);
  Slot* t = table(s);
  struct Extent {
    uint64_t off;
    uint64_t size;
    Slot* slot;
  };
  Extent* exts = new Extent[kTableSize + kMaxReserved];
  uint64_t n = 0;
  uint64_t sealed = 0;
  for (uint32_t i = 0; i < kTableSize; i++) {
    Slot* slot = &t[i];
    if (slot->state != SLOT_CREATED && slot->state != SLOT_SEALED &&
        slot->state != SLOT_PENDING_DELETE) {
      continue;
    }
    // Overflow-safe bounds check: offset + alloc_size could wrap uint64
    // for a torn slot with a huge offset, sneaking it past `<= capacity`
    // and corrupting the rebuilt free list.
    bool valid = slot->alloc_size > 0 && slot->offset <= h->capacity &&
                 slot->alloc_size <= h->capacity - slot->offset &&
                 slot->size <= slot->alloc_size;
    if (!valid) {  // half-written by the dead owner
      slot->state = SLOT_TOMBSTONE;
      continue;
    }
    exts[n++] = {slot->offset, slot->alloc_size, slot};
  }
  // Insertion sort by offset (n is small in practice; bounded by table).
  for (uint64_t i = 1; i < n; i++) {
    Extent e = exts[i];
    uint64_t j = i;
    while (j > 0 && exts[j - 1].off > e.off) {
      exts[j] = exts[j - 1];
      j--;
    }
    exts[j] = e;
  }
  // Drop overlapping extents (a torn allocation). A SEALED slot is
  // authoritative — a torn CREATED/PENDING_DELETE extent claiming the
  // same bytes loses regardless of offset order; among equal states the
  // earlier (lower-offset) extent wins. The kept list stays strictly
  // disjoint (sorted, increasing ends), so checking the current extent
  // against the stack top is sufficient. A losing slot still pinned by
  // a surviving reader moves to a separate RESERVED list: its bytes
  // stay out of the free list forever (a reader still maps them and the
  // winner may own an overlapping subrange, so they can never be freed
  // safely — a bounded leak until the arena is recreated). Its
  // alloc_size is zeroed so the reader's final release tombstones the
  // slot without arena_free'ing bytes it no longer owns.
  Extent* resv = new Extent[kTableSize];
  uint64_t n_resv = 0;
  auto rank_of = [](uint32_t st) {
    return st == SLOT_SEALED ? 2 : st == SLOT_CREATED ? 1 : 0;
  };
  auto lose = [&](const Extent& e) {
    if (e.slot->refcount > 0) {
      e.slot->state = SLOT_PENDING_DELETE;
      e.slot->alloc_size = 0;  // release must never free these bytes
      e.slot->size = 0;
      resv[n_resv++] = e;  // extent (by value) stays space-reserved
      // Persist the reservation: a WINNING slot may own an overlapping
      // subrange, and its own later delete must not recycle bytes this
      // loser's surviving reader still maps — arena_free clips against
      // this list. If the list is full, fall back to the in-walk
      // reservation only (the residual winner-delete hazard returns for
      // that extent; 64 torn-pinned extents in one arena lifetime is
      // already deep in crash-of-crashes territory).
      if (h->reserved_count < kMaxReserved) {
        h->reserved_off[h->reserved_count] = e.off;
        h->reserved_size[h->reserved_count] = e.size;
        h->reserved_count++;
      }
    } else {
      e.slot->state = SLOT_TOMBSTONE;
    }
  };
  uint64_t kept = 0;
  for (uint64_t i = 0; i < n; i++) {
    bool drop_cur = false;
    while (kept > 0) {
      Extent& top = exts[kept - 1];
      if (exts[i].off >= top.off + top.size) break;  // disjoint
      if (rank_of(exts[i].slot->state) > rank_of(top.slot->state)) {
        lose(top);
        kept--;  // recheck the new top for overlap
      } else {
        lose(exts[i]);
        drop_cur = true;
        break;
      }
    }
    if (!drop_cur) exts[kept++] = exts[i];
  }
  // Fold reserved extents back in for the free-list complement — both
  // this repair's (resv) and any persisted by earlier repairs (header
  // list; slotless) — and re-sort; reserved ranges may overlap winners,
  // so walk the union with a monotonic cursor.
  for (uint64_t i = 0; i < n_resv; i++) exts[kept + i] = resv[i];
  uint64_t m = kept + n_resv;
  delete[] resv;
  for (uint64_t i = 0; i < h->reserved_count && m < kTableSize + kMaxReserved;
       i++) {
    if (h->reserved_size[i] > 0 && h->reserved_off[i] < h->capacity &&
        h->reserved_size[i] <= h->capacity - h->reserved_off[i]) {
      exts[m++] = {h->reserved_off[i], h->reserved_size[i], nullptr};
    }
  }
  for (uint64_t i = 1; i < m; i++) {
    Extent e = exts[i];
    uint64_t j = i;
    while (j > 0 && exts[j - 1].off > e.off) {
      exts[j] = exts[j - 1];
      j--;
    }
    exts[j] = e;
  }
  uint64_t used = 0;
  uint64_t free_head = 0;
  uint64_t* link = &free_head;  // where to write the next block's off+1
  uint64_t cursor = 0;
  for (uint64_t i = 0; i <= m; i++) {
    uint64_t gap_end = (i < m) ? exts[i].off : h->capacity;
    if (gap_end > cursor && gap_end - cursor >= sizeof(FreeBlock)) {
      FreeBlock* blk = reinterpret_cast<FreeBlock*>(arena(s) + cursor);
      blk->size = gap_end - cursor;
      blk->next = 0;
      *link = cursor + 1;
      link = &blk->next;
    }
    if (i < m) {
      uint64_t end = exts[i].off + exts[i].size;
      if (end > cursor) {
        uint64_t start = exts[i].off > cursor ? exts[i].off : cursor;
        used += end - start;
        cursor = end;
      }
    }
  }
  h->free_head = free_head;
  h->used_bytes = used;
  for (uint64_t i = 0; i < m; i++) {
    if (exts[i].slot && exts[i].slot->state == SLOT_SEALED) sealed++;
  }
  h->num_objects = sealed;
  delete[] exts;
}

static int lock_robust(Store* s) {
  StoreHeader* h = header(s);
  int rc = pthread_mutex_lock(&h->mutex);
  if (rc == EOWNERDEAD) {
    // The mutex is usable again, but the state it guarded may be torn —
    // repair before letting anyone allocate from it.
    repair_store(s);
    pthread_mutex_consistent(&h->mutex);
    rc = 0;
  }
  return rc;
}

// Allocate + copy + seal in one call. Returns 0 ok, -1 exists, -2 full,
// -3 table full, -4 error, -5 key is pending-delete (old extent still
// pinned by readers; retry after they release).
int rt_store_put(void* handle, const uint8_t* key, const uint8_t* data,
                 uint64_t size) {
  Store* s = static_cast<Store*>(handle);
  StoreHeader* h = header(s);
  if (lock_robust(s) != 0) return -4;
  Slot* existing = find_slot(s, key, false);
  if (existing && existing->state == SLOT_PENDING_DELETE) {
    pthread_mutex_unlock(&h->mutex);
    return -5;
  }
  if (existing && existing->state == SLOT_SEALED) {
    pthread_mutex_unlock(&h->mutex);
    return -1;
  }
  Slot* slot = find_slot(s, key, true);
  if (!slot) {
    pthread_mutex_unlock(&h->mutex);
    return -3;
  }
  uint64_t actual = 0;
  uint64_t off = arena_alloc(s, size ? size : 1, &actual);
  if (off == UINT64_MAX) {
    pthread_mutex_unlock(&h->mutex);
    return -2;
  }
  memcpy(slot->key, key, kKeySize);
  slot->offset = off;
  slot->size = size;
  slot->alloc_size = actual;
  slot->refcount = 0;
  memcpy(arena(s) + off, data, size);
  slot->state = SLOT_SEALED;
  h->num_objects++;
  pthread_mutex_unlock(&h->mutex);
  return 0;
}

/// Reserve space for zero-copy writes: returns pointer to write into, or
// null with *err_out set (-1 sealed-exists, -2 arena full, -3 table
// full, -4 lock error, -5 pending-delete, -6 unsealed reservation
// exists — a prior writer died between create and seal; the owner may
// rt_store_abort it and retry). Seal with rt_store_seal when done;
// rt_store_abort frees an unsealed reservation.
uint8_t* rt_store_create_object(void* handle, const uint8_t* key,
                                uint64_t size, int32_t* err_out) {
  Store* s = static_cast<Store*>(handle);
  StoreHeader* h = header(s);
  *err_out = 0;
  if (lock_robust(s) != 0) {
    *err_out = -4;
    return nullptr;
  }
  Slot* slot = find_slot(s, key, true);
  if (!slot || slot->state == SLOT_SEALED || slot->state == SLOT_CREATED ||
      slot->state == SLOT_PENDING_DELETE) {
    if (!slot) {
      *err_out = -3;
    } else if (slot->state == SLOT_PENDING_DELETE) {
      *err_out = -5;
    } else if (slot->state == SLOT_CREATED) {
      *err_out = -6;
    } else {
      *err_out = -1;
    }
    pthread_mutex_unlock(&h->mutex);
    return nullptr;
  }
  uint64_t actual = 0;
  uint64_t off = arena_alloc(s, size ? size : 1, &actual);
  if (off == UINT64_MAX) {
    *err_out = -2;
    pthread_mutex_unlock(&h->mutex);
    return nullptr;
  }
  memcpy(slot->key, key, kKeySize);
  slot->offset = off;
  slot->size = size;
  slot->alloc_size = actual;
  slot->refcount = 0;
  slot->state = SLOT_CREATED;
  pthread_mutex_unlock(&h->mutex);
  return reinterpret_cast<uint8_t*>(arena(s) + off);
}

// Owner put of a serialized frame in ONE call: reserve the extent
// (create_object semantics), copy header + inband + 64B-aligned
// out-of-band buffers with the lock RELEASED (plasma semantics — a
// slow copy must not serialize other clients' store ops), then seal.
// The frame layout mirrors serialization.py write_into/_split_frames
// exactly. Versus driving create/write/seal from Python this saves one
// mutex round plus per-op ctypes dispatch — measurable on the 10MB put
// hot path where every post-copy header access runs on cold caches.
// Returns 0 ok, else create_object's codes (-1 exists, -2 full, -3
// table full, -4 lock error, -5 pending-delete, -6 unsealed).
int rt_store_put_frame(void* handle, const uint8_t* key,
                       const uint8_t* inband, uint64_t inband_len,
                       const uint8_t* const* bufs,
                       const uint64_t* buf_lens, uint32_t nbufs);

// Free an unsealed reservation (failed write between create and seal).
int rt_store_abort(void* handle, const uint8_t* key) {
  Store* s = static_cast<Store*>(handle);
  StoreHeader* h = header(s);
  if (lock_robust(s) != 0) return -4;
  Slot* slot = find_slot(s, key, false);
  if (!slot || slot->state != SLOT_CREATED) {
    pthread_mutex_unlock(&h->mutex);
    return -1;
  }
  arena_free(s, slot->offset, slot->alloc_size);
  slot->state = SLOT_TOMBSTONE;
  pthread_mutex_unlock(&h->mutex);
  return 0;
}

int rt_store_seal(void* handle, const uint8_t* key) {
  Store* s = static_cast<Store*>(handle);
  StoreHeader* h = header(s);
  if (lock_robust(s) != 0) return -4;
  Slot* slot = find_slot(s, key, false);
  if (!slot || slot->state != SLOT_CREATED) {
    pthread_mutex_unlock(&h->mutex);
    return -1;
  }
  slot->state = SLOT_SEALED;
  h->num_objects++;
  pthread_mutex_unlock(&h->mutex);
  return 0;
}

int rt_store_put_frame(void* handle, const uint8_t* key,
                       const uint8_t* inband, uint64_t inband_len,
                       const uint8_t* const* bufs,
                       const uint64_t* buf_lens, uint32_t nbufs) {
  uint64_t n = 1 + (uint64_t)nbufs;
  uint64_t off = 4 + 8 * n + inband_len;
  for (uint32_t i = 0; i < nbufs; i++) {
    off = ((off + 63) & ~63ull) + buf_lens[i];
  }
  int32_t err = 0;
  uint8_t* dst = rt_store_create_object(handle, key, off, &err);
  if (!dst) return err;
  uint32_t n32 = (uint32_t)n;
  memcpy(dst, &n32, 4);  // all supported targets are little-endian
  memcpy(dst + 4, &inband_len, 8);
  for (uint32_t i = 0; i < nbufs; i++) {
    memcpy(dst + 4 + 8 * (1 + i), &buf_lens[i], 8);
  }
  uint64_t w = 4 + 8 * n;
  if (inband_len) memcpy(dst + w, inband, inband_len);
  w += inband_len;
  for (uint32_t i = 0; i < nbufs; i++) {
    uint64_t aligned = (w + 63) & ~63ull;
    if (aligned != w) memset(dst + w, 0, aligned - w);
    if (buf_lens[i]) memcpy(dst + aligned, bufs[i], buf_lens[i]);
    w = aligned + buf_lens[i];
  }
  int rc = rt_store_seal(handle, key);
  if (rc != 0) {
    rt_store_abort(handle, key);
    return -4;
  }
  return 0;
}

// Get a sealed object: returns pointer into the arena (zero-copy) and
// writes size. Pins the object (caller must rt_store_release).
const uint8_t* rt_store_get(void* handle, const uint8_t* key,
                            uint64_t* size_out) {
  Store* s = static_cast<Store*>(handle);
  StoreHeader* h = header(s);
  if (lock_robust(s) != 0) return nullptr;
  Slot* slot = find_slot(s, key, false);
  if (!slot || slot->state != SLOT_SEALED) {
    pthread_mutex_unlock(&h->mutex);
    return nullptr;
  }
  slot->refcount++;
  *size_out = slot->size;
  const uint8_t* ptr = reinterpret_cast<uint8_t*>(arena(s) + slot->offset);
  pthread_mutex_unlock(&h->mutex);
  return ptr;
}

int rt_store_release(void* handle, const uint8_t* key) {
  Store* s = static_cast<Store*>(handle);
  StoreHeader* h = header(s);
  if (lock_robust(s) != 0) return -4;
  Slot* slot = find_slot(s, key, false);
  if (slot && slot->refcount > 0) {
    slot->refcount--;
    if (slot->refcount == 0 && slot->state == SLOT_PENDING_DELETE) {
      // alloc_size == 0 marks a repair-reserved slot whose bytes were
      // in overlap conflict; they stay reserved (never refreed).
      if (slot->alloc_size > 0) {
        arena_free(s, slot->offset, slot->alloc_size);
      }
      slot->state = SLOT_TOMBSTONE;
    }
  }
  pthread_mutex_unlock(&h->mutex);
  return 0;
}

int rt_store_contains(void* handle, const uint8_t* key) {
  Store* s = static_cast<Store*>(handle);
  StoreHeader* h = header(s);
  if (lock_robust(s) != 0) return 0;
  Slot* slot = find_slot(s, key, false);
  int ok = (slot && slot->state == SLOT_SEALED) ? 1 : 0;
  pthread_mutex_unlock(&h->mutex);
  return ok;
}

// Delete. If readers still pin the object (zero-copy views in other
// processes), the extent free is deferred until the last rt_store_release —
// the slot moves to PENDING_DELETE and stops being gettable immediately.
// Returns 0 when the extent was freed now, 1 when the free was deferred,
// -1 when the key does not exist.
int rt_store_delete(void* handle, const uint8_t* key) {
  Store* s = static_cast<Store*>(handle);
  StoreHeader* h = header(s);
  if (lock_robust(s) != 0) return -4;
  Slot* slot = find_slot(s, key, false);
  if (!slot || slot->state == SLOT_FREE ||
      slot->state == SLOT_PENDING_DELETE) {
    pthread_mutex_unlock(&h->mutex);
    return -1;
  }
  int deferred = 0;
  if (slot->refcount > 0) {
    slot->state = SLOT_PENDING_DELETE;
    deferred = 1;
  } else {
    arena_free(s, slot->offset, slot->alloc_size);
    slot->state = SLOT_TOMBSTONE;
  }
  h->num_objects--;
  pthread_mutex_unlock(&h->mutex);
  return deferred;
}

void rt_store_stats(void* handle, uint64_t* capacity, uint64_t* used,
                    uint64_t* num_objects) {
  Store* s = static_cast<Store*>(handle);
  StoreHeader* h = header(s);
  lock_robust(s);
  *capacity = h->capacity;
  *used = h->used_bytes;
  *num_objects = h->num_objects;
  pthread_mutex_unlock(&h->mutex);
}

void rt_store_close(void* handle, int unlink_shm) {
  Store* s = static_cast<Store*>(handle);
  munmap(s->base, s->map_size);
  close(s->fd);
  if (unlink_shm) shm_unlink(s->name);
  delete s;
}

// TEST ONLY: take the store mutex and return WITHOUT unlocking. A
// process that calls this and exits (or is SIGKILLed) simulates dying
// inside a critical section: the kernel's robust-futex list marks the
// mutex OWNER_DIED, the next locker gets EOWNERDEAD, and lock_robust
// runs repair_store. Never called by the runtime.
int rt_store_test_lock_hold(void* handle) {
  Store* s = static_cast<Store*>(handle);
  return pthread_mutex_lock(&header(s)->mutex);
}

// TEST ONLY: simulate a writer dying MID-ALLOCATION — take the mutex,
// scribble a torn slot (CREATED state, impossible extent) and corrupt
// the free-list head, then return still holding the lock. The caller
// process then exits; the next locker's repair must tombstone the torn
// slot and rebuild the free list from the surviving table entries.
int rt_store_test_die_mid_alloc(void* handle, const uint8_t* key) {
  Store* s = static_cast<Store*>(handle);
  StoreHeader* h = header(s);
  int rc = pthread_mutex_lock(&h->mutex);
  if (rc != 0 && rc != EOWNERDEAD) return rc;
  Slot* slot = find_slot(s, key, true);
  if (slot) {
    memcpy(slot->key, key, kKeySize);
    slot->offset = h->capacity * 2;  // structurally invalid
    slot->size = 1;
    slot->alloc_size = 0;
    slot->refcount = 0;
    slot->state = SLOT_CREATED;
  }
  h->free_head = h->capacity + 7;  // dangling free-list head
  return 0;
}

}  // extern "C"
