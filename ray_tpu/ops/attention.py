"""Attention ops: Pallas flash attention (TPU) + reference implementation.

The reference framework has no attention kernels (model code is user-space
there); this framework ships them because long-context SP/ring attention is
first-class (SURVEY §5.7). Design follows the standard online-softmax flash
algorithm, tiled for the MXU:

  - grid over (batch*heads, query blocks)
  - K/V stream through VMEM in ``block_k`` chunks with running (m, l, acc)
  - causal masking skips fully-masked K blocks (block-level early exit)
  - bf16 inputs, fp32 accumulation (``preferred_element_type``)

``flash_attention`` is differentiable: forward = Pallas kernel, backward =
blockwise recompute in XLA (flash-style memory footprint, no S×S
materialization).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


# ---------------------------------------------------------------------------
# Reference implementation (also the CPU-test path and the backward building
# block). Shapes: q [B, H, Sq, D], k/v [B, H, Sk, D].
# ---------------------------------------------------------------------------

def mha_reference(q, k, v, causal: bool = True,
                  scale: Optional[float] = None,
                  q_offset: int = 0):
    """Plain attention; ``q_offset`` shifts causal positions (ring steps)."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    logits = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        sq, sk = q.shape[2], k.shape[2]
        q_pos = jnp.arange(sq)[:, None] + q_offset
        k_pos = jnp.arange(sk)[None, :]
        logits = jnp.where(q_pos >= k_pos, logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum(
        "bhqk,bhkd->bhqd", probs.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    ).astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas forward kernel
# ---------------------------------------------------------------------------

def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                      *, block_k: int, seq_k: int, scale: float,
                      causal: bool, block_q: int):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    # CRITICAL for MXU throughput: matmul operands stay in bf16 — only the
    # accumulator is fp32 (preferred_element_type). Casting inputs to fp32
    # first would push the dots off the fast MXU path (~8x slower).
    q = q_ref[0]  # [block_q, D], input dtype
    d = q.shape[-1]

    m0 = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)

    num_kb = seq_k // block_k
    if causal:
        # Only K blocks at or before this Q block's diagonal contribute.
        upper = jnp.minimum(
            num_kb, (qi + 1) * block_q // block_k + (block_q // block_k == 0)
        )
        upper = jnp.maximum(upper, 1)
    else:
        upper = num_kb

    q_pos = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    def body(kb, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [block_q, block_k] fp32
        if causal:
            k_pos = (
                jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
                + kb * block_k
            )
            s = jnp.where(q_pos + qi * block_q >= k_pos, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, upper, body, (m0, l0, acc0))
    safe_l = jnp.where(l == 0, 1.0, l)
    o_ref[0] = (acc / safe_l).astype(o_ref.dtype)
    lse_ref[0] = m + jnp.log(safe_l)  # [block_q, 1]


def _flash_fwd_pallas(q, k, v, causal: bool, scale: float,
                      block_q: int, block_k: int, interpret: bool):
    from jax.experimental import pallas as pl

    b, h, sq, d = q.shape
    sk = k.shape[2]
    bh = b * h
    q3 = q.reshape(bh, sq, d)
    k3 = k.reshape(bh, sk, d)
    v3 = v.reshape(bh, sk, d)
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    grid = (bh, sq // block_q)

    kernel = functools.partial(
        _flash_fwd_kernel, block_k=block_k, seq_k=sk, scale=scale,
        causal=causal, block_q=block_q,
    )
    out_shape = [
        jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        jax.ShapeDtypeStruct((bh, sq, 1), jnp.float32),
    ]
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
        pl.BlockSpec((1, sk, d), lambda i, j: (i, 0, 0)),
        pl.BlockSpec((1, sk, d), lambda i, j: (i, 0, 0)),
    ]
    out_specs = [
        pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
        pl.BlockSpec((1, block_q, 1), lambda i, j: (i, j, 0)),
    ]
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(q3, k3, v3)
    return o.reshape(b, h, sq, d), lse.reshape(b, h, sq)


# ---------------------------------------------------------------------------
# Pallas backward kernels: dq (grid over Q blocks) + dk/dv (grid over K
# blocks). P/dS tiles live in VMEM — the XLA-recompute fallback materializes
# them to HBM, which dominates attention cost at training shapes.
# ---------------------------------------------------------------------------

def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, *, block_k: int, seq_k: int, scale: float,
                         causal: bool, block_q: int):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    q = q_ref[0]            # [bq, d] input dtype
    do = do_ref[0]          # [bq, d]
    lse = lse_ref[0]        # [bq, 1] fp32
    delta = delta_ref[0]    # [bq, 1] fp32
    d = q.shape[-1]

    num_kb = seq_k // block_k
    if causal:
        upper = jnp.minimum(
            num_kb, (qi + 1) * block_q // block_k + (block_q // block_k == 0)
        )
        upper = jnp.maximum(upper, 1)
    else:
        upper = num_kb

    q_pos = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    def body(kb, dq_acc):
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            k_pos = (jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1) + kb * block_k)
            s = jnp.where(q_pos + qi * block_q >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * scale).astype(q.dtype)
        return dq_acc + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, upper, body,
                           jnp.zeros((block_q, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, *, block_q: int, seq_q: int,
                          scale: float, causal: bool, block_k: int):
    from jax.experimental import pallas as pl

    ki = pl.program_id(1)
    k = k_ref[0]  # [bk, d]
    v = v_ref[0]  # [bk, d]
    d = k.shape[-1]

    num_qb = seq_q // block_q
    if causal:
        # Only Q blocks at or after this K block's diagonal contribute.
        lower = jnp.maximum(0, (ki * block_k) // block_q)
    else:
        lower = 0

    k_pos = (jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
             + ki * block_k)

    def body(qb, carry):
        dk_acc, dv_acc = carry
        q_blk = q_ref[0, pl.ds(qb * block_q, block_q), :]
        do_blk = do_ref[0, pl.ds(qb * block_q, block_q), :]
        lse_blk = lse_ref[0, pl.ds(qb * block_q, block_q), :]
        delta_blk = delta_ref[0, pl.ds(qb * block_q, block_q), :]
        s = jax.lax.dot_general(
            q_blk, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = (jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0) + qb * block_q)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse_blk)  # [bq, bk] fp32
        p_lo = p.astype(do_blk.dtype)
        dv_new = dv_acc + jax.lax.dot_general(
            p_lo, do_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do_blk, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - delta_blk) * scale).astype(q_blk.dtype)
        dk_new = dk_acc + jax.lax.dot_general(
            ds, q_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk_new, dv_new

    dk, dv = jax.lax.fori_loop(
        lower, num_qb, body,
        (jnp.zeros((block_k, d), jnp.float32),
         jnp.zeros((block_k, d), jnp.float32)))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd_pallas(q, k, v, o, lse, do, causal, scale,
                      block_q, block_k, interpret):
    from jax.experimental import pallas as pl

    b, h, sq, d = q.shape
    sk = k.shape[2]
    bh = b * h
    q3 = q.reshape(bh, sq, d)
    k3 = k.reshape(bh, sk, d)
    v3 = v.reshape(bh, sk, d)
    do3 = do.reshape(bh, sq, d)
    lse3 = lse.reshape(bh, sq, 1)
    delta3 = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                     axis=-1).reshape(bh, sq, 1)
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)

    qb_spec = pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0))
    qb1_spec = pl.BlockSpec((1, block_q, 1), lambda i, j: (i, j, 0))
    kb_spec = pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0))
    full_q = pl.BlockSpec((1, sq, d), lambda i, j: (i, 0, 0))
    full_q1 = pl.BlockSpec((1, sq, 1), lambda i, j: (i, 0, 0))
    full_k = pl.BlockSpec((1, sk, d), lambda i, j: (i, 0, 0))

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, block_k=block_k, seq_k=sk,
                          scale=scale, causal=causal, block_q=block_q),
        grid=(bh, sq // block_q),
        in_specs=[qb_spec, full_k, full_k, qb_spec, qb1_spec, qb1_spec],
        out_specs=qb_spec,
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        interpret=interpret,
    )(q3, k3, v3, do3, lse3, delta3)

    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, block_q=block_q, seq_q=sq,
                          scale=scale, causal=causal, block_k=block_k),
        grid=(bh, sk // block_k),
        in_specs=[full_q, kb_spec, kb_spec, full_q, full_q1, full_q1],
        out_specs=[kb_spec, kb_spec],
        out_shape=[jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
                   jax.ShapeDtypeStruct((bh, sk, d), v.dtype)],
        interpret=interpret,
    )(q3, k3, v3, do3, lse3, delta3)

    return (dq.reshape(b, h, sq, d), dk.reshape(b, h, sk, d),
            dv.reshape(b, h, sk, d))


# ---------------------------------------------------------------------------
# Differentiable wrapper: pallas forward, blockwise-recompute backward.
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, scale, block_q, block_k):
    o, _ = _flash_fwd_pallas(q, k, v, causal, scale, block_q, block_k,
                             interpret=not _on_tpu())
    return o


def _flash_fwd_rule(q, k, v, causal, scale, block_q, block_k):
    from jax.ad_checkpoint import checkpoint_name

    o, lse = _flash_fwd_pallas(q, k, v, causal, scale, block_q, block_k,
                               interpret=not _on_tpu())
    # Named so remat policies (gpt2 "dots_attn") can save BOTH outputs:
    # with o and lse saved, the rematerialized forward's kernel call is
    # dead code and the backward never re-runs flash.
    o = checkpoint_name(o, "attn_out")
    lse = checkpoint_name(lse, "attn_lse")
    return o, (q, k, v, o, lse)


def _flash_bwd_rule(causal, scale, block_q, block_k, res, do):
    """Backward: pallas kernels (dq + dk/dv) when shapes tile; XLA
    blockwise recompute otherwise. Both recompute P per block from the
    saved LSE (no S×S materialization across blocks) with bf16 matmul
    operands and fp32 accumulation.
    """
    q, k, v, o, lse = res
    sq, sk = q.shape[2], k.shape[2]
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    if sq % bq == 0 and sk % bk == 0:
        return _flash_bwd_pallas(q, k, v, o, lse, do, causal, scale,
                                 bq, bk, interpret=not _on_tpu())

    # delta = rowsum(dO * O), fp32 elementwise (cheap, bandwidth-bound)
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)  # [B,H,Sq]

    n_blocks = max(1, sk // block_k)

    def body(kb, carry):
        dq, dk, dv = carry
        ks = jax.lax.dynamic_slice_in_dim(k, kb * block_k, block_k, axis=2)
        vs = jax.lax.dynamic_slice_in_dim(v, kb * block_k, block_k, axis=2)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, ks,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = jnp.arange(sq)[:, None]
            k_pos = jnp.arange(block_k)[None, :] + kb * block_k
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse[..., None])  # [B,H,Sq,block_k] fp32
        p_lo = p.astype(q.dtype)
        dv_blk = jnp.einsum("bhqk,bhqd->bhkd", p_lo, do,
                            preferred_element_type=jnp.float32)
        dp = jnp.einsum("bhqd,bhkd->bhqk", do, vs,
                        preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[..., None]) * scale).astype(q.dtype)
        dq_blk = jnp.einsum("bhqk,bhkd->bhqd", ds, ks,
                            preferred_element_type=jnp.float32)
        dk_blk = jnp.einsum("bhqk,bhqd->bhkd", ds, q,
                            preferred_element_type=jnp.float32)
        dk = jax.lax.dynamic_update_slice_in_dim(
            dk, dk_blk, kb * block_k, axis=2)
        dv = jax.lax.dynamic_update_slice_in_dim(
            dv, dv_blk, kb * block_k, axis=2)
        return dq + dq_blk, dk, dv

    shape_f32 = lambda t: jnp.zeros(t.shape, jnp.float32)
    dq, dk, dv = jax.lax.fori_loop(
        0, n_blocks, body, (shape_f32(q), shape_f32(k), shape_f32(v)))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(q, k, v, causal: bool = True,
                    scale: Optional[float] = None,
                    block_q: int = 512, block_k: int = 512):
    """Flash attention. q/k/v: [batch, heads, seq, head_dim].

    Pallas kernel on TPU; interpreter mode (same code path) on CPU tests.
    Falls back to :func:`mha_reference` for shapes the kernel can't tile.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    sq, sk = q.shape[2], k.shape[2]
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    if sq % bq != 0 or sk % bk != 0 or (causal and bq % bk != 0 and bk % bq != 0):
        return mha_reference(q, k, v, causal=causal, scale=scale)
    return _flash(q, k, v, causal, scale, bq, bk)


def attention(q, k, v, causal: bool = True, impl: str = "auto",
              scale: Optional[float] = None):
    """Dispatch: 'flash' | 'reference' | 'auto' (flash on TPU)."""
    if impl == "reference" or (impl == "auto" and not _on_tpu()):
        return mha_reference(q, k, v, causal=causal, scale=scale)
    return flash_attention(q, k, v, causal=causal, scale=scale)
