"""Attention ops: Pallas flash attention (TPU) + reference implementation.

The reference framework has no attention kernels (model code is user-space
there); this framework ships them because long-context SP/ring attention is
first-class (SURVEY §5.7). Design follows the standard online-softmax flash
algorithm, tiled for the MXU:

  - grid over (batch, query blocks) with ALL heads processed inside each
    program. At LM training shapes (head_dim 64, seq ~1-8k) the per-head
    tile work is far smaller than Mosaic's per-program overhead, so a
    (batch*heads, q-blocks) grid spends most of its time sequencing; head
    folding raises per-program work ~H× and measured ~4-5× kernel speedup
  - K/V resident in VMEM per program, streamed in ``block_k`` chunks with
    running (m, l, acc) online softmax
  - causal masking skips fully-masked K blocks (block-level early exit)
  - bf16 matmul operands, fp32 accumulation (``preferred_element_type``)

``flash_attention`` is differentiable end-to-end in Pallas: forward kernel
plus dq and dk/dv backward kernels (blockwise recompute from the saved LSE
— no S×S materialization anywhere). An XLA blockwise fallback covers
shapes the kernels can't tile.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


# ---------------------------------------------------------------------------
# Reference implementation (also the CPU-test path and the backward building
# block). Shapes: q [B, H, Sq, D], k/v [B, H, Sk, D].
# ---------------------------------------------------------------------------

def mha_reference_with_lse(q, k, v, causal: bool = True,
                           scale: Optional[float] = None,
                           q_offset: int = 0):
    """Reference attention returning (o, lse [B,H,Sq] fp32) — the
    mergeable form ring attention's block steps need. ``q_offset``
    shifts causal positions (ring steps). Fully-masked rows produce
    lse ~= -1e30 (finite), so downstream logaddexp merges never see
    inf-inf NaNs."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    logits = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        sq, sk = q.shape[2], k.shape[2]
        q_pos = jnp.arange(sq)[:, None] + q_offset
        k_pos = jnp.arange(sk)[None, :]
        logits = jnp.where(q_pos >= k_pos, logits, _NEG_INF)
    m = jnp.max(logits, axis=-1, keepdims=True)
    p = jnp.exp(logits - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bhqk,bhkd->bhqd", (p / l).astype(v.dtype), v,
                   preferred_element_type=jnp.float32).astype(q.dtype)
    return o, (m + jnp.log(l))[..., 0]


def mha_reference(q, k, v, causal: bool = True,
                  scale: Optional[float] = None,
                  q_offset: int = 0):
    """Plain attention; ``q_offset`` shifts causal positions (ring steps)."""
    return mha_reference_with_lse(q, k, v, causal=causal, scale=scale,
                                  q_offset=q_offset)[0]


# ---------------------------------------------------------------------------
# Pallas forward kernel
# ---------------------------------------------------------------------------

def _causal_upper(qi, block_q: int, block_k: int, num_kb: int):
    """Number of K blocks the online-softmax loop must visit for Q block
    ``qi`` under causal masking (blocks past the diagonal are all-masked)."""
    upper = jnp.minimum(
        num_kb, (qi + 1) * block_q // block_k + (block_q // block_k == 0)
    )
    return jnp.maximum(upper, 1)


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                      *, block_k: int, seq_k: int, scale: float,
                      causal: bool, block_q: int, num_heads: int):
    from jax.experimental import pallas as pl

    qi = pl.program_id(2)
    num_kb = seq_k // block_k
    upper = _causal_upper(qi, block_q, block_k, num_kb) if causal else num_kb
    q_pos = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    d = q_ref.shape[-1]

    def head_body(hh, _):
        # CRITICAL for MXU throughput: matmul operands stay in bf16 — only
        # the accumulator is fp32 (preferred_element_type). Casting inputs
        # to fp32 first pushes the dots off the fast MXU path (~8x slower).
        q = q_ref[0, hh]  # [block_q, D], input dtype

        m0 = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
        l0 = jnp.zeros((block_q, 1), jnp.float32)
        acc0 = jnp.zeros((block_q, d), jnp.float32)

        def body(kb, carry):
            m, l, acc = carry
            k_blk = k_ref[0, hh, pl.ds(kb * block_k, block_k), :]
            v_blk = v_ref[0, hh, pl.ds(kb * block_k, block_k), :]
            s = jax.lax.dot_general(
                q, k_blk, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale  # [block_q, block_k] fp32
            if causal:
                k_pos = (
                    jax.lax.broadcasted_iota(
                        jnp.int32, (block_q, block_k), 1)
                    + kb * block_k
                )
                s = jnp.where(q_pos + qi * block_q >= k_pos, s, _NEG_INF)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
            p = jnp.exp(s - m_new)
            alpha = jnp.exp(m - m_new)
            l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
            acc_new = acc * alpha + jax.lax.dot_general(
                p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            return m_new, l_new, acc_new

        m, l, acc = jax.lax.fori_loop(0, upper, body, (m0, l0, acc0))
        safe_l = jnp.where(l == 0, 1.0, l)
        o_ref[0, hh] = (acc / safe_l).astype(o_ref.dtype)
        lse_ref[0, hh] = m + jnp.log(safe_l)  # [block_q, 1]
        return 0

    jax.lax.fori_loop(0, num_heads, head_body, 0)


# Per-program VMEM budget for choosing how many heads to fold into one
# program (v5e/v4 have 128MB VMEM; leave ample headroom for double
# buffering + the score tile + compiler temps).
_VMEM_BUDGET = 48 * 1024 * 1024
_VMEM_LIMIT = 110 * 1024 * 1024


def _pick_head_block(h: int, per_head_bytes: int) -> int:
    """Largest divisor of ``h`` whose folded working set fits the budget."""
    hb = h
    while hb > 1 and (hb * per_head_bytes > _VMEM_BUDGET or h % hb != 0):
        hb -= 1
    while h % hb != 0:
        hb -= 1
    return max(hb, 1)


def _compiler_params(interpret: bool):
    if interpret:
        return None
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.CompilerParams(vmem_limit_bytes=_VMEM_LIMIT)


def _flash_fwd_single_pass_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                                  *, seq_k: int, scale: float, causal: bool,
                                  block_q: int, num_heads: int):
    """Short-sequence forward: the whole K/V fits VMEM, so compute the full
    [block_q, seq_k] score tile with ONE dot and a single softmax pass —
    no online-softmax carry chain (whose per-K-block VPU rescales dominate
    at seq ~1k where there are only 1-2 K blocks anyway)."""
    from jax.experimental import pallas as pl

    qi = pl.program_id(2)
    q_pos = jax.lax.broadcasted_iota(jnp.int32, (block_q, seq_k), 0)
    k_pos = jax.lax.broadcasted_iota(jnp.int32, (block_q, seq_k), 1)

    def head_body(hh, _):
        q = q_ref[0, hh]          # [block_q, d]
        k = k_ref[0, hh]          # [seq_k, d]
        v = v_ref[0, hh]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            s = jnp.where(q_pos + qi * block_q >= k_pos, s, _NEG_INF)
        m = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - m)
        l = jnp.sum(p, axis=-1, keepdims=True)
        o = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        safe_l = jnp.where(l == 0, 1.0, l)
        o_ref[0, hh] = (o / safe_l).astype(o_ref.dtype)
        lse_ref[0, hh] = m + jnp.log(safe_l)
        return 0

    jax.lax.fori_loop(0, num_heads, head_body, 0)


# Below this K length the single-pass forward kernel (full score tile in
# VMEM) wins over the online-softmax loop.
_SINGLE_PASS_MAX_SK = 2048


def _flash_fwd_pallas(q, k, v, causal: bool, scale: float,
                      block_q: int, block_k: int, interpret: bool):
    from jax.experimental import pallas as pl

    b, h, sq, d = q.shape
    sk = k.shape[2]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    esize = q.dtype.itemsize
    # q + o blocks, full-seq k + v, lse; ×2 for pipeline double-buffering.
    per_head = 2 * (2 * block_q * d * esize + 2 * sk * d * esize
                    + 4 * block_q)
    hb = _pick_head_block(h, per_head)
    grid = (b, h // hb, sq // block_q)

    if sk <= _SINGLE_PASS_MAX_SK:
        kernel = functools.partial(
            _flash_fwd_single_pass_kernel, seq_k=sk, scale=scale,
            causal=causal, block_q=block_q, num_heads=hb,
        )
    else:
        kernel = functools.partial(
            _flash_fwd_kernel, block_k=block_k, seq_k=sk, scale=scale,
            causal=causal, block_q=block_q, num_heads=hb,
        )
    out_shape = [
        jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
        jax.ShapeDtypeStruct((b, h, sq, 1), jnp.float32),
    ]
    in_specs = [
        pl.BlockSpec((1, hb, block_q, d), lambda i, g, j: (i, g, j, 0)),
        pl.BlockSpec((1, hb, sk, d), lambda i, g, j: (i, g, 0, 0)),
        pl.BlockSpec((1, hb, sk, d), lambda i, g, j: (i, g, 0, 0)),
    ]
    out_specs = [
        pl.BlockSpec((1, hb, block_q, d), lambda i, g, j: (i, g, j, 0)),
        pl.BlockSpec((1, hb, block_q, 1), lambda i, g, j: (i, g, j, 0)),
    ]
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
        compiler_params=_compiler_params(interpret),
    )(q, k, v)
    return o, lse.reshape(b, h, sq)


# ---------------------------------------------------------------------------
# Pallas backward kernels: dq (grid over Q blocks) + dk/dv (grid over K
# blocks). P/dS tiles live in VMEM — the XLA-recompute fallback materializes
# them to HBM, which dominates attention cost at training shapes.
# ---------------------------------------------------------------------------

def _flash_bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                            dq_ref, dk_ref, dv_ref, dk_acc, dv_acc, *,
                            block_q: int, block_k: int, seq_q: int,
                            seq_k: int, scale: float, causal: bool,
                            num_heads: int):
    """dq + dk + dv in ONE pallas program (per (batch, head-group)).

    Every pallas_call costs a large fixed launch overhead on TPU relative
    to this kernel's work, so the two classic backward kernels (dq gridded
    over Q blocks, dk/dv gridded over K blocks) are fused: one program
    walks Q blocks, recomputes P per (Q,K) tile from the saved LSE, and
    accumulates dk/dv into fp32 VMEM scratch across the Q loop.
    """
    from jax.experimental import pallas as pl

    num_qb = seq_q // block_q
    num_kb = seq_k // block_k
    d = q_ref.shape[-1]
    q_pos0 = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    k_pos0 = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)

    def head_body(hh, _):
        dk_acc[...] = jnp.zeros((seq_k, d), jnp.float32)
        dv_acc[...] = jnp.zeros((seq_k, d), jnp.float32)

        def q_body(qb, _q):
            q = q_ref[0, hh, pl.ds(qb * block_q, block_q), :]
            do = do_ref[0, hh, pl.ds(qb * block_q, block_q), :]
            lse = lse_ref[0, hh, pl.ds(qb * block_q, block_q), :]
            delta = delta_ref[0, hh, pl.ds(qb * block_q, block_q), :]
            upper = (_causal_upper(qb, block_q, block_k, num_kb)
                     if causal else num_kb)

            def k_body(kb, dq_part):
                k_blk = k_ref[0, hh, pl.ds(kb * block_k, block_k), :]
                v_blk = v_ref[0, hh, pl.ds(kb * block_k, block_k), :]
                s = jax.lax.dot_general(
                    q, k_blk, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32) * scale
                if causal:
                    s = jnp.where(
                        q_pos0 + qb * block_q >= k_pos0 + kb * block_k,
                        s, _NEG_INF)
                p = jnp.exp(s - lse)  # [bq, bk] fp32
                p_lo = p.astype(do.dtype)
                dp = jax.lax.dot_general(
                    do, v_blk, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)
                ds = (p * (dp - delta) * scale).astype(q.dtype)
                dv_acc[pl.ds(kb * block_k, block_k), :] += (
                    jax.lax.dot_general(
                        p_lo, do, (((0,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32))
                dk_acc[pl.ds(kb * block_k, block_k), :] += (
                    jax.lax.dot_general(
                        ds, q, (((0,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32))
                return dq_part + jax.lax.dot_general(
                    ds, k_blk, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)

            dq = jax.lax.fori_loop(
                0, upper, k_body, jnp.zeros((block_q, d), jnp.float32))
            dq_ref[0, hh, pl.ds(qb * block_q, block_q), :] = (
                dq.astype(dq_ref.dtype))
            return 0

        jax.lax.fori_loop(0, num_qb, q_body, 0)
        dk_ref[0, hh] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, hh] = dv_acc[...].astype(dv_ref.dtype)
        return 0

    jax.lax.fori_loop(0, num_heads, head_body, 0)


def _flash_bwd_pallas(q, k, v, o, lse, do, causal, scale,
                      block_q, block_k, interpret):
    from jax.experimental import pallas as pl

    b, h, sq, d = q.shape
    sk = k.shape[2]
    lse4 = lse.reshape(b, h, sq, 1)
    delta4 = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                     axis=-1, keepdims=True)  # [b, h, sq, 1] fp32
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    esize = q.dtype.itemsize
    # Full-seq q/k/v/do in, dq/dk/dv out, double-buffered, plus fp32
    # compiler temps for the tile chain — empirically ~5.5MB/head at
    # seq 1024/d 64, so budget ~40*sq*d bytes per folded head.
    per_head = 5 * (7 * sq * d * esize + 8 * sq) + 8 * sk * d
    hb = _pick_head_block(h, per_head)

    full_q = pl.BlockSpec((1, hb, sq, d), lambda i, g: (i, g, 0, 0))
    full_q1 = pl.BlockSpec((1, hb, sq, 1), lambda i, g: (i, g, 0, 0))
    full_k = pl.BlockSpec((1, hb, sk, d), lambda i, g: (i, g, 0, 0))

    from jax.experimental.pallas import tpu as pltpu
    scratch = [pltpu.VMEM((sk, d), jnp.float32),
               pltpu.VMEM((sk, d), jnp.float32)]

    dq, dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_fused_kernel, block_q=block_q,
                          block_k=block_k, seq_q=sq, seq_k=sk, scale=scale,
                          causal=causal, num_heads=hb),
        grid=(b, h // hb),
        in_specs=[full_q, full_k, full_k, full_q, full_q1, full_q1],
        out_specs=[full_q, full_k, full_k],
        out_shape=[jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
                   jax.ShapeDtypeStruct((b, h, sk, d), k.dtype),
                   jax.ShapeDtypeStruct((b, h, sk, d), v.dtype)],
        scratch_shapes=scratch,
        interpret=interpret,
        compiler_params=_compiler_params(interpret),
    )(q, k, v, do, lse4, delta4)

    return dq, dk, dv


# ---------------------------------------------------------------------------
# Differentiable wrapper: pallas forward, blockwise-recompute backward.
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, scale, block_q, block_k):
    o, _ = _flash_fwd_pallas(q, k, v, causal, scale, block_q, block_k,
                             interpret=not _on_tpu())
    return o


def _flash_fwd_rule(q, k, v, causal, scale, block_q, block_k):
    from jax.ad_checkpoint import checkpoint_name

    o, lse = _flash_fwd_pallas(q, k, v, causal, scale, block_q, block_k,
                               interpret=not _on_tpu())
    # Named so remat policies (gpt2 "dots_attn") can save BOTH outputs:
    # with o and lse saved, the rematerialized forward's kernel call is
    # dead code and the backward never re-runs flash.
    o = checkpoint_name(o, "attn_out")
    lse = checkpoint_name(lse, "attn_lse")
    return o, (q, k, v, o, lse)


def _flash_bwd_rule(causal, scale, block_q, block_k, res, do):
    """Backward: pallas kernels (dq + dk/dv) when shapes tile; XLA
    blockwise recompute otherwise. Both recompute P per block from the
    saved LSE (no S×S materialization across blocks) with bf16 matmul
    operands and fp32 accumulation.
    """
    q, k, v, o, lse = res
    sq, sk = q.shape[2], k.shape[2]
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    if sq % bq == 0 and sk % bk == 0:
        return _flash_bwd_pallas(q, k, v, o, lse, do, causal, scale,
                                 bq, bk, interpret=not _on_tpu())

    # delta = rowsum(dO * O), fp32 elementwise (cheap, bandwidth-bound)
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)  # [B,H,Sq]

    n_blocks = max(1, sk // block_k)

    def body(kb, carry):
        dq, dk, dv = carry
        ks = jax.lax.dynamic_slice_in_dim(k, kb * block_k, block_k, axis=2)
        vs = jax.lax.dynamic_slice_in_dim(v, kb * block_k, block_k, axis=2)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, ks,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = jnp.arange(sq)[:, None]
            k_pos = jnp.arange(block_k)[None, :] + kb * block_k
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse[..., None])  # [B,H,Sq,block_k] fp32
        p_lo = p.astype(q.dtype)
        dv_blk = jnp.einsum("bhqk,bhqd->bhkd", p_lo, do,
                            preferred_element_type=jnp.float32)
        dp = jnp.einsum("bhqd,bhkd->bhqk", do, vs,
                        preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[..., None]) * scale).astype(q.dtype)
        dq_blk = jnp.einsum("bhqk,bhkd->bhqd", ds, ks,
                            preferred_element_type=jnp.float32)
        dk_blk = jnp.einsum("bhqk,bhqd->bhkd", ds, q,
                            preferred_element_type=jnp.float32)
        dk = jax.lax.dynamic_update_slice_in_dim(
            dk, dk_blk, kb * block_k, axis=2)
        dv = jax.lax.dynamic_update_slice_in_dim(
            dv, dv_blk, kb * block_k, axis=2)
        return dq + dq_blk, dk, dv

    shape_f32 = lambda t: jnp.zeros(t.shape, jnp.float32)
    dq, dk, dv = jax.lax.fori_loop(
        0, n_blocks, body, (shape_f32(q), shape_f32(k), shape_f32(v)))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def _tileable(q, k, causal: bool, block_q: int, block_k: int):
    """Clamp block sizes to the sequence and decide whether the pallas
    kernels can tile this shape; (bq, bk, ok)."""
    sq, sk = q.shape[2], k.shape[2]
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    ok = not (sq % bq != 0 or sk % bk != 0
              or (causal and bq % bk != 0 and bk % bq != 0))
    return bq, bk, ok


def flash_attention(q, k, v, causal: bool = True,
                    scale: Optional[float] = None,
                    block_q: int = 512, block_k: int = 512):
    """Flash attention. q/k/v: [batch, heads, seq, head_dim].

    Pallas kernel on TPU; interpreter mode (same code path) on CPU tests.
    Falls back to :func:`mha_reference` for shapes the kernel can't tile.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    bq, bk, ok = _tileable(q, k, causal, block_q, block_k)
    if not ok:
        return mha_reference(q, k, v, causal=causal, scale=scale)
    return _flash(q, k, v, causal, scale, bq, bk)


def attention(q, k, v, causal: bool = True, impl: str = "auto",
              scale: Optional[float] = None):
    """Dispatch: 'flash' | 'reference' | 'auto' (flash on TPU)."""
    if impl == "reference" or (impl == "auto" and not _on_tpu()):
        return mha_reference(q, k, v, causal=causal, scale=scale)
    return flash_attention(q, k, v, causal=causal, scale=scale)


def attention_with_lse(q, k, v, causal: bool = True,
                       scale: Optional[float] = None, impl: str = "auto",
                       block_q: int = 512, block_k: int = 512):
    """Attention returning (o, lse) — pallas flash forward on TPU,
    reference path elsewhere. Forward-only contract (no custom vjp):
    the ring TRAINING path uses the autodiff-able einsum body; this is
    the serving/inference block used by ``ring_flash_attention_local``.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if impl == "reference" or (impl == "auto" and not _on_tpu()):
        return mha_reference_with_lse(q, k, v, causal=causal, scale=scale)
    bq, bk, ok = _tileable(q, k, causal, block_q, block_k)
    if not ok:
        return mha_reference_with_lse(q, k, v, causal=causal, scale=scale)
    return _flash_fwd_pallas(q, k, v, causal, scale, bq, bk,
                             interpret=not _on_tpu())
