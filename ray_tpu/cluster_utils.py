"""Multi-node-on-one-host test cluster.

Reference analog: ``python/ray/cluster_utils.py:99`` — the central fixture
for testing scheduling, spillback, fault tolerance, and node failure without
real machines: multiple node managers (each with its own worker pool, store,
and resource ledger) share one control store in the head process.
``add_node(**resources)`` / ``remove_node(node)`` drive membership.
"""

from __future__ import annotations

import os
import sys
from typing import Dict, Optional

from .core import runtime as runtime_mod
from .core.ids import NodeID


def chaos_seed(seed: Optional[int] = None) -> int:
    """Resolve a chaos harness's RNG seed: an explicit ``seed`` wins,
    else ``RT_CHAOS_SEED`` from the environment, else 0. Every killer
    logs the resolved value at start so a failing chaos run can be
    replayed bit-for-bit (same seed -> same victim sequence)."""
    if seed is not None:
        return int(seed)
    return int(os.environ.get("RT_CHAOS_SEED", "0") or 0)


def _log_seed(harness: str, seed: int) -> None:
    print("[rt-chaos] %s seed=%d (explicit seed arg or RT_CHAOS_SEED "
          "env replays this run)" % (harness, seed), file=sys.stderr,
          flush=True)


class Cluster:
    def __init__(self, initialize_head: bool = True,
                 head_node_args: Optional[dict] = None):
        self.head_node_id: Optional[NodeID] = None
        self._nodes: list = []
        if initialize_head:
            args = dict(head_node_args or {})
            num_cpus = args.pop("num_cpus", 2)
            self.runtime = runtime_mod.init(num_cpus=num_cpus, **args)
            self.head_node_id = self.runtime.scheduler.nodes()[0].node_id
            self._nodes.append(self.head_node_id)
        else:
            self.runtime = None

    def add_node(self, num_cpus: float = 2, num_tpus: float = 0,
                 resources: Optional[Dict[str, float]] = None,
                 object_store_memory: Optional[int] = None,
                 topology: Optional[dict] = None,
                 labels: Optional[dict] = None,
                 remote: Optional[bool] = None) -> NodeID:
        """``remote=True`` runs the node as a separate OS-process daemon
        (its own worker pool + shm store, attached over TCP) — the
        multi-host path; default in-process node managers simulate
        multi-node cheaply (reference: Cluster.add_node raylets)."""
        node_resources = {"CPU": float(num_cpus)}
        if num_tpus:
            node_resources["TPU"] = float(num_tpus)
        node_resources.update(resources or {})
        node_id = self.runtime.add_node(
            node_resources, object_store_memory=object_store_memory,
            labels=labels, topology=topology, remote=remote,
        )
        self._nodes.append(node_id)
        return node_id

    def remove_node(self, node_id: NodeID) -> None:
        """Simulated node failure: workers killed, store destroyed."""
        self.runtime.remove_node(node_id)
        if node_id in self._nodes:
            self._nodes.remove(node_id)

    def wait_for_nodes(self, timeout: float = 30.0) -> None:
        """Block until every node's worker pool has a registered worker.

        Reference analog: ``Cluster.wait_for_nodes`` — tests that need
        deterministic placement call this after ``add_node``.
        """
        import time

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            pools_ready = all(
                any(w._registered.is_set() for w in n.pool.all_workers())
                for n in self.runtime.scheduler.nodes()
            )
            if pools_ready:
                return
            time.sleep(0.02)
        raise TimeoutError("worker pools did not become ready")

    def shutdown(self) -> None:
        runtime_mod.shutdown()


class NodeKiller:
    """Chaos fault injector: kills random non-head nodes on a timer.

    Reference analog: ``_private/test_utils.get_and_run_node_killer``'s
    ``NodeKillerActor`` (:1116) driving chaos release tests
    (``release/nightly_tests/chaos_test/``) — workloads must survive
    repeated node loss through lineage reconstruction and retries.
    """

    def __init__(self, cluster: Cluster, kill_interval_s: float = 1.0,
                 max_kills: Optional[int] = None,
                 seed: Optional[int] = None):
        import random
        import threading

        self.cluster = cluster
        self.kill_interval_s = kill_interval_s
        self.max_kills = max_kills
        self.killed: list = []
        self.seed = chaos_seed(seed)
        _log_seed("NodeKiller", self.seed)
        self._rng = random.Random(self.seed)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _victims(self) -> list:
        return [nid for nid in self.cluster._nodes
                if nid != self.cluster.head_node_id]

    def kill_one(self) -> Optional[NodeID]:
        """Kill one random non-head node now; returns its id (or None).

        Daemon-backed nodes are SIGKILLed (a real host crash: the driver
        notices via connection EOF, no cooperative teardown); in-process
        nodes go through the simulated removal path.
        """
        victims = self._victims()
        if not victims:
            return None
        node_id = self._rng.choice(victims)
        node = self.cluster.runtime.scheduler.get_node(node_id)
        if node is not None and getattr(node, "is_remote", False):
            try:
                node.process.kill()
            except Exception:
                self.cluster.remove_node(node_id)
            if node_id in self.cluster._nodes:
                self.cluster._nodes.remove(node_id)
        else:
            self.cluster.remove_node(node_id)
        self.killed.append(node_id)
        return node_id

    def run(self) -> None:
        import threading

        def loop():
            while not self._stop.wait(self.kill_interval_s):
                if (self.max_kills is not None
                        and len(self.killed) >= self.max_kills):
                    return
                self.kill_one()

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="rt-node-killer")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None


class ReplicaKiller:
    """Chaos fault injector for the SERVE plane: SIGKILLs a random
    replica worker of one deployment on a timer (sibling of
    :class:`NodeKiller` / :class:`HeadKiller`).

    A replica dies like a real worker crash — no cooperative teardown,
    the head notices via pipe EOF, the controller's health sweep /
    death path evicts it, and target-count reconciliation replaces it.
    Used by ``bench_serve_chaos`` and the fault-tolerance tests to
    prove requests in flight on the victim are retried (or fail with a
    typed error), never hung.
    """

    def __init__(self, deployment: str, kill_interval_s: float = 1.0,
                 max_kills: Optional[int] = None,
                 seed: Optional[int] = None):
        import random
        import threading

        self.deployment = deployment
        self.kill_interval_s = kill_interval_s
        self.max_kills = max_kills
        self.killed: list = []  # (actor_id, pid) per kill
        self.seed = chaos_seed(seed)
        _log_seed("ReplicaKiller", self.seed)
        self._rng = random.Random(self.seed)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _replicas(self) -> list:
        from .serve import api as serve_api

        ctrl = serve_api._controller()
        if ctrl is None:
            return []
        rt = runtime_mod.get_head_runtime()
        return rt.get(ctrl.get_replicas.remote(self.deployment),
                      timeout=10)

    def replica_pids(self) -> Dict[bytes, int]:
        """actor_id bytes -> worker pid for the deployment's live
        replicas (skips replicas whose worker is gone already)."""
        rt = runtime_mod.get_head_runtime()
        out: Dict[bytes, int] = {}
        for r in self._replicas():
            rec = rt.get_actor_record(r._actor_id)
            worker = getattr(rec, "worker", None)
            proc = getattr(worker, "process", None)
            pid = getattr(proc, "pid", None)
            if pid is not None:
                out[r._actor_id.binary()] = pid
        return out

    def kill_one(self) -> Optional[bytes]:
        """SIGKILL one random replica worker now; returns the victim's
        actor_id bytes (or None if no killable replica exists)."""
        import os
        import signal

        pids = self.replica_pids()
        if not pids:
            return None
        victim = self._rng.choice(sorted(pids))
        pid = pids[victim]
        try:
            os.kill(pid, signal.SIGKILL)
        except ProcessLookupError:
            return None
        self.killed.append((victim, pid))
        return victim

    def run(self) -> None:
        import threading

        def loop():
            while not self._stop.wait(self.kill_interval_s):
                if (self.max_kills is not None
                        and len(self.killed) >= self.max_kills):
                    return
                try:
                    self.kill_one()
                except Exception:
                    pass  # serve shutting down mid-chaos is fine

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="rt-replica-killer")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None


# Driver script run by each HeadKiller head process. Cycle 1 creates the
# named chaos actor; every later cycle is a RECOVERY: the replacement
# head replays the WAL during init, the actor re-resolves by name, and
# the first call (queued while the actor restarts) completes. Prints one
# parseable READY line, then keeps the actor-call workload running until
# the killer SIGKILLs the process mid-workload.
_HEADKILLER_DRIVER_SRC = r"""
import time
_t0 = time.perf_counter()
import ray_tpu as rt
from ray_tpu.core import runtime as _rtm

rt.init(num_cpus=2)
_init_ms = (time.perf_counter() - _t0) * 1000.0


@rt.remote
class _ChaosCounter:
    def __init__(self):
        self.n = 0

    def bump(self):
        self.n += 1
        return self.n


_t1 = time.perf_counter()
try:
    h = rt.get_actor("chaos_counter")
    created = 0
except ValueError:
    h = _ChaosCounter.options(name="chaos_counter",
                              max_restarts=100000).remote()
    created = 1
v = rt.get(h.bump.remote(), timeout=120)
_recover_ms = (time.perf_counter() - _t1) * 1000.0
_rep = getattr(_rtm.get_head_runtime(), "recovery_report", None) or {}
print("HEADKILLER_READY value=%d created=%d init_ms=%.1f "
      "recover_ms=%.1f restarted=%d actor=%s"
      % (v, created, _init_ms, _recover_ms,
         _rep.get("actors_restarted", 0), h._actor_id.hex()), flush=True)
while True:
    rt.get(h.bump.remote())
    time.sleep(0.005)
"""


class HeadKiller:
    """Chaos fault injector for the HEAD: the NodeKiller counterpart for
    the control plane's single point of failure.

    Each cycle runs a driver/head process (with the native control store
    on a shared WAL ``persist_path``), waits until it reports READY, lets
    the actor-call workload run, then SIGKILLs the head mid-workload —
    no teardown, exactly like a head-host crash. The next cycle's head
    replays the WAL, re-resolves the named actor, restarts it
    (``max_restarts``), and completes the queued call; the time that
    takes is the recovery sample (reference:
    ``release/nightly_tests/chaos_test`` + GCS FT restart drills).
    """

    READY_PREFIX = "HEADKILLER_READY"

    def __init__(self, persist_path: str, kill_after_s: float = 0.5,
                 spawn_timeout_s: float = 180.0,
                 env: Optional[Dict[str, str]] = None,
                 head_src: str = _HEADKILLER_DRIVER_SRC,
                 seed: Optional[int] = None):
        import random

        self.persist_path = persist_path
        self.kill_after_s = kill_after_s
        self.spawn_timeout_s = spawn_timeout_s
        self.killed: list = []
        self._env = dict(env or {})
        self._head_src = head_src
        # Seeded jitter on the kill point (0.75x-1.25x kill_after_s):
        # varies WHERE in the workload the SIGKILL lands while keeping
        # the whole victim sequence replayable from one seed.
        self.seed = chaos_seed(seed)
        _log_seed("HeadKiller", self.seed)
        self._rng = random.Random(self.seed)

    def _child_env(self) -> Dict[str, str]:
        import os

        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        env = dict(os.environ)
        env.update({
            "RT_NATIVE_CONTROL_STORE": "1",
            "RT_CONTROL_STORE_PERSIST_PATH": self.persist_path,
            "JAX_PLATFORMS": "cpu",
            "RT_JAX_PLATFORM": "cpu",
            # Small arena: SIGKILLed heads leak their /dev/shm files
            # until reboot; keep the per-cycle footprint tiny.
            "RT_OBJECT_STORE_MEMORY": str(64 * 1024 * 1024),
            "PYTHONUNBUFFERED": "1",
            "PYTHONPATH": repo_root + os.pathsep + env.get(
                "PYTHONPATH", ""),
        })
        env.update(self._env)
        return env

    def run_cycle(self, kill: bool = True) -> Dict[str, float]:
        """One head lifetime: spawn → READY → (workload) → SIGKILL.

        Returns the parsed READY fields plus ``total_ms`` (process spawn
        to READY — the full restart-to-recovered wall time, imports and
        WAL replay included).
        """
        import signal
        import subprocess
        import sys
        import threading
        import time

        t_spawn = time.monotonic()
        proc = subprocess.Popen(
            [sys.executable, "-c", self._head_src],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True, env=self._child_env(),
        )
        watchdog = threading.Timer(self.spawn_timeout_s, proc.kill)
        watchdog.daemon = True
        watchdog.start()
        info: Optional[Dict[str, float]] = None
        try:
            for line in proc.stdout:
                if line.startswith(self.READY_PREFIX):
                    info = {}
                    for kv in line.split()[1:]:
                        k, _, v = kv.partition("=")
                        try:
                            info[k] = float(v)
                        except ValueError:
                            info[k] = v  # type: ignore[assignment]
                    break
        finally:
            watchdog.cancel()
        if info is None:
            proc.kill()
            proc.wait()
            proc.stdout.close()
            raise RuntimeError(
                "head process exited before READY (rc=%s)"
                % proc.returncode)
        info["total_ms"] = (time.monotonic() - t_spawn) * 1000.0
        if kill:
            # let the workload run; seeded jitter moves the kill point
            time.sleep(self.kill_after_s * self._rng.uniform(0.75, 1.25))
            proc.send_signal(signal.SIGKILL)
            proc.wait()
            self.killed.append(proc.pid)
        proc.stdout.close()
        return info

    def run(self, cycles: int) -> list:
        """``cycles`` head lifetimes on one WAL; every cycle after the
        first is a recovery (``created == 0``)."""
        return [self.run_cycle() for _ in range(cycles)]
