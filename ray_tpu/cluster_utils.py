"""Multi-node-on-one-host test cluster.

Reference analog: ``python/ray/cluster_utils.py:99`` — the central fixture
for testing scheduling, spillback, fault tolerance, and node failure without
real machines: multiple node managers (each with its own worker pool, store,
and resource ledger) share one control store in the head process.
``add_node(**resources)`` / ``remove_node(node)`` drive membership.
"""

from __future__ import annotations

from typing import Dict, Optional

from .core import runtime as runtime_mod
from .core.ids import NodeID


class Cluster:
    def __init__(self, initialize_head: bool = True,
                 head_node_args: Optional[dict] = None):
        self.head_node_id: Optional[NodeID] = None
        self._nodes: list = []
        if initialize_head:
            args = dict(head_node_args or {})
            num_cpus = args.pop("num_cpus", 2)
            self.runtime = runtime_mod.init(num_cpus=num_cpus, **args)
            self.head_node_id = self.runtime.scheduler.nodes()[0].node_id
            self._nodes.append(self.head_node_id)
        else:
            self.runtime = None

    def add_node(self, num_cpus: float = 2, num_tpus: float = 0,
                 resources: Optional[Dict[str, float]] = None,
                 object_store_memory: Optional[int] = None,
                 topology: Optional[dict] = None,
                 labels: Optional[dict] = None,
                 remote: Optional[bool] = None) -> NodeID:
        """``remote=True`` runs the node as a separate OS-process daemon
        (its own worker pool + shm store, attached over TCP) — the
        multi-host path; default in-process node managers simulate
        multi-node cheaply (reference: Cluster.add_node raylets)."""
        node_resources = {"CPU": float(num_cpus)}
        if num_tpus:
            node_resources["TPU"] = float(num_tpus)
        node_resources.update(resources or {})
        node_id = self.runtime.add_node(
            node_resources, object_store_memory=object_store_memory,
            labels=labels, topology=topology, remote=remote,
        )
        self._nodes.append(node_id)
        return node_id

    def remove_node(self, node_id: NodeID) -> None:
        """Simulated node failure: workers killed, store destroyed."""
        self.runtime.remove_node(node_id)
        if node_id in self._nodes:
            self._nodes.remove(node_id)

    def wait_for_nodes(self, timeout: float = 30.0) -> None:
        """Block until every node's worker pool has a registered worker.

        Reference analog: ``Cluster.wait_for_nodes`` — tests that need
        deterministic placement call this after ``add_node``.
        """
        import time

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            pools_ready = all(
                any(w._registered.is_set() for w in n.pool.all_workers())
                for n in self.runtime.scheduler.nodes()
            )
            if pools_ready:
                return
            time.sleep(0.02)
        raise TimeoutError("worker pools did not become ready")

    def shutdown(self) -> None:
        runtime_mod.shutdown()


class NodeKiller:
    """Chaos fault injector: kills random non-head nodes on a timer.

    Reference analog: ``_private/test_utils.get_and_run_node_killer``'s
    ``NodeKillerActor`` (:1116) driving chaos release tests
    (``release/nightly_tests/chaos_test/``) — workloads must survive
    repeated node loss through lineage reconstruction and retries.
    """

    def __init__(self, cluster: Cluster, kill_interval_s: float = 1.0,
                 max_kills: Optional[int] = None, seed: int = 0):
        import random
        import threading

        self.cluster = cluster
        self.kill_interval_s = kill_interval_s
        self.max_kills = max_kills
        self.killed: list = []
        self._rng = random.Random(seed)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _victims(self) -> list:
        return [nid for nid in self.cluster._nodes
                if nid != self.cluster.head_node_id]

    def kill_one(self) -> Optional[NodeID]:
        """Kill one random non-head node now; returns its id (or None).

        Daemon-backed nodes are SIGKILLed (a real host crash: the driver
        notices via connection EOF, no cooperative teardown); in-process
        nodes go through the simulated removal path.
        """
        victims = self._victims()
        if not victims:
            return None
        node_id = self._rng.choice(victims)
        node = self.cluster.runtime.scheduler.get_node(node_id)
        if node is not None and getattr(node, "is_remote", False):
            try:
                node.process.kill()
            except Exception:
                self.cluster.remove_node(node_id)
            if node_id in self.cluster._nodes:
                self.cluster._nodes.remove(node_id)
        else:
            self.cluster.remove_node(node_id)
        self.killed.append(node_id)
        return node_id

    def run(self) -> None:
        import threading

        def loop():
            while not self._stop.wait(self.kill_interval_s):
                if (self.max_kills is not None
                        and len(self.killed) >= self.max_kills):
                    return
                self.kill_one()

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="rt-node-killer")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
