"""Per-handler event-loop statistics.

Reference analog: ``src/ray/common/asio/instrumented_io_context.h`` +
``event_stats.h`` — every handler posted to a raylet/GCS event loop is
timed, and ``RAY_event_stats_print_interval_ms`` dumps a table of
per-handler count / total / mean / max. Here the instrumented "loops"
are the runtime's worker-message pump, the node daemon's control-message
handler, and the control-store client ops; stats surface through the
state API (``event_loop_stats``), the dashboard (``/api/event_stats``),
and ``rt status -v``.

Recording is one dict update per event under the GIL (a lock guards
only the aggregate swap in snapshot) — cheap enough for hot dispatch
paths.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional


class _HandlerStat:
    __slots__ = ("count", "total_s", "max_s")

    def __init__(self):
        self.count = 0
        self.total_s = 0.0
        self.max_s = 0.0


class EventStats:
    def __init__(self):
        self._stats: Dict[str, _HandlerStat] = {}
        self._lock = threading.Lock()

    def record(self, name: str, duration_s: float) -> None:
        stat = self._stats.get(name)
        if stat is None:
            # Rare path; the lock only guards first-insert races.
            with self._lock:
                stat = self._stats.setdefault(name, _HandlerStat())
        stat.count += 1
        stat.total_s += duration_s
        if duration_s > stat.max_s:
            stat.max_s = duration_s

    def measure(self, name: str) -> "_Timer":
        return _Timer(self, name)

    def snapshot(self, top: Optional[int] = None) -> List[Dict[str, Any]]:
        """Rows sorted by total time descending (the reference table's
        ordering — total time is what finds a hot handler)."""
        rows = []
        for name, s in list(self._stats.items()):
            count = s.count
            if not count:
                continue
            rows.append({
                "handler": name,
                "count": count,
                "total_ms": round(s.total_s * 1e3, 3),
                "mean_us": round(s.total_s / count * 1e6, 1),
                "max_ms": round(s.max_s * 1e3, 3),
            })
        rows.sort(key=lambda r: -r["total_ms"])
        return rows[:top] if top else rows

    def reset(self) -> None:
        with self._lock:
            self._stats.clear()

    def format_table(self, top: int = 20) -> str:
        rows = self.snapshot(top)
        if not rows:
            return "(no events recorded)"
        w = max(len(r["handler"]) for r in rows)
        lines = [f"{'handler':<{w}}  {'count':>8}  {'total_ms':>10} "
                 f"{'mean_us':>9}  {'max_ms':>8}"]
        for r in rows:
            lines.append(
                f"{r['handler']:<{w}}  {r['count']:>8}  "
                f"{r['total_ms']:>10.3f} {r['mean_us']:>9.1f}  "
                f"{r['max_ms']:>8.3f}")
        return "\n".join(lines)


class _Timer:
    __slots__ = ("_stats", "_name", "_t0")

    def __init__(self, stats: EventStats, name: str):
        self._stats = stats
        self._name = name

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._stats.record(self._name,
                           time.perf_counter() - self._t0)
        return False


_GLOBAL = EventStats()


def global_event_stats() -> EventStats:
    return _GLOBAL


def record(name: str, duration_s: float) -> None:
    _GLOBAL.record(name, duration_s)


def measure(name: str) -> _Timer:
    return _GLOBAL.measure(name)
