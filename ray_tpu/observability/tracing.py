"""Tracing: spans around task/actor submission and execution.

Reference analog: ``python/ray/util/tracing/tracing_helper.py`` —
opt-in OpenTelemetry spans wrapping ``submit_task``/``execute_task``
with trace context propagated inside the TaskSpec. Here spans are
in-process records exported as chrome://tracing events
(:meth:`Tracer.chrome_trace_events`), mergeable with the
``observability.state.timeline`` output.

Enable with ``tracing.enable()`` (or config flag ``tracing_enabled``);
``@trace_span("name")`` / ``with span("name"):`` for app code.
"""

from __future__ import annotations

import contextlib
import functools
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional

_local = threading.local()


@dataclass
class Span:
    name: str
    span_id: str
    parent_id: Optional[str]
    trace_id: str
    start_s: float
    end_s: Optional[float] = None
    attributes: Dict[str, Any] = field(default_factory=dict)

    @property
    def duration_ms(self) -> Optional[float]:
        if self.end_s is None:
            return None
        return (self.end_s - self.start_s) * 1000.0


class Tracer:
    """Process-wide span collector (bounded ring)."""

    def __init__(self, max_spans: int = 10_000):
        self.enabled = False
        self.max_spans = max_spans
        self._spans: List[Span] = []
        self._lock = threading.Lock()
        # Export plane (cluster telemetry): when a TelemetryExporter is
        # attached it flips export_enabled and drains finished spans on
        # each flush; bounded the same way so a stalled flusher can't
        # grow the process.
        self.export_enabled = False
        self._export: List[Span] = []

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def record(self, span: Span) -> None:
        with self._lock:
            self._spans.append(span)
            if len(self._spans) > self.max_spans:
                self._spans = self._spans[-self.max_spans:]
            if self.export_enabled:
                self._export.append(span)
                if len(self._export) > self.max_spans:
                    self._export = self._export[-self.max_spans:]

    def drain_export(self) -> List[Span]:
        """Finished spans recorded since the last drain (telemetry
        flush path; worker/daemon processes ship these to the head)."""
        with self._lock:
            out, self._export = self._export, []
        return out

    def spans(self, name_prefix: str = "") -> List[Span]:
        with self._lock:
            return [s for s in self._spans if s.name.startswith(name_prefix)]

    def clear(self) -> None:
        with self._lock:
            self._spans = []
            self._export = []  # cleared means cleared: nothing ships

    def chrome_trace_events(self) -> List[dict]:
        """Spans as chrome://tracing 'X' (complete) events, mergeable
        with ``observability.state.timeline`` output. The pid is THIS
        process's real pid so merged cluster timelines show one row per
        process (driver / workers / daemons)."""
        import os

        with self._lock:
            spans = list(self._spans)
        pid = os.getpid()
        return [span_chrome_event(s, pid) for s in spans
                if s.end_s is not None]


def span_chrome_event(s: Span, pid) -> dict:
    """One finished span as a chrome://tracing complete event; shared by
    the local dump and the telemetry export path (which stamps the
    ORIGIN process's pid before shipping)."""
    return {
        "name": s.name, "ph": "X", "cat": "span",
        "ts": s.start_s * 1e6,
        "dur": ((s.end_s or s.start_s) - s.start_s) * 1e6,
        "pid": pid, "tid": s.trace_id[:8],
        "args": {**s.attributes, "span_id": s.span_id,
                 "parent_id": s.parent_id},
    }


_tracer = Tracer()


def get_tracer() -> Tracer:
    return _tracer


def enable() -> None:
    _tracer.enable()


def disable() -> None:
    _tracer.disable()


def current_span() -> Optional[Span]:
    stack = getattr(_local, "stack", None)
    return stack[-1] if stack else None


@contextlib.contextmanager
def span(name: str, **attributes) -> Iterator[Optional[Span]]:
    """Context-managed span; nests under the thread's current span and
    continues a propagated remote context when present."""
    if not _tracer.enabled:
        yield None
        return
    parent = current_span()
    remote_ctx = getattr(_local, "remote_context", None)
    if parent is not None:
        trace_id, parent_id = parent.trace_id, parent.span_id
    elif remote_ctx is not None:
        trace_id, parent_id = remote_ctx
    else:
        trace_id, parent_id = uuid.uuid4().hex, None
    s = Span(name=name, span_id=uuid.uuid4().hex[:16], parent_id=parent_id,
             trace_id=trace_id, start_s=time.time(), attributes=attributes)
    stack = getattr(_local, "stack", None)
    if stack is None:
        stack = _local.stack = []
    stack.append(s)
    try:
        yield s
    finally:
        s.end_s = time.time()
        stack.pop()
        _tracer.record(s)


def trace_span(name: Optional[str] = None, **attributes):
    """Decorator form of :func:`span`."""

    def wrap(fn):
        span_name = name or fn.__qualname__

        @functools.wraps(fn)
        def inner(*args, **kwargs):
            with span(span_name, **attributes):
                return fn(*args, **kwargs)

        return inner

    return wrap


# -- remote propagation (reference: trace context in TaskSpec) --------------

def inject_context() -> Optional[tuple]:
    """Capture (trace_id, span_id) to ship inside a TaskSpec."""
    if not _tracer.enabled:
        return None
    s = current_span()
    if s is None:
        return None
    return (s.trace_id, s.span_id)


@contextlib.contextmanager
def remote_context(ctx: Optional[tuple]) -> Iterator[None]:
    """Worker-side: adopt the submitted task's trace context so execution
    spans join the submitter's trace."""
    if ctx is None:
        yield
        return
    _local.remote_context = tuple(ctx)
    try:
        yield
    finally:
        _local.remote_context = None
