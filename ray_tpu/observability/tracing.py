"""Tracing: spans around task/actor submission and execution.

Reference analog: ``python/ray/util/tracing/tracing_helper.py`` —
opt-in OpenTelemetry spans wrapping ``submit_task``/``execute_task``
with trace context propagated inside the TaskSpec. Here spans are
in-process records exported as chrome://tracing events
(:meth:`Tracer.chrome_trace_events`), mergeable with the
``observability.state.timeline`` output.

Enable with ``tracing.enable()`` (or config flag ``tracing_enabled``);
``@trace_span("name")`` / ``with span("name"):`` for app code.
"""

from __future__ import annotations

import contextvars
import functools
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

_local = threading.local()

# Async-safe request context: the serve replica's event loop interleaves
# many requests on ONE thread, so the thread-local span stack cannot
# carry a per-request trace context across awaits. A ContextVar is
# task-local under asyncio — each request's handler task sees only its
# own (trace_id, span_id).
_request_ctx: contextvars.ContextVar = contextvars.ContextVar(
    "rt_request_trace_ctx", default=None)


@dataclass
class Span:
    name: str
    span_id: str
    parent_id: Optional[str]
    trace_id: str
    start_s: float
    end_s: Optional[float] = None
    attributes: Dict[str, Any] = field(default_factory=dict)

    @property
    def duration_ms(self) -> Optional[float]:
        if self.end_s is None:
            return None
        return (self.end_s - self.start_s) * 1000.0


class Tracer:
    """Process-wide span collector (bounded ring)."""

    def __init__(self, max_spans: int = 10_000):
        self.enabled = False
        self.max_spans = max_spans
        # deque(maxlen): a full ring drops the oldest span in O(1).
        # The list version re-sliced 10k elements on EVERY record once
        # full — ~15us/span of steady-state trim cost on the task hot
        # path (caught by the ISSUE 20 overhead A/B).
        self._spans: deque = deque(maxlen=max_spans)
        self._lock = threading.Lock()
        # Export plane (cluster telemetry): when a TelemetryExporter is
        # attached it flips export_enabled and drains finished spans on
        # each flush; bounded the same way so a stalled flusher can't
        # grow the process.
        self.export_enabled = False
        self._export: deque = deque(maxlen=max_spans)
        # Head-side sink: the trace store installs itself here so
        # spans recorded IN the head process (proxy/router) reach the
        # same per-trace index the telemetry plane feeds with shipped
        # worker spans. Called outside the lock with the finished span.
        self.on_record: Optional[Callable[[Span], None]] = None

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def record(self, span: Span) -> None:
        dropped = 0
        with self._lock:
            if len(self._spans) == self.max_spans:
                dropped += 1  # deque drops the oldest on append
            self._spans.append(span)
            if self.export_enabled:
                if len(self._export) == self.max_spans:
                    dropped += 1
                self._export.append(span)
        if dropped:
            # The ring used to trim SILENTLY — a truncated trace looked
            # identical to a quiet process. Counted + warn-once, same
            # policy as every other bounded telemetry buffer.
            from . import telemetry

            telemetry.count_dropped("tracer", dropped)
        hook = self.on_record
        if hook is not None:
            try:
                hook(span)
            except Exception:  # noqa: BLE001 — sink must not break apps
                pass

    def drain_export(self) -> List[Span]:
        """Finished spans recorded since the last drain (telemetry
        flush path; worker/daemon processes ship these to the head)."""
        with self._lock:
            out = list(self._export)
            self._export.clear()
        return out

    def spans(self, name_prefix: str = "") -> List[Span]:
        with self._lock:
            return [s for s in self._spans if s.name.startswith(name_prefix)]

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self._export.clear()  # cleared means cleared: nothing ships

    def chrome_trace_events(self) -> List[dict]:
        """Spans as chrome://tracing 'X' (complete) events, mergeable
        with ``observability.state.timeline`` output. The pid is THIS
        process's real pid so merged cluster timelines show one row per
        process (driver / workers / daemons)."""
        import os

        with self._lock:
            spans = list(self._spans)
        pid = os.getpid()
        return [span_chrome_event(s, pid) for s in spans
                if s.end_s is not None]


def span_chrome_event(s: Span, pid) -> dict:
    """One finished span as a chrome://tracing complete event; shared by
    the local dump and the telemetry export path (which stamps the
    ORIGIN process's pid before shipping)."""
    return {
        "name": s.name, "ph": "X", "cat": "span",
        "ts": s.start_s * 1e6,
        "dur": ((s.end_s or s.start_s) - s.start_s) * 1e6,
        "pid": pid, "tid": s.trace_id[:8],
        # Full trace id travels in args (the tid row label is truncated
        # for chrome://tracing readability): the head trace store keys
        # its per-request index on it.
        "args": {**s.attributes, "span_id": s.span_id,
                 "parent_id": s.parent_id, "trace_id": s.trace_id},
    }


_tracer = Tracer()


def get_tracer() -> Tracer:
    return _tracer


def enable() -> None:
    _tracer.enable()


def disable() -> None:
    _tracer.disable()


def current_span() -> Optional[Span]:
    stack = getattr(_local, "stack", None)
    return stack[-1] if stack else None


class _NullSpanCtx:
    """Shared no-op CM for the tracing-disabled fast path."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpanCtx()


class _SpanCtx:
    """Hand-rolled context manager (the @contextmanager generator form
    costs ~3us/span of frame churn — this sits on the task hot path)."""

    __slots__ = ("_name", "_attributes", "_span")

    def __init__(self, name: str, attributes: Dict[str, Any]):
        self._name = name
        self._attributes = attributes
        self._span: Optional[Span] = None

    def __enter__(self) -> Span:
        # Parent resolution happens HERE, not in __init__: callers
        # build the span CM before entering remote_context (see
        # worker_main's `with trace_cm, span_cm:`), so resolving
        # eagerly would miss the adopted context.
        parent = current_span()
        # Same fallback chain as inject_context: thread-local remote
        # ctx (worker executing a task), then the asyncio request ctx
        # (serve replica handler) — so a span opened inside an async
        # handler joins the request's trace instead of minting a fresh
        # id.
        remote_ctx = (getattr(_local, "remote_context", None)
                      or _request_ctx.get())
        if parent is not None:
            trace_id, parent_id = parent.trace_id, parent.span_id
        elif remote_ctx is not None:
            trace_id, parent_id = remote_ctx
        else:
            trace_id, parent_id = os.urandom(16).hex(), None
        s = self._span = Span(
            name=self._name, span_id=os.urandom(8).hex(),
            parent_id=parent_id, trace_id=trace_id, start_s=time.time(),
            attributes=self._attributes)
        stack = getattr(_local, "stack", None)
        if stack is None:
            stack = _local.stack = []
        stack.append(s)
        return s

    def __exit__(self, *exc):
        s = self._span
        s.end_s = time.time()
        _local.stack.pop()
        _tracer.record(s)
        return False


def span(name: str, **attributes):
    """Context-managed span; nests under the thread's current span and
    continues a propagated remote context when present."""
    if not _tracer.enabled:
        return _NULL_SPAN
    return _SpanCtx(name, attributes)


def trace_span(name: Optional[str] = None, **attributes):
    """Decorator form of :func:`span`."""

    def wrap(fn):
        span_name = name or fn.__qualname__

        @functools.wraps(fn)
        def inner(*args, **kwargs):
            with span(span_name, **attributes):
                return fn(*args, **kwargs)

        return inner

    return wrap


# -- remote propagation (reference: trace context in TaskSpec) --------------

def inject_context() -> Optional[tuple]:
    """Capture (trace_id, span_id) to ship inside a TaskSpec.

    Resolution order mirrors :func:`span`: the thread's current span,
    then a remote context adopted from a submitted task, then the
    async request context set by the serve replica — so a nested
    ``.remote()`` inside an async handler still joins the request's
    trace even though no thread-local span is open across the await."""
    if not _tracer.enabled:
        return None
    s = current_span()
    if s is not None:
        return (s.trace_id, s.span_id)
    remote_ctx = getattr(_local, "remote_context", None)
    if remote_ctx is not None:
        return tuple(remote_ctx)
    req_ctx = _request_ctx.get()
    return tuple(req_ctx) if req_ctx is not None else None


class _RemoteCtx:
    """Class CM (not @contextmanager) — wraps every task execution."""

    __slots__ = ("_ctx",)

    def __init__(self, ctx: Optional[tuple]):
        self._ctx = ctx

    def __enter__(self):
        if self._ctx is not None:
            _local.remote_context = tuple(self._ctx)
        return None

    def __exit__(self, *exc):
        if self._ctx is not None:
            _local.remote_context = None
        return False


def remote_context(ctx: Optional[tuple]) -> "_RemoteCtx":
    """Worker-side: adopt the submitted task's trace context so execution
    spans join the submitter's trace."""
    return _RemoteCtx(ctx)


def set_request_context(ctx: Optional[tuple]):
    """Bind a request's (trace_id, span_id) to the CURRENT asyncio task
    (or thread, outside a loop). Returns a token for
    :func:`reset_request_context`. No-op (returns None) without a ctx."""
    if ctx is None:
        return None
    return _request_ctx.set(tuple(ctx))


def reset_request_context(token) -> None:
    if token is not None:
        _request_ctx.reset(token)


def get_request_context() -> Optional[tuple]:
    """The (trace_id, span_id) bound to this task/thread, if any."""
    return _request_ctx.get()


def new_span_id() -> str:
    return os.urandom(8).hex()


def record_span(name: str, trace_id: str,
                parent_id: Optional[str] = None,
                start_s: Optional[float] = None,
                end_s: Optional[float] = None,
                span_id: Optional[str] = None,
                **attributes) -> Optional[Span]:
    """Record a finished span with EXPLICIT identity and timestamps.

    The context-managed :func:`span` can't express two shapes this PR
    needs: spans synthesized after the fact from stage stamps (the LLM
    engine's timing breakdown) and spans whose lifetime crosses awaits
    on a shared event-loop thread (the proxy's root request span, the
    router's assign). Both know their trace id and wall-clock bounds up
    front; this records them without touching the thread-local stack."""
    if not _tracer.enabled:
        return None
    now = time.time()
    s = Span(name=name, span_id=span_id or new_span_id(),
             parent_id=parent_id, trace_id=trace_id,
             start_s=now if start_s is None else start_s,
             end_s=now if end_s is None else end_s,
             attributes=attributes)
    _tracer.record(s)
    return s
