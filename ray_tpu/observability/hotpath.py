"""Hot-path copy/op counters — the data-plane profile ledger.

Counts bulk-byte copies (and other per-op events) at the exact code
sites that touch object payloads, so benchmarks and tests can PIN the
copy count of a path instead of inferring it from throughput: a 10MB
``put`` must be exactly one ``copy.serialize.write_into`` and a shm
``get`` must be zero copies (the value deserializes as views into the
arena). Counting is a dict increment (~0.1us) per *operation*, not per
byte, so the counters stay on in production.

Process-local (each worker has its own table); the microbenchmark reads
the driver's table, which is where put/get copies happen.
"""

from __future__ import annotations

import threading
from typing import Dict

_lock = threading.Lock()
_counts: Dict[str, int] = {}
_bytes: Dict[str, int] = {}


def count(site: str, nbytes: int = 0, n: int = 1) -> None:
    """Record ``n`` events (optionally carrying ``nbytes`` payload bytes)
    at a dotted site name, e.g. ``copy.serialize.write_into``."""
    with _lock:
        _counts[site] = _counts.get(site, 0) + n
        if nbytes:
            _bytes[site] = _bytes.get(site, 0) + nbytes


def reset(prefix: str = "") -> None:
    with _lock:
        for table in (_counts, _bytes):
            for k in [k for k in table if k.startswith(prefix)]:
                del table[k]


def breakdown(prefix: str = "") -> Dict[str, int]:
    """Event counts for sites under ``prefix``."""
    with _lock:
        return {k: v for k, v in _counts.items() if k.startswith(prefix)}


def byte_breakdown(prefix: str = "") -> Dict[str, int]:
    with _lock:
        return {k: v for k, v in _bytes.items() if k.startswith(prefix)}
