"""State API: list/inspect cluster entities.

Reference analog: ``python/ray/experimental/state/api.py`` (list_tasks/
list_actors/list_objects/list_nodes/summarize) + the dashboard
``state_aggregator.py``. Queries run against the live head runtime.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional


def _head():
    from ..core.runtime import get_head_runtime

    rt = get_head_runtime()
    if rt is None:
        raise RuntimeError("state API requires an initialized head runtime")
    return rt


def list_nodes() -> List[Dict[str, Any]]:
    rt = _head()
    out = []
    for info in rt.gcs.nodes.values():
        node = rt.scheduler.get_node(info.node_id)
        out.append({
            "node_id": info.node_id.hex(),
            "alive": info.alive,
            "resources_total": dict(info.resources),
            "resources_available": (dict(node.ledger.available)
                                    if node else {}),
            "labels": dict(info.labels),
            "topology": dict(info.topology),
            "object_store": node.store.stats() if node else {},
        })
    return out


def _filter_get(row: Dict[str, Any], path: str) -> Any:
    """Resolve a (possibly dotted) filter key against a row:
    ``resources.CPU`` walks nested dicts; a plain key is a direct get."""
    cur: Any = row
    for part in path.split("."):
        if not isinstance(cur, dict):
            return None
        cur = cur.get(part)
    return cur


def _matches(row: Dict[str, Any],
             filters: Optional[Dict[str, str]]) -> bool:
    if not filters:
        return True
    return all(str(_filter_get(row, k)) == str(v)
               for k, v in filters.items())


def _copy_ts(ts: Optional[Dict[str, float]]) -> Optional[Dict[str, float]]:
    if ts is None:
        return None
    try:
        return dict(ts)
    except RuntimeError:  # stamp landed mid-copy; second pass settles
        return dict(ts)


def list_tasks(filters: Optional[Dict[str, str]] = None,
               limit: int = 1000) -> List[Dict[str, Any]]:
    """Task table rows. ``filters`` match on equality, including nested
    fields via dotted paths (``--filter resources.CPU=1.0``,
    ``state_ts.dispatched=None``); ``rt list tasks --state RUNNING`` is
    the CLI spelling of ``filters={"state": "RUNNING"}``. ``state_ts``
    carries the flight recorder's per-transition monotonic stamps."""
    rt = _head()
    out = []
    with rt._lock:
        records = list(rt._tasks.values())
    for rec in records[-limit:]:
        row = {
            "task_id": rec.spec.task_id.hex(),
            "name": rec.spec.name or rec.spec.method_name or "",
            "type": rec.spec.task_type.name,
            "state": rec.state,
            "resources": dict(rec.spec.resources),
            "node_id": rec.node.node_id.hex() if rec.node else None,
            "actor_id": (rec.spec.actor_id.hex()
                         if getattr(rec.spec, "actor_id", None) else None),
            "state_ts": _copy_ts(rec.state_ts),
        }
        if not _matches(row, filters):
            continue
        out.append(row)
    return out


def task_detail(task_id_hex: str) -> Dict[str, Any]:
    """Per-task drill-down (reference: dashboard task page): full spec
    metadata, placement, retries, args, and return-object states."""
    from ..core.ids import TaskID

    rt = _head()
    try:
        task_id = TaskID.from_hex(task_id_hex)
    except (ValueError, TypeError):
        return {"error": f"invalid task id {task_id_hex!r}"}
    with rt._lock:
        rec = rt._tasks.get(task_id)
        if rec is None:
            return {"error": f"unknown task {task_id_hex}"}
        # Snapshot mutable record fields under the ONE lock hold: the
        # retry path nulls node/worker concurrently (check-then-use
        # outside the lock races an AttributeError into a 500).
        spec = rec.spec
        node, worker = rec.node, rec.worker
        state, retries_left = rec.state, rec.retries_left
        returns = []
        for oid in spec.return_ids():
            entry = rt._objects.get(oid)
            returns.append({
                "object_id": oid.hex(),
                "status": entry.status if entry else None,
            })
    return {
        "task_id": spec.task_id.hex(),
        "name": spec.name or spec.method_name or "",
        "type": spec.task_type.name,
        "state": state,
        "resources": dict(spec.resources),
        "strategy": spec.strategy.kind,
        "node_id": node.node_id.hex() if node else None,
        "worker_id": worker.worker_id.hex() if worker else None,
        "actor_id": spec.actor_id.hex() if spec.actor_id else None,
        "retries_left": retries_left,
        "max_retries": spec.max_retries,
        "num_args": len(spec.arg_refs),
        "arg_object_ids": [o.hex() for o in spec.arg_refs],
        "returns": returns,
    }


def worker_log_tail(worker_id_prefix: str, n: int = 200
                    ) -> Dict[str, Any]:
    """Tail a worker's captured stdout/stderr over HTTP (reference:
    dashboard log proxying via the log directory)."""
    import os
    import re

    from ..core.log_monitor import worker_log_path

    # The prefix comes straight from the URL; reject anything that is
    # not a short hex worker id so it can never traverse out of the
    # log directory (e.g. ``..%2F..%2Fetc%2Fpasswd``).
    if not re.fullmatch(r"[0-9a-f]{1,32}", worker_id_prefix):
        return {"error": "invalid worker id prefix"}
    rt = _head()
    log_dir = getattr(rt, "session_log_dir", None)
    if not log_dir or not os.path.isdir(log_dir):
        return {"error": "worker log capture is not enabled"}
    out: Dict[str, Any] = {"worker": worker_id_prefix[:8]}
    for stream in ("out", "err"):
        path = worker_log_path(log_dir, worker_id_prefix, stream)
        if os.path.exists(path):
            # Bounded read: seek a window near the end instead of
            # loading a potentially huge capture file into memory.
            window = max(64 * 1024, n * 512)
            with open(path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - window))
                tail = f.read().decode(errors="replace")
            lines = tail.splitlines(keepends=True)
            if size > window and lines:
                lines = lines[1:]  # drop the partial first line
            out[stream] = lines[-n:]
        else:
            out[stream] = None
    return out


def list_actors(limit: int = 1000) -> List[Dict[str, Any]]:
    rt = _head()
    out = []
    for info in rt.gcs.list_actors()[-limit:]:
        out.append({
            "actor_id": info.actor_id.hex(),
            "name": info.name,
            "state": info.state,
            "node_id": info.node_id.hex() if info.node_id else None,
            "num_restarts": info.num_restarts,
            "death_cause": info.death_cause,
        })
    return out


def actor_detail(actor_id_hex: str) -> Dict[str, Any]:
    """Per-actor drill-down (reference: the dashboard actor page,
    ``dashboard/modules/actor``): actor table row + its tasks + the
    worker hosting it."""
    rt = _head()
    info = None
    for row in rt.gcs.list_actors():
        if row.actor_id.hex().startswith(actor_id_hex):
            info = row
            break
    if info is None:
        raise KeyError(f"no actor with id prefix {actor_id_hex!r}")
    tasks = [t for t in list_tasks()
             if t.get("actor_id") == info.actor_id.hex()]
    worker = None
    if info.worker_id:
        for w in list_workers():
            if w["worker_id"] == info.worker_id.hex():
                worker = w
                break
    return {
        "actor_id": info.actor_id.hex(),
        "name": info.name,
        "state": info.state,
        "node_id": info.node_id.hex() if info.node_id else None,
        "num_restarts": info.num_restarts,
        "max_restarts": info.max_restarts,
        "death_cause": info.death_cause,
        "tasks": tasks[-50:],
        "num_tasks": len(tasks),
        "worker": worker,
    }


def event_loop_stats(top: int = 50) -> List[Dict[str, Any]]:
    """Per-handler dispatch latency aggregates, aggregated across the
    head process AND every node-daemon process (reference:
    event_stats.h GetStatsString; each raylet's loop is per-process).
    Daemon rows carry a ``node`` column; unreachable daemons are
    skipped rather than failing the whole listing."""
    from .event_stats import global_event_stats

    rows = global_event_stats().snapshot(top)
    for r in rows:
        r["node"] = "head"
    try:
        rt = _head()
        nodes = [n for n in rt.scheduler.nodes()
                 if getattr(n, "event_stats", None) is not None
                 and getattr(n, "alive", True)]
        if nodes:
            # Concurrent fetches: one wedged daemon must cost ONE
            # timeout, not timeout x num_nodes, on a path the dashboard
            # polls every few seconds.
            from concurrent.futures import ThreadPoolExecutor

            ex = ThreadPoolExecutor(max_workers=min(8, len(nodes)))
            try:
                futs = {ex.submit(n.event_stats): n for n in nodes}
                for fut, node in futs.items():
                    try:
                        for r in fut.result(timeout=3.0):
                            r["node"] = node.node_id.hex()[:8]
                            rows.append(r)
                    except Exception:
                        continue
            finally:
                # wait=False: a hung daemon fetch must not stall this
                # (dashboard-polled) call at executor teardown either —
                # the stragglers die with their daemon threads.
                ex.shutdown(wait=False)
    except Exception:
        pass
    rows.sort(key=lambda r: -r["total_ms"])
    return rows[:top] if top else rows


def list_objects(limit: int = 1000) -> List[Dict[str, Any]]:
    rt = _head()
    out = []
    with rt._lock:
        items = list(rt._objects.items())
    for oid, entry in items[-limit:]:
        loc = entry.location
        out.append({
            "object_id": oid.hex(),
            "status": entry.status,
            "location": (loc[0] if loc else None),
            "node_id": (loc[1].hex() if loc and loc[0] == "shm" else None),
            "size": (loc[2] if loc and loc[0] == "shm" else None),
            "refcount": rt._refcounts.get(oid, 0),
        })
    return out


def list_jobs() -> List[Dict[str, Any]]:
    """Driver jobs from the GCS job table (reference: dashboard job
    module / GcsJobManager)."""
    rt = _head()
    out = []
    for info in rt.gcs.jobs.values():
        out.append({
            "job_id": info.job_id.hex(),
            "status": info.status,
            "entrypoint": info.entrypoint,
            "start_time": info.start_time,
            "end_time": info.end_time,
        })
    return out


def list_placement_groups() -> List[Dict[str, Any]]:
    rt = _head()
    return [
        {
            "pg_id": pg.id.hex(),
            "name": pg.name,
            "state": pg.state,
            "strategy": pg.strategy,
            "bundles": pg.bundles,
        }
        for pg in rt.gcs.placement_groups.values()
    ]


def list_workers() -> List[Dict[str, Any]]:
    rt = _head()
    out = []
    for node in rt.scheduler.nodes():
        for w in node.pool.all_workers():
            out.append({
                "worker_id": w.worker_id.hex(),
                "node_id": node.node_id.hex(),
                "state": w.state,
                "pid": w.process.pid,
                "alive": w.alive(),
            })
    return out


def summarize_tasks() -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for row in list_tasks():
        counts[row["state"]] = counts.get(row["state"], 0) + 1
    return counts


def cluster_status() -> str:
    """Human-readable summary (reference: `ray status` output shape)."""
    rt = _head()
    lines = ["======== Cluster status ========"]
    total = rt.cluster_resources()
    avail = rt.available_resources()
    lines.append("Resources")
    for k in sorted(total):
        lines.append(f"  {total.get(k, 0) - avail.get(k, 0):.1f}/"
                     f"{total[k]:.1f} {k}")
    nodes = list_nodes()
    lines.append(f"Nodes: {sum(1 for n in nodes if n['alive'])} alive, "
                 f"{sum(1 for n in nodes if not n['alive'])} dead")
    tasks = summarize_tasks()
    if tasks:
        lines.append("Tasks: " + ", ".join(
            f"{v} {k}" for k, v in sorted(tasks.items())))
    actors = list_actors()
    alive = sum(1 for a in actors if a["state"] == "ALIVE")
    lines.append(f"Actors: {alive} alive / {len(actors)} total")
    return "\n".join(lines)


# -- timeline (reference: ray.timeline -> chrome://tracing JSON) -------------

import threading as _threading
from collections import deque as _deque

# Bounded: an app recording spans forever must not grow the head process
# without limit; and the buffer is written from many threads (app code,
# telemetry absorb callers), so the lock is real, not a placeholder.
_EVENTS_MAX = 100_000
_events: _deque = _deque(maxlen=_EVENTS_MAX)
_events_lock = _threading.Lock()


def record_span(name: str, category: str, start_s: float, end_s: float,
                pid: int = 0, tid: int = 0, args: Optional[dict] = None):
    with _events_lock:
        if len(_events) >= _EVENTS_MAX:
            from . import telemetry

            telemetry.count_dropped("timeline")
        _events.append({
            "name": name, "cat": category, "ph": "X",
            "ts": start_s * 1e6, "dur": (end_s - start_s) * 1e6,
            "pid": pid, "tid": tid, "args": args or {},
        })


def timeline(filename: Optional[str] = None):
    """Dump ONE merged chrome://tracing stream (reference:
    _private/state.py:828 ``ray.timeline``): app-recorded spans
    (:func:`record_span`), this process's tracer spans, and every span
    shipped to the head by worker/daemon telemetry — each process on its
    own real pid row, named via ``process_name`` metadata events."""
    import json

    from . import telemetry
    from .tracing import get_tracer

    with _events_lock:
        data = list(_events)
    data.extend(get_tracer().chrome_trace_events())
    data.extend(telemetry.remote_chrome_events())
    data.extend(telemetry.chrome_process_metadata())
    if filename:
        with open(filename, "w") as f:
            json.dump(data, f)
        return filename
    return data
