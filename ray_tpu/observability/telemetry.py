"""Cluster-wide telemetry plane: worker/daemon -> head metric + span shipping.

Reference analog: ``_private/metrics_agent.py`` (per-node OpenCensus agent
aggregating worker metrics) + ``dashboard/modules/reporter/reporter_agent.py``
and the dashboard-head aggregation that makes cluster ``/metrics`` and
``ray timeline`` see every process, not just the head.

Two halves:

- :class:`TelemetryExporter` lives in every NON-HEAD process (task/actor
  workers, node daemons). Each flush it snapshots the process-local
  metrics registry, computes DELTAS against the previous flush (counters
  and histograms subtract; gauges ship current values when changed),
  drains finished spans from the local tracer, and returns one compact
  payload. Workers ship it over the existing worker pipe as a
  ``("telemetry", payload)`` message; daemons over their control
  connection. Flush period is ``metrics_report_interval_ms``; a final
  flush runs at clean worker exit so short-lived workers aren't lost.

- :func:`absorb` runs on the head: merges metric deltas into the head
  registry with ``node``/``worker`` tags added, and files the shipped
  spans (already chrome events, stamped with the origin pid) into a
  bounded buffer that ``observability.state.timeline`` merges — one
  Chrome trace with a real pid row per process.

Everything is gated on the ``telemetry_enabled`` config flag (default
on); ``RT_TELEMETRY_ENABLED=0`` turns the whole plane off for overhead
A/B runs (see BASELINE.md "Telemetry overhead").
"""

from __future__ import annotations

import logging
import os
import threading
from collections import deque
from typing import Any, Dict, List, Optional

from .metrics import (
    Counter,
    Gauge,
    Histogram,
    core_metrics,
    get_or_create,
    registry,
)
from .tracing import get_tracer, span_chrome_event

# Spans shipped from remote processes, already in chrome-event form with
# their origin pid. Bounded: a chatty cluster must not grow the head.
_REMOTE_EVENTS_MAX = 50_000
# Backstop for absorbed metric SERIES too: worker churn mints a fresh
# worker-id tag per short-lived worker, and each absorbed (node, worker)
# tag set is a permanent series in the head registry. Beyond this many
# series per metric, absorb updates existing series but creates no new
# ones (same philosophy as the bounded span buffers).
_ABSORB_SERIES_MAX = 10_000
_remote_events: deque = deque(maxlen=_REMOTE_EVENTS_MAX)
# pid -> human name ("worker ab12cd34" / "daemon ef567890") for the
# chrome trace process_name metadata rows. Bounded like the other
# buffers: worker churn mints a fresh pid per short-lived worker.
_PROC_NAMES_MAX = 4096
_proc_names: Dict[int, str] = {}
_absorb_lock = threading.Lock()

# Flight-recorder exec deltas buffered per exporter between flushes.
_FLIGHT_BUF_MAX = 8_192

_logger = logging.getLogger(__name__)
_dropped_counter = None
_warned_buffers: set = set()
_dropped_lock = threading.Lock()


def count_dropped(buffer: str, n: int = 1) -> None:
    """Every bounded telemetry buffer drops SILENTLY when full — which
    makes a truncated trace indistinguishable from a quiet cluster.
    Count each drop in ``rt_telemetry_dropped_total{buffer}`` and log
    one warning per buffer per process so truncation is detectable."""
    global _dropped_counter
    with _dropped_lock:
        if _dropped_counter is None:
            _dropped_counter = get_or_create(
                Counter, "rt_telemetry_dropped_total",
                "Telemetry events dropped by full bounded buffers",
                ("buffer",))
    _dropped_counter.inc_key((("buffer", buffer),), float(n))
    if buffer not in _warned_buffers:
        with _dropped_lock:
            if buffer in _warned_buffers:
                return
            _warned_buffers.add(buffer)
        _logger.warning(
            "telemetry buffer %r full: dropping events (counted in "
            "rt_telemetry_dropped_total; this warns once per process)",
            buffer)


class TelemetryExporter:
    """Per-process delta snapshotter (worker / daemon side)."""

    def __init__(self, node: Optional[str] = None,
                 worker: Optional[str] = None,
                 proc: Optional[str] = None):
        self.node = node
        self.worker = worker
        self.proc = proc
        self.pid = os.getpid()
        self._last: Dict[str, tuple] = {}
        # Serializes collect(): the worker's exit flush runs on the task
        # loop thread while the periodic flusher thread may be mid-cycle;
        # an unsynchronized read-modify-write of _last would ship the
        # same delta twice and double-count on the head.
        self._collect_lock = threading.Lock()
        # Flight-recorder exec durations, drained into payload["flight"]
        # each flush. deque(maxlen) drops oldest silently, so overflow
        # is counted explicitly before append.
        self._flight: deque = deque(maxlen=_FLIGHT_BUF_MAX)
        # Spans recorded from here on are kept for export too.
        get_tracer().export_enabled = True

    def record_flight(self, task_id_hex: str, exec_s: float) -> None:
        """Buffer one task's measured execution wall time (a DURATION —
        monotonic timestamps don't compare across processes) for the
        head's flight recorder to join with its own stage stamps."""
        if len(self._flight) >= _FLIGHT_BUF_MAX:
            count_dropped("flight_exporter")
        self._flight.append((task_id_hex, exec_s))

    def collect(self) -> Optional[dict]:
        """One flush: metric deltas + newly finished spans, or None when
        nothing moved (so idle processes cost zero pipe traffic)."""
        with self._collect_lock:
            return self._collect_locked()

    def _collect_locked(self) -> Optional[dict]:
        metrics_out: List[tuple] = []
        for name, (kind, data) in registry.collect_all().items():
            _prev_kind, prev = self._last.get(name, (kind, {}))
            deltas: Dict[tuple, Any] = {}
            if kind == "gauge":
                if data != prev:
                    deltas = dict(data)
            elif kind == "counter":
                for key, val in data.items():
                    d = val - prev.get(key, 0.0)
                    if d:
                        deltas[key] = d
            else:  # histogram
                for key, h in data.items():
                    ph = prev.get(key)
                    if ph is None:
                        d = h
                    else:
                        d = {"buckets": [a - b for a, b in
                                         zip(h["buckets"], ph["buckets"])],
                             "sum": h["sum"] - ph["sum"],
                             "count": h["count"] - ph["count"]}
                    if d["count"]:
                        deltas[key] = d
            self._last[name] = (kind, data)
            if deltas:
                boundaries = None
                if kind == "histogram":
                    metric = registry.get(name)
                    boundaries = (list(metric.boundaries)
                                  if metric is not None else None)
                metrics_out.append((name, kind, boundaries, deltas))
        spans = [span_chrome_event(s, self.pid)
                 for s in get_tracer().drain_export()
                 if s.end_s is not None]
        flight = []
        while self._flight:
            flight.append(self._flight.popleft())
        if not metrics_out and not spans and not flight:
            return None
        payload = {
            "node": self.node, "worker": self.worker,
            "pid": self.pid, "proc": self.proc,
            "metrics": metrics_out, "spans": spans,
        }
        if flight:
            payload["flight"] = flight
        return payload


def absorb(payload: dict) -> None:
    """Head side: merge one telemetry payload into the head registry
    and the remote-span buffer.

    Counters and histograms are ADDITIVE: ``node``/``worker`` tags are
    added so concurrent processes' deltas land in distinct series.
    Gauges keep the PRODUCER's tags unchanged — a gauge's identity is
    whatever tag set its owner chose (e.g. the serve controller's
    ``rt_serve_replicas{deployment}``, the daemon's node-tagged store
    gauge), so a restarted producer overwrites its old value instead of
    leaving a stale per-worker series that consumers would double-sum."""
    if not isinstance(payload, dict):
        return
    extra = {}
    if payload.get("node"):
        extra["node"] = payload["node"]
    if payload.get("worker"):
        extra["worker"] = payload["worker"]
    with _absorb_lock:
        for name, kind, boundaries, data in payload.get("metrics", ()):
            # get_or_create: atomic vs the lazy factories (core/serve)
            # racing to the same name from other threads.
            if kind == "counter":
                metric = get_or_create(Counter, name)
            elif kind == "gauge":
                metric = get_or_create(Gauge, name)
            else:
                metric = get_or_create(Histogram, name,
                                       boundaries=boundaries or ())
            capped = metric.series_count() >= _ABSORB_SERIES_MAX
            for tags_key, value in data.items():
                tags = dict(tags_key)
                if kind != "gauge":
                    tags.update(extra)
                try:
                    if capped and not metric.has_series(
                            metric._tags_key(tags)):
                        count_dropped("absorb_series")
                        continue
                    if kind == "counter" and isinstance(metric, Counter):
                        metric.inc(value, tags=tags)
                    elif kind == "gauge" and isinstance(metric, Gauge):
                        metric.set(value, tags=tags)
                    elif kind == "histogram" and isinstance(metric,
                                                            Histogram):
                        metric.merge_delta(value, tags=tags)
                except Exception:  # noqa: BLE001 — one bad series max
                    continue
        pid = payload.get("pid")
        if pid is not None:
            if payload.get("proc"):
                _proc_names[int(pid)] = payload["proc"]
                while len(_proc_names) > _PROC_NAMES_MAX:
                    _proc_names.pop(next(iter(_proc_names)))  # oldest
                    count_dropped("proc_names")
            for event in payload.get("spans", ()):
                if len(_remote_events) >= _REMOTE_EVENTS_MAX:
                    count_dropped("remote_events")
                _remote_events.append(event)
    if payload.get("spans"):
        # Same absorb stream feeds the per-request trace store (outside
        # _absorb_lock: the store has its own lock and the LRU/sampling
        # work must not serialize the metric merge path).
        from . import tracestore

        tracestore.flush_local()  # interleave buffered head-local spans
        for event in payload["spans"]:
            tracestore.ingest_event(event)
    flight_events = payload.get("flight")
    if flight_events:
        from . import flight as flight_mod

        flight_mod.ingest(flight_events)


def remote_chrome_events() -> List[dict]:
    with _absorb_lock:
        return list(_remote_events)


def chrome_process_metadata() -> List[dict]:
    """chrome://tracing ``process_name`` metadata rows: the driver plus
    every remote process that has shipped telemetry."""
    events = [{"name": "process_name", "ph": "M", "pid": os.getpid(),
               "args": {"name": "driver"}}]
    with _absorb_lock:
        names = dict(_proc_names)
    for pid, name in sorted(names.items()):
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "args": {"name": name}})
    return events


def clear() -> None:
    """Drop absorbed remote state (test isolation)."""
    with _absorb_lock:
        _remote_events.clear()
        _proc_names.clear()
    clear_history()


# -- metrics history ring (server-side sparklines / `rt top`) ---------------
#
# The head registry is a point-in-time surface: a dashboard reload (or a
# freshly attached `rt top`) used to start its sparklines from nothing
# because history lived client-side (dashboard.py JS). Here the head
# snapshots the interesting rt_* series into a bounded time-series ring
# every scrape interval; /api/history serves it and `rt top` renders it.

_HISTORY_MAX = 720  # samples; 12 min at the default 1s interval
_history: deque = deque(maxlen=_HISTORY_MAX)
_history_lock = threading.Lock()
_history_prev: Dict[str, Any] = {}


def _sum_series(snap: Dict[str, tuple], name: str) -> float:
    entry = snap.get(name)
    if entry is None:
        return 0.0
    _kind, data = entry
    try:
        return float(sum(data.values()))
    except TypeError:
        return 0.0


def _agg_hist(snap: Dict[str, tuple], name: str) -> Optional[dict]:
    entry = snap.get(name)
    if entry is None or entry[0] != "histogram":
        return None
    buckets: Optional[List[float]] = None
    total_sum, total_count = 0.0, 0
    for h in entry[1].values():
        b = h.get("buckets") or []
        if buckets is None:
            buckets = [0.0] * len(b)
        if len(b) == len(buckets):
            for i, c in enumerate(b):
                buckets[i] += c
        total_sum += float(h.get("sum", 0.0))
        total_count += int(h.get("count", 0))
    if buckets is None:
        return None
    return {"buckets": buckets, "sum": total_sum, "count": total_count}


def _hist_window_pct(name: str, agg: Optional[dict],
                     prev: Optional[dict], q: float) -> float:
    """Percentile estimate over the observations that arrived since the
    previous sample (bucket deltas, linear interpolation within the
    winning bucket; the +Inf bucket answers with its lower bound)."""
    if agg is None:
        return 0.0
    metric = registry.get(name)
    boundaries = list(metric.boundaries) if metric is not None else []
    cur = agg["buckets"]
    old = (prev or {}).get("buckets") or [0.0] * len(cur)
    if len(old) != len(cur):
        old = [0.0] * len(cur)
    deltas = [max(0.0, a - b) for a, b in zip(cur, old)]
    total = sum(deltas)
    if total <= 0:
        return -1.0  # nothing new this window; caller carries forward
    target = q * total
    seen = 0.0
    for i, d in enumerate(deltas):
        if seen + d >= target and d > 0:
            lo = boundaries[i - 1] if i > 0 and i - 1 < len(boundaries) \
                else 0.0
            hi = boundaries[i] if i < len(boundaries) else lo
            frac = (target - seen) / d
            return lo + (hi - lo) * frac
        seen += d
    return boundaries[-1] if boundaries else 0.0


def record_history_sample(now: Optional[float] = None) -> Optional[dict]:
    """Snapshot one history sample from the head registry (plus host
    load/mem). Called by the dashboard's sampler thread every scrape
    interval; safe to call ad hoc (tests, `rt top --local`)."""
    import time as _time

    from ..core.config import config as _config

    if not _config().telemetry_enabled:
        return None
    now = _time.time() if now is None else now
    snap = registry.collect_all()
    ttft = _agg_hist(snap, "rt_llm_ttft_seconds")
    itl = _agg_hist(snap, "rt_llm_decode_per_token_seconds")
    with _history_lock:
        prev = dict(_history_prev)
        dt = max(1e-6, now - prev["t"]) if prev else None

        def rate(name: str, total: float) -> float:
            if not prev or dt is None:
                return 0.0
            return max(0.0, total - prev.get(name, 0.0)) / dt

        tasks_total = _sum_series(snap, "rt_tasks_finished")
        tokens_total = _sum_series(snap, "rt_llm_tokens_generated_total")
        last = _history[-1] if _history else {}

        def pct(name: str, agg, prev_key: str, q: float,
                carry_key: str) -> float:
            v = _hist_window_pct(name, agg, prev.get(prev_key), q)
            if v < 0:  # quiet window: carry the last estimate forward
                return float(last.get(carry_key, 0.0))
            return round(v * 1e3, 3)

        sample = {
            "ts": round(now, 3),
            "tasks_per_s": round(rate("tasks_total", tasks_total), 3),
            "tokens_per_s": round(rate("tokens_total", tokens_total), 3),
            "queue_depth": _sum_series(snap, "rt_serve_queue_depth"),
            "replicas": _sum_series(snap, "rt_serve_replicas"),
            "workers": _sum_series(snap, "rt_workers_alive"),
            "pages_used": _sum_series(snap, "rt_llm_pages_used"),
            "pages_free": _sum_series(snap, "rt_llm_pages_free"),
            "ttft_p50_ms": pct("rt_llm_ttft_seconds", ttft, "ttft",
                               0.5, "ttft_p50_ms"),
            "ttft_p99_ms": pct("rt_llm_ttft_seconds", ttft, "ttft",
                               0.99, "ttft_p99_ms"),
            "itl_p50_ms": pct("rt_llm_decode_per_token_seconds", itl,
                              "itl", 0.5, "itl_p50_ms"),
            "itl_p99_ms": pct("rt_llm_decode_per_token_seconds", itl,
                              "itl", 0.99, "itl_p99_ms"),
        }
        try:
            with open("/proc/loadavg") as f:
                sample["load_1m"] = float(f.read().split()[0])
        except Exception:  # noqa: BLE001 — non-Linux host
            sample["load_1m"] = 0.0
        try:
            mem = {}
            with open("/proc/meminfo") as f:
                for line in f:
                    k, _, v = line.partition(":")
                    mem[k] = v.strip()
            total_kb = int(mem["MemTotal"].split()[0])
            avail_kb = int(mem["MemAvailable"].split()[0])
            sample["mem_used_frac"] = round(1 - avail_kb / total_kb, 4)
        except Exception:  # noqa: BLE001
            sample["mem_used_frac"] = 0.0
        _history.append(sample)
        _history_prev.clear()
        _history_prev.update({
            "t": now, "tasks_total": tasks_total,
            "tokens_total": tokens_total, "ttft": ttft, "itl": itl,
        })
    return sample


def history(limit: int = _HISTORY_MAX) -> Dict[str, Any]:
    """The ring, newest last — the ``/api/history`` body."""
    from ..core.config import config as _config

    with _history_lock:
        samples = list(_history)[-limit:]
    return {
        "interval_ms": _config().metrics_report_interval_ms,
        "samples": samples,
    }


def clear_history() -> None:
    with _history_lock:
        _history.clear()
        _history_prev.clear()


def refresh_cluster_gauges() -> None:
    """Sample head-visible cluster gauges into ``core_metrics()``:
    actors/workers alive from the GCS/scheduler tables and per-node
    object-store bytes for in-process stores (daemon-backed nodes report
    their own store through their exporter). Called on every ``/metrics``
    scrape so the gauges can't go stale or bitrot."""
    from ..core.config import config
    from ..core.gcs import ActorState
    from ..core.runtime import get_head_runtime

    rt = get_head_runtime()
    if rt is None or not config().telemetry_enabled:
        return
    m = core_metrics()
    try:
        alive = sum(1 for a in rt.gcs.list_actors()
                    if a.state == ActorState.ALIVE)
        m["actors_alive"].set(float(alive))
    except Exception:  # noqa: BLE001 — scrape must never 500
        pass
    workers = 0
    for node in rt.scheduler.nodes():
        try:
            workers += sum(1 for w in node.pool.all_workers() if w.alive())
        except Exception:  # noqa: BLE001
            continue
        if getattr(node, "is_remote", False):
            continue  # daemon reports its own store over its conn
        try:
            used = node.store.stats().get("used_bytes", 0)
            m["object_store_bytes"].set(
                float(used), tags={"node": node.node_id.hex()[:8]})
        except Exception:  # noqa: BLE001
            pass
    m["workers_alive"].set(float(workers))
    mem_stats = getattr(rt.memory_store, "stats", None)
    if mem_stats is not None:
        try:
            m["object_store_bytes"].set(
                float(mem_stats().get("used_bytes", 0)),
                tags={"node": "driver-memory"})
        except Exception:  # noqa: BLE001
            pass
