"""Cluster-wide telemetry plane: worker/daemon -> head metric + span shipping.

Reference analog: ``_private/metrics_agent.py`` (per-node OpenCensus agent
aggregating worker metrics) + ``dashboard/modules/reporter/reporter_agent.py``
and the dashboard-head aggregation that makes cluster ``/metrics`` and
``ray timeline`` see every process, not just the head.

Two halves:

- :class:`TelemetryExporter` lives in every NON-HEAD process (task/actor
  workers, node daemons). Each flush it snapshots the process-local
  metrics registry, computes DELTAS against the previous flush (counters
  and histograms subtract; gauges ship current values when changed),
  drains finished spans from the local tracer, and returns one compact
  payload. Workers ship it over the existing worker pipe as a
  ``("telemetry", payload)`` message; daemons over their control
  connection. Flush period is ``metrics_report_interval_ms``; a final
  flush runs at clean worker exit so short-lived workers aren't lost.

- :func:`absorb` runs on the head: merges metric deltas into the head
  registry with ``node``/``worker`` tags added, and files the shipped
  spans (already chrome events, stamped with the origin pid) into a
  bounded buffer that ``observability.state.timeline`` merges — one
  Chrome trace with a real pid row per process.

Everything is gated on the ``telemetry_enabled`` config flag (default
on); ``RT_TELEMETRY_ENABLED=0`` turns the whole plane off for overhead
A/B runs (see BASELINE.md "Telemetry overhead").
"""

from __future__ import annotations

import logging
import os
import threading
from collections import deque
from typing import Any, Dict, List, Optional

from .metrics import (
    Counter,
    Gauge,
    Histogram,
    core_metrics,
    get_or_create,
    registry,
)
from .tracing import get_tracer, span_chrome_event

# Spans shipped from remote processes, already in chrome-event form with
# their origin pid. Bounded: a chatty cluster must not grow the head.
_REMOTE_EVENTS_MAX = 50_000
# Backstop for absorbed metric SERIES too: worker churn mints a fresh
# worker-id tag per short-lived worker, and each absorbed (node, worker)
# tag set is a permanent series in the head registry. Beyond this many
# series per metric, absorb updates existing series but creates no new
# ones (same philosophy as the bounded span buffers).
_ABSORB_SERIES_MAX = 10_000
_remote_events: deque = deque(maxlen=_REMOTE_EVENTS_MAX)
# pid -> human name ("worker ab12cd34" / "daemon ef567890") for the
# chrome trace process_name metadata rows. Bounded like the other
# buffers: worker churn mints a fresh pid per short-lived worker.
_PROC_NAMES_MAX = 4096
_proc_names: Dict[int, str] = {}
_absorb_lock = threading.Lock()

# Flight-recorder exec deltas buffered per exporter between flushes.
_FLIGHT_BUF_MAX = 8_192

_logger = logging.getLogger(__name__)
_dropped_counter = None
_warned_buffers: set = set()
_dropped_lock = threading.Lock()


def count_dropped(buffer: str, n: int = 1) -> None:
    """Every bounded telemetry buffer drops SILENTLY when full — which
    makes a truncated trace indistinguishable from a quiet cluster.
    Count each drop in ``rt_telemetry_dropped_total{buffer}`` and log
    one warning per buffer per process so truncation is detectable."""
    global _dropped_counter
    with _dropped_lock:
        if _dropped_counter is None:
            _dropped_counter = get_or_create(
                Counter, "rt_telemetry_dropped_total",
                "Telemetry events dropped by full bounded buffers",
                ("buffer",))
    _dropped_counter.inc_key((("buffer", buffer),), float(n))
    if buffer not in _warned_buffers:
        with _dropped_lock:
            if buffer in _warned_buffers:
                return
            _warned_buffers.add(buffer)
        _logger.warning(
            "telemetry buffer %r full: dropping events (counted in "
            "rt_telemetry_dropped_total; this warns once per process)",
            buffer)


class TelemetryExporter:
    """Per-process delta snapshotter (worker / daemon side)."""

    def __init__(self, node: Optional[str] = None,
                 worker: Optional[str] = None,
                 proc: Optional[str] = None):
        self.node = node
        self.worker = worker
        self.proc = proc
        self.pid = os.getpid()
        self._last: Dict[str, tuple] = {}
        # Serializes collect(): the worker's exit flush runs on the task
        # loop thread while the periodic flusher thread may be mid-cycle;
        # an unsynchronized read-modify-write of _last would ship the
        # same delta twice and double-count on the head.
        self._collect_lock = threading.Lock()
        # Flight-recorder exec durations, drained into payload["flight"]
        # each flush. deque(maxlen) drops oldest silently, so overflow
        # is counted explicitly before append.
        self._flight: deque = deque(maxlen=_FLIGHT_BUF_MAX)
        # Spans recorded from here on are kept for export too.
        get_tracer().export_enabled = True

    def record_flight(self, task_id_hex: str, exec_s: float) -> None:
        """Buffer one task's measured execution wall time (a DURATION —
        monotonic timestamps don't compare across processes) for the
        head's flight recorder to join with its own stage stamps."""
        if len(self._flight) >= _FLIGHT_BUF_MAX:
            count_dropped("flight_exporter")
        self._flight.append((task_id_hex, exec_s))

    def collect(self) -> Optional[dict]:
        """One flush: metric deltas + newly finished spans, or None when
        nothing moved (so idle processes cost zero pipe traffic)."""
        with self._collect_lock:
            return self._collect_locked()

    def _collect_locked(self) -> Optional[dict]:
        metrics_out: List[tuple] = []
        for name, (kind, data) in registry.collect_all().items():
            _prev_kind, prev = self._last.get(name, (kind, {}))
            deltas: Dict[tuple, Any] = {}
            if kind == "gauge":
                if data != prev:
                    deltas = dict(data)
            elif kind == "counter":
                for key, val in data.items():
                    d = val - prev.get(key, 0.0)
                    if d:
                        deltas[key] = d
            else:  # histogram
                for key, h in data.items():
                    ph = prev.get(key)
                    if ph is None:
                        d = h
                    else:
                        d = {"buckets": [a - b for a, b in
                                         zip(h["buckets"], ph["buckets"])],
                             "sum": h["sum"] - ph["sum"],
                             "count": h["count"] - ph["count"]}
                    if d["count"]:
                        deltas[key] = d
            self._last[name] = (kind, data)
            if deltas:
                boundaries = None
                if kind == "histogram":
                    metric = registry.get(name)
                    boundaries = (list(metric.boundaries)
                                  if metric is not None else None)
                metrics_out.append((name, kind, boundaries, deltas))
        spans = [span_chrome_event(s, self.pid)
                 for s in get_tracer().drain_export()
                 if s.end_s is not None]
        flight = []
        while self._flight:
            flight.append(self._flight.popleft())
        if not metrics_out and not spans and not flight:
            return None
        payload = {
            "node": self.node, "worker": self.worker,
            "pid": self.pid, "proc": self.proc,
            "metrics": metrics_out, "spans": spans,
        }
        if flight:
            payload["flight"] = flight
        return payload


def absorb(payload: dict) -> None:
    """Head side: merge one telemetry payload into the head registry
    and the remote-span buffer.

    Counters and histograms are ADDITIVE: ``node``/``worker`` tags are
    added so concurrent processes' deltas land in distinct series.
    Gauges keep the PRODUCER's tags unchanged — a gauge's identity is
    whatever tag set its owner chose (e.g. the serve controller's
    ``rt_serve_replicas{deployment}``, the daemon's node-tagged store
    gauge), so a restarted producer overwrites its old value instead of
    leaving a stale per-worker series that consumers would double-sum."""
    if not isinstance(payload, dict):
        return
    extra = {}
    if payload.get("node"):
        extra["node"] = payload["node"]
    if payload.get("worker"):
        extra["worker"] = payload["worker"]
    with _absorb_lock:
        for name, kind, boundaries, data in payload.get("metrics", ()):
            # get_or_create: atomic vs the lazy factories (core/serve)
            # racing to the same name from other threads.
            if kind == "counter":
                metric = get_or_create(Counter, name)
            elif kind == "gauge":
                metric = get_or_create(Gauge, name)
            else:
                metric = get_or_create(Histogram, name,
                                       boundaries=boundaries or ())
            capped = metric.series_count() >= _ABSORB_SERIES_MAX
            for tags_key, value in data.items():
                tags = dict(tags_key)
                if kind != "gauge":
                    tags.update(extra)
                try:
                    if capped and not metric.has_series(
                            metric._tags_key(tags)):
                        count_dropped("absorb_series")
                        continue
                    if kind == "counter" and isinstance(metric, Counter):
                        metric.inc(value, tags=tags)
                    elif kind == "gauge" and isinstance(metric, Gauge):
                        metric.set(value, tags=tags)
                    elif kind == "histogram" and isinstance(metric,
                                                            Histogram):
                        metric.merge_delta(value, tags=tags)
                except Exception:  # noqa: BLE001 — one bad series max
                    continue
        pid = payload.get("pid")
        if pid is not None:
            if payload.get("proc"):
                _proc_names[int(pid)] = payload["proc"]
                while len(_proc_names) > _PROC_NAMES_MAX:
                    _proc_names.pop(next(iter(_proc_names)))  # oldest
                    count_dropped("proc_names")
            for event in payload.get("spans", ()):
                if len(_remote_events) >= _REMOTE_EVENTS_MAX:
                    count_dropped("remote_events")
                _remote_events.append(event)
    flight_events = payload.get("flight")
    if flight_events:
        from . import flight as flight_mod

        flight_mod.ingest(flight_events)


def remote_chrome_events() -> List[dict]:
    with _absorb_lock:
        return list(_remote_events)


def chrome_process_metadata() -> List[dict]:
    """chrome://tracing ``process_name`` metadata rows: the driver plus
    every remote process that has shipped telemetry."""
    events = [{"name": "process_name", "ph": "M", "pid": os.getpid(),
               "args": {"name": "driver"}}]
    with _absorb_lock:
        names = dict(_proc_names)
    for pid, name in sorted(names.items()):
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "args": {"name": name}})
    return events


def clear() -> None:
    """Drop absorbed remote state (test isolation)."""
    with _absorb_lock:
        _remote_events.clear()
        _proc_names.clear()


def refresh_cluster_gauges() -> None:
    """Sample head-visible cluster gauges into ``core_metrics()``:
    actors/workers alive from the GCS/scheduler tables and per-node
    object-store bytes for in-process stores (daemon-backed nodes report
    their own store through their exporter). Called on every ``/metrics``
    scrape so the gauges can't go stale or bitrot."""
    from ..core.config import config
    from ..core.gcs import ActorState
    from ..core.runtime import get_head_runtime

    rt = get_head_runtime()
    if rt is None or not config().telemetry_enabled:
        return
    m = core_metrics()
    try:
        alive = sum(1 for a in rt.gcs.list_actors()
                    if a.state == ActorState.ALIVE)
        m["actors_alive"].set(float(alive))
    except Exception:  # noqa: BLE001 — scrape must never 500
        pass
    workers = 0
    for node in rt.scheduler.nodes():
        try:
            workers += sum(1 for w in node.pool.all_workers() if w.alive())
        except Exception:  # noqa: BLE001
            continue
        if getattr(node, "is_remote", False):
            continue  # daemon reports its own store over its conn
        try:
            used = node.store.stats().get("used_bytes", 0)
            m["object_store_bytes"].set(
                float(used), tags={"node": node.node_id.hex()[:8]})
        except Exception:  # noqa: BLE001
            pass
    m["workers_alive"].set(float(workers))
    mem_stats = getattr(rt.memory_store, "stats", None)
    if mem_stats is not None:
        try:
            m["object_store_bytes"].set(
                float(mem_stats().get("used_bytes", 0)),
                tags={"node": "driver-memory"})
        except Exception:  # noqa: BLE001
            pass
