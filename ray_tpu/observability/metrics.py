"""Application + runtime metrics: Counter/Gauge/Histogram with a registry.

Reference analog: ``python/ray/util/metrics.py`` (user-facing API) +
``src/ray/stats/metric_defs.cc`` (runtime metric definitions) +
``_private/metrics_agent.py`` (aggregation + Prometheus text export).
Single-process registry here; the dashboard module serves the Prometheus
text format over HTTP.
"""

from __future__ import annotations

import bisect
import re
import threading
import time
from collections import defaultdict
from typing import Dict, List, Optional, Sequence, Tuple

_TagKey = Tuple[Tuple[str, str], ...]

# Prometheus line-format rules: metric names admit [a-zA-Z0-9_:], label
# names only [a-zA-Z0-9_]; label VALUES are free-form but must escape
# backslash, double-quote and newline.
_NAME_BAD = re.compile(r"[^a-zA-Z0-9_:]")
_LABEL_BAD = re.compile(r"[^a-zA-Z0-9_]")


def _sanitize_name(name: str) -> str:
    safe = _NAME_BAD.sub("_", name)
    if not safe or safe[0].isdigit():
        safe = "_" + safe
    return safe


def _sanitize_label(name: str) -> str:
    safe = _LABEL_BAD.sub("_", name)
    if not safe or safe[0].isdigit():
        safe = "_" + safe
    return safe


def _escape_label_value(value) -> str:
    return (str(value).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _fmt_num(v) -> str:
    f = float(v)
    if f != f:
        return "NaN"
    if f in (float("inf"), float("-inf")):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _fmt_labels(pairs) -> str:
    body = ",".join(f'{_sanitize_label(k)}="{_escape_label_value(v)}"'
                    for k, v in pairs)
    return "{" + body + "}" if body else ""


class Metric:
    def __init__(self, name: str, description: str = "",
                 tag_keys: Sequence[str] = ()):
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._lock = threading.Lock()
        registry.register(self)

    def _tags_key(self, tags: Optional[Dict[str, str]]) -> _TagKey:
        if not tags:
            return ()
        return tuple(sorted(tags.items()))

    def _series(self) -> dict:  # overridden where the store differs
        return self._values  # type: ignore[attr-defined]

    def series_count(self) -> int:
        with self._lock:
            return len(self._series())

    def has_series(self, key: _TagKey) -> bool:
        with self._lock:
            return key in self._series()


class Counter(Metric):
    def __init__(self, name, description="", tag_keys=()):
        self._values: Dict[_TagKey, float] = defaultdict(float)
        super().__init__(name, description, tag_keys)

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._values[self._tags_key(tags)] += value

    def inc_key(self, key: _TagKey, value: float = 1.0) -> None:
        """Hot-path increment with a PREcomputed tag key (skips the
        per-call dict build + sort — the runtime submit/finish paths
        run at sync-call rates)."""
        with self._lock:
            self._values[key] += value

    def collect(self):
        with self._lock:
            return ("counter", dict(self._values))


class Gauge(Metric):
    def __init__(self, name, description="", tag_keys=()):
        self._values: Dict[_TagKey, float] = {}
        super().__init__(name, description, tag_keys)

    def set(self, value: float, tags: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._values[self._tags_key(tags)] = value

    def set_key(self, key: _TagKey, value: float) -> None:
        """Hot-path set with a precomputed tag key (router per-request)."""
        with self._lock:
            self._values[key] = value

    def collect(self):
        with self._lock:
            return ("gauge", dict(self._values))


class Histogram(Metric):
    def __init__(self, name, description="", boundaries: Sequence[float] = (),
                 tag_keys=()):
        self.boundaries = sorted(boundaries) or [
            0.001, 0.01, 0.1, 1, 10, 100, 1000
        ]
        self._counts: Dict[_TagKey, List[int]] = {}
        self._sums: Dict[_TagKey, float] = defaultdict(float)
        self._totals: Dict[_TagKey, int] = defaultdict(int)
        super().__init__(name, description, tag_keys)

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None) -> None:
        self.observe_key(self._tags_key(tags), value)

    def observe_key(self, key: _TagKey, value: float,
                    count: int = 1) -> None:
        """Hot-path observe with a precomputed tag key; ``count`` folds
        a coalesced batch of identical observations into one lock round."""
        with self._lock:
            if key not in self._counts:
                self._counts[key] = [0] * (len(self.boundaries) + 1)
            idx = bisect.bisect_left(self.boundaries, value)
            self._counts[key][idx] += count
            self._sums[key] += value * count
            self._totals[key] += count

    def _series(self) -> dict:
        return self._counts

    def merge_delta(self, delta: dict,
                    tags: Optional[Dict[str, str]] = None) -> None:
        """Fold a remote histogram delta ({"buckets", "sum", "count"},
        as produced by the telemetry exporter) into this series. A
        boundary mismatch (different config between processes) lumps the
        whole delta into the +Inf bucket rather than mis-binning."""
        key = self._tags_key(tags)
        with self._lock:
            counts = self._counts.setdefault(
                key, [0] * (len(self.boundaries) + 1))
            buckets = delta.get("buckets") or []
            if len(buckets) == len(counts):
                for i, c in enumerate(buckets):
                    counts[i] += c
            else:
                counts[-1] += int(delta.get("count", 0))
            self._sums[key] += float(delta.get("sum", 0.0))
            self._totals[key] += int(delta.get("count", 0))

    def collect(self):
        with self._lock:
            return ("histogram", {
                k: {"buckets": list(v), "sum": self._sums[k],
                    "count": self._totals[k]}
                for k, v in self._counts.items()
            })


class MetricsRegistry:
    def __init__(self):
        self._metrics: Dict[str, Metric] = {}
        self._lock = threading.Lock()

    def register(self, metric: Metric) -> None:
        with self._lock:
            self._metrics[metric.name] = metric

    def get(self, name: str) -> Optional[Metric]:
        with self._lock:
            return self._metrics.get(name)

    def collect_all(self) -> Dict[str, tuple]:
        with self._lock:
            metrics = list(self._metrics.values())
        return {m.name: m.collect() for m in metrics}

    def prometheus_text(self) -> str:
        """Prometheus exposition format (reference: prometheus_exporter.py).

        Strictly line-format clean: metric/label names sanitized with one
        rule everywhere, label values escaped, and the open histogram
        bucket labeled ``le="+Inf"`` (the spec spelling — a bare ``inf``
        is rejected by prometheus scrapers)."""
        lines = []
        for name, (kind, data) in sorted(self.collect_all().items()):
            safe = _sanitize_name(name)
            lines.append(f"# TYPE {safe} "
                         f"{'counter' if kind == 'counter' else 'gauge' if kind == 'gauge' else 'histogram'}")
            if kind in ("counter", "gauge"):
                for tags, value in data.items():
                    lines.append(f"{safe}{_fmt_labels(tags)} {_fmt_num(value)}")
            else:
                for tags, h in data.items():
                    metric = self._metrics.get(name)
                    cumulative = 0
                    bounds = [_fmt_num(b) for b in metric.boundaries]
                    bounds.append("+Inf")
                    for b, c in zip(bounds, h["buckets"]):
                        cumulative += c
                        lbl = _fmt_labels(list(tags) + [("le", b)])
                        lines.append(f"{safe}_bucket{lbl} {cumulative}")
                    lbl = _fmt_labels(tags)
                    lines.append(f"{safe}_sum{lbl} {_fmt_num(h['sum'])}")
                    lines.append(f"{safe}_count{lbl} {h['count']}")
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        with self._lock:
            self._metrics.clear()


registry = MetricsRegistry()


_create_lock = threading.Lock()


def get_or_create(cls, name: str, *args, **kwargs):
    """ATOMIC get-or-construct by name: reuse the registered metric when
    its type matches, else construct (which registers). Every lazy
    factory (core/serve) AND the telemetry absorber route through here
    under one lock — racing constructions would otherwise ``register``-
    overwrite each other, silently dropping every series the loser had
    merged (or leaving a caller holding an unregistered orphan)."""
    with _create_lock:
        existing = registry.get(name)
        if type(existing) is cls:
            return existing
        return cls(name, *args, **kwargs)

# -- core runtime metrics (reference: stats/metric_defs.cc subset) -----------

_core_lock = threading.Lock()
_core: Dict[str, Metric] = {}


def core_metrics() -> Dict[str, Metric]:
    with _core_lock:
        if not _core:
            _core["tasks_submitted"] = get_or_create(
                Counter, "rt_tasks_submitted", "Tasks submitted", ("type",))
            _core["tasks_finished"] = get_or_create(
                Counter, "rt_tasks_finished", "Tasks finished", ("state",))
            _core["task_latency_s"] = get_or_create(
                Histogram, "rt_task_latency_seconds",
                "Task execution wall time")
            _core["object_store_bytes"] = get_or_create(
                Gauge, "rt_object_store_bytes", "Per-node store usage",
                ("node",))
            _core["actors_alive"] = get_or_create(
                Gauge, "rt_actors_alive", "Live actors")
            _core["workers_alive"] = get_or_create(
                Gauge, "rt_workers_alive", "Live workers")
        return _core
