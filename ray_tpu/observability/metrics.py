"""Application + runtime metrics: Counter/Gauge/Histogram with a registry.

Reference analog: ``python/ray/util/metrics.py`` (user-facing API) +
``src/ray/stats/metric_defs.cc`` (runtime metric definitions) +
``_private/metrics_agent.py`` (aggregation + Prometheus text export).
Single-process registry here; the dashboard module serves the Prometheus
text format over HTTP.
"""

from __future__ import annotations

import bisect
import threading
import time
from collections import defaultdict
from typing import Dict, List, Optional, Sequence, Tuple

_TagKey = Tuple[Tuple[str, str], ...]


class Metric:
    def __init__(self, name: str, description: str = "",
                 tag_keys: Sequence[str] = ()):
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._lock = threading.Lock()
        registry.register(self)

    def _tags_key(self, tags: Optional[Dict[str, str]]) -> _TagKey:
        tags = tags or {}
        return tuple(sorted(tags.items()))


class Counter(Metric):
    def __init__(self, name, description="", tag_keys=()):
        self._values: Dict[_TagKey, float] = defaultdict(float)
        super().__init__(name, description, tag_keys)

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._values[self._tags_key(tags)] += value

    def collect(self):
        with self._lock:
            return ("counter", dict(self._values))


class Gauge(Metric):
    def __init__(self, name, description="", tag_keys=()):
        self._values: Dict[_TagKey, float] = {}
        super().__init__(name, description, tag_keys)

    def set(self, value: float, tags: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._values[self._tags_key(tags)] = value

    def collect(self):
        with self._lock:
            return ("gauge", dict(self._values))


class Histogram(Metric):
    def __init__(self, name, description="", boundaries: Sequence[float] = (),
                 tag_keys=()):
        self.boundaries = sorted(boundaries) or [
            0.001, 0.01, 0.1, 1, 10, 100, 1000
        ]
        self._counts: Dict[_TagKey, List[int]] = {}
        self._sums: Dict[_TagKey, float] = defaultdict(float)
        self._totals: Dict[_TagKey, int] = defaultdict(int)
        super().__init__(name, description, tag_keys)

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None) -> None:
        key = self._tags_key(tags)
        with self._lock:
            if key not in self._counts:
                self._counts[key] = [0] * (len(self.boundaries) + 1)
            idx = bisect.bisect_left(self.boundaries, value)
            self._counts[key][idx] += 1
            self._sums[key] += value
            self._totals[key] += 1

    def collect(self):
        with self._lock:
            return ("histogram", {
                k: {"buckets": list(v), "sum": self._sums[k],
                    "count": self._totals[k]}
                for k, v in self._counts.items()
            })


class MetricsRegistry:
    def __init__(self):
        self._metrics: Dict[str, Metric] = {}
        self._lock = threading.Lock()

    def register(self, metric: Metric) -> None:
        with self._lock:
            self._metrics[metric.name] = metric

    def get(self, name: str) -> Optional[Metric]:
        with self._lock:
            return self._metrics.get(name)

    def collect_all(self) -> Dict[str, tuple]:
        with self._lock:
            metrics = list(self._metrics.values())
        return {m.name: m.collect() for m in metrics}

    def prometheus_text(self) -> str:
        """Prometheus exposition format (reference: prometheus_exporter.py)."""
        lines = []
        for name, (kind, data) in sorted(self.collect_all().items()):
            safe = name.replace(".", "_").replace("-", "_")
            lines.append(f"# TYPE {safe} "
                         f"{'counter' if kind == 'counter' else 'gauge' if kind == 'gauge' else 'histogram'}")
            if kind in ("counter", "gauge"):
                for tags, value in data.items():
                    label = ",".join(f'{k}="{v}"' for k, v in tags)
                    label = "{" + label + "}" if label else ""
                    lines.append(f"{safe}{label} {value}")
            else:
                for tags, h in data.items():
                    base = ",".join(f'{k}="{v}"' for k, v in tags)
                    metric = self._metrics.get(name)
                    cumulative = 0
                    for b, c in zip(metric.boundaries + [float("inf")],
                                    h["buckets"]):
                        cumulative += c
                        le = f'le="{b}"'
                        lbl = "{" + (base + "," if base else "") + le + "}"
                        lines.append(f"{safe}_bucket{lbl} {cumulative}")
                    lbl = "{" + base + "}" if base else ""
                    lines.append(f"{safe}_sum{lbl} {h['sum']}")
                    lines.append(f"{safe}_count{lbl} {h['count']}")
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        with self._lock:
            self._metrics.clear()


registry = MetricsRegistry()

# -- core runtime metrics (reference: stats/metric_defs.cc subset) -----------

_core_lock = threading.Lock()
_core: Dict[str, Metric] = {}


def core_metrics() -> Dict[str, Metric]:
    with _core_lock:
        if not _core:
            _core["tasks_submitted"] = Counter(
                "rt_tasks_submitted", "Tasks submitted", ("type",))
            _core["tasks_finished"] = Counter(
                "rt_tasks_finished", "Tasks finished", ("state",))
            _core["task_latency_s"] = Histogram(
                "rt_task_latency_seconds", "Task execution wall time")
            _core["object_store_bytes"] = Gauge(
                "rt_object_store_bytes", "Per-node store usage", ("node",))
            _core["actors_alive"] = Gauge("rt_actors_alive", "Live actors")
            _core["workers_alive"] = Gauge("rt_workers_alive", "Live workers")
        return _core
