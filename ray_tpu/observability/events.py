"""Structured event log.

Reference analog: ``src/ray/util/event.h`` (structured JSON events with
labels/severity) consumed by the dashboard event module. Events append to a
bounded in-memory ring + optional JSONL file.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional


class Severity:
    DEBUG = "DEBUG"
    INFO = "INFO"
    WARNING = "WARNING"
    ERROR = "ERROR"
    FATAL = "FATAL"


class EventLog:
    def __init__(self, max_events: int = 10_000,
                 file_path: Optional[str] = None):
        self._ring: deque = deque(maxlen=max_events)
        self._lock = threading.Lock()
        self._file_path = file_path
        self._file = None
        if file_path:
            os.makedirs(os.path.dirname(file_path), exist_ok=True)
            self._file = open(file_path, "a", buffering=1)

    def emit(self, label: str, message: str,
             severity: str = Severity.INFO,
             custom_fields: Optional[Dict[str, Any]] = None) -> Dict:
        event = {
            "timestamp": time.time(),
            "severity": severity,
            "label": label,
            "message": message,
            "custom_fields": custom_fields or {},
            "pid": os.getpid(),
        }
        with self._lock:
            self._ring.append(event)
            if self._file:
                self._file.write(json.dumps(event) + "\n")
        return event

    def query(self, label: Optional[str] = None,
              severity: Optional[str] = None,
              limit: int = 100) -> List[Dict]:
        with self._lock:
            events = list(self._ring)
        if label:
            events = [e for e in events if e["label"] == label]
        if severity:
            events = [e for e in events if e["severity"] == severity]
        return events[-limit:]

    def close(self):
        if self._file:
            self._file.close()
            self._file = None


_global_log: Optional[EventLog] = None
_global_lock = threading.Lock()


def global_event_log() -> EventLog:
    global _global_log
    with _global_lock:
        if _global_log is None:
            _global_log = EventLog()
        return _global_log


def emit(label: str, message: str, severity: str = Severity.INFO,
         **custom_fields) -> None:
    global_event_log().emit(label, message, severity, custom_fields)
