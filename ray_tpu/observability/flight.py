"""Per-request flight recorder: stage-attributed task latency.

Reference analog: the task-events backend
(``src/ray/gcs/gcs_server/gcs_task_manager`` + the task-event protos),
surfaced to users as ``ray summary tasks`` — every task records
timestamps at each lifecycle transition and the head aggregates them
into per-function, per-stage latency distributions.

Division of labor:

- The HEAD stamps transitions it observes directly onto each
  ``_TaskRecord.state_ts`` (``submitted`` / ``scheduled`` /
  ``dispatched`` / ``finished``|``failed``) with ``time.monotonic()``
  — all of those happen in the head process, so one clock orders them.
- WORKERS measure the one interval the head cannot see (execution wall
  time inside the worker) and ship it as a compact
  ``(task_id_hex, exec_s)`` delta through the existing PR-13 telemetry
  channel (``TelemetryExporter.record_flight`` →
  ``payload["flight"]`` → :func:`ingest`). Durations, not timestamps:
  monotonic clocks are not comparable across processes.
- This module joins the two halves per task id and folds the result
  into bounded per-(function, stage) reservoirs from which
  :func:`summary` computes p50/p99.

Stage decomposition (sums to the end-to-end latency by construction):

    queue     submitted -> scheduled   (deps + scheduler wait)
    sched     scheduled -> dispatched  (arg resolution + pipe send)
    exec      worker-measured execution wall time
    transfer  (finished - dispatched) - exec  (pipe transit both ways
              + result store/registration; clamped at 0)

Everything is bounded and gated on ``flight_recorder_enabled`` (itself
dependent on the telemetry plane); a replacement head after failover
starts with a clean store (``clear()`` runs in ``Runtime.__init__``).
"""

from __future__ import annotations

import threading
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional

# Completed tasks waiting for their worker exec delta (ships on the next
# telemetry flush, up to metrics_report_interval_ms later) — bounded so
# a worker that never reports (telemetry disabled mid-flight, crash)
# cannot grow the head.
_JOIN_MAX = 50_000
# Per-(function, stage) duration reservoir: enough samples for stable
# p99 estimates without unbounded growth.
_SAMPLES_MAX = 2_048
# Distinct function names tracked (runaway dynamic-name backstop).
_FUNCS_MAX = 1_024
# Recently completed tasks with their full stage breakdown (drill-down
# + tests); bounded like everything else.
_RECENT_MAX = 512

_STAGES = ("queue", "sched", "exec", "transfer", "total")

_lock = threading.Lock()
# task_id_hex -> (name, head-side durations dict)  [awaiting exec join]
_joins: "OrderedDict[str, tuple]" = OrderedDict()
# exec deltas that arrived before their head-side record (re-init races)
_early_exec: "OrderedDict[str, float]" = OrderedDict()
# name -> stage -> deque[float seconds]
_stats: Dict[str, Dict[str, deque]] = {}
_recent: deque = deque(maxlen=_RECENT_MAX)
_stage_hist = None  # rt_task_stage_seconds, created lazily


def enabled() -> bool:
    from ..core.config import config

    cfg = config()
    return cfg.telemetry_enabled and cfg.flight_recorder_enabled


def _hist():
    """``rt_task_stage_seconds{stage}`` — the cluster-visible histogram
    form of the per-stage distributions (autoscaling/alerting signal)."""
    global _stage_hist
    if _stage_hist is None:
        from .metrics import Histogram, get_or_create

        _stage_hist = get_or_create(
            Histogram, "rt_task_stage_seconds",
            "Task latency attributed per lifecycle stage",
            boundaries=[0.0001, 0.001, 0.01, 0.1, 1, 10, 100],
            tag_keys=("stage",))
    return _stage_hist


# Interned histogram tag keys — commits run on the task completion path.
_STAGE_KEYS = {s: (("stage", s),) for s in _STAGES}


def _commit_locked(name: str, stage: str, seconds: float) -> None:
    per_fn = _stats.get(name)
    if per_fn is None:
        if len(_stats) >= _FUNCS_MAX:
            from . import telemetry

            telemetry.count_dropped("flight_funcs")
            return
        per_fn = _stats[name] = {s: deque(maxlen=_SAMPLES_MAX)
                                 for s in _STAGES}
    per_fn[stage].append(seconds)


def task_finished(task_id_hex: str, name: str,
                  state_ts: Dict[str, float], state: str) -> None:
    """Head side, called once per task reaching DONE/FAILED: fold the
    head-observed stages in now; park the record until the worker's
    exec delta arrives to attribute the dispatched->finished interval."""
    sub = state_ts.get("submitted")
    end = state_ts.get("finished") or state_ts.get("failed")
    if sub is None or end is None:
        return
    sched = state_ts.get("scheduled", sub)
    disp = state_ts.get("dispatched", sched)
    queue_s = max(0.0, sched - sub)
    sched_s = max(0.0, disp - sched)
    total_s = max(0.0, end - sub)
    hist = _hist()
    with _lock:
        _commit_locked(name, "queue", queue_s)
        _commit_locked(name, "sched", sched_s)
        _commit_locked(name, "total", total_s)
        exec_s = _early_exec.pop(task_id_hex, None) \
            if task_id_hex else None
        if exec_s is None and task_id_hex and state == "DONE":
            while len(_joins) >= _JOIN_MAX:
                _joins.popitem(last=False)
                from . import telemetry

                telemetry.count_dropped("flight_joins")
            _joins[task_id_hex] = (name, disp, end, queue_s, sched_s,
                                   total_s)
    hist.observe_key(_STAGE_KEYS["queue"], queue_s)
    hist.observe_key(_STAGE_KEYS["sched"], sched_s)
    hist.observe_key(_STAGE_KEYS["total"], total_s)
    if exec_s is not None:
        _join(task_id_hex, name, disp, end, queue_s, sched_s, total_s,
              exec_s)


def _join(task_id_hex: str, name: str, disp: float, end: float,
          queue_s: float, sched_s: float, total_s: float,
          exec_s: float) -> None:
    exec_s = min(max(0.0, exec_s), max(0.0, end - disp))
    transfer_s = max(0.0, (end - disp) - exec_s)
    hist = _hist()
    with _lock:
        _commit_locked(name, "exec", exec_s)
        _commit_locked(name, "transfer", transfer_s)
        _recent.append({
            "task_id": task_id_hex, "name": name,
            "queue_s": queue_s, "sched_s": sched_s, "exec_s": exec_s,
            "transfer_s": transfer_s, "total_s": total_s,
        })
    hist.observe_key(_STAGE_KEYS["exec"], exec_s)
    hist.observe_key(_STAGE_KEYS["transfer"], transfer_s)


def ingest(events: List[tuple]) -> None:
    """Absorb worker-shipped ``(task_id_hex, exec_s)`` flight deltas
    (called from ``telemetry.absorb`` on the head)."""
    for item in events:
        try:
            task_id_hex, exec_s = item[0], float(item[1])
        except (TypeError, ValueError, IndexError):
            continue
        with _lock:
            parked = _joins.pop(task_id_hex, None)
            if parked is None:
                # Done message not processed yet (or task failed before
                # completing): park the delta briefly instead.
                while len(_early_exec) >= _JOIN_MAX:
                    _early_exec.popitem(last=False)
                _early_exec[task_id_hex] = exec_s
                continue
        name, disp, end, queue_s, sched_s, total_s = parked
        _join(task_id_hex, name, disp, end, queue_s, sched_s, total_s,
              exec_s)


def _pct(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def summary() -> Dict[str, Any]:
    """Per-function, per-stage latency aggregates:
    ``{name: {count, stages: {stage: {count, mean_ms, p50_ms,
    p99_ms}}}}`` — the ``rt summary tasks`` / ``/api/summary`` body."""
    with _lock:
        snap = {name: {stage: list(vals) for stage, vals in per_fn.items()}
                for name, per_fn in _stats.items()}
    out: Dict[str, Any] = {}
    for name, per_fn in snap.items():
        stages = {}
        for stage, vals in per_fn.items():
            if not vals:
                continue
            vals.sort()
            stages[stage] = {
                "count": len(vals),
                "mean_ms": round(sum(vals) / len(vals) * 1e3, 3),
                "p50_ms": round(_pct(vals, 0.5) * 1e3, 3),
                "p99_ms": round(_pct(vals, 0.99) * 1e3, 3),
            }
        if stages:
            out[name] = {"count": stages["total"]["count"]
                         if "total" in stages else
                         max(s["count"] for s in stages.values()),
                         "stages": stages}
    return out


def recent_tasks(limit: int = 100) -> List[Dict[str, Any]]:
    """Most recently completed tasks with their full stage breakdown
    (exec-joined only); newest last."""
    with _lock:
        rows = list(_recent)
    return rows[-limit:]


def format_summary(data: Optional[Dict[str, Any]] = None) -> str:
    """Render :func:`summary` as the ``rt summary tasks`` table."""
    data = summary() if data is None else data
    if not data:
        return "(no completed tasks recorded)"
    header = (f"{'function':<32} {'stage':<9} {'count':>6} "
              f"{'p50_ms':>9} {'p99_ms':>9} {'mean_ms':>9}")
    lines = [header, "-" * len(header)]
    for name in sorted(data):
        stages = data[name]["stages"]
        for stage in _STAGES:
            row = stages.get(stage)
            if row is None:
                continue
            lines.append(
                f"{name[:32]:<32} {stage:<9} {row['count']:>6} "
                f"{row['p50_ms']:>9.3f} {row['p99_ms']:>9.3f} "
                f"{row['mean_ms']:>9.3f}")
    return "\n".join(lines)


def clear() -> None:
    """Drop every recorded event (test isolation; and a replacement
    head after failover must start with a clean store, never inherit a
    possibly-torn aggregator from the process's previous runtime)."""
    with _lock:
        _joins.clear()
        _early_exec.clear()
        _stats.clear()
        _recent.clear()


# Package-export spellings (the short names collide with the state API's
# generic vocabulary at the ``ray_tpu.observability`` level).
flight_summary = summary
format_flight_summary = format_summary
recent_flight_tasks = recent_tasks
