"""Head-side trace store: per-request span index behind ``rt trace``.

Reference analog: the OpenTelemetry collector's tail-sampling processor
plus the trace page of any APM backend — the piece the reference leaves
to an external OTLP endpoint. Here the head IS the backend: spans
already flow to it over the PR-13 telemetry plane (worker/daemon
payloads land in ``telemetry.absorb``) and, for head-local spans
(proxy/router), through the tracer's ``on_record`` sink — this module
indexes both streams by ``trace_id`` so one HTTP request's whole
proxy → router → replica → engine tree is queryable by the id the proxy
returned in ``x-request-id``.

Policy, bounded like every other head aggregate:

- **LRU store** of ``trace_store_max_traces`` distinct trace ids;
  evictions are counted in
  ``rt_telemetry_dropped_total{buffer="tracestore"}`` (warn-once).
- **Head sampling**: ``trace_sample_rate`` decides per trace id
  (deterministic hash, so every span of a request shares the verdict).
- **Tail retention**: sampled-out traces sit in a small probation
  buffer; a slow (``trace_slow_ms``) or errored span promotes the whole
  trace into the store, so tail exemplars are never sampled away.
- A replacement head after failover starts clean (:func:`clear` runs in
  ``Runtime.__init__``, mirroring ``flight.clear()``).
"""

from __future__ import annotations

import os
import threading
import zlib
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional

# Spans kept per trace: a runaway span producer (a decode loop emitting
# per-token spans, say) must not let one trace eat the store.
_SPANS_PER_TRACE_MAX = 512
# Sampled-out traces awaiting a tail-retention verdict. Small on
# purpose: probation only needs to span one request's lifetime.
_PROBATION_MAX = 256

_lock = threading.Lock()
# trace_id -> {"spans": [event], "t0": us, "t1": us, "reason": str}
_traces: "OrderedDict[str, dict]" = OrderedDict()
_probation: "OrderedDict[str, list]" = OrderedDict()
_kept_counter = None
_store_gauge = None
_KEPT_KEYS = {r: (("reason", r),) for r in ("sampled", "tail")}


def _cfg():
    from ..core.config import config

    return config()


def _metrics():
    global _kept_counter, _store_gauge
    if _kept_counter is None:
        from .metrics import Counter, Gauge, get_or_create

        _kept_counter = get_or_create(
            Counter, "rt_trace_store_kept_total",
            "Traces admitted to the head trace store, by retention "
            "reason (sampled = head sampling, tail = slow/errored "
            "promotion)", ("reason",))
        _store_gauge = get_or_create(
            Gauge, "rt_trace_store_traces",
            "Distinct traces resident in the head trace store")
    return _kept_counter, _store_gauge


def sampled(trace_id: str) -> bool:
    """Deterministic head-sampling verdict for a trace id: every span
    of the trace — whichever process shipped it — gets the same answer
    without coordination."""
    rate = float(_cfg().trace_sample_rate)
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    h = zlib.crc32(trace_id.encode("utf-8", "replace")) & 0xFFFFFFFF
    return h / float(1 << 32) < rate


def _is_tail_event(event: dict) -> bool:
    """Slow or errored span => the trace is a tail exemplar."""
    try:
        if event.get("dur", 0.0) >= float(_cfg().trace_slow_ms) * 1e3:
            return True
    except (TypeError, ValueError):
        pass
    args = event.get("args")
    return bool(isinstance(args, dict) and args.get("error"))


def _append_locked(rec: dict, event: dict) -> None:
    if len(rec["spans"]) >= _SPANS_PER_TRACE_MAX:
        from . import telemetry

        telemetry.count_dropped("tracestore_spans")
        return
    rec["spans"].append(event)
    ts = float(event.get("ts", 0.0))
    dur = float(event.get("dur", 0.0) or 0.0)
    rec["t0"] = ts if rec["t0"] is None else min(rec["t0"], ts)
    rec["t1"] = max(rec["t1"] or 0.0, ts + dur)


def _admit_locked(trace_id: str, reason: str) -> dict:
    rec = _traces.get(trace_id)
    if rec is not None:
        _traces.move_to_end(trace_id)
        return rec
    evicted = 0
    maxn = int(_cfg().trace_store_max_traces)
    while len(_traces) >= max(1, maxn):
        _traces.popitem(last=False)
        evicted += 1
    rec = _traces[trace_id] = {"spans": [], "t0": None, "t1": None,
                               "reason": reason}
    kept, gauge = _metrics()
    kept.inc_key(_KEPT_KEYS.get(reason, _KEPT_KEYS["sampled"]))
    gauge.set(float(len(_traces)))
    if evicted:
        from . import telemetry

        telemetry.count_dropped("tracestore", evicted)
    return rec


def ingest_event(event: dict) -> None:
    """File one chrome-form span event (shipped or head-local) under its
    trace id. Called from ``telemetry.absorb`` for remote payloads and
    from :func:`ingest_local_span` for head-recorded spans."""
    if not isinstance(event, dict):
        return
    args = event.get("args")
    trace_id = args.get("trace_id") if isinstance(args, dict) else None
    if not trace_id:
        return
    with _lock:
        rec = _traces.get(trace_id)
        if rec is not None:
            _traces.move_to_end(trace_id)
            _append_locked(rec, event)
            return
        if sampled(trace_id):
            _append_locked(_admit_locked(trace_id, "sampled"), event)
            return
        # Sampled out: park on probation until a slow/errored span
        # proves the trace is a tail exemplar worth keeping anyway.
        pending = _probation.get(trace_id)
        if pending is None:
            while len(_probation) >= _PROBATION_MAX:
                _probation.popitem(last=False)  # by-design discard
            pending = _probation[trace_id] = []
        else:
            _probation.move_to_end(trace_id)
        if len(pending) < _SPANS_PER_TRACE_MAX:
            pending.append(event)
        if _is_tail_event(event):
            rec = _admit_locked(trace_id, "tail")
            for ev in _probation.pop(trace_id, ()):
                _append_locked(rec, ev)


# Head-local spans park here until a query/absorb drains them: the
# tracer's on_record hook fires on the task-submit hot path, and inline
# indexing (chrome-event conversion + LRU admit + metrics) costs ~20us
# per span — measured by the ISSUE 20 overhead A/B. deque append is
# atomic, so the hot path pays one append and nothing else.
_local_pending: deque = deque(maxlen=4096)


def ingest_local_span(span) -> None:
    """Tracer ``on_record`` sink (head process only): buffer the
    finished Span; :func:`flush_local` indexes it on the next query or
    telemetry absorb. Installed by ``Runtime.__init__`` on the head."""
    if span.end_s is None:
        return
    if len(_local_pending) == _local_pending.maxlen:
        from . import telemetry

        telemetry.count_dropped("tracestore_pending")
    _local_pending.append((span, os.getpid()))


def flush_local() -> None:
    """Drain buffered head-local spans into the trace index. Called
    from every query entry point and from ``telemetry.absorb`` — off
    the span producers' critical path."""
    from .tracing import span_chrome_event

    while True:
        try:
            s, pid = _local_pending.popleft()
        except IndexError:
            return
        ingest_event(span_chrome_event(s, pid))


def install_head_sink() -> None:
    from .tracing import get_tracer

    get_tracer().on_record = ingest_local_span


def _proc_label(pid) -> str:
    from . import telemetry

    if pid == os.getpid():
        return "driver"
    name = telemetry._proc_names.get(pid)
    return name if name else f"pid {pid}"


def _normalize(event: dict) -> dict:
    args = dict(event.get("args") or {})
    return {
        "name": event.get("name"),
        "span_id": args.pop("span_id", None),
        "parent_id": args.pop("parent_id", None),
        "trace_id": args.pop("trace_id", None),
        "start_us": float(event.get("ts", 0.0)),
        "dur_ms": round(float(event.get("dur", 0.0) or 0.0) / 1e3, 3),
        "pid": event.get("pid"),
        "proc": _proc_label(event.get("pid")),
        "attributes": args,
    }


def lookup(trace_id_or_prefix: str) -> Optional[str]:
    """Resolve a (possibly truncated) trace id to a stored one."""
    flush_local()
    with _lock:
        if trace_id_or_prefix in _traces:
            return trace_id_or_prefix
        matches = [t for t in _traces if t.startswith(trace_id_or_prefix)]
    return matches[0] if len(matches) == 1 else None


def get_trace(trace_id: str) -> Optional[Dict[str, Any]]:
    """One trace: normalized spans (sorted by start), joined flight
    records for any task ids its spans reference, and the process set —
    the ``rt trace <id>`` / ``/api/traces/<id>`` body."""
    resolved = lookup(trace_id)
    if resolved is None:
        return None
    with _lock:
        rec = _traces.get(resolved)
        if rec is None:
            return None
        spans = [_normalize(e) for e in rec["spans"]]
        t0, t1 = rec["t0"], rec["t1"]
        reason = rec["reason"]
    spans.sort(key=lambda s: s["start_us"])
    task_ids = {s["attributes"].get("task_id") for s in spans
                if s["attributes"].get("task_id")}
    tasks: List[dict] = []
    if task_ids:
        from . import flight

        tasks = [row for row in flight.recent_tasks(limit=500)
                 if row.get("task_id") in task_ids]
    return {
        "trace_id": resolved,
        "duration_ms": round(((t1 or 0.0) - (t0 or 0.0)) / 1e3, 3),
        "retention": reason,
        "procs": sorted({s["proc"] for s in spans}),
        "spans": spans,
        "tasks": tasks,
    }


def _root_name(spans: List[dict]) -> str:
    ids = {s["span_id"] for s in spans}
    for s in spans:
        if s["parent_id"] is None or s["parent_id"] not in ids:
            return s["name"] or "?"
    return spans[0]["name"] if spans else "?"


def list_traces(limit: int = 100) -> List[Dict[str, Any]]:
    """Summaries of resident traces, most recently touched last."""
    flush_local()
    with _lock:
        items = [(tid, [_normalize(e) for e in rec["spans"]],
                  rec["t0"], rec["t1"], rec["reason"])
                 for tid, rec in _traces.items()]
    out = []
    for tid, spans, t0, t1, reason in items[-limit:]:
        spans.sort(key=lambda s: s["start_us"])
        out.append({
            "trace_id": tid,
            "root": _root_name(spans),
            "duration_ms": round(((t1 or 0.0) - (t0 or 0.0)) / 1e3, 3),
            "spans": len(spans),
            "procs": sorted({s["proc"] for s in spans}),
            "retention": reason,
            "error": any(s["attributes"].get("error") for s in spans),
        })
    return out


def slow_traces(n: int = 10) -> List[Dict[str, Any]]:
    """Tail exemplars: the n longest resident traces, slowest first."""
    rows = list_traces(limit=int(_cfg().trace_store_max_traces))
    rows.sort(key=lambda r: r["duration_ms"], reverse=True)
    return rows[:n]


def format_trace(data: Dict[str, Any]) -> str:
    """Render :func:`get_trace` as an indented span tree with durations
    and the owning process — the human side of ``rt trace <id>``."""
    spans = data["spans"]
    by_parent: Dict[Optional[str], List[dict]] = {}
    ids = {s["span_id"] for s in spans}
    for s in spans:
        parent = s["parent_id"] if s["parent_id"] in ids else None
        by_parent.setdefault(parent, []).append(s)
    lines = [f"trace {data['trace_id']} — {data['duration_ms']:.3f}ms, "
             f"{len(spans)} spans, {len(data['procs'])} proc(s) "
             f"[{data['retention']}]"]

    def walk(parent: Optional[str], depth: int) -> None:
        for s in sorted(by_parent.get(parent, ()),
                        key=lambda x: x["start_us"]):
            attrs = {k: v for k, v in s["attributes"].items()
                     if k not in ("trace_id",)}
            extra = (" " + " ".join(f"{k}={v}" for k, v in
                                    sorted(attrs.items()))) if attrs else ""
            lines.append(f"{'  ' * (depth + 1)}{s['name']}  "
                         f"{s['dur_ms']:.3f}ms  [{s['proc']}]{extra}")
            walk(s["span_id"], depth + 1)

    walk(None, 0)
    for row in data.get("tasks", ()):
        lines.append(
            f"  task {row['task_id'][:12]} {row['name']}: "
            f"queue {row['queue_s'] * 1e3:.3f}ms sched "
            f"{row['sched_s'] * 1e3:.3f}ms exec "
            f"{row['exec_s'] * 1e3:.3f}ms transfer "
            f"{row['transfer_s'] * 1e3:.3f}ms")
    return "\n".join(lines)


def stats() -> Dict[str, int]:
    flush_local()
    with _lock:
        return {"traces": len(_traces), "probation": len(_probation)}


def clear() -> None:
    """Drop every indexed trace (test isolation; and a replacement head
    after failover must start clean, mirroring ``flight.clear()``)."""
    _local_pending.clear()
    with _lock:
        _traces.clear()
        _probation.clear()
    if _store_gauge is not None:
        _store_gauge.set(0.0)


# Package-export spellings (match flight.py's convention: the short
# names are too generic at the ``ray_tpu.observability`` level).
trace_detail = get_trace
trace_list = list_traces
format_trace_tree = format_trace
