"""Observability: metrics, state API, events, timeline, dashboard.

Reference analog: ``ray.util.metrics``, ``ray.experimental.state.api``,
``src/ray/stats``, ``src/ray/util/event.h``, ``dashboard/``.
"""

from .dashboard import Dashboard, start_dashboard, stop_dashboard
from .events import EventLog, Severity, emit, global_event_log
from .flight import (
    flight_summary,
    format_flight_summary,
    recent_flight_tasks,
)
from .metrics import Counter, Gauge, Histogram, core_metrics, registry
from .event_stats import EventStats, global_event_stats
from .telemetry import (
    TelemetryExporter,
    history,
    record_history_sample,
    refresh_cluster_gauges,
)
from .tracestore import (
    format_trace_tree,
    slow_traces,
    trace_detail,
    trace_list,
)
from .state import (
    actor_detail,
    cluster_status,
    event_loop_stats,
    list_actors,
    list_nodes,
    list_objects,
    list_placement_groups,
    list_tasks,
    list_workers,
    record_span,
    summarize_tasks,
    timeline,
)

__all__ = [
    "Counter", "Dashboard", "EventLog", "EventStats", "Gauge",
    "Histogram", "Severity", "actor_detail",
    "cluster_status", "core_metrics", "emit", "event_loop_stats",
    "flight_summary", "format_flight_summary", "format_trace_tree",
    "history", "recent_flight_tasks",
    "global_event_log", "global_event_stats",
    "list_actors", "list_nodes", "list_objects", "list_placement_groups",
    "list_tasks", "list_workers", "record_history_sample", "record_span",
    "refresh_cluster_gauges",
    "registry", "slow_traces", "start_dashboard", "stop_dashboard",
    "summarize_tasks", "TelemetryExporter", "timeline",
    "trace_detail", "trace_list",
]
