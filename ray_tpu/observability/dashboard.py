"""Dashboard-lite: HTTP JSON endpoints over the state API + metrics.

Reference analog: ``dashboard/head.py`` (aiohttp module host) +
``dashboard/state_aggregator.py`` + ``modules/metrics`` — served here by a
stdlib threading HTTP server:

  GET /api/nodes /api/tasks /api/actors /api/objects /api/workers
      /api/placement_groups /api/summary /api/events
  GET /metrics          (Prometheus text)
  GET /healthz          (reference: modules/healthz)
"""

from __future__ import annotations

import json
import threading
from typing import Optional

from . import state as state_api
from .events import global_event_log
from .metrics import registry


def node_stats() -> dict:
    """Per-node hardware stats (reference: the per-node dashboard AGENT's
    reporter module, ``modules/reporter/reporter_agent.py`` — psutil
    cpu/mem publisher; stdlib /proc reads here)."""
    stats: dict = {}
    try:
        with open("/proc/loadavg") as f:
            parts = f.read().split()
        stats["loadavg_1m"] = float(parts[0])
    except Exception:
        pass
    try:
        mem = {}
        with open("/proc/meminfo") as f:
            for line in f:
                k, _, v = line.partition(":")
                mem[k] = v.strip()
        total_kb = int(mem["MemTotal"].split()[0])
        avail_kb = int(mem["MemAvailable"].split()[0])
        stats["mem_total_bytes"] = total_kb * 1024
        stats["mem_available_bytes"] = avail_kb * 1024
        stats["mem_used_frac"] = round(1 - avail_kb / total_kb, 4)
    except Exception:
        pass
    try:
        import os as _os

        stats["num_cpus"] = _os.cpu_count()
        stats["pid"] = _os.getpid()
    except Exception:
        pass
    return stats


_INDEX_HTML = """<!doctype html>
<html><head><title>ray_tpu dashboard</title><style>
body{font-family:system-ui,sans-serif;margin:1.5rem;background:#fafafa}
h1{font-size:1.2rem} h2{font-size:1rem;margin-top:1.2rem}
table{border-collapse:collapse;font-size:.85rem;width:100%}
td,th{border:1px solid #ddd;padding:.25rem .5rem;text-align:left}
th{background:#eee} code{background:#eee;padding:0 .25rem}
#err{color:#b00}
</style></head><body>
<h1>ray_tpu dashboard</h1>
<div id="err"></div>
<div id="sections"></div>
<script>
const APIS = ["summary","nodes","actors","tasks","workers",
              "placement_groups","events"];
function esc(v){
  // API values include user-controlled strings (task/actor names, event
  // messages) — escape before interpolating into innerHTML (stored XSS).
  return String(v).replace(/[&<>"']/g, ch => ({"&":"&amp;","<":"&lt;",
    ">":"&gt;",'"':"&quot;","'":"&#39;"}[ch]));
}
function render(name, data){
  const rows = Array.isArray(data) ? data :
    Object.entries(data).map(([k,v])=>({key:k,value:JSON.stringify(v)}));
  if(!rows.length) return `<h2>${esc(name)}</h2><p>(empty)</p>`;
  const cols = Object.keys(rows[0]);
  const head = cols.map(c=>`<th>${esc(c)}</th>`).join("");
  const body = rows.slice(0,100).map(r=>"<tr>"+cols.map(
    c=>`<td>${esc(typeof r[c]==="object"?JSON.stringify(r[c]):r[c])}</td>`
  ).join("")+"</tr>").join("");
  return `<h2>${esc(name)} (${rows.length})</h2>
          <table><tr>${head}</tr>${body}</table>`;
}
async function refresh(){
  let html = "";
  for(const api of APIS){
    try{
      const res = await fetch("/api/"+api);
      html += render(api, await res.json());
    }catch(e){
      document.getElementById("err").textContent = String(e);
    }
  }
  document.getElementById("sections").innerHTML = html;
}
refresh(); setInterval(refresh, 5000);
</script></body></html>"""


class Dashboard:
    def __init__(self, host: str = "127.0.0.1", port: int = 8265):
        self.host = host
        self.port = port
        self._server = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "Dashboard":
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        routes = {
            "/api/nodes": state_api.list_nodes,
            "/api/tasks": state_api.list_tasks,
            "/api/actors": state_api.list_actors,
            "/api/objects": state_api.list_objects,
            "/api/workers": state_api.list_workers,
            "/api/placement_groups": state_api.list_placement_groups,
            "/api/summary": state_api.summarize_tasks,
            "/api/events": lambda: global_event_log().query(limit=200),
            "/api/node_stats": node_stats,
        }

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def do_GET(self):
                path = self.path.split("?")[0]
                if path in ("/", "/index.html"):
                    body = _INDEX_HTML.encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/html")
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if path == "/healthz":
                    self.send_response(200)
                    self.end_headers()
                    self.wfile.write(b"success")
                    return
                if path == "/metrics":
                    body = registry.prometheus_text().encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain")
                    self.end_headers()
                    self.wfile.write(body)
                    return
                # Drill-down routes: /api/task/<hex>, /api/logs/<worker>
                # (reference: dashboard per-task pages + log proxying).
                fn = routes.get(path)
                if fn is None and path.startswith("/api/task/"):
                    task_hex = path[len("/api/task/"):]
                    fn = lambda: state_api.task_detail(task_hex)  # noqa: E731
                if fn is None and path.startswith("/api/logs/"):
                    from urllib.parse import parse_qs, urlparse

                    worker = path[len("/api/logs/"):]
                    try:
                        n = int(parse_qs(urlparse(self.path).query).get(
                            "n", ["200"])[0])
                    except ValueError:
                        n = 200
                    n = max(1, min(10000, n))
                    fn = lambda: state_api.worker_log_tail(worker, n)  # noqa: E731
                if fn is None:
                    self.send_response(404)
                    self.end_headers()
                    return
                try:
                    body = json.dumps(fn()).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.end_headers()
                    self.wfile.write(body)
                except Exception as e:  # noqa: BLE001
                    self.send_response(500)
                    self.end_headers()
                    self.wfile.write(json.dumps({"error": str(e)}).encode())

        self._server = ThreadingHTTPServer((self.host, self.port), Handler)
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True, name="rt-dashboard")
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server = None


_dashboard: Optional[Dashboard] = None


def start_dashboard(host: str = "127.0.0.1", port: int = 8265) -> Dashboard:
    global _dashboard
    if _dashboard is None:
        _dashboard = Dashboard(host, port).start()
    return _dashboard


def stop_dashboard() -> None:
    global _dashboard
    if _dashboard is not None:
        _dashboard.stop()
        _dashboard = None
