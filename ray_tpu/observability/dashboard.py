"""Dashboard-lite: HTTP JSON endpoints over the state API + metrics.

Reference analog: ``dashboard/head.py`` (aiohttp module host) +
``dashboard/state_aggregator.py`` + ``modules/metrics`` — served here by a
stdlib threading HTTP server:

  GET /api/nodes /api/tasks /api/actors /api/objects /api/workers
      /api/placement_groups /api/summary /api/events
  GET /metrics          (Prometheus text)
  GET /healthz          (reference: modules/healthz)
"""

from __future__ import annotations

import json
import threading
from typing import Optional

from . import flight
from . import state as state_api
from . import telemetry
from .events import global_event_log
from .metrics import registry


def _trace_index() -> dict:
    """``/api/traces``: trace-store summaries + store stats."""
    from . import tracestore

    return {"stats": tracestore.stats(),
            "traces": tracestore.list_traces(limit=200)}


def _trace_one(trace_id: str):
    """``/api/traces/<id>``: one request's span tree (prefix ok)."""
    from . import tracestore

    data = tracestore.get_trace(trace_id)
    return data if data is not None else {"error": "unknown trace",
                                          "trace_id": trace_id}


def _serve_status() -> dict:
    """``/api/serve``: deployment/router snapshot (reference: the serve
    dashboard module). Lazy import — serve may never have been loaded."""
    try:
        from ..serve.api import serve_status_snapshot

        return serve_status_snapshot()
    except Exception as e:  # noqa: BLE001 — endpoint must answer
        return {"running": False, "error": str(e), "deployments": {}}


def node_stats() -> dict:
    """Per-node hardware stats (reference: the per-node dashboard AGENT's
    reporter module, ``modules/reporter/reporter_agent.py`` — psutil
    cpu/mem publisher; stdlib /proc reads here)."""
    stats: dict = {}
    try:
        with open("/proc/loadavg") as f:
            parts = f.read().split()
        stats["loadavg_1m"] = float(parts[0])
    except Exception:
        pass
    try:
        mem = {}
        with open("/proc/meminfo") as f:
            for line in f:
                k, _, v = line.partition(":")
                mem[k] = v.strip()
        total_kb = int(mem["MemTotal"].split()[0])
        avail_kb = int(mem["MemAvailable"].split()[0])
        stats["mem_total_bytes"] = total_kb * 1024
        stats["mem_available_bytes"] = avail_kb * 1024
        stats["mem_used_frac"] = round(1 - avail_kb / total_kb, 4)
    except Exception:
        pass
    try:
        import os as _os

        stats["num_cpus"] = _os.cpu_count()
        stats["pid"] = _os.getpid()
    except Exception:
        pass
    return stats


_INDEX_HTML = """<!doctype html>
<html><head><title>ray_tpu dashboard</title><style>
body{font-family:system-ui,sans-serif;margin:0;background:#fafafa}
header{background:#1a1c23;color:#fff;padding:.6rem 1.2rem;display:flex;
  align-items:baseline;gap:1.2rem}
header h1{font-size:1.05rem;margin:0}
nav a{color:#9aa3b2;text-decoration:none;margin-right:.9rem;
  font-size:.9rem;padding:.15rem 0}
nav a.active{color:#fff;border-bottom:2px solid #6ba4ff}
main{padding:1rem 1.2rem}
h2{font-size:1rem;margin:.8rem 0 .4rem}
table{border-collapse:collapse;font-size:.83rem;width:100%;background:#fff}
td,th{border:1px solid #ddd;padding:.25rem .5rem;text-align:left}
th{background:#eee;position:sticky;top:0}
tr.clickable{cursor:pointer} tr.clickable:hover{background:#eef4ff}
code{background:#eee;padding:0 .25rem} #err{color:#b00}
.cards{display:flex;gap:.8rem;flex-wrap:wrap;margin:.4rem 0 1rem}
.card{background:#fff;border:1px solid #ddd;border-radius:6px;
  padding:.5rem .9rem;min-width:7.5rem}
.card .v{font-size:1.3rem;font-weight:600}
.card .k{font-size:.75rem;color:#667}
svg.spark{background:#fff;border:1px solid #ddd;border-radius:4px}
#detail{background:#fff;border:1px solid #bcd;border-radius:6px;
  padding:.6rem .9rem;margin:.6rem 0;white-space:pre-wrap;
  font-family:ui-monospace,monospace;font-size:.8rem;display:none}
</style></head><body>
<header><h1>ray_tpu</h1><nav id="nav"></nav></header>
<main><div id="err"></div><div id="detail"></div><div id="view"></div></main>
<script>
const TABS = ["overview","nodes","actors","tasks","objects","workers",
  "placement_groups","jobs","serve","events","event_stats"];
// Client-side metric history for the sparklines (one poll per refresh).
const hist = {running:[], total:[], load:[], mem:[]};
function esc(v){
  // API values include user-controlled strings (task/actor names, event
  // messages) — escape before interpolating into innerHTML (stored XSS).
  return String(v).replace(/[&<>"']/g, ch => ({"&":"&amp;","<":"&lt;",
    ">":"&gt;",'"':"&quot;","'":"&#39;"}[ch]));
}
function spark(values, w=220, h=44, color="#4a7fd4"){
  if(values.length < 2) return `<svg class="spark" width="${w}" height="${h}"></svg>`;
  const max = Math.max(...values, 1e-9), min = Math.min(...values, 0);
  const pts = values.map((v,i)=>
    `${(i/(values.length-1)*(w-6)+3).toFixed(1)},` +
    `${(h-4-(v-min)/(max-min||1)*(h-10)).toFixed(1)}`).join(" ");
  return `<svg class="spark" width="${w}" height="${h}">
    <polyline fill="none" stroke="${color}" stroke-width="1.5"
      points="${pts}"/>
    <text x="${w-4}" y="11" text-anchor="end" font-size="9"
      fill="#667">${values[values.length-1].toFixed(2)}</text></svg>`;
}
function table(rows, opts={}){
  if(!rows || !rows.length) return "<p>(empty)</p>";
  const cols = Object.keys(rows[0]);
  const head = cols.map(c=>`<th>${esc(c)}</th>`).join("");
  const body = rows.slice(0, 200).map(r=>{
    const click = opts.idcol && r[opts.idcol] ?
      ` class="clickable" data-id="${esc(r[opts.idcol])}"` : "";
    return `<tr${click}>`+cols.map(c=>`<td>${esc(
      typeof r[c]==="object" && r[c]!==null?JSON.stringify(r[c]):r[c]
    )}</td>`).join("")+"</tr>";
  }).join("");
  return `<table><tr>${head}</tr>${body}</table>`;
}
async function fetchJson(path){
  const res = await fetch(path);
  if(!res.ok) throw new Error(path + " -> " + res.status);
  return res.json();
}
async function renderOverview(){
  const [summary, stats, nodes, history] = await Promise.all([
    fetchJson("/api/summary"), fetchJson("/api/node_stats"),
    fetchJson("/api/nodes"),
    fetchJson("/api/history").catch(()=>({samples:[]}))]);
  const states = summary.states || {};
  const total = Object.values(states).reduce((a,b)=>a+b,0);
  hist.running.push(states.RUNNING||0); hist.total.push(total);
  hist.load.push(stats.loadavg_1m||0);
  hist.mem.push(stats.mem_used_frac||0);
  for(const k in hist) if(hist[k].length>120) hist[k].shift();
  // Server-side history ring: sparklines survive a page reload (the
  // client-side hist above is only the fallback for old heads).
  const hs = (history.samples||[]).slice(-120);
  const tasksSeries = hs.length ? hs.map(s=>s.tasks_per_s) : hist.running;
  const loadSeries  = hs.length ? hs.map(s=>s.load_1m) : hist.load;
  const memSeries   = hs.length ? hs.map(s=>s.mem_used_frac) : hist.mem;
  const tokSeries   = hs.map(s=>s.tokens_per_s);
  const tokRow = tokSeries.some(v=>v>0) ?
    `<h2>tokens/s</h2>${spark(tokSeries, 220, 44, "#2e9e62")}` : "";
  const flightRows = Object.entries(summary.flight||{}).flatMap(
    ([fn,d])=>Object.entries(d.stages).map(([stage,s])=>(
      {fn, stage, count:s.count, p50_ms:s.p50_ms, p99_ms:s.p99_ms})));
  const cards = [["nodes", nodes.length], ["tasks total", total],
    ["running", states.RUNNING||0], ["done", states.DONE||0],
    ["load 1m", (stats.loadavg_1m??0).toFixed(2)],
    ["mem used", ((stats.mem_used_frac??0)*100).toFixed(1)+"%"]]
    .map(([k,v])=>`<div class="card"><div class="v">${esc(v)}</div>
      <div class="k">${esc(k)}</div></div>`).join("");
  return `<div class="cards">${cards}</div>
    <h2>${hs.length ? "tasks/s" : "running tasks"}</h2>${spark(tasksSeries)}
    ${tokRow}
    <h2>host load (1m)</h2>${spark(loadSeries, 220, 44, "#d4824a")}
    <h2>memory used fraction</h2>${spark(memSeries, 220, 44, "#7a4ad4")}
    <h2>task stage latency (flight recorder)</h2>${table(flightRows)}
    <h2>nodes</h2>${table(nodes)}`;
}
async function renderTab(tab){
  if(tab === "overview") return renderOverview();
  const data = await fetchJson("/api/"+tab);
  const rows = Array.isArray(data) ? data :
    Object.entries(data).map(([k,v])=>({key:k, value:JSON.stringify(v)}));
  const opts = tab === "actors" ? {idcol: "actor_id"} :
               tab === "tasks" ? {idcol: "task_id"} : {};
  let hint = opts.idcol ? "<p style='font-size:.8rem;color:#667'>" +
    "click a row for details</p>" : "";
  return `<h2>${esc(tab)} (${rows.length})</h2>${hint}` +
    table(rows, opts);
}
async function showDetail(tab, id){
  const api = tab === "actors" ? "/api/actor/" : "/api/task/";
  try{
    const d = await fetchJson(api + id);
    const el = document.getElementById("detail");
    el.style.display = "block";
    el.textContent = JSON.stringify(d, null, 2);
  }catch(e){ document.getElementById("err").textContent = String(e); }
}
function activeTab(){
  const t = location.hash.replace("#","");
  return TABS.includes(t) ? t : "overview";
}
async function refresh(){
  const tab = activeTab();
  document.getElementById("nav").innerHTML = TABS.map(t=>
    `<a href="#${t}" class="${t===tab?"active":""}">${t}</a>`).join("");
  try{
    document.getElementById("view").innerHTML = await renderTab(tab);
    document.getElementById("err").textContent = "";
    document.querySelectorAll("tr.clickable").forEach(tr=>
      tr.addEventListener("click", ()=>showDetail(tab, tr.dataset.id)));
  }catch(e){
    document.getElementById("err").textContent = String(e);
  }
}
window.addEventListener("hashchange", ()=>{
  document.getElementById("detail").style.display = "none";
  refresh();
});
refresh(); setInterval(refresh, 4000);
</script></body></html>"""


class Dashboard:
    def __init__(self, host: str = "127.0.0.1", port: int = 8265):
        self.host = host
        self.port = port
        self._server = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "Dashboard":
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        routes = {
            "/api/nodes": state_api.list_nodes,
            "/api/tasks": state_api.list_tasks,
            "/api/actors": state_api.list_actors,
            "/api/objects": state_api.list_objects,
            "/api/workers": state_api.list_workers,
            "/api/placement_groups": state_api.list_placement_groups,
            # states: FSM counts; flight: per-function per-stage p50/p99
            # from the flight recorder (queue/sched/exec/transfer).
            "/api/summary": lambda: {"states": state_api.summarize_tasks(),
                                     "flight": flight.summary()},
            "/api/events": lambda: global_event_log().query(limit=200),
            "/api/node_stats": node_stats,
            "/api/jobs": state_api.list_jobs,
            "/api/event_stats": state_api.event_loop_stats,
            "/api/serve": _serve_status,
            "/api/traces": _trace_index,
            # Server-side metrics history ring: sparklines survive a
            # page reload, and `rt top` renders the same body.
            "/api/history": telemetry.history,
        }

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def do_GET(self):
                path = self.path.split("?")[0]
                if path in ("/", "/index.html"):
                    body = _INDEX_HTML.encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/html")
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if path == "/healthz":
                    self.send_response(200)
                    self.end_headers()
                    self.wfile.write(b"success")
                    return
                if path == "/metrics":
                    # Sample cluster gauges (actors/workers alive, store
                    # bytes) at scrape time so they can't go stale.
                    try:
                        telemetry.refresh_cluster_gauges()
                    except Exception:  # noqa: BLE001 — scrape anyway
                        pass
                    body = registry.prometheus_text().encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain")
                    self.end_headers()
                    self.wfile.write(body)
                    return
                # Drill-down routes: /api/task/<hex>, /api/logs/<worker>
                # (reference: dashboard per-task pages + log proxying).
                fn = routes.get(path)
                if fn is None and path.startswith("/api/traces/"):
                    trace_id = path[len("/api/traces/"):]
                    fn = lambda: _trace_one(trace_id)  # noqa: E731
                if fn is None and path.startswith("/api/task/"):
                    task_hex = path[len("/api/task/"):]
                    fn = lambda: state_api.task_detail(task_hex)  # noqa: E731
                if fn is None and path.startswith("/api/actor/"):
                    actor_hex = path[len("/api/actor/"):]
                    fn = lambda: state_api.actor_detail(actor_hex)  # noqa: E731
                if fn is None and path.startswith("/api/logs/"):
                    from urllib.parse import parse_qs, urlparse

                    worker = path[len("/api/logs/"):]
                    try:
                        n = int(parse_qs(urlparse(self.path).query).get(
                            "n", ["200"])[0])
                    except ValueError:
                        n = 200
                    n = max(1, min(10000, n))
                    fn = lambda: state_api.worker_log_tail(worker, n)  # noqa: E731
                if fn is None:
                    self.send_response(404)
                    self.end_headers()
                    return
                try:
                    body = json.dumps(fn()).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.end_headers()
                    self.wfile.write(body)
                except Exception as e:  # noqa: BLE001
                    self.send_response(500)
                    self.end_headers()
                    self.wfile.write(json.dumps({"error": str(e)}).encode())

        self._server = ThreadingHTTPServer((self.host, self.port), Handler)
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True, name="rt-dashboard")
        self._thread.start()
        # History sampler: one snapshot of the head registry into the
        # bounded time-series ring per scrape interval. Owned by the
        # dashboard (it is the head's long-lived observability process
        # anchor); gauges refresh first so the sample sees live values.
        self._sampler_stop = threading.Event()

        def _sample_loop():
            from ..core.config import config

            period = max(0.1, config().metrics_report_interval_ms / 1e3)
            while not self._sampler_stop.wait(period):
                try:
                    telemetry.refresh_cluster_gauges()
                    telemetry.record_history_sample()
                except Exception:  # noqa: BLE001 — sampler must survive
                    pass

        self._sampler = threading.Thread(target=_sample_loop, daemon=True,
                                         name="rt-history-sampler")
        self._sampler.start()
        return self

    def stop(self) -> None:
        if getattr(self, "_sampler_stop", None) is not None:
            self._sampler_stop.set()
        if self._server is not None:
            self._server.shutdown()
            self._server = None


_dashboard: Optional[Dashboard] = None


def start_dashboard(host: str = "127.0.0.1", port: int = 8265) -> Dashboard:
    global _dashboard
    if _dashboard is None:
        _dashboard = Dashboard(host, port).start()
    return _dashboard


def stop_dashboard() -> None:
    global _dashboard
    if _dashboard is not None:
        _dashboard.stop()
        _dashboard = None
