"""Dashboard-lite: HTTP JSON endpoints over the state API + metrics.

Reference analog: ``dashboard/head.py`` (aiohttp module host) +
``dashboard/state_aggregator.py`` + ``modules/metrics`` — served here by a
stdlib threading HTTP server:

  GET /api/nodes /api/tasks /api/actors /api/objects /api/workers
      /api/placement_groups /api/summary /api/events
  GET /metrics          (Prometheus text)
  GET /healthz          (reference: modules/healthz)
"""

from __future__ import annotations

import json
import threading
from typing import Optional

from . import state as state_api
from .events import global_event_log
from .metrics import registry


class Dashboard:
    def __init__(self, host: str = "127.0.0.1", port: int = 8265):
        self.host = host
        self.port = port
        self._server = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "Dashboard":
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        routes = {
            "/api/nodes": state_api.list_nodes,
            "/api/tasks": state_api.list_tasks,
            "/api/actors": state_api.list_actors,
            "/api/objects": state_api.list_objects,
            "/api/workers": state_api.list_workers,
            "/api/placement_groups": state_api.list_placement_groups,
            "/api/summary": state_api.summarize_tasks,
            "/api/events": lambda: global_event_log().query(limit=200),
        }

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def do_GET(self):
                path = self.path.split("?")[0]
                if path == "/healthz":
                    self.send_response(200)
                    self.end_headers()
                    self.wfile.write(b"success")
                    return
                if path == "/metrics":
                    body = registry.prometheus_text().encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain")
                    self.end_headers()
                    self.wfile.write(body)
                    return
                fn = routes.get(path)
                if fn is None:
                    self.send_response(404)
                    self.end_headers()
                    return
                try:
                    body = json.dumps(fn()).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.end_headers()
                    self.wfile.write(body)
                except Exception as e:  # noqa: BLE001
                    self.send_response(500)
                    self.end_headers()
                    self.wfile.write(json.dumps({"error": str(e)}).encode())

        self._server = ThreadingHTTPServer((self.host, self.port), Handler)
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True, name="rt-dashboard")
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server = None


_dashboard: Optional[Dashboard] = None


def start_dashboard(host: str = "127.0.0.1", port: int = 8265) -> Dashboard:
    global _dashboard
    if _dashboard is None:
        _dashboard = Dashboard(host, port).start()
    return _dashboard


def stop_dashboard() -> None:
    global _dashboard
    if _dashboard is not None:
        _dashboard.stop()
        _dashboard = None
