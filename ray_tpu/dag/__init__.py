"""Lazy task/actor call graphs.

Reference analog: ``python/ray/dag/`` — ``DAGNode`` base with
``FunctionNode``/``ClassNode``/``ClassMethodNode``/``InputNode``;
``.bind(...)`` builds the graph, ``.execute(...)`` walks it submitting
tasks/actor calls. Used by Serve deployment graphs and Workflow.
"""

from __future__ import annotations

import uuid
from typing import Any, Dict, List, Optional


class DAGNode:
    def __init__(self, args: tuple, kwargs: dict):
        self._bound_args = args
        self._bound_kwargs = kwargs
        self._uuid = uuid.uuid4().hex

    # -- graph walking -------------------------------------------------------
    def _children(self) -> List["DAGNode"]:
        out = []
        for a in list(self._bound_args) + list(self._bound_kwargs.values()):
            if isinstance(a, DAGNode):
                out.append(a)
        return out

    def topological(self) -> List["DAGNode"]:
        seen: Dict[str, DAGNode] = {}
        order: List[DAGNode] = []

        def visit(node: DAGNode):
            if node._uuid in seen:
                return
            seen[node._uuid] = node
            for child in node._children():
                visit(child)
            order.append(node)

        visit(self)
        return order

    def _resolve_args(self, resolved: Dict[str, Any], input_value):
        def sub(x):
            if isinstance(x, InputNode):
                return input_value
            if isinstance(x, InputAttributeNode):
                return x.extract(input_value)
            if isinstance(x, DAGNode):
                return resolved[x._uuid]
            return x

        args = tuple(sub(a) for a in self._bound_args)
        kwargs = {k: sub(v) for k, v in self._bound_kwargs.items()}
        return args, kwargs

    def execute(self, input_value: Any = None):
        """Submit the graph; returns the root's ObjectRef (or value)."""
        from ..core import get

        resolved: Dict[str, Any] = {}
        for node in self.topological():
            if isinstance(node, (InputNode, InputAttributeNode)):
                continue
            resolved[node._uuid] = node._execute_one(resolved, input_value)
        return resolved[self._uuid]

    def _execute_one(self, resolved, input_value):
        raise NotImplementedError


class InputNode(DAGNode):
    """Placeholder for the runtime input (reference: dag/input_node.py)."""

    def __init__(self):
        super().__init__((), {})

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def __getattr__(self, item):
        if item.startswith("_"):
            raise AttributeError(item)
        return InputAttributeNode(self, item)

    def __getitem__(self, key):
        return InputAttributeNode(self, key, is_item=True)


class InputAttributeNode(DAGNode):
    def __init__(self, parent: InputNode, key, is_item: bool = False):
        super().__init__((), {})
        self._key = key
        self._is_item = is_item

    def extract(self, input_value):
        if self._is_item:
            return input_value[self._key]
        return getattr(input_value, self._key)


class FunctionNode(DAGNode):
    def __init__(self, remote_fn, args, kwargs):
        super().__init__(args, kwargs)
        self._fn = remote_fn

    def _execute_one(self, resolved, input_value):
        args, kwargs = self._resolve_args(resolved, input_value)
        return self._fn.remote(*args, **kwargs)

    def __repr__(self):
        return f"FunctionNode({getattr(self._fn, '__name__', 'fn')})"


class ClassNode(DAGNode):
    """Actor instantiation node; method calls on it yield ClassMethodNodes."""

    def __init__(self, actor_cls, args, kwargs):
        super().__init__(args, kwargs)
        self._cls = actor_cls

    def _execute_one(self, resolved, input_value):
        args, kwargs = self._resolve_args(resolved, input_value)
        return self._cls.remote(*args, **kwargs)

    def __getattr__(self, item):
        if item.startswith("_"):
            raise AttributeError(item)
        return _MethodBinder(self, item)


class _MethodBinder:
    def __init__(self, class_node: ClassNode, method: str):
        self._node = class_node
        self._method = method

    def bind(self, *args, **kwargs) -> "ClassMethodNode":
        return ClassMethodNode(self._node, self._method, args, kwargs)


class ClassMethodNode(DAGNode):
    def __init__(self, class_node: ClassNode, method: str, args, kwargs):
        super().__init__(args, kwargs)
        self._class_node = class_node
        self._method = method

    def _children(self):
        return super()._children() + [self._class_node]

    def _execute_one(self, resolved, input_value):
        handle = resolved[self._class_node._uuid]
        args, kwargs = self._resolve_args(resolved, input_value)
        return getattr(handle, self._method).remote(*args, **kwargs)


def bind_function(remote_fn, *args, **kwargs) -> FunctionNode:
    return FunctionNode(remote_fn, args, kwargs)


def bind_class(actor_cls, *args, **kwargs) -> ClassNode:
    return ClassNode(actor_cls, args, kwargs)


# ``.bind`` lives ON RemoteFunction/ActorClass themselves (reference API
# shape) so it exists in every process — see core/remote_function.py and
# core/actor.py.
