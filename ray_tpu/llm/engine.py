"""Continuous-batching engine: a PAGED KV cache driven by two compiled
programs, with a radix prefix cache that skips redundant prefill.

Design (TPU-first, static shapes throughout):

- The KV cache is a pool of fixed-size PAGES (``llama.init_paged_kv_cache``)
  reached through a per-slot page table, not dense per-slot rows: a
  request whose prompt prefix is already resident borrows those pages
  read-only (refcounted) and starts prefill at the matched length; a
  prefix dying mid-page is copied on write into a fresh page at
  admission. Freed pages return to an LRU free-list; full prompt pages
  are filed in a radix index keyed on page-size token chunks so the
  NEXT turn of a session (or another session sharing the system prompt)
  hits them. PagedAttention (vLLM) + RadixAttention (SGLang),
  re-expressed as plain gather/scatter in the engine's
  two-XLA-program style.
- ``decode_slots_paged`` advances EVERY slot one token per call with
  per-slot positions; idle slots are parked past ``max_seq`` where
  their garbage writes are routed to the reserved scratch page.
- The fused program additionally runs one fixed-size prompt chunk in
  the same params read (chunked prefill), so a long prompt admission
  adds bounded latency to in-flight decodes.
- Sampling is fused into both programs and is DETERMINISTIC PER
  REQUEST: token q of a request is drawn with
  ``fold_in(PRNGKey(request_seed), q)``, so a prefix-hit admission
  (fewer prefill dispatches) produces bit-for-bit the same output as a
  cold one — only ``[num_slots]`` int32 tokens cross the device
  boundary per step, never ``[B, vocab]`` logits.

Exactly two compiled programs serve any mix of request lengths; there
is no shape-dependent recompilation after warmup.

Reference intent matched (and exceeded — the reference never touches
the accelerator): ``/root/reference/python/ray/serve/_private/replica.py``
request plane + ``/root/reference/python/ray/serve/batching.py``.
"""

from __future__ import annotations

import queue
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.exceptions import EngineStoppedError
from ..models import llama
from .paged import OverloadedError, PagePool, RadixIndex, llm_metrics

# Interned tag keys for the per-stage histogram (request finish path).
_LLM_STAGE_KEYS = {s: (("stage", s),) for s in
                   ("admission", "queue", "prefix_match", "prefill",
                    "decode")}


def _sample(logits, temps, seeds, qpos):
    """Greedy when temp == 0, else temperature sampling with a
    per-request deterministic stream: token index ``qpos`` of seed ``s``
    always draws from ``fold_in(PRNGKey(s), qpos)`` — independent of
    batching, decode blocking, or how much prefill a prefix hit
    skipped. [B,V] -> [B]."""
    greedy = jnp.argmax(logits, axis=-1)

    def one(lg, t, s, q):
        key = jax.random.fold_in(jax.random.PRNGKey(s), q)
        return jax.random.categorical(key, lg / jnp.maximum(t, 1e-6))

    sampled = jax.vmap(one)(logits, temps, seeds, qpos)
    return jnp.where(temps > 0, sampled, greedy).astype(jnp.int32)


@dataclass
class GenerationResult:
    tokens: List[int]
    prompt_len: int
    finish_reason: str  # "stop" (eos) | "length"
    # Flight-recorder stage breakdown (seconds): admission_s, queue_s,
    # prefix_match_s, prefill_s, decode_s, decode_per_token_s, total_s,
    # matched_tokens. None when the request errored before finishing.
    timing: Optional[dict] = None


class RequestHandle:
    """Thread-safe consumer side of one generation request.

    Iterating yields token ids as they are produced; ``result()`` blocks
    for the final :class:`GenerationResult`. ``on_token`` (if given at
    submit) is called from the engine thread instead — useful to bridge
    into an asyncio loop without a queue hop.
    """

    def __init__(self, prompt_len: int):
        self._q: "queue.Queue" = queue.Queue()
        self._tokens: List[int] = []
        self._prompt_len = prompt_len
        self._done = threading.Event()
        self._finish_reason = "length"
        self.error: Optional[BaseException] = None
        self.timing: Optional[dict] = None  # set by the engine at finish

    def __iter__(self):
        while True:
            item = self._q.get()
            if item is None:
                if self.error is not None:
                    raise self.error
                return
            yield item

    def result(self, timeout: Optional[float] = None) -> GenerationResult:
        if not self._done.wait(timeout):
            raise TimeoutError("generation did not finish in time")
        if self.error is not None:
            raise self.error
        return GenerationResult(tokens=list(self._tokens),
                                prompt_len=self._prompt_len,
                                finish_reason=self._finish_reason,
                                timing=self.timing)

    # engine-side
    def _emit(self, tok: int) -> None:
        self._tokens.append(tok)
        self._q.put(tok)

    def _finish(self, reason: str,
                error: Optional[BaseException] = None) -> None:
        self._finish_reason = reason
        self.error = error
        self._done.set()
        self._q.put(None)


@dataclass
class _Slot:
    handle: RequestHandle
    prompt: np.ndarray  # int32 [prompt_len]
    max_new: int
    temperature: float
    eos_id: Optional[int]
    on_token: Optional[Callable[[Optional[int]], None]]
    seed: int = 0  # per-request sampling stream
    # Chat-session identity: at request finish the engine records the
    # session's transcript so drain can export it (KV page migration)
    # and the crash path can re-prefill it elsewhere.
    session_id: Optional[str] = None
    # (trace_id, parent_span_id) propagated from the serve request; at
    # finish the stage stamps below become child spans on that trace.
    trace_ctx: Optional[tuple] = None
    submit_t: float = 0.0  # monotonic submit time (TTFT + queue timeout)
    # Flight-recorder stamps (monotonic) + measured prefix-match cost:
    # submit -> admit (queue wait) -> first prefill dispatch -> first
    # token -> finish decomposes the request's end-to-end latency.
    admit_t: float = 0.0
    prefill_start_t: float = 0.0
    first_tok_t: float = 0.0
    prefix_match_s: float = 0.0
    prefill_offset: int = 0  # next chunk start; == len(prompt) when done
    matched_len: int = 0  # prompt tokens whose prefill the radix skipped
    pos: int = 0  # write position of the NEXT decode step
    last_token: int = 0
    produced: int = 0
    # Physical pages in logical order; the first ``shared_pages`` are
    # borrowed read-only from the radix index (refcounted, never
    # written), the rest are exclusively owned until freed.
    pages: List[int] = field(default_factory=list)
    shared_pages: int = 0
    inserted: bool = False  # prompt pages filed in the radix index
    # True once this slot's current token lives on-device (row of the
    # previous decode block's `last` output) — its next block input
    # chains device-side with no host round trip.
    on_device_chain: bool = False
    # True between dispatching the FINAL prefill chunk and fetching its
    # sampled first token (lag-1 pipeline): the slot must not join the
    # decode batch until that token is known host-side.
    first_tok_pending: bool = False

    @property
    def prefill_done(self) -> bool:
        return self.prefill_offset >= len(self.prompt)


class SlotEngine:
    """Continuous-batching generation over a paged KV-cache pool."""

    # Serving rule table deltas over parallel.sharding.DEFAULT_RULES:
    # the page pool's heads axis is the KV-heads axis, which the default
    # (training) table leaves replicated — tp-sharded serving maps it to
    # tp so the KV pages (the decode bandwidth bill) split across chips.
    SERVE_RULES = {"kv": "tp"}

    def __init__(self, params, cfg: llama.LlamaConfig, num_slots: int = 8,
                 chunk: int = 64, seed: int = 0, decode_block: int = 1,
                 page_size: int = 16, num_pages: Optional[int] = None,
                 prefix_cache: bool = True,
                 max_pending: Optional[int] = None,
                 queue_timeout_s: Optional[float] = None,
                 max_sessions: int = 256,
                 mesh=None, rules=None):
        if cfg.max_seq % chunk != 0:
            raise ValueError(
                f"chunk ({chunk}) must divide max_seq ({cfg.max_seq}): "
                "a padded tail chunk would clamp past the cache end")
        if cfg.max_seq % page_size != 0:
            raise ValueError(
                f"page_size ({page_size}) must divide max_seq "
                f"({cfg.max_seq})")
        self.cfg = cfg
        self.num_slots = num_slots
        self.chunk = chunk
        self.page_size = page_size
        # decode_block K > 1 amortizes the host<->device round trip: ONE
        # program advances every slot K tokens (an in-program lax.scan
        # chaining sampled tokens device-side), and the host fetches a
        # block's tokens only AFTER dispatching the next block — on a
        # remote-tunneled TPU a fetch of a still-pending result costs
        # ~20x a fetch of a finished one, so the lag-1 pipeline keeps
        # fetches on the fast path. Cost: tokens stream in bursts of K
        # and EOS is noticed up to 2K-1 tokens late (the overshoot is
        # discarded; garbage K/V is overwritten before ever attended).
        self.decode_block = decode_block
        self.max_pending = max_pending
        self.queue_timeout_s = queue_timeout_s
        # Mesh-sharded serving (ROADMAP item 2): with a mesh, params
        # shard by their logical axes (heads/mlp/vocab over tp) and the
        # page pool's KV-heads axis shards over tp — each chip holds
        # 1/tp of the weights AND 1/tp of every KV page, so a model too
        # big for one chip's HBM serves from several. Without a mesh
        # every constraint no-ops and placement is plain device_put.
        self._mesh = mesh
        if mesh is not None:
            from ..parallel import sharding as shd

            sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
            tp = sizes.get("tp", 1)
            if tp > 1 and (cfg.num_kv_heads % tp or cfg.num_heads % tp
                           or cfg.d_mlp % tp or cfg.vocab_size % tp):
                raise ValueError(
                    f"tp={tp} must divide num_kv_heads "
                    f"({cfg.num_kv_heads}), num_heads ({cfg.num_heads}), "
                    f"d_mlp ({cfg.d_mlp}) and vocab ({cfg.vocab_size})")
            self._rules = shd.prune_rules_for_mesh(
                mesh, dict(self.SERVE_RULES, **(rules or {})))
            self._params = shd.place(mesh, params, llama.param_axes(),
                                     self._rules)
        else:
            self._rules = None
            self._params = jax.device_put(params)
        self._pages_per_seq = cfg.max_seq // page_size
        # Pool default: exactly the dense footprint (num_slots full
        # sequences) plus the single reserved scratch page — the old
        # dense layout burned a whole scratch ROW (max_seq worth of KV)
        # for idle prefill-lane parking; the scratch PAGE costs
        # 1/pages_per_seq of that. Larger pools leave headroom for the
        # radix index to keep evicted sessions' prefixes warm.
        self._num_pages = (num_pages if num_pages is not None
                           else num_slots * self._pages_per_seq + 1)
        self._pool = PagePool(self._num_pages)
        self._radix: Optional[RadixIndex] = (
            RadixIndex(self._pool, page_size) if prefix_cache else None)
        self._tables = np.zeros((num_slots, self._pages_per_seq),
                                dtype=np.int32)
        self._cache = llama.init_paged_kv_cache(cfg, self._num_pages,
                                                page_size)
        if mesh is not None:
            from jax.sharding import NamedSharding

            from ..parallel import sharding as shd

            kv_sharding = NamedSharding(
                mesh, shd.spec_for(llama.PAGED_KV_AXES, self._rules))
            self._cache = jax.tree.map(
                lambda x: jax.device_put(x, kv_sharding), self._cache)
        self._base_seed = seed
        self._req_counter = 0
        ps = page_size
        rules_ = self._rules

        def block_fn(params, cache, tables, override_vals, override_mask,
                     prev_last, pos, temps, seeds,
                     pre_tokens, pre_slot, pre_p0, pre_n_valid,
                     pre_temp, pre_seed):
            """K-token decode block with the prefill lane fused into the
            FIRST step (decode_slots_with_prefill_paged): a prompt chunk
            rides the same params read as the decode batch, so prefill
            no longer costs a separate full-model pass."""
            tokens0 = jnp.where(override_mask, override_vals, prev_last)
            dec_logits, pre_logits, cache = \
                llama.decode_slots_with_prefill_paged(
                    params, cache, tables, tokens0, pos, pre_tokens,
                    pre_slot, pre_p0, pre_n_valid, cfg, ps,
                    rules=rules_)
            tok1 = _sample(dec_logits, temps, seeds, pos + 1)
            pre_tok = _sample(pre_logits[None], pre_temp[None],
                              pre_seed[None],
                              (pre_p0 + pre_n_valid)[None])[0]

            def body(carry, _):
                toks, cache, p = carry
                logits, cache = llama.decode_slots_paged(
                    params, cache, tables, toks, p, cfg, ps,
                    rules=rules_)
                nxt = _sample(logits, temps, seeds, p + 1)
                return (nxt, cache, p + 1), nxt

            (last, cache, _), toks_rest = jax.lax.scan(
                body, (tok1, cache, pos + 1), None,
                length=decode_block - 1)
            toks_k = jnp.concatenate([tok1[None], toks_rest], axis=0)
            return toks_k, last, pre_tok, cache

        def decode_only_fn(params, cache, tables, override_vals,
                           override_mask, prev_last, pos, temps, seeds):
            """Pure K-step decode block — dispatched whenever no prompt
            chunk is pending, so idle steps never pay the fused
            program's C-token prefill lane."""
            tokens0 = jnp.where(override_mask, override_vals, prev_last)

            def body(carry, _):
                toks, cache, p = carry
                logits, cache = llama.decode_slots_paged(
                    params, cache, tables, toks, p, cfg, ps,
                    rules=rules_)
                nxt = _sample(logits, temps, seeds, p + 1)
                return (nxt, cache, p + 1), nxt

            (last, cache, _), toks_k = jax.lax.scan(
                body, (tokens0, cache, pos), None, length=decode_block)
            return toks_k, last, cache

        # The cache is donated: XLA updates it in place, so a decode
        # step never copies the (potentially multi-GB) KV pages. Under
        # a mesh, every compiled-program call is wrapped so constrain()
        # resolves (ambient mesh + current-mesh global); the in-kernel
        # constraints pin the output cache to the input's sharding, so
        # donation stays an in-place aliasing across steps.
        def _maybe_mesh(fn):
            if mesh is None:
                return fn
            from ..parallel.sharding import under_mesh

            return under_mesh(mesh, fn)

        self._block = _maybe_mesh(jax.jit(block_fn, donate_argnums=(1,)))
        self._decode_only = _maybe_mesh(
            jax.jit(decode_only_fn, donate_argnums=(1,)))
        self._copy_pages = _maybe_mesh(
            jax.jit(llama.copy_pages, donate_argnums=(0,)))
        # Session import (page migration): compiled lazily on first use
        # from the engine thread's control-op slot, where no concurrent
        # dispatch can be touching the donated cache.
        self._write_pages = _maybe_mesh(
            jax.jit(llama.write_pages, donate_argnums=(0,)))
        # Pre-compile the COW page-copy program NOW, while no engine
        # thread can be touching the (donated) cache: the first partial
        # prefix hit must not stall on a compile, and compiling from
        # warmup() would race a running engine thread's dispatches.
        zero = jnp.zeros((1,), jnp.int32)
        self._cache = self._copy_pages(self._cache, zero, zero)
        # Decode-step roofline profiler (flight recorder, LLM path): a
        # decode step is memory-bound — it must stream the params plus
        # every resident KV page through HBM once. Model footprint is
        # measured from the actual pytrees; achieved bytes/s over the
        # configured peak bandwidth is rt_llm_roofline_frac.
        self._param_bytes = sum(
            x.size * x.dtype.itemsize
            for x in jax.tree_util.tree_leaves(self._params))
        cache_bytes = sum(x.size * x.dtype.itemsize
                          for x in jax.tree_util.tree_leaves(self._cache))
        self._kv_page_bytes = cache_bytes // max(1, self._num_pages)
        self._prof_steps = 0
        self._prof_wall = 0.0
        self._prof_bytes = 0.0
        self._prof_t0: Optional[float] = None
        # lag-1 decode pipeline state
        self._inflight = None  # (snapshot, pre_info, toks_k, pre_tok)
        self._last_dev = jnp.zeros((num_slots,), jnp.int32)

        self._slots: List[Optional[_Slot]] = [None] * num_slots
        self._pending: deque = deque()
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        # Resident chat sessions (LRU-bounded): session_id ->
        # {transcript, seed, temperature, t}. The KV pages themselves
        # live in the radix index; this is the metadata that lets
        # export_session find them and the crash path re-prefill.
        self.max_sessions = max_sessions
        self._sessions: "OrderedDict[str, dict]" = OrderedDict()
        # Control ops (export/import/...) run ON THE ENGINE THREAD at a
        # step boundary: the cache is donated to compiled programs and
        # mutated by the dispatch path outside the lock, so another
        # thread must never touch it directly.
        self._control: deque = deque()
        # counters (observability / autoscaling signals)
        self.tokens_generated = 0
        self.requests_completed = 0
        self.requests_shed = 0
        self.prefix_hits = 0
        self.prefix_misses = 0
        self.prefix_tokens_saved = 0

    # -- public API --------------------------------------------------------

    def submit(self, prompt: Sequence[int], max_new: int = 64,
               temperature: float = 0.0, eos_id: Optional[int] = None,
               on_token: Optional[Callable[[Optional[int]], None]] = None,
               seed: Optional[int] = None,
               session_id: Optional[str] = None,
               trace_ctx: Optional[tuple] = None) -> RequestHandle:
        prompt = np.asarray(prompt, dtype=np.int32)
        if prompt.ndim != 1 or len(prompt) == 0:
            raise ValueError("prompt must be a non-empty 1D token list")
        if len(prompt) + max_new > self.cfg.max_seq:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new ({max_new}) exceeds "
                f"max_seq ({self.cfg.max_seq})")
        n_total = -(-(len(prompt) + max_new) // self.page_size)
        if n_total > self._num_pages - 1:
            # Admission reserves the worst-case footprint; a request the
            # pool can never cover would head-of-line block the FIFO
            # queue forever. Reject it at the door instead.
            raise ValueError(
                f"request needs {n_total} KV pages but the pool only "
                f"has {self._num_pages - 1} allocatable")
        if trace_ctx is None:
            # Direct submits (no serve hop) still join a caller's trace
            # when one is open on this thread / task.
            from ..observability import tracing

            trace_ctx = tracing.inject_context()
        handle = RequestHandle(len(prompt))
        slot = _Slot(handle=handle, prompt=prompt, max_new=max_new,
                     temperature=float(temperature), eos_id=eos_id,
                     on_token=on_token, submit_t=time.monotonic(),
                     session_id=session_id, trace_ctx=trace_ctx)
        with self._work:
            if (self.max_pending is not None
                    and len(self._pending) >= self.max_pending):
                self.requests_shed += 1
                raise OverloadedError(
                    f"engine overloaded: {len(self._pending)} requests "
                    f"pending (max_pending={self.max_pending})")
            self._req_counter += 1
            # Masked to int32 range either way: the seed rides a
            # np.int32 vector into the compiled program, and an
            # out-of-range user seed must not OverflowError the engine
            # thread (which would fail every tenant's request).
            slot.seed = (int(seed) if seed is not None else
                         self._base_seed * 1000003
                         + self._req_counter) & 0x7FFFFFFF
            self._pending.append(slot)
            self._work.notify()
        return handle

    def start(self) -> "SlotEngine":
        if self._thread is None:
            self._thread = threading.Thread(target=self._run,
                                            name="llm-engine", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        with self._work:
            self._stop = True
            self._work.notify()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        # Whether or not a thread ever ran (or won the race to drain),
        # no caller may be left hanging: flush queued control ops and
        # fail any still-registered request with the typed error.
        with self._lock:
            self._drain_control_locked()
            self._fail_all_locked(EngineStoppedError("engine stopped"))

    def warmup(self) -> None:
        """Compile both programs before serving traffic. Safe to call
        whether or not the engine thread is running."""
        h = self.submit([1, 2, 3], max_new=2)
        if self._thread is not None:
            h.result(timeout=600)
            return
        while not h._done.is_set():
            if not self.step():
                break
        h.result(timeout=0)

    # -- paged-pool introspection -----------------------------------------

    @property
    def pages_total(self) -> int:
        return self._pool.num_pages

    @property
    def pages_used(self) -> int:
        return self._pool.used_count

    @property
    def pages_free(self) -> int:
        return self._pool.free_count

    def prefix_cache_len(self) -> int:
        return 0 if self._radix is None else len(self._radix)

    def clear_prefix_cache(self) -> int:
        """Drop every radix entry (and the pages only it held). Returns
        pages freed; used for cold-run benching and tests."""
        with self._lock:
            freed = 0 if self._radix is None else self._radix.clear()
            self._publish_page_gauges()
            return freed

    def _publish_page_gauges(self) -> None:
        m = llm_metrics()
        if m is not None:
            m["pages_used"].set(float(self._pool.used_count))
            m["pages_free"].set(float(self._pool.free_count))

    # -- stateful sessions (migration & drain) -----------------------------

    def sessions(self) -> List[str]:
        """Resident session ids (insertion/LRU order, oldest first)."""
        with self._lock:
            return list(self._sessions.keys())

    @property
    def session_count(self) -> int:
        with self._lock:
            return len(self._sessions)

    def _record_session_locked(self, session_id: str, transcript,
                               seed, temperature: float) -> None:
        self._sessions[session_id] = {
            "transcript": np.asarray(transcript, dtype=np.int32),
            "seed": int(seed or 0) & 0x7FFFFFFF,
            "temperature": float(temperature),
            "t": time.monotonic(),
        }
        self._sessions.move_to_end(session_id)
        while len(self._sessions) > self.max_sessions:
            self._sessions.popitem(last=False)
        m = llm_metrics()
        if m is not None:
            m["sessions_resident"].set(float(len(self._sessions)))

    def _run_control(self, fn, timeout: float = 60.0):
        """Run ``fn`` under the engine lock ON THE ENGINE THREAD at a
        step boundary. The KV cache is donated to the compiled programs
        and reassigned by the dispatch path OUTSIDE the lock, so a
        foreign thread must never read or write it directly; with no
        engine thread running the caller becomes the executor."""
        thread = self._thread
        if (thread is None or not thread.is_alive()
                or thread is threading.current_thread()):
            with self._lock:
                return fn()
        box: dict = {}
        done = threading.Event()

        def op():
            try:
                box["result"] = fn()
            except BaseException as e:  # noqa: BLE001 — relayed below
                box["error"] = e
            finally:
                done.set()

        with self._work:
            self._control.append(op)
            self._work.notify()
        if not done.wait(timeout):
            raise TimeoutError("engine control op timed out")
        if "error" in box:
            raise box["error"]
        return box.get("result")

    def export_session(self, session_id: str) -> dict:
        """Snapshot a session between decode steps: transcript, sampling
        seed, and the radix-resident KV pages covering its prefix packed
        page-major into ONE contiguous frame — shipped zero-copy by the
        object plane (``put_frame`` lays out-of-band buffers 64B-aligned
        in the frame). Raises KeyError for an unknown session and
        RuntimeError while the session has a generation in flight."""
        return self._run_control(
            lambda: self._export_session_locked(session_id))

    def _export_session_locked(self, session_id: str) -> dict:
        sess = self._sessions.get(session_id)
        if sess is None:
            raise KeyError(f"unknown session {session_id!r}")
        live = [s for s in self._slots if s is not None]
        for s in list(self._pending) + live:
            if s.session_id == session_id:
                raise RuntimeError(
                    f"session {session_id!r} has a generation in flight")
        transcript = sess["transcript"]
        pages: List[int] = []
        if self._radix is not None:
            pages, _ = self._radix.match(transcript)
        frames = None
        if pages:
            idx = np.asarray(pages, dtype=np.int32)
            # Device gather -> host; pages stay index-owned (we hold
            # the lock, so no concurrent eviction can free them).
            frames = np.ascontiguousarray(
                np.asarray(self._cache["kv"][:, :, idx]))
        m = llm_metrics()
        if m is not None:
            m["session_migrations"].inc(tags={"result": "export"})
        return {
            "session_id": session_id,
            "transcript": np.asarray(transcript, dtype=np.int32),
            "seed": sess["seed"],
            "temperature": sess["temperature"],
            "page_size": self.page_size,
            "covered_tokens": len(pages) * self.page_size,
            "pages_kv": frames,
        }

    def import_session(self, snapshot: dict) -> dict:
        """Rebuild an exported session on THIS engine: prefix chunks
        already present in the local radix index are re-matched (COW
        borrow — never shipped twice), the rest are scattered into
        freshly allocated pages and filed in the index. Runs out of
        pool room -> partial import (the uncovered tail simply
        re-prefills on the session's next turn)."""
        return self._run_control(
            lambda: self._import_session_locked(dict(snapshot)))

    def _import_session_locked(self, snap: dict) -> dict:
        ps = self.page_size
        m = llm_metrics()
        try:
            if int(snap["page_size"]) != ps:
                raise ValueError(
                    f"page_size mismatch: snapshot {snap['page_size']} "
                    f"vs engine {ps}")
            transcript = np.asarray(snap["transcript"], dtype=np.int32)
            frames = snap.get("pages_kv")
            n_chunks = int(snap.get("covered_tokens", 0)) // ps
            matched: List[int] = []
            fresh: List[int] = []
            if (self._radix is not None and n_chunks > 0
                    and frames is not None):
                kv_shape = self._cache["kv"].shape
                if (tuple(frames.shape[:2]) != tuple(kv_shape[:2])
                        or tuple(frames.shape[3:]) != tuple(kv_shape[3:])):
                    raise ValueError(
                        f"KV frame shape {frames.shape} does not match "
                        f"cache {kv_shape}")
                matched, _ = self._radix.match(transcript[:n_chunks * ps])
                need = n_chunks - len(matched)
                if need > 0 and self._pool.free_count < need:
                    self._radix.evict(need - self._pool.free_count)
                fresh = [self._pool.alloc() for _ in
                         range(min(max(0, need), self._pool.free_count))]
                if fresh:
                    have = len(matched)
                    self._write_frames_locked(
                        fresh, frames[:, :, have:have + len(fresh)])
                pages = matched + fresh
                if pages:
                    self._radix.insert(transcript[:len(pages) * ps],
                                       pages)
                # insert() took the index's own refs on NEW nodes; drop
                # our allocation refs so the index is the sole owner
                # and normal LRU eviction applies.
                for pg in fresh:
                    self._pool.unref(pg)
                self._publish_page_gauges()
            self._record_session_locked(
                snap["session_id"], transcript, snap.get("seed", 0),
                snap.get("temperature", 0.0))
        except Exception:
            if m is not None:
                m["session_migrations"].inc(tags={"result": "error"})
            raise
        if m is not None:
            m["session_migrations"].inc(tags={"result": "import"})
        return {"session_id": snap["session_id"],
                "pages_imported": len(fresh),
                "pages_matched": len(matched),
                "tokens_resident": (len(matched) + len(fresh)) * ps}

    def _write_frames_locked(self, pages: List[int], frames) -> None:
        """Scatter host KV frames into device pages. N is padded to the
        next power of two — padding rows aim at the reserved scratch
        page 0, which absorbs them — so repeated imports compile at
        most O(log pool) program variants."""
        n = len(pages)
        bucket = 1
        while bucket < n:
            bucket *= 2
        dst = np.zeros((bucket,), dtype=np.int32)
        dst[:n] = pages
        vals = np.zeros(frames.shape[:2] + (bucket,) + frames.shape[3:],
                        dtype=frames.dtype)
        vals[:, :, :n] = frames[:, :, :n]
        self._cache = self._write_pages(self._cache, jnp.asarray(dst),
                                        jnp.asarray(vals))

    def prefill_session(self, session_id: str, transcript,
                        seed=None, temperature: float = 0.0,
                        timeout: float = 120.0) -> dict:
        """Crash-path recovery: rebuild a session the cheap-but-correct
        way by re-prefilling its transcript (radix hit -> near no-op,
        cold -> one full prefill). The single sampled token is
        discarded; the transcript's pages land in the radix index so
        the session's next turn admits warm. Publishes
        ``rt_llm_session_recovery_seconds``."""
        t0 = time.monotonic()
        toks = np.asarray(transcript, dtype=np.int32)
        if toks.ndim != 1 or len(toks) == 0:
            raise ValueError("transcript must be a non-empty token list")
        toks = toks[:self.cfg.max_seq - 1]
        h = self.submit(toks, max_new=1,
                        seed=None if seed is None else int(seed))
        if self._thread is not None and self._thread.is_alive():
            h.result(timeout=timeout)
        else:
            while not h._done.is_set():
                if not self.step():
                    break
        res = h.result(timeout=0)
        with self._lock:
            self._record_session_locked(
                session_id, np.asarray(transcript, dtype=np.int32),
                seed, temperature)
        dt = time.monotonic() - t0
        m = llm_metrics()
        if m is not None:
            m["session_recovery"].observe(dt)
        return {"session_id": session_id, "seconds": dt,
                "matched_tokens": (res.timing or {}).get(
                    "matched_tokens", 0),
                "transcript_len": int(len(toks))}

    # -- engine loop -------------------------------------------------------

    def _run(self) -> None:
        while True:
            with self._work:
                while not self._stop and not self._has_work_locked():
                    self._work.wait()
                if self._stop:
                    self._drain_control_locked()
                    self._fail_all_locked(
                        EngineStoppedError("engine stopped"))
                    return
            try:
                self.step()
            except Exception as e:  # noqa: BLE001 — device fault is fatal
                with self._work:
                    self._drain_control_locked()
                    self._fail_all_locked(e)
                return

    def _has_work_locked(self) -> bool:
        return (bool(self._pending) or self._inflight is not None
                or bool(self._control)
                or any(s is not None for s in self._slots))

    def _drain_control_locked(self) -> None:
        # Control-op wrappers trap their own exceptions into the
        # caller's result box, so draining never throws.
        while self._control:
            self._control.popleft()()

    def _release_slot_pages_locked(self, s: _Slot) -> None:
        for pg in s.pages:
            self._pool.unref(pg)
        s.pages = []
        s.shared_pages = 0

    def _fail_all_locked(self, err: BaseException) -> None:
        self._inflight = None
        for i, s in enumerate(self._slots):
            if s is not None:
                self._release_slot_pages_locked(s)
                self._tables[i] = 0
                s.handle._finish("error", err)
                if s.on_token:
                    s.on_token(None)
                self._slots[i] = None
        while self._pending:
            s = self._pending.popleft()
            s.handle._finish("error", err)
            if s.on_token:
                s.on_token(None)
        self._publish_page_gauges()

    # -- admission (paged + radix match) -----------------------------------

    def _shed_expired_locked(self) -> None:
        if self.queue_timeout_s is None:
            return
        now = time.monotonic()
        while self._pending and (now - self._pending[0].submit_t
                                 > self.queue_timeout_s):
            s = self._pending.popleft()
            self.requests_shed += 1
            s.handle._finish("error", OverloadedError(
                f"engine overloaded: request queued longer than "
                f"queue_timeout_s={self.queue_timeout_s}"))
            if s.on_token:
                s.on_token(None)

    def _admit_locked(self, idx: int, s: _Slot) -> bool:
        """Install a pending request into slot ``idx``: radix-match its
        prompt, borrow the matched pages read-only, COW-copy a partial
        tail page, and eagerly allocate the rest of its worst-case
        footprint (prompt + max_new). Returns False — leaving the
        request pending, FIFO order preserved — when even after LRU
        eviction the pool cannot cover it."""
        ps = self.page_size
        n_total = -(-(len(s.prompt) + s.max_new) // ps)
        full_pages: List[int] = []
        partial = None
        if self._radix is not None:
            match_t0 = time.monotonic()
            full_pages, partial = self._radix.match(s.prompt)
            s.prefix_match_s = time.monotonic() - match_t0
            # The engine needs the LAST prompt token's logits to sample
            # the first output, so at least one prompt token must
            # prefill: cap the match at len(prompt) - 1.
            while len(full_pages) * ps >= len(s.prompt):
                full_pages.pop()
                partial = None
            if partial is not None:
                cap = len(s.prompt) - 1 - len(full_pages) * ps
                if min(partial[1], cap) <= 0:
                    partial = None
                else:
                    partial = (partial[0], min(partial[1], cap))
        # Borrow refs BEFORE any eviction so the matched nodes stop
        # being eviction candidates (their refcount leaves 1).
        for pg in full_pages:
            self._pool.ref(pg)
        if partial is not None:
            self._pool.ref(partial[0])
        n_fresh = n_total - len(full_pages)
        if self._pool.free_count < n_fresh and self._radix is not None:
            self._radix.evict(n_fresh - self._pool.free_count)
        if self._pool.free_count < n_fresh and partial is not None:
            # A full-page borrow is feasibility-neutral (it pins one
            # page but also saves one fresh page), but the partial
            # borrow pins its source WITHOUT reducing n_fresh — the COW
            # copy lands in a fresh page. For a request whose footprint
            # needs the whole pool that pin makes admission impossible
            # forever (the pinned page can never be evicted), so drop
            # the partial match and retry before giving up.
            self._pool.unref(partial[0])
            partial = None
            if self._radix is not None:
                self._radix.evict(n_fresh - self._pool.free_count)
        if self._pool.free_count < n_fresh:
            for pg in full_pages:  # rollback the borrow; stay pending
                self._pool.unref(pg)
            if partial is not None:
                self._pool.unref(partial[0])
            return False
        fresh = [self._pool.alloc() for _ in range(n_fresh)]
        s.pages = full_pages + fresh
        s.shared_pages = len(full_pages)
        s.matched_len = len(full_pages) * ps
        if partial is not None:
            # Copy-on-write: the borrowed page's first n tokens are
            # reused, but this slot will write the rest of that page —
            # device-copy it into the slot's own fresh page, then drop
            # the temporary borrow ref.
            src, n_tok = partial
            dst = fresh[0]
            self._cache = self._copy_pages(
                self._cache, jnp.asarray([src], jnp.int32),
                jnp.asarray([dst], jnp.int32))
            self._pool.unref(src)
            s.matched_len += n_tok
        self._tables[idx, :n_total] = s.pages
        self._tables[idx, n_total:] = 0
        s.prefill_offset = s.matched_len
        s.pos = 0
        hit = s.matched_len > 0
        if hit:
            self.prefix_hits += 1
            self.prefix_tokens_saved += s.matched_len
        else:
            self.prefix_misses += 1
        m = llm_metrics()
        if m is not None:
            m["prefix"].inc(tags={"result": "hit" if hit else "miss"})
            if hit:
                m["prefix_tokens"].inc(s.matched_len)
        self._publish_page_gauges()
        s.admit_t = time.monotonic()
        self._slots[idx] = s
        return True

    def step(self) -> bool:
        """One scheduler iteration: admit, dispatch a fused
        decode+prefill block, then fetch the PREVIOUS block's tokens
        (ready by now — lag-1 pipelining). Returns True if any work
        ran."""
        ran_control = False
        with self._lock:
            # Session export/import and friends run HERE, between
            # decode steps: the previous block's cache assignment is
            # complete and the next dispatch hasn't consumed it.
            while self._control:
                self._control.popleft()()
                ran_control = True
            self._shed_expired_locked()
            for i in range(self.num_slots):
                if self._slots[i] is None and self._pending:
                    if not self._admit_locked(i, self._pending[0]):
                        break  # pool exhausted; FIFO order preserved
                    self._pending.popleft()
            prefill_idx = next(
                (i for i, s in enumerate(self._slots)
                 if s is not None and not s.prefill_done), None)
            active = [(i, s) for i, s in enumerate(self._slots)
                      if s is not None and s.prefill_done
                      and not s.first_tok_pending]
        ran = ran_control
        had_fetch = self._inflight is not None
        new_block = (self._dispatch_block(active, prefill_idx)
                     if (active or prefill_idx is not None) else None)
        if had_fetch:
            self._process_fetch()
            ran = True
        if new_block is not None:
            self._inflight = new_block
            ran = True
        # Roofline accounting: only steady pipeline intervals count —
        # a step that both dispatched a block with active decode slots
        # AND fetched the previous one spans exactly decode_block
        # device steps; anything else (admission-only, pipeline fill or
        # drain, idle) would pollute the bytes/s estimate.
        if new_block is not None and had_fetch and active:
            now = time.monotonic()
            if self._prof_t0 is not None:
                steps = self.decode_block
                self._prof_wall += now - self._prof_t0
                self._prof_steps += steps
                self._prof_bytes += steps * (
                    self._param_bytes
                    + self._pool.used_count * self._kv_page_bytes)
            self._prof_t0 = now
        else:
            self._prof_t0 = None
        return ran

    def _dispatch_block(self, active, prefill_idx):
        """Dispatch one K-step block: every active slot decodes K
        tokens and (when a slot is mid-prompt) ONE prefill chunk rides
        the first step's fused program. Continuing slots chain their
        input token device-side; freshly prefilled slots inject theirs
        via the override vector."""
        cfg = self.cfg
        rows = self.num_slots
        override_vals = np.zeros((rows,), dtype=np.int32)
        override_mask = np.ones((rows,), dtype=bool)
        # Parked rows sit AT max_seq: the paged scatter routes any write
        # at pos >= max_seq to the scratch page, so a parked row can
        # never touch a live (possibly shared) page.
        pos = np.full((rows,), cfg.max_seq, dtype=np.int32)
        temps = np.zeros((rows,), dtype=np.float32)
        seeds = np.zeros((rows,), dtype=np.int32)
        for i, s in active:
            pos[i] = s.pos
            temps[i] = s.temperature
            seeds[i] = s.seed
            if s.on_device_chain:
                override_mask[i] = False
            else:
                override_vals[i] = s.last_token
        tables = jnp.asarray(self._tables)
        if prefill_idx is None:
            # No prompt chunk pending: the cheap pure-decode program.
            toks_k, self._last_dev, self._cache = self._decode_only(
                self._params, self._cache, tables,
                jnp.asarray(override_vals), jnp.asarray(override_mask),
                self._last_dev, jnp.asarray(pos), jnp.asarray(temps),
                jnp.asarray(seeds))
            for i, s in active:
                s.pos += self.decode_block
                s.on_device_chain = True
            return (list(active), None, toks_k, None)
        # Prefill lane: one chunk of one slot's prompt rides the fused
        # program's first step.
        pre_buf = np.zeros((self.chunk,), dtype=np.int32)
        s = self._slots[prefill_idx]
        if s.prefill_start_t == 0.0:
            s.prefill_start_t = time.monotonic()
        p0 = s.prefill_offset
        piece = s.prompt[p0:p0 + self.chunk]
        n_valid = len(piece)
        pre_buf[:n_valid] = piece
        s.prefill_offset = p0 + n_valid
        final = s.prefill_done
        if final:
            s.first_tok_pending = True
        pre_info = (prefill_idx, s, final)
        toks_k, self._last_dev, pre_tok, self._cache = self._block(
            self._params, self._cache, tables,
            jnp.asarray(override_vals), jnp.asarray(override_mask),
            self._last_dev, jnp.asarray(pos), jnp.asarray(temps),
            jnp.asarray(seeds),
            jnp.asarray(pre_buf), jnp.asarray(prefill_idx, jnp.int32),
            jnp.asarray(p0, jnp.int32), jnp.asarray(n_valid, jnp.int32),
            jnp.asarray(s.temperature, jnp.float32),
            jnp.asarray(s.seed, jnp.int32))
        for i, s in active:
            s.pos += self.decode_block
            s.on_device_chain = True
        return (list(active), pre_info, toks_k, pre_tok)

    def _process_fetch(self) -> None:
        snapshot, pre_info, toks_k, pre_tok = self._inflight
        self._inflight = None
        arr = np.asarray(toks_k)  # [K, rows]; ready -> fast fetch
        for idx, s in snapshot:
            if self._slots[idx] is not s:
                continue  # finished in an earlier block; rows are garbage
            for k in range(arr.shape[0]):
                self._deliver(idx, s, int(arr[k, idx]))
                if self._slots[idx] is not s:
                    break  # eos / length hit mid-block; drop overshoot
        if pre_info is not None:
            idx, s, final = pre_info
            if final and self._slots[idx] is s:
                # Prefill complete: file the prompt's fully-covered
                # pages in the radix index NOW (not at request end), so
                # a concurrent same-prefix admission already hits them.
                if self._radix is not None and not s.inserted:
                    with self._lock:
                        self._radix.insert(
                            s.prompt, s.pages[:len(s.prompt)
                                              // self.page_size])
                    s.inserted = True
                # The prompt's sampled first token arrives with the
                # block fetch; the slot joins the decode batch next
                # dispatch (override lane — the token is host-side).
                s.first_tok_pending = False
                s.pos = len(s.prompt)
                s.on_device_chain = False
                self._deliver(idx, s, int(pre_tok))

    def _request_timing(self, s: _Slot) -> dict:
        """Stage decomposition of one finished request. admission =
        waiting in the pending FIFO for a slot + pages; queue = admitted
        but not yet in the prefill lane; prefill = first chunk dispatch
        to first token; decode = the rest. Sums to ~total by
        construction (clamps only absorb clock jitter)."""
        end = time.monotonic()
        admit = s.admit_t or s.submit_t
        pre0 = s.prefill_start_t or admit
        first = s.first_tok_t or end
        timing = {
            "admission_s": max(0.0, admit - s.submit_t),
            "queue_s": max(0.0, pre0 - admit),
            "prefix_match_s": s.prefix_match_s,
            "prefill_s": max(0.0, first - pre0),
            "decode_s": max(0.0, end - first),
            "decode_per_token_s": (max(0.0, end - first)
                                   / max(1, s.produced - 1)),
            "total_s": max(0.0, end - s.submit_t),
            "matched_tokens": s.matched_len,
            "produced_tokens": s.produced,
        }
        m = llm_metrics()
        if m is not None:
            st = m["stage"]
            st.observe_key(_LLM_STAGE_KEYS["admission"],
                           timing["admission_s"])
            st.observe_key(_LLM_STAGE_KEYS["queue"], timing["queue_s"])
            st.observe_key(_LLM_STAGE_KEYS["prefix_match"],
                           timing["prefix_match_s"])
            st.observe_key(_LLM_STAGE_KEYS["prefill"],
                           timing["prefill_s"])
            st.observe_key(_LLM_STAGE_KEYS["decode"], timing["decode_s"])
            m["decode_per_token"].observe(timing["decode_per_token_s"])
        return timing

    def _emit_trace_spans(self, s: _Slot, timing: dict) -> None:
        """Turn the finished request's `timing` stage breakdown into
        child spans on its propagated trace: an ``llm.request`` span
        parented to the serve request, with admission/queue/prefix_match/
        prefill/decode children laid out from the SAME durations the
        timing dict reports (so span tree and `timing` metadata agree by
        construction). Stamps are monotonic; the wall offset lines them
        up with proxy/replica spans within clock-sampling noise."""
        from ..observability import tracing

        if not tracing.get_tracer().enabled:
            return
        off = time.time() - time.monotonic()
        t0 = s.submit_t + off
        trace_id, parent = s.trace_ctx
        root = tracing.record_span(
            "llm.request", trace_id=trace_id, parent_id=parent,
            start_s=t0, end_s=t0 + timing["total_s"],
            prompt_len=int(len(s.prompt)), produced=int(s.produced),
            matched_tokens=int(s.matched_len))
        if root is None:
            return
        cur = t0
        for stage in ("admission", "queue", "prefill", "decode"):
            dur = timing[f"{stage}_s"]
            tracing.record_span(f"llm.{stage}", trace_id=trace_id,
                                parent_id=root.span_id, start_s=cur,
                                end_s=cur + dur)
            cur += dur
        if timing["prefix_match_s"] > 0.0:
            # Overlaps the queue->prefill boundary (the match runs at
            # admission into the prefill lane); rendered as its own
            # child rather than folded into either stage.
            match_t0 = t0 + timing["admission_s"] + timing["queue_s"]
            tracing.record_span("llm.prefix_match", trace_id=trace_id,
                                parent_id=root.span_id, start_s=match_t0,
                                end_s=match_t0 + timing["prefix_match_s"])

    def reset_decode_profile(self) -> None:
        """Zero the roofline window. Successive bench stages call this
        between phases so each measures its OWN steady-state interval —
        without it, a long-gen stage inherits the warmup/prefill
        stage's lag-1 state and pollutes its bytes/s estimate."""
        self._prof_steps = 0
        self._prof_wall = 0.0
        self._prof_bytes = 0.0
        self._prof_t0 = None

    def decode_profile(self) -> dict:
        """Achieved-vs-peak HBM accounting for the decode loop
        (ROADMAP item 2's ``roofline_frac``). Publishes the
        ``rt_llm_roofline_frac`` / ``rt_llm_decode_steps_per_s``
        gauges as a side effect. The roof scales with the mesh size:
        a tp-sharded pool streams 1/n of the bytes per chip, so the
        aggregate peak is n chips' bandwidth."""
        from ..core.config import config

        steps, wall = self._prof_steps, self._prof_wall
        hbm_gbps = float(config().hbm_bandwidth_gbps)
        devices = 1 if self._mesh is None else int(self._mesh.devices.size)
        peak_gbps = hbm_gbps * devices
        if steps == 0 or wall <= 0.0:
            prof = {"steps": 0, "wall_s": 0.0, "avg_step_ms": 0.0,
                    "steps_per_s": 0.0, "bytes_per_step": 0,
                    "achieved_gbps": 0.0, "hbm_gbps": hbm_gbps,
                    "devices": devices, "roofline_frac": 0.0}
        else:
            achieved_gbps = self._prof_bytes / wall / 1e9
            prof = {
                "steps": steps,
                "wall_s": round(wall, 6),
                "avg_step_ms": round(wall / steps * 1e3, 4),
                "steps_per_s": round(steps / wall, 2),
                "bytes_per_step": int(self._prof_bytes / steps),
                "achieved_gbps": round(achieved_gbps, 4),
                "hbm_gbps": hbm_gbps,
                "devices": devices,
                # Guarded: hbm_bandwidth_gbps <= 0 (unknown hardware /
                # disabled roof) must degrade to frac 0.0, never
                # ZeroDivisionError the engine's stats path.
                "roofline_frac": (achieved_gbps / peak_gbps
                                  if peak_gbps > 0 else 0.0),
            }
        # Publish only MEASURED windows: an idle engine's stats() call
        # (zero steps since the last reset) would ship a 0.0 gauge that
        # overwrites another process's live roofline on the head —
        # last-writer-wins gauge merge — so the scrape-time value raced
        # with whichever engine happened to flush last. The gauges read
        # as "last measured decode window" cluster-wide.
        m = llm_metrics()
        if m is not None and steps > 0:
            m["roofline_frac"].set(prof["roofline_frac"])
            m["decode_steps"].set(prof["steps_per_s"])
        return prof

    def _deliver(self, idx: int, s: _Slot, tok: int) -> None:
        s.last_token = tok
        s.produced += 1
        self.tokens_generated += 1
        m = llm_metrics()
        if m is not None:
            m["tokens"].inc(1.0)
        if s.produced == 1:
            s.first_tok_t = time.monotonic()
            if m is not None:
                m["ttft"].observe(s.first_tok_t - s.submit_t)
        s.handle._emit(tok)
        if s.on_token:
            s.on_token(tok)
        hit_eos = s.eos_id is not None and tok == s.eos_id
        out_of_room = (len(s.prompt) + s.produced) >= self.cfg.max_seq
        if hit_eos or s.produced >= s.max_new or out_of_room:
            s.handle.timing = self._request_timing(s)
            if s.trace_ctx is not None:
                self._emit_trace_spans(s, s.handle.timing)
            s.handle._finish("stop" if hit_eos else "length")
            if s.on_token:
                s.on_token(None)
            self.requests_completed += 1
            with self._lock:
                if s.session_id is not None:
                    # Transcript = prompt + everything produced: the
                    # session's next turn (or its migration target)
                    # reconstructs from exactly this token list.
                    self._record_session_locked(
                        s.session_id,
                        np.concatenate([
                            s.prompt,
                            np.asarray(s.handle._tokens, np.int32)]),
                        s.seed, s.temperature)
                self._release_slot_pages_locked(s)
                self._tables[idx] = 0
                self._slots[idx] = None
                self._publish_page_gauges()
