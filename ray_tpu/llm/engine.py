"""Continuous-batching engine: a slot-based KV cache driven by two
compiled programs.

Design (TPU-first, static shapes throughout):

- ``decode_slots`` advances EVERY slot one token per call with per-slot
  positions; idle slots are parked at ``max_seq - 1`` where their
  garbage writes are provably overwritten before ever being attended.
- ``prefill_chunk`` writes one fixed-size prompt chunk into one slot's
  pages. The host loop runs at most one chunk per iteration, so a long
  prompt admission adds bounded latency to in-flight decodes (chunked
  prefill, the vLLM scheduling insight re-expressed as two XLA programs
  instead of a paged-attention kernel).
- Sampling is fused into both programs — only ``[num_slots]`` int32
  tokens cross the device boundary per step, never ``[B, vocab]``
  logits.

Exactly two compiled programs serve any mix of request lengths; there
is no shape-dependent recompilation after warmup.

Reference intent matched (and exceeded — the reference never touches
the accelerator): ``/root/reference/python/ray/serve/_private/replica.py``
request plane + ``/root/reference/python/ray/serve/batching.py``.
"""

from __future__ import annotations

import queue
import threading
from collections import deque
from dataclasses import dataclass, field
from functools import partial
from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..models import llama


def _sample(logits, temps, key):
    """Greedy when temp == 0, else temperature sampling. [B,V] -> [B]."""
    greedy = jnp.argmax(logits, axis=-1)
    scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
    sampled = jax.random.categorical(key, scaled, axis=-1)
    return jnp.where(temps > 0, sampled, greedy).astype(jnp.int32)


@dataclass
class GenerationResult:
    tokens: List[int]
    prompt_len: int
    finish_reason: str  # "stop" (eos) | "length"


class RequestHandle:
    """Thread-safe consumer side of one generation request.

    Iterating yields token ids as they are produced; ``result()`` blocks
    for the final :class:`GenerationResult`. ``on_token`` (if given at
    submit) is called from the engine thread instead — useful to bridge
    into an asyncio loop without a queue hop.
    """

    def __init__(self, prompt_len: int):
        self._q: "queue.Queue" = queue.Queue()
        self._tokens: List[int] = []
        self._prompt_len = prompt_len
        self._done = threading.Event()
        self._finish_reason = "length"
        self.error: Optional[BaseException] = None

    def __iter__(self):
        while True:
            item = self._q.get()
            if item is None:
                if self.error is not None:
                    raise self.error
                return
            yield item

    def result(self, timeout: Optional[float] = None) -> GenerationResult:
        if not self._done.wait(timeout):
            raise TimeoutError("generation did not finish in time")
        if self.error is not None:
            raise self.error
        return GenerationResult(tokens=list(self._tokens),
                                prompt_len=self._prompt_len,
                                finish_reason=self._finish_reason)

    # engine-side
    def _emit(self, tok: int) -> None:
        self._tokens.append(tok)
        self._q.put(tok)

    def _finish(self, reason: str,
                error: Optional[BaseException] = None) -> None:
        self._finish_reason = reason
        self.error = error
        self._done.set()
        self._q.put(None)


@dataclass
class _Slot:
    handle: RequestHandle
    prompt: np.ndarray  # int32 [prompt_len]
    max_new: int
    temperature: float
    eos_id: Optional[int]
    on_token: Optional[Callable[[Optional[int]], None]]
    prefill_offset: int = 0  # next chunk start; == len(prompt) when done
    pos: int = 0  # write position of the NEXT decode step
    last_token: int = 0
    produced: int = 0
    # True once this slot's current token lives on-device (row of the
    # previous decode block's `last` output) — its next block input
    # chains device-side with no host round trip.
    on_device_chain: bool = False
    # True between dispatching the FINAL prefill chunk and fetching its
    # sampled first token (lag-1 pipeline): the slot must not join the
    # decode batch until that token is known host-side.
    first_tok_pending: bool = False

    @property
    def prefill_done(self) -> bool:
        return self.prefill_offset >= len(self.prompt)


class SlotEngine:
    """Continuous-batching generation over a fixed pool of KV slots."""

    def __init__(self, params, cfg: llama.LlamaConfig, num_slots: int = 8,
                 chunk: int = 64, seed: int = 0, decode_block: int = 1):
        if cfg.max_seq % chunk != 0:
            raise ValueError(
                f"chunk ({chunk}) must divide max_seq ({cfg.max_seq}): "
                "a padded tail chunk would clamp past the cache end")
        self.cfg = cfg
        self.num_slots = num_slots
        self.chunk = chunk
        # decode_block K > 1 amortizes the host<->device round trip: ONE
        # program advances every slot K tokens (an in-program lax.scan
        # chaining sampled tokens device-side), and the host fetches a
        # block's tokens only AFTER dispatching the next block — on a
        # remote-tunneled TPU a fetch of a still-pending result costs
        # ~20x a fetch of a finished one, so the lag-1 pipeline keeps
        # fetches on the fast path. Cost: tokens stream in bursts of K
        # and EOS is noticed up to 2K-1 tokens late (the overshoot is
        # discarded; garbage K/V is overwritten before ever attended).
        self.decode_block = decode_block
        self._params = jax.device_put(params)
        # One extra SCRATCH slot: idle steps point the fused program's
        # prefill lane at it, so inactive-prefill writes never touch a
        # real request's pages. Requests only ever occupy slots
        # [0, num_slots).
        self._nrows = num_slots + 1
        self._scratch = num_slots
        self._cache = llama.init_kv_cache(cfg, self._nrows)
        self._key = jax.random.PRNGKey(seed)

        def block_fn(params, cache, override_vals, override_mask,
                     prev_last, pos, temps, key,
                     pre_tokens, pre_slot, pre_p0, pre_last_idx,
                     pre_temp):
            """K-token decode block with the prefill lane fused into the
            FIRST step (decode_slots_with_prefill): a prompt chunk rides
            the same params read as the decode batch, so prefill no
            longer costs a separate full-model pass."""
            tokens0 = jnp.where(override_mask, override_vals, prev_last)
            key, k0, kp = jax.random.split(key, 3)
            dec_logits, pre_logits, cache = \
                llama.decode_slots_with_prefill(
                    params, cache, tokens0, pos, pre_tokens, pre_slot,
                    pre_p0, pre_last_idx, cfg)
            tok1 = _sample(dec_logits, temps, k0)
            pre_tok = _sample(pre_logits[None], pre_temp[None], kp)[0]

            def body(carry, _):
                toks, cache, p, key = carry
                key, sub = jax.random.split(key)
                logits, cache = llama.decode_slots(params, cache, toks, p,
                                                   cfg)
                nxt = _sample(logits, temps, sub)
                return (nxt, cache, p + 1, key), nxt

            (last, cache, _, _), toks_rest = jax.lax.scan(
                body, (tok1, cache, pos + 1, key), None,
                length=decode_block - 1)
            toks_k = jnp.concatenate([tok1[None], toks_rest], axis=0)
            return toks_k, last, pre_tok, cache

        def decode_only_fn(params, cache, override_vals, override_mask,
                           prev_last, pos, temps, key):
            """Pure K-step decode block — dispatched whenever no prompt
            chunk is pending, so idle steps never pay the fused
            program's C-token prefill lane."""
            tokens0 = jnp.where(override_mask, override_vals, prev_last)

            def body(carry, _):
                toks, cache, p, key = carry
                key, sub = jax.random.split(key)
                logits, cache = llama.decode_slots(params, cache, toks, p,
                                                   cfg)
                nxt = _sample(logits, temps, sub)
                return (nxt, cache, p + 1, key), nxt

            (last, cache, _, _), toks_k = jax.lax.scan(
                body, (tokens0, cache, pos, key), None,
                length=decode_block)
            return toks_k, last, cache

        # The cache is donated: XLA updates it in place, so a decode
        # step never copies the (potentially multi-GB) KV pages.
        self._block = jax.jit(block_fn, donate_argnums=(1,))
        self._decode_only = jax.jit(decode_only_fn, donate_argnums=(1,))
        # lag-1 decode pipeline state
        self._inflight = None  # (snapshot, pre_info, toks_k, pre_tok)
        self._last_dev = jnp.zeros((self._nrows,), jnp.int32)

        self._slots: List[Optional[_Slot]] = [None] * num_slots
        self._pending: deque = deque()
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        # counters (observability / autoscaling signals)
        self.tokens_generated = 0
        self.requests_completed = 0

    # -- public API --------------------------------------------------------

    def submit(self, prompt: Sequence[int], max_new: int = 64,
               temperature: float = 0.0, eos_id: Optional[int] = None,
               on_token: Optional[Callable[[Optional[int]], None]] = None,
               ) -> RequestHandle:
        prompt = np.asarray(prompt, dtype=np.int32)
        if prompt.ndim != 1 or len(prompt) == 0:
            raise ValueError("prompt must be a non-empty 1D token list")
        if len(prompt) + max_new > self.cfg.max_seq:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new ({max_new}) exceeds "
                f"max_seq ({self.cfg.max_seq})")
        handle = RequestHandle(len(prompt))
        slot = _Slot(handle=handle, prompt=prompt, max_new=max_new,
                     temperature=float(temperature), eos_id=eos_id,
                     on_token=on_token)
        with self._work:
            self._pending.append(slot)
            self._work.notify()
        return handle

    def start(self) -> "SlotEngine":
        if self._thread is None:
            self._thread = threading.Thread(target=self._run,
                                            name="llm-engine", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        with self._work:
            self._stop = True
            self._work.notify()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None

    def warmup(self) -> None:
        """Compile both programs before serving traffic. Safe to call
        whether or not the engine thread is running."""
        h = self.submit([1, 2, 3], max_new=2)
        if self._thread is not None:
            h.result(timeout=600)
            return
        while not h._done.is_set():
            if not self.step():
                break
        h.result(timeout=0)

    # -- engine loop -------------------------------------------------------

    def _run(self) -> None:
        while True:
            with self._work:
                while not self._stop and not self._has_work_locked():
                    self._work.wait()
                if self._stop:
                    self._fail_all_locked(RuntimeError("engine stopped"))
                    return
            try:
                self.step()
            except Exception as e:  # noqa: BLE001 — device fault is fatal
                with self._work:
                    self._fail_all_locked(e)
                return

    def _has_work_locked(self) -> bool:
        return (bool(self._pending) or self._inflight is not None
                or any(s is not None for s in self._slots))

    def _fail_all_locked(self, err: BaseException) -> None:
        self._inflight = None
        for i, s in enumerate(self._slots):
            if s is not None:
                s.handle._finish("error", err)
                if s.on_token:
                    s.on_token(None)
                self._slots[i] = None
        while self._pending:
            s = self._pending.popleft()
            s.handle._finish("error", err)
            if s.on_token:
                s.on_token(None)

    def step(self) -> bool:
        """One scheduler iteration: admit, dispatch a fused
        decode+prefill block, then fetch the PREVIOUS block's tokens
        (ready by now — lag-1 pipelining). Returns True if any work
        ran."""
        with self._lock:
            for i in range(self.num_slots):
                if self._slots[i] is None and self._pending:
                    self._slots[i] = self._pending.popleft()
            prefill_idx = next(
                (i for i, s in enumerate(self._slots)
                 if s is not None and not s.prefill_done), None)
            active = [(i, s) for i, s in enumerate(self._slots)
                      if s is not None and s.prefill_done
                      and not s.first_tok_pending]
        ran = False
        new_block = (self._dispatch_block(active, prefill_idx)
                     if (active or prefill_idx is not None) else None)
        if self._inflight is not None:
            self._process_fetch()
            ran = True
        if new_block is not None:
            self._inflight = new_block
            ran = True
        return ran

    def _next_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    def _dispatch_block(self, active, prefill_idx):
        """Dispatch one K-step block: every active slot decodes K
        tokens and (when a slot is mid-prompt) ONE prefill chunk rides
        the first step's fused program. Continuing slots chain their
        input token device-side; freshly prefilled slots inject theirs
        via the override vector."""
        cfg = self.cfg
        rows = self._nrows
        override_vals = np.zeros((rows,), dtype=np.int32)
        override_mask = np.ones((rows,), dtype=bool)
        pos = np.full((rows,), cfg.max_seq - 1, dtype=np.int32)
        temps = np.zeros((rows,), dtype=np.float32)
        for i, s in active:
            pos[i] = s.pos
            temps[i] = s.temperature
            if s.on_device_chain:
                override_mask[i] = False
            else:
                override_vals[i] = s.last_token
        if prefill_idx is None:
            # No prompt chunk pending: the cheap pure-decode program.
            toks_k, self._last_dev, self._cache = self._decode_only(
                self._params, self._cache, jnp.asarray(override_vals),
                jnp.asarray(override_mask), self._last_dev,
                jnp.asarray(pos), jnp.asarray(temps), self._next_key())
            for i, s in active:
                s.pos += self.decode_block
                s.on_device_chain = True
            return (list(active), None, toks_k, None)
        # Prefill lane: one chunk of one slot's prompt rides the fused
        # program's first step.
        pre_buf = np.zeros((self.chunk,), dtype=np.int32)
        s = self._slots[prefill_idx]
        p0 = s.prefill_offset
        piece = s.prompt[p0:p0 + self.chunk]
        n_valid = len(piece)
        pre_buf[:n_valid] = piece
        s.prefill_offset = p0 + n_valid
        final = s.prefill_done
        if final:
            s.first_tok_pending = True
        pre_info = (prefill_idx, s, final)
        toks_k, self._last_dev, pre_tok, self._cache = self._block(
            self._params, self._cache, jnp.asarray(override_vals),
            jnp.asarray(override_mask), self._last_dev, jnp.asarray(pos),
            jnp.asarray(temps), self._next_key(),
            jnp.asarray(pre_buf), jnp.asarray(prefill_idx, jnp.int32),
            jnp.asarray(p0, jnp.int32),
            jnp.asarray(n_valid - 1, jnp.int32),
            jnp.asarray(s.temperature, jnp.float32))
        for i, s in active:
            s.pos += self.decode_block
            s.on_device_chain = True
        return (list(active), pre_info, toks_k, pre_tok)

    def _process_fetch(self) -> None:
        snapshot, pre_info, toks_k, pre_tok = self._inflight
        self._inflight = None
        arr = np.asarray(toks_k)  # [K, rows]; ready -> fast fetch
        for idx, s in snapshot:
            if self._slots[idx] is not s:
                continue  # finished in an earlier block; rows are garbage
            for k in range(arr.shape[0]):
                self._deliver(idx, s, int(arr[k, idx]))
                if self._slots[idx] is not s:
                    break  # eos / length hit mid-block; drop overshoot
        if pre_info is not None:
            idx, s, final = pre_info
            if final and self._slots[idx] is s:
                # The prompt's sampled first token arrives with the
                # block fetch; the slot joins the decode batch next
                # dispatch (override lane — the token is host-side).
                s.first_tok_pending = False
                s.pos = len(s.prompt)
                s.on_device_chain = False
                self._deliver(idx, s, int(pre_tok))

    def _deliver(self, idx: int, s: _Slot, tok: int) -> None:
        s.last_token = tok
        s.produced += 1
        self.tokens_generated += 1
        s.handle._emit(tok)
        if s.on_token:
            s.on_token(tok)
        hit_eos = s.eos_id is not None and tok == s.eos_id
        out_of_room = (len(s.prompt) + s.produced) >= self.cfg.max_seq
        if hit_eos or s.produced >= s.max_new or out_of_room:
            s.handle._finish("stop" if hit_eos else "length")
            if s.on_token:
                s.on_token(None)
            self.requests_completed += 1
            with self._lock:
                self._slots[idx] = None
