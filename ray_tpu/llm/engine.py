"""Continuous-batching engine: a slot-based KV cache driven by two
compiled programs.

Design (TPU-first, static shapes throughout):

- ``decode_slots`` advances EVERY slot one token per call with per-slot
  positions; idle slots are parked at ``max_seq - 1`` where their
  garbage writes are provably overwritten before ever being attended.
- ``prefill_chunk`` writes one fixed-size prompt chunk into one slot's
  pages. The host loop runs at most one chunk per iteration, so a long
  prompt admission adds bounded latency to in-flight decodes (chunked
  prefill, the vLLM scheduling insight re-expressed as two XLA programs
  instead of a paged-attention kernel).
- Sampling is fused into both programs — only ``[num_slots]`` int32
  tokens cross the device boundary per step, never ``[B, vocab]``
  logits.

Exactly two compiled programs serve any mix of request lengths; there
is no shape-dependent recompilation after warmup.

Reference intent matched (and exceeded — the reference never touches
the accelerator): ``/root/reference/python/ray/serve/_private/replica.py``
request plane + ``/root/reference/python/ray/serve/batching.py``.
"""

from __future__ import annotations

import queue
import threading
from collections import deque
from dataclasses import dataclass, field
from functools import partial
from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..models import llama


def _sample(logits, temps, key):
    """Greedy when temp == 0, else temperature sampling. [B,V] -> [B]."""
    greedy = jnp.argmax(logits, axis=-1)
    scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
    sampled = jax.random.categorical(key, scaled, axis=-1)
    return jnp.where(temps > 0, sampled, greedy).astype(jnp.int32)


@dataclass
class GenerationResult:
    tokens: List[int]
    prompt_len: int
    finish_reason: str  # "stop" (eos) | "length"


class RequestHandle:
    """Thread-safe consumer side of one generation request.

    Iterating yields token ids as they are produced; ``result()`` blocks
    for the final :class:`GenerationResult`. ``on_token`` (if given at
    submit) is called from the engine thread instead — useful to bridge
    into an asyncio loop without a queue hop.
    """

    def __init__(self, prompt_len: int):
        self._q: "queue.Queue" = queue.Queue()
        self._tokens: List[int] = []
        self._prompt_len = prompt_len
        self._done = threading.Event()
        self._finish_reason = "length"
        self.error: Optional[BaseException] = None

    def __iter__(self):
        while True:
            item = self._q.get()
            if item is None:
                if self.error is not None:
                    raise self.error
                return
            yield item

    def result(self, timeout: Optional[float] = None) -> GenerationResult:
        if not self._done.wait(timeout):
            raise TimeoutError("generation did not finish in time")
        if self.error is not None:
            raise self.error
        return GenerationResult(tokens=list(self._tokens),
                                prompt_len=self._prompt_len,
                                finish_reason=self._finish_reason)

    # engine-side
    def _emit(self, tok: int) -> None:
        self._tokens.append(tok)
        self._q.put(tok)

    def _finish(self, reason: str,
                error: Optional[BaseException] = None) -> None:
        self._finish_reason = reason
        self.error = error
        self._done.set()
        self._q.put(None)


@dataclass
class _Slot:
    handle: RequestHandle
    prompt: np.ndarray  # int32 [prompt_len]
    max_new: int
    temperature: float
    eos_id: Optional[int]
    on_token: Optional[Callable[[Optional[int]], None]]
    prefill_offset: int = 0  # next chunk start; == len(prompt) when done
    pos: int = 0  # write position of the NEXT decode step
    last_token: int = 0
    produced: int = 0
    # True once this slot's current token lives on-device (row of the
    # previous decode block's `last` output) — its next block input
    # chains device-side with no host round trip.
    on_device_chain: bool = False

    @property
    def prefill_done(self) -> bool:
        return self.prefill_offset >= len(self.prompt)


class SlotEngine:
    """Continuous-batching generation over a fixed pool of KV slots."""

    def __init__(self, params, cfg: llama.LlamaConfig, num_slots: int = 8,
                 chunk: int = 64, seed: int = 0, decode_block: int = 1):
        self.cfg = cfg
        self.num_slots = num_slots
        self.chunk = chunk
        # decode_block K > 1 amortizes the host<->device round trip: ONE
        # program advances every slot K tokens (an in-program lax.scan
        # chaining sampled tokens device-side), and the host fetches a
        # block's tokens only AFTER dispatching the next block — on a
        # remote-tunneled TPU a fetch of a still-pending result costs
        # ~20x a fetch of a finished one, so the lag-1 pipeline keeps
        # fetches on the fast path. Cost: tokens stream in bursts of K
        # and EOS is noticed up to 2K-1 tokens late (the overshoot is
        # discarded; garbage K/V is overwritten before ever attended).
        self.decode_block = decode_block
        self._params = jax.device_put(params)
        self._cache = llama.init_kv_cache(cfg, num_slots)
        self._key = jax.random.PRNGKey(seed)

        def decode_block_fn(params, cache, override_vals, override_mask,
                            prev_last, pos, temps, key):
            tokens0 = jnp.where(override_mask, override_vals, prev_last)

            def body(carry, _):
                toks, cache, p, key = carry
                key, sub = jax.random.split(key)
                logits, cache = llama.decode_slots(params, cache, toks, p,
                                                   cfg)
                nxt = _sample(logits, temps, sub)
                return (nxt, cache, p + 1, key), nxt

            (last, cache, _, _), toks_k = jax.lax.scan(
                body, (tokens0, cache, pos, key), None,
                length=decode_block)
            return toks_k, last, cache

        def prefill_step(params, cache, tokens, slot, p0, last_idx, temp,
                         key):
            logits, cache = llama.prefill_chunk(params, cache, tokens,
                                                slot, p0, cfg,
                                                last_idx=last_idx)
            tok = _sample(logits[None], temp[None], key)[0]
            return tok, cache

        # The cache is donated: XLA updates it in place, so a decode
        # step never copies the (potentially multi-GB) KV pages.
        self._decode = jax.jit(decode_block_fn, donate_argnums=(1,))
        self._prefill = jax.jit(prefill_step, donate_argnums=(1,))
        # lag-1 decode pipeline state
        self._inflight = None  # (snapshot, toks_k_dev)
        self._last_dev = jnp.zeros((num_slots,), jnp.int32)

        self._slots: List[Optional[_Slot]] = [None] * num_slots
        self._pending: deque = deque()
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        # counters (observability / autoscaling signals)
        self.tokens_generated = 0
        self.requests_completed = 0

    # -- public API --------------------------------------------------------

    def submit(self, prompt: Sequence[int], max_new: int = 64,
               temperature: float = 0.0, eos_id: Optional[int] = None,
               on_token: Optional[Callable[[Optional[int]], None]] = None,
               ) -> RequestHandle:
        prompt = np.asarray(prompt, dtype=np.int32)
        if prompt.ndim != 1 or len(prompt) == 0:
            raise ValueError("prompt must be a non-empty 1D token list")
        if len(prompt) + max_new > self.cfg.max_seq:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new ({max_new}) exceeds "
                f"max_seq ({self.cfg.max_seq})")
        handle = RequestHandle(len(prompt))
        slot = _Slot(handle=handle, prompt=prompt, max_new=max_new,
                     temperature=float(temperature), eos_id=eos_id,
                     on_token=on_token)
        with self._work:
            self._pending.append(slot)
            self._work.notify()
        return handle

    def start(self) -> "SlotEngine":
        if self._thread is None:
            self._thread = threading.Thread(target=self._run,
                                            name="llm-engine", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        with self._work:
            self._stop = True
            self._work.notify()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None

    def warmup(self) -> None:
        """Compile both programs before serving traffic. Safe to call
        whether or not the engine thread is running."""
        h = self.submit([1, 2, 3], max_new=2)
        if self._thread is not None:
            h.result(timeout=600)
            return
        while not h._done.is_set():
            if not self.step():
                break
        h.result(timeout=0)

    # -- engine loop -------------------------------------------------------

    def _run(self) -> None:
        while True:
            with self._work:
                while not self._stop and not self._has_work_locked():
                    self._work.wait()
                if self._stop:
                    self._fail_all_locked(RuntimeError("engine stopped"))
                    return
            try:
                self.step()
            except Exception as e:  # noqa: BLE001 — device fault is fatal
                with self._work:
                    self._fail_all_locked(e)
                return

    def _has_work_locked(self) -> bool:
        return (bool(self._pending) or self._inflight is not None
                or any(s is not None for s in self._slots))

    def _fail_all_locked(self, err: BaseException) -> None:
        self._inflight = None
        for i, s in enumerate(self._slots):
            if s is not None:
                s.handle._finish("error", err)
                if s.on_token:
                    s.on_token(None)
                self._slots[i] = None
        while self._pending:
            s = self._pending.popleft()
            s.handle._finish("error", err)
            if s.on_token:
                s.on_token(None)

    def step(self) -> bool:
        """One scheduler iteration: admit, one prefill chunk, dispatch a
        decode block, then fetch the PREVIOUS block's tokens (which are
        ready by now — lag-1 pipelining). Returns True if any work ran."""
        with self._lock:
            for i in range(self.num_slots):
                if self._slots[i] is None and self._pending:
                    self._slots[i] = self._pending.popleft()
            prefill_idx = next(
                (i for i, s in enumerate(self._slots)
                 if s is not None and not s.prefill_done), None)
            active = [(i, s) for i, s in enumerate(self._slots)
                      if s is not None and s.prefill_done]
        ran = False
        if prefill_idx is not None:
            self._prefill_one_chunk(prefill_idx)
            ran = True
        new_block = self._decode_dispatch(active) if active else None
        if self._inflight is not None:
            self._process_fetch()
            ran = True
        if new_block is not None:
            self._inflight = new_block
            ran = True
        return ran

    def _next_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    def _prefill_one_chunk(self, idx: int) -> None:
        s = self._slots[idx]
        c = self.chunk
        p0 = s.prefill_offset
        piece = s.prompt[p0:p0 + c]
        n_valid = len(piece)
        buf = np.zeros((c,), dtype=np.int32)
        buf[:n_valid] = piece
        tok, self._cache = self._prefill(
            self._params, self._cache, jnp.asarray(buf),
            jnp.asarray(idx, jnp.int32), jnp.asarray(p0, jnp.int32),
            jnp.asarray(n_valid - 1, jnp.int32),
            jnp.asarray(s.temperature, jnp.float32), self._next_key())
        s.prefill_offset = p0 + n_valid
        if s.prefill_done:
            first = int(tok)  # device sync: one int
            s.pos = len(s.prompt)
            self._deliver(idx, s, first)

    def _decode_dispatch(self, active):
        """Dispatch one K-step decode block; returns the pipeline entry.
        Continuing slots chain their input token device-side (no host
        round trip); freshly prefilled slots inject theirs via the
        override vector."""
        cfg = self.cfg
        override_vals = np.zeros((self.num_slots,), dtype=np.int32)
        override_mask = np.ones((self.num_slots,), dtype=bool)
        pos = np.full((self.num_slots,), cfg.max_seq - 1, dtype=np.int32)
        temps = np.zeros((self.num_slots,), dtype=np.float32)
        for i, s in active:
            pos[i] = s.pos
            temps[i] = s.temperature
            if s.on_device_chain:
                override_mask[i] = False
            else:
                override_vals[i] = s.last_token
        toks_k, self._last_dev, self._cache = self._decode(
            self._params, self._cache, jnp.asarray(override_vals),
            jnp.asarray(override_mask), self._last_dev, jnp.asarray(pos),
            jnp.asarray(temps), self._next_key())
        for i, s in active:
            s.pos += self.decode_block
            s.on_device_chain = True
        return (list(active), toks_k)

    def _process_fetch(self) -> None:
        snapshot, toks_k = self._inflight
        self._inflight = None
        arr = np.asarray(toks_k)  # [K, num_slots]; ready -> fast fetch
        for idx, s in snapshot:
            if self._slots[idx] is not s:
                continue  # finished in an earlier block; rows are garbage
            for k in range(arr.shape[0]):
                self._deliver(idx, s, int(arr[k, idx]))
                if self._slots[idx] is not s:
                    break  # eos / length hit mid-block; drop overshoot

    def _deliver(self, idx: int, s: _Slot, tok: int) -> None:
        s.last_token = tok
        s.produced += 1
        self.tokens_generated += 1
        s.handle._emit(tok)
        if s.on_token:
            s.on_token(tok)
        hit_eos = s.eos_id is not None and tok == s.eos_id
        out_of_room = (len(s.prompt) + s.produced) >= self.cfg.max_seq
        if hit_eos or s.produced >= s.max_new or out_of_room:
            s.handle._finish("stop" if hit_eos else "length")
            if s.on_token:
                s.on_token(None)
            self.requests_completed += 1
            with self._lock:
                self._slots[idx] = None
