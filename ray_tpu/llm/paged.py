"""Host-side bookkeeping for the paged KV cache: a refcounted page
pool with an LRU free-list, and a radix/prefix index over page-size
token chunks so multi-turn sessions sharing a prompt prefix skip the
redundant prefill (RadixAttention, SGLang — re-expressed over this
repo's page-table indirection instead of a custom attention kernel).

Division of labor with :mod:`ray_tpu.models.llama`:

- device side: ``init_paged_kv_cache`` / ``*_paged`` programs read and
  write physical pages through a ``[rows, P]`` page table; physical
  page 0 is the reserved scratch page every invalid write is routed to.
- host side (this module): who owns which page. ``PagePool`` refcounts
  pages; ``RadixIndex`` keys full pages on their page-size token chunk
  so a later prompt sharing the prefix maps the SAME physical pages
  into its table (read-only share, refcount +1 per borrower). A prefix
  that dies mid-page is matched token-granular: the borrower gets the
  page copy-on-write — the engine device-copies it into a fresh page at
  admission and continues writing there, so shared pages are never
  written after insertion.

Eviction: index-held pages whose only reference IS the index are
reclaimed leaf-first in LRU order when an admission needs more pages
than the free list holds — a conversation tree's cold tails die before
its hot shared system-prompt root.
"""

from __future__ import annotations

import itertools
import threading
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple


# Shared typed admission-shed error (moved to core.exceptions so the
# serve proxy can isinstance-check it across planes); re-exported here
# for compat with existing `from .paged import OverloadedError` imports.
from ..core.exceptions import OverloadedError  # noqa: F401,E402


class PagePool:
    """Refcounted physical-page allocator. Page 0 is the reserved
    scratch page: never allocated, never freed, absorbs every invalid
    device write. Freed pages return to an LRU free-list (appended on
    free, popped oldest-first)."""

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError("need at least 2 pages (scratch + 1)")
        self.num_pages = num_pages
        self._free: deque = deque(range(1, num_pages))
        self._refs: Dict[int, int] = {}

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used_count(self) -> int:
        """Allocated pages + the scratch page."""
        return self.num_pages - len(self._free)

    def alloc(self) -> int:
        if not self._free:
            raise RuntimeError("page pool exhausted")
        page = self._free.popleft()
        self._refs[page] = 1
        return page

    def ref(self, page: int) -> None:
        self._refs[page] += 1

    def unref(self, page: int) -> bool:
        """Drop one reference; returns True when the page was freed."""
        n = self._refs[page] - 1
        if n:
            self._refs[page] = n
            return False
        del self._refs[page]
        self._free.append(page)
        return True

    def refcount(self, page: int) -> int:
        return self._refs.get(page, 0)


class _Node:
    __slots__ = ("chunk", "page", "parent", "children", "tick")

    def __init__(self, chunk: Tuple[int, ...], page: int,
                 parent: Optional["_Node"]):
        self.chunk = chunk
        self.page = page
        self.parent = parent
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.tick = 0


class RadixIndex:
    """Prefix index keyed on page-size token chunks. Each node owns one
    reference on its physical page (taken at insert, dropped at evict);
    borrowers (slots) take their own references via the pool."""

    def __init__(self, pool: PagePool, page_size: int):
        self._pool = pool
        self._ps = page_size
        self._root = _Node((), -1, None)
        self._tick = itertools.count(1)
        self._nodes = 0

    def __len__(self) -> int:
        return self._nodes

    def match(self, prompt: Sequence[int]
              ) -> Tuple[List[int], Optional[Tuple[int, int]]]:
        """Longest indexed prefix of ``prompt``: a list of fully-matched
        physical page ids, plus an optional ``(page, n_tokens)`` partial
        match — a child chunk sharing >= 1 leading token with the
        remainder, whose page the borrower must take copy-on-write."""
        tick = next(self._tick)
        node = self._root
        pages: List[int] = []
        i = 0
        ps = self._ps
        while i + ps <= len(prompt):
            child = node.children.get(tuple(prompt[i:i + ps]))
            if child is None:
                break
            child.tick = tick
            pages.append(child.page)
            node = child
            i += ps
        partial: Optional[Tuple[int, int]] = None
        rest = tuple(prompt[i:i + ps])
        if rest:
            best = 0
            for chunk, child in node.children.items():
                n = 0
                for a, b in zip(chunk, rest):
                    if a != b:
                        break
                    n += 1
                if n > best:
                    best, partial = n, (child.page, n)
                    child.tick = tick
        return pages, partial

    def insert(self, prompt: Sequence[int], pages: Sequence[int]) -> int:
        """File ``prompt``'s fully-covered pages under their chunks.
        ``pages[j]`` is the physical page holding tokens
        ``prompt[j*ps:(j+1)*ps]``. Chunks already indexed are left
        pointing at their existing page (first writer wins — borrowers
        of either copy see identical content). Returns the number of
        newly indexed pages (each took one pool reference)."""
        tick = next(self._tick)
        node = self._root
        added = 0
        ps = self._ps
        for j in range(len(prompt) // ps):
            chunk = tuple(prompt[j * ps:(j + 1) * ps])
            child = node.children.get(chunk)
            if child is None:
                child = _Node(chunk, pages[j], node)
                node.children[chunk] = child
                self._pool.ref(pages[j])
                self._nodes += 1
                added += 1
            child.tick = tick
            node = child
        return added

    def evict(self, n_pages: int) -> int:
        """Reclaim up to ``n_pages`` pages by dropping index nodes whose
        page has no borrower (pool refcount 1 — only the index) and no
        children, LRU-first. One tree traversal seeds a min-heap of
        evictable leaves; freeing a leaf pushes its parent when that
        made it evictable, so a cold chain unwinds tail-first without
        re-walking the tree per page. Returns pages actually freed."""
        import heapq

        freed = 0
        heap: List[Tuple[int, int, _Node]] = []
        tiebreak = itertools.count()
        stack = list(self._root.children.values())
        while stack:
            node = stack.pop()
            if node.children:
                stack.extend(node.children.values())
            elif self._pool.refcount(node.page) == 1:
                heapq.heappush(heap, (node.tick, next(tiebreak), node))
        while freed < n_pages and heap:
            _, _, victim = heapq.heappop(heap)
            parent = victim.parent
            del parent.children[victim.chunk]
            self._nodes -= 1
            if self._pool.unref(victim.page):
                freed += 1
            if (parent is not self._root and not parent.children
                    and self._pool.refcount(parent.page) == 1):
                heapq.heappush(heap, (parent.tick, next(tiebreak),
                                      parent))
        return freed

    def clear(self) -> int:
        """Drop every index node (releasing its page reference);
        returns pages freed. Used by tests and cold-run benches."""
        freed = 0
        stack = list(self._root.children.values())
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            if self._pool.unref(node.page):
                freed += 1
        self._root.children.clear()
        self._nodes = 0
        return freed


# -- rt_llm_* metrics (same lazy, telemetry-gated idiom as
# serve_metrics: created in whichever process hosts the engine, shipped
# head-ward by the PR-13 exporter when that process is a worker). ------

_llm_metrics_cache: Optional[Dict[str, Any]] = None
_llm_metrics_lock = threading.Lock()


def llm_metrics() -> Optional[Dict[str, Any]]:
    """The LLM-engine metric family, or None with telemetry disabled."""
    global _llm_metrics_cache

    from ..core.config import config
    from ..observability.metrics import (
        Counter,
        Gauge,
        Histogram,
        get_or_create,
    )

    if not config().telemetry_enabled:
        return None
    with _llm_metrics_lock:
        if _llm_metrics_cache is None:
            _llm_metrics_cache = {
                "prefix": get_or_create(
                    Counter, "rt_llm_prefix_hit",
                    "Prompt admissions by prefix-cache outcome",
                    ("result",)),
                "prefix_tokens": get_or_create(
                    Counter, "rt_llm_prefix_tokens_saved",
                    "Prompt tokens whose prefill was skipped"),
                "pages_used": get_or_create(
                    Gauge, "rt_llm_pages_used",
                    "KV pages allocated (incl. scratch)"),
                "pages_free": get_or_create(
                    Gauge, "rt_llm_pages_free", "KV pages on the free list"),
                "ttft": get_or_create(
                    Histogram, "rt_llm_ttft_seconds",
                    "Submit-to-first-token latency",
                    boundaries=[0.001, 0.005, 0.01, 0.05, 0.1, 0.5,
                                1.0, 5.0, 30.0]),
                # Per-request stage breakdown (flight recorder, LLM
                # path): admission wait + queue wait + prefix match +
                # prefill + per-token decode sum to roughly the
                # end-to-end request latency.
                "stage": get_or_create(
                    Histogram, "rt_llm_stage_seconds",
                    "LLM request latency attributed per stage",
                    boundaries=[0.0001, 0.001, 0.01, 0.1, 1.0, 10.0,
                                60.0],
                    tag_keys=("stage",)),
                "decode_per_token": get_or_create(
                    Histogram, "rt_llm_decode_per_token_seconds",
                    "Mean inter-token decode latency per request",
                    boundaries=[0.0005, 0.001, 0.005, 0.01, 0.05, 0.1,
                                0.5, 1.0]),
                "roofline_frac": get_or_create(
                    Gauge, "rt_llm_roofline_frac",
                    "Achieved decode HBM bytes/s over the configured "
                    "peak bandwidth (hbm_bandwidth_gbps x mesh size)"),
                "decode_steps": get_or_create(
                    Gauge, "rt_llm_decode_steps_per_s",
                    "Steady-state decode steps/s over the current "
                    "roofline window"),
                # Monotone token production: the rate source behind the
                # history ring's tok/s series (`rt top`); a gauge of
                # engine.tokens_generated would reset on replica
                # replacement and fake a negative rate.
                "tokens": get_or_create(
                    Counter, "rt_llm_tokens_generated_total",
                    "Decode tokens produced (all requests)"),
                # Stateful sessions (migration & drain): residency,
                # export/import outcomes, and crash-path re-prefill
                # recovery latency.
                "sessions_resident": get_or_create(
                    Gauge, "rt_llm_sessions_resident",
                    "Chat sessions whose transcript (and usually KV "
                    "prefix) is resident on this engine"),
                "session_migrations": get_or_create(
                    Counter, "rt_llm_session_migrations",
                    "Session export/import attempts by outcome",
                    ("result",)),
                "session_recovery": get_or_create(
                    Histogram, "rt_llm_session_recovery_seconds",
                    "Crash-path session recovery latency "
                    "(transcript re-prefill on the new replica)",
                    boundaries=[0.001, 0.005, 0.01, 0.05, 0.1, 0.5,
                                1.0, 5.0, 30.0]),
            }
        return _llm_metrics_cache
