"""Serve deployment hosting a :class:`SlotEngine` — the on-TPU LLM
serving path.

A replica owns one compiled model + KV-slot pool; HTTP requests join
free slots mid-flight and stream tokens back over the proxy's chunked
path. Request schema (POST body JSON):

    {"prompt": [token ids...], "max_tokens": 64, "temperature": 0.0,
     "eos_id": null, "stream": false}

Responses: ``{"tokens": [...], "finish_reason": ..., "prompt_len": N,
"timing": {...}}`` — ``timing`` is the flight recorder's per-request
stage breakdown (admission/queue/prefix_match/prefill/decode seconds) —
or, with ``stream: true``, one JSON token-id per chunk line.

Reference analog: ``/root/reference/python/ray/serve/_private/replica.py``
(replica request plane) — then beyond it: the reference has no
accelerator-resident serving loop at all.
"""

from __future__ import annotations

import asyncio
from typing import Optional

import jax

from ..models import llama
from .engine import SlotEngine
from .paged import OverloadedError


def _build_params(model: str, seed: int,
                  checkpoint_path: Optional[str] = None):
    cfg = llama.CONFIGS[model]
    if checkpoint_path:
        from ..train.checkpoint import restore_arrays

        params = restore_arrays(checkpoint_path)
    else:
        params, _ = llama.init_params(jax.random.PRNGKey(seed), cfg)
    if cfg.dtype is not None:
        params = jax.tree.map(lambda x: x.astype(cfg.dtype), params)
    return params, cfg


class LLMServer:
    """Deployment class: one engine per replica, asyncio request plane.

    The engine thread drives the TPU; handlers only bridge tokens into
    the replica's event loop, so hundreds of concurrent streams cost one
    queue hop each, never a device touch.
    """

    def __init__(self, model: str = "llama-tiny", num_slots: int = 8,
                 chunk: int = 64, seed: int = 0,
                 checkpoint_path: Optional[str] = None,
                 default_max_tokens: int = 64,
                 page_size: int = 16, num_pages: Optional[int] = None,
                 prefix_cache: bool = True,
                 max_pending: Optional[int] = 256,
                 queue_timeout_s: Optional[float] = 30.0,
                 decode_block: int = 1, tp: int = 1):
        params, cfg = _build_params(model, seed, checkpoint_path)
        self.default_max_tokens = default_max_tokens
        # tp > 1: tensor-shard this replica over the first tp local
        # devices — params by their logical axes, KV pages on the
        # kv-heads axis (SlotEngine.SERVE_RULES). Per-request fold_in
        # sampling keeps outputs bit-for-bit identical to tp=1.
        mesh = None
        if tp > 1:
            from ..parallel.mesh import MeshSpec

            devs = jax.devices()
            if len(devs) < tp:
                raise ValueError(
                    f"tp={tp} needs {tp} devices, have {len(devs)}")
            mesh = MeshSpec(tp=tp).build(devs[:tp])
        # Per-deployment admission control: the pending queue is BOUNDED
        # (max_pending) and queued requests expire after queue_timeout_s
        # — both shed load as a typed OverloadedError that the HTTP
        # proxy maps to 503, instead of letting a traffic wave grow
        # engine._pending without limit and stall resident sessions.
        self.engine = SlotEngine(params, cfg, num_slots=num_slots,
                                 chunk=chunk, seed=seed,
                                 page_size=page_size, num_pages=num_pages,
                                 prefix_cache=prefix_cache,
                                 max_pending=max_pending,
                                 queue_timeout_s=queue_timeout_s,
                                 decode_block=decode_block, mesh=mesh)
        self.engine.warmup()  # compile before the replica is routable
        self.engine.start()
        self._recoveries: list = []  # crash-path restore latencies (ms)

    def __del__(self):
        try:
            self.engine.stop()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass

    async def __call__(self, payload):
        if not isinstance(payload, dict) or "prompt" not in payload:
            return {"error": "body must be JSON with a 'prompt' "
                             "token-id list"}
        prompt = payload["prompt"]
        max_tokens = int(payload.get("max_tokens",
                                     self.default_max_tokens))
        temperature = float(payload.get("temperature", 0.0))
        eos_id = payload.get("eos_id")
        # Client-pinned seed: a safe retry after replica death replays
        # the identical request elsewhere; with the seed in the payload
        # the fold_in sampling stream — and therefore the output — is
        # bit-for-bit the same on the survivor.
        seed = payload.get("seed")
        session_id = payload.get("session")
        loop = asyncio.get_running_loop()
        q: asyncio.Queue = asyncio.Queue()
        from ..observability import tracing

        handle = self.engine.submit(
            prompt, max_new=max_tokens, temperature=temperature,
            eos_id=None if eos_id is None else int(eos_id),
            seed=None if seed is None else int(seed),
            session_id=None if session_id is None else str(session_id),
            on_token=lambda t: loop.call_soon_threadsafe(q.put_nowait, t),
            # The replica bound the request's trace ctx to THIS asyncio
            # task (handle_request); hand it to the engine thread so the
            # stage spans it synthesizes at finish join the same trace.
            trace_ctx=tracing.get_request_context())
        if payload.get("stream"):
            # Hold the response until the FIRST token (or failure): the
            # proxy writes the chunked 200 header as soon as it sees a
            # stream, so an admission shed surfacing after that point
            # could only be reported as a dropped connection. Raising
            # here instead lets the proxy send the typed 503. TTFB was
            # going to be the first token anyway.
            first = await q.get()
            if first is None and handle.error is not None:
                raise handle.error

            async def token_stream():
                tok = first
                while tok is not None:
                    yield tok
                    tok = await q.get()
                if handle.error is not None:
                    raise handle.error

            return token_stream()
        while True:
            if await q.get() is None:
                break
        if handle.error is not None:
            raise handle.error
        res = handle.result(timeout=0)
        # "timing": the flight recorder's per-request stage breakdown
        # (admission/queue/prefix_match/prefill/decode seconds) — every
        # response carries its own latency attribution.
        return {"tokens": res.tokens, "finish_reason": res.finish_reason,
                "prompt_len": res.prompt_len, "timing": res.timing}

    # -- stateful sessions (migration & drain, ISSUE 19) -------------------

    def sessions(self) -> list:
        """Resident session ids on this replica's engine."""
        return self.engine.sessions()

    def export_sessions(self, session_ids=None) -> list:
        """Snapshot sessions for migration (controller drain path).
        Skips ids with a generation currently in flight — the drain
        quiesce wait retries nothing; those sessions recover via the
        crash path's re-prefill if they move."""
        ids = session_ids if session_ids else self.engine.sessions()
        out = []
        for sid in ids:
            try:
                out.append(self.engine.export_session(sid))
            except (KeyError, RuntimeError):
                continue
        return out

    def import_session(self, snapshot) -> dict:
        return self.engine.import_session(snapshot)

    def restore_session(self, session_id, transcript, seed=None,
                        temperature: float = 0.0) -> dict:
        """Crash-path recovery: re-prefill the transcript (proxy calls
        this on re-pin when the old replica died without exporting)."""
        info = self.engine.prefill_session(session_id, transcript,
                                           seed=seed,
                                           temperature=temperature)
        self._recoveries.append(round(info["seconds"] * 1e3, 3))
        del self._recoveries[:-64]
        return info

    def stats(self) -> dict:
        return {
            "tokens_generated": self.engine.tokens_generated,
            "requests_completed": self.engine.requests_completed,
            "requests_shed": self.engine.requests_shed,
            "num_slots": self.engine.num_slots,
            "prefix_hits": self.engine.prefix_hits,
            "prefix_misses": self.engine.prefix_misses,
            "prefix_tokens_saved": self.engine.prefix_tokens_saved,
            "pages_used": self.engine.pages_used,
            "pages_free": self.engine.pages_free,
            "sessions_resident": self.engine.session_count,
            "session_recovery_ms": list(self._recoveries),
            "decode_profile": self.engine.decode_profile(),
        }


def build_llm_app(model: str = "llama-tiny", num_slots: int = 8,
                  chunk: int = 64, seed: int = 0,
                  checkpoint_path: Optional[str] = None,
                  name: str = "llm", page_size: int = 16,
                  num_pages: Optional[int] = None,
                  prefix_cache: bool = True,
                  max_pending: Optional[int] = 256,
                  queue_timeout_s: Optional[float] = 30.0,
                  decode_block: int = 1, tp: int = 1,
                  **deploy_opts):
    """Build a Serve application for ``serve.run`` hosting the engine."""
    from ..serve import deployment

    # Mirror the engine's admission knobs into the deployment config so
    # the router sheds at the same bound BEFORE a request crosses into
    # the replica (the engine's own bounded queue stays authoritative
    # for in-replica admission).
    deploy_opts.setdefault("max_pending", max_pending)
    deploy_opts.setdefault("queue_timeout_s", queue_timeout_s)
    dep = deployment(LLMServer, name=name, **deploy_opts)
    return dep.bind(model=model, num_slots=num_slots, chunk=chunk,
                    seed=seed, checkpoint_path=checkpoint_path,
                    page_size=page_size, num_pages=num_pages,
                    prefix_cache=prefix_cache, max_pending=max_pending,
                    queue_timeout_s=queue_timeout_s,
                    decode_block=decode_block, tp=tp)
