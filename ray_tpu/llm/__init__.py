"""TPU-native LLM serving: continuous batching over a jitted decode loop.

The reference has no on-device serving path — its Serve batches at the
request level (``/root/reference/python/ray/serve/batching.py``) and the
replica runs arbitrary Python (``serve/_private/replica.py``). Here the
replica hosts a compiled model: a slot-based KV cache where requests
join free slots mid-flight, finished sequences leave without stalling
the batch, and prefill runs chunked alongside decode (SURVEY §7.2
step 9).
"""

from .engine import GenerationResult, RequestHandle, SlotEngine
from .paged import OverloadedError, PagePool, RadixIndex
from .serve import LLMServer, build_llm_app

__all__ = [
    "SlotEngine",
    "RequestHandle",
    "GenerationResult",
    "LLMServer",
    "build_llm_app",
    "OverloadedError",
    "PagePool",
    "RadixIndex",
]
