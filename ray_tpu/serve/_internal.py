"""Serve internals: controller, replica, router, autoscaling.

Reference analog (call stack SURVEY §3.5):
  - ``serve/controller.py:61,229,330`` — ServeController actor with a
    reconcile loop driving DeploymentState replica scaling
  - ``serve/_private/deployment_state.py:942,1248`` — target-vs-actual
    replica reconciliation
  - ``serve/_private/router.py:62,221`` — replica set + assignment honoring
    ``max_concurrent_queries``
  - ``serve/_private/autoscaling_policy.py:93,127`` — queue-metric-based
    replica target (the policy math carries over unchanged)
  - ``serve/_private/replica.py`` — replica actor wrapping the user
    callable.

TPU note: replicas hosting pjit-compiled models are plain actors here —
model placement/sharding happens inside the replica via ``parallel``.
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ..core import get, kill, remote, wait
from ..core.actor import ActorHandle


@dataclass
class AutoscalingConfig:
    """Reference: serve/config.py AutoscalingConfig."""

    min_replicas: int = 1
    max_replicas: int = 4
    target_num_ongoing_requests_per_replica: float = 2.0
    upscale_delay_s: float = 0.5
    downscale_delay_s: float = 2.0


@dataclass
class DeploymentInfo:
    name: str
    deployment_def: Any  # class or function (cloudpickleable)
    init_args: tuple = ()
    init_kwargs: dict = field(default_factory=dict)
    num_replicas: int = 1
    max_concurrent_queries: int = 100
    route_prefix: Optional[str] = None
    autoscaling: Optional[AutoscalingConfig] = None
    ray_actor_options: dict = field(default_factory=dict)
    version: int = 0


class _Replica:
    """Replica actor body (reference: RayServeReplica)."""

    def __init__(self, deployment_def, init_args, init_kwargs):
        import inspect

        if inspect.isclass(deployment_def):
            self.callable = deployment_def(*init_args, **init_kwargs)
        else:
            self.callable = deployment_def
        self._ongoing = 0
        self._total = 0

    def handle_request(self, args, kwargs):
        self._ongoing += 1
        self._total += 1
        try:
            fn = self.callable
            if not callable(fn):
                raise TypeError("deployment is not callable")
            if hasattr(fn, "__call__") and not isinstance(fn, type):
                result = fn(*args, **kwargs)
            else:
                result = fn(*args, **kwargs)
            import inspect

            if inspect.iscoroutine(result):
                import asyncio

                result = asyncio.new_event_loop().run_until_complete(result)
            return result
        finally:
            self._ongoing -= 1

    def call_method(self, method, args, kwargs):
        self._ongoing += 1
        try:
            return getattr(self.callable, method)(*args, **kwargs)
        finally:
            self._ongoing -= 1

    def metrics(self):
        return {"ongoing": self._ongoing, "total": self._total}

    def reconfigure(self, user_config):
        if hasattr(self.callable, "reconfigure"):
            self.callable.reconfigure(user_config)
        return True


class ServeController:
    """Controller actor: owns deployment state, reconciles replicas.

    Reference: serve/controller.py — ``deploy`` (:330) +
    ``run_control_loop`` (:229). The loop runs inside actor method calls
    (each ``reconcile`` tick) driven by the proxy/handles polling — or
    explicitly by ``serve.run``.
    """

    def __init__(self):
        self.deployments: Dict[str, DeploymentInfo] = {}
        self.replicas: Dict[str, List[Any]] = {}
        self._metrics: Dict[str, List[float]] = {}
        self._last_scale_up: Dict[str, float] = {}
        self._last_scale_down: Dict[str, float] = {}

    # -- deploy API ----------------------------------------------------------
    def deploy(self, info: DeploymentInfo) -> bool:
        existing = self.deployments.get(info.name)
        if existing is not None:
            info.version = existing.version + 1
        self.deployments[info.name] = info
        self._reconcile_deployment(info.name, redeploy=existing is not None)
        return True

    def delete_deployment(self, name: str) -> bool:
        info = self.deployments.pop(name, None)
        for r in self.replicas.pop(name, []):
            try:
                kill(r)
            except Exception:
                pass
        return info is not None

    def list_deployments(self) -> Dict[str, dict]:
        return {
            name: {
                "num_replicas": len(self.replicas.get(name, [])),
                "target": self._target_replicas(name),
                "route_prefix": info.route_prefix,
                "version": info.version,
            }
            for name, info in self.deployments.items()
        }

    def get_replicas(self, name: str) -> List[Any]:
        return list(self.replicas.get(name, []))

    def get_deployment_names(self) -> List[str]:
        return list(self.deployments)

    # -- reconciliation ------------------------------------------------------
    def _target_replicas(self, name: str) -> int:
        info = self.deployments.get(name)
        if info is None:
            return 0
        if info.autoscaling is None:
            return info.num_replicas
        return self._autoscale_target(name, info)

    def _autoscale_target(self, name: str, info: DeploymentInfo) -> int:
        """Reference: autoscaling_policy.py:127 get_decision_num_replicas —
        target = ceil(total_ongoing / target_per_replica), clamped, with
        up/downscale delay."""
        cfg = info.autoscaling
        current = len(self.replicas.get(name, []))
        ongoing = self._collect_ongoing(name)
        desired = math.ceil(
            ongoing / max(cfg.target_num_ongoing_requests_per_replica, 1e-9)
        )
        desired = max(cfg.min_replicas, min(cfg.max_replicas, desired))
        now = time.monotonic()
        if desired > current:
            first = self._last_scale_up.setdefault(name, now)
            if now - first >= cfg.upscale_delay_s:
                self._last_scale_up.pop(name, None)
                return desired
            return current
        self._last_scale_up.pop(name, None)
        if desired < current:
            first = self._last_scale_down.setdefault(name, now)
            if now - first >= cfg.downscale_delay_s:
                self._last_scale_down.pop(name, None)
                return desired
            return current
        self._last_scale_down.pop(name, None)
        return current

    def _collect_ongoing(self, name: str) -> float:
        total = 0.0
        refs = []
        replicas = self.replicas.get(name, [])
        for r in replicas:
            refs.append(r.metrics.remote())
        if refs:
            ready, _ = wait(refs, num_returns=len(refs), timeout=1.0)
            for ref in ready:
                try:
                    total += get(ref)["ongoing"]
                except Exception:
                    pass
        return total

    def reconcile(self) -> Dict[str, int]:
        """One control-loop tick (reference: run_control_loop body)."""
        out = {}
        for name in list(self.deployments):
            out[name] = self._reconcile_deployment(name)
        return out

    def _reconcile_deployment(self, name: str, redeploy: bool = False) -> int:
        info = self.deployments[name]
        current = self.replicas.setdefault(name, [])
        if redeploy:
            for r in current:
                try:
                    kill(r)
                except Exception:
                    pass
            current.clear()
        target = self._target_replicas(name)
        replica_cls = remote(_Replica)
        while len(current) < target:
            opts = dict(info.ray_actor_options)
            actor = replica_cls.options(
                max_concurrency=max(2, info.max_concurrent_queries),
                **opts,
            ).remote(info.deployment_def, info.init_args, info.init_kwargs)
            current.append(actor)
        while len(current) > target:
            victim = current.pop()
            try:
                kill(victim)
            except Exception:
                pass
        return len(current)


class Router:
    """Client-side replica selection (reference: router.py ReplicaSet).

    Round-robin with in-flight caps per replica; refreshes its replica
    cache from the controller (the long-poll snapshot equivalent,
    long_poll.py:67) when stale or empty.
    """

    def __init__(self, controller, deployment_name: str,
                 max_concurrent_queries: int = 100,
                 refresh_interval: float = 0.5):
        self._controller = controller
        self._name = deployment_name
        self._max_cq = max_concurrent_queries
        self._replicas: List[Any] = []
        self._rr = 0
        self._inflight: Dict[int, int] = {}
        self._last_refresh = 0.0
        self._refresh_interval = refresh_interval

    def _refresh(self, force: bool = False):
        now = time.monotonic()
        if (not force and self._replicas
                and now - self._last_refresh < self._refresh_interval):
            return
        self._replicas = get(
            self._controller.get_replicas.remote(self._name)
        )
        self._last_refresh = now

    def assign(self, method: Optional[str], args, kwargs):
        """Pick a replica with capacity; round-robin (router.py:221)."""
        deadline = time.monotonic() + 30
        while True:
            self._refresh()
            n = len(self._replicas)
            if n:
                for probe in range(n):
                    idx = (self._rr + probe) % n
                    if self._inflight.get(idx, 0) < self._max_cq:
                        self._rr = idx + 1
                        replica = self._replicas[idx]
                        self._inflight[idx] = self._inflight.get(idx, 0) + 1
                        try:
                            if method:
                                return replica.call_method.remote(
                                    method, args, kwargs
                                )
                            return replica.handle_request.remote(args, kwargs)
                        finally:
                            # In-flight decremented optimistically after
                            # dispatch; precise tracking uses replica
                            # metrics (collected by the controller).
                            self._inflight[idx] -= 1
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"no replica available for {self._name!r}"
                )
            self._refresh(force=True)
            time.sleep(0.05)
