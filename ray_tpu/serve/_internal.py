"""Serve internals: controller, replica, router, autoscaling.

Reference analog (call stack SURVEY §3.5):
  - ``serve/controller.py:61,229,330`` — ServeController actor with a
    reconcile loop driving DeploymentState replica scaling
  - ``serve/_private/deployment_state.py:942,1248`` — target-vs-actual
    replica reconciliation
  - ``serve/_private/router.py:62,221`` — replica set + assignment honoring
    ``max_concurrent_queries``
  - ``serve/_private/autoscaling_policy.py:93,127`` — queue-metric-based
    replica target (the policy math carries over unchanged)
  - ``serve/_private/replica.py`` — replica actor wrapping the user
    callable.

TPU note: replicas hosting pjit-compiled models are plain actors here —
model placement/sharding happens inside the replica via ``parallel``.
"""

from __future__ import annotations

import math
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ..core import get, kill, remote, wait
from ..core.actor import ActorHandle

# -- first-class Serve metrics (reference: serve/_private/metrics_utils +
# the serve_* series of metric_defs.cc). Created lazily in whichever
# process first serves traffic: replica processes observe request
# counts/latency (shipped to the head by worker telemetry, which tags
# node/worker), the controller process sets the replica-count gauge, and
# driver-side routers set queue depth directly in the head registry.
_serve_metrics_cache: Optional[Dict[str, Any]] = None
_serve_metrics_lock = threading.Lock()


def serve_metrics() -> Optional[Dict[str, Any]]:
    """The serve metric family, or None with telemetry disabled."""
    global _serve_metrics_cache

    from ..core.config import config
    from ..observability.metrics import (
        Counter,
        Gauge,
        Histogram,
        get_or_create,
    )

    if not config().telemetry_enabled:
        return None
    with _serve_metrics_lock:
        if _serve_metrics_cache is None:
            # get_or_create: the telemetry absorber may have minted
            # these names first (controller/replica flushes land before
            # the driver's first Router) — reconstructing would REPLACE
            # the registered metric and drop the absorbed series.
            _serve_metrics_cache = {
                "requests": get_or_create(
                    Counter, "rt_serve_requests",
                    "Serve requests handled per deployment",
                    ("deployment", "result")),
                "latency": get_or_create(
                    Histogram, "rt_serve_request_latency_seconds",
                    "Replica-side request latency",
                    boundaries=[0.001, 0.005, 0.01, 0.05, 0.1, 0.5,
                                1.0, 5.0],
                    tag_keys=("deployment",)),
                "queue_depth": get_or_create(
                    Gauge, "rt_serve_queue_depth",
                    "Router in-flight requests per deployment",
                    ("deployment",)),
                "replicas": get_or_create(
                    Gauge, "rt_serve_replicas",
                    "Live replicas per deployment", ("deployment",)),
            }
        return _serve_metrics_cache


# Deployment-wide in-flight totals shared by EVERY driver-side router
# of a deployment (the proxy and each handle own separate Routers): the
# queue-depth gauge must report their sum, not whichever router wrote
# last. One tiny process-wide lock; the heavy per-request coordination
# stays on each router's own condvar.
_qd_lock = threading.Lock()
_qd_totals: Dict[str, int] = {}


def _queue_depth_note(name: str, delta: int, gauge=None,
                      key=None) -> int:
    """Update the deployment total and (when given) mirror it into the
    gauge UNDER the same lock — a set outside it can interleave with
    another router's update and publish a stale value (e.g. nonzero at
    idle). The metric lock is a leaf, so nesting it here is safe."""
    with _qd_lock:
        total = max(0, _qd_totals.get(name, 0) + delta)
        _qd_totals[name] = total
        if gauge is not None:
            gauge.set_key(key, float(total))
    return total


@dataclass
class AutoscalingConfig:
    """Reference: serve/config.py AutoscalingConfig."""

    min_replicas: int = 1
    max_replicas: int = 4
    target_num_ongoing_requests_per_replica: float = 2.0
    upscale_delay_s: float = 0.5
    downscale_delay_s: float = 2.0


@dataclass
class DeploymentInfo:
    name: str
    deployment_def: Any  # class or function (cloudpickleable)
    init_args: tuple = ()
    init_kwargs: dict = field(default_factory=dict)
    num_replicas: int = 1
    max_concurrent_queries: int = 100
    route_prefix: Optional[str] = None
    autoscaling: Optional[AutoscalingConfig] = None
    ray_actor_options: dict = field(default_factory=dict)
    version: int = 0
    request_timeout_s: Optional[float] = None
    user_config: Optional[dict] = None


class _Replica:
    """Replica actor body (reference: RayServeReplica).

    Request methods are ASYNC: the actor machinery runs every coroutine
    method on the replica's ONE persistent asyncio event loop (see
    ``core/worker_main.py`` async-actor support), so concurrent requests
    interleave at awaits instead of each spinning up a throwaway loop —
    the asyncio request plane of ``serve/_private/replica.py``. Streaming
    responses register a (async) generator under a stream id which the
    caller drains with ``next_chunks`` (chunked-pull streaming).
    """

    def __init__(self, deployment_def, init_args, init_kwargs,
                 request_timeout_s: Optional[float] = None,
                 user_config: Optional[dict] = None,
                 deployment_name: str = ""):
        import inspect

        if inspect.isclass(deployment_def):
            self.callable = deployment_def(*init_args, **init_kwargs)
        else:
            self.callable = deployment_def
        if user_config is not None:
            # Applied during construction, BEFORE the replica is
            # routable — a post-creation reconfigure RPC could race with
            # routed requests on a concurrent actor.
            self.reconfigure(user_config)
        self._ongoing = 0
        self._total = 0
        self._timeout = request_timeout_s
        self._streams: Dict[int, Any] = {}
        self._stream_counter = 0
        # Request counter + latency histogram, deployment-tagged; the
        # worker telemetry flusher ships them to the head registry. Tag
        # keys interned once — this runs per request.
        self._deployment = deployment_name
        self._metrics = serve_metrics()
        if self._metrics is not None:
            self._key_ok = (("deployment", deployment_name),
                            ("result", "ok"))
            self._key_err = (("deployment", deployment_name),
                             ("result", "error"))
            self._key_lat = (("deployment", deployment_name),)

    def _observe(self, start: float, n: int, ok: bool) -> None:
        if self._metrics is None:
            return
        elapsed = time.perf_counter() - start
        self._metrics["requests"].inc_key(
            self._key_ok if ok else self._key_err, n)
        self._metrics["latency"].observe_key(self._key_lat, elapsed,
                                             count=n)

    def _observe_batch(self, start: float, n: int, results) -> None:
        """Coalesced-entry accounting: ``results`` is the final
        ("ok"|"err", value) list, or None when the whole batch raised —
        per-item errors must land in result="error", not "ok"."""
        if self._metrics is None:
            return
        elapsed = time.perf_counter() - start
        n_err = (sum(1 for tag, _ in results if tag == "err")
                 if results is not None else n)
        if n - n_err:
            self._metrics["requests"].inc_key(self._key_ok, n - n_err)
        if n_err:
            self._metrics["requests"].inc_key(self._key_err, n_err)
        self._metrics["latency"].observe_key(self._key_lat, elapsed,
                                             count=n)

    @staticmethod
    def _resolve_target(fn):
        import inspect

        return fn.__call__ if not inspect.isfunction(fn) and not \
            inspect.ismethod(fn) and callable(fn) else fn

    def _register_stream(self, gen):
        """Register a generator result under a stream id (must run on
        the replica's event loop — _streams is loop-confined)."""
        self._sweep_streams()
        self._stream_counter += 1
        self._streams[self._stream_counter] = (gen, time.monotonic())
        return ("__rt_stream__", self._stream_counter)

    async def _invoke(self, fn, args, kwargs):
        import asyncio
        import functools
        import inspect

        target = self._resolve_target(fn)
        if inspect.iscoroutinefunction(target):
            coro = fn(*args, **kwargs)
            result = await (asyncio.wait_for(coro, self._timeout)
                            if self._timeout else coro)
        else:
            # Sync handlers run off-loop so concurrent requests (e.g.
            # @serve.batch coalescing) aren't serialized behind the
            # replica's event loop.
            loop = asyncio.get_running_loop()
            call = loop.run_in_executor(
                None, functools.partial(fn, *args, **kwargs))
            result = await (asyncio.wait_for(call, self._timeout)
                            if self._timeout else call)
            if inspect.iscoroutine(result):
                result = await (asyncio.wait_for(result, self._timeout)
                                if self._timeout else result)
        if inspect.isgenerator(result) or inspect.isasyncgen(result):
            return self._register_stream(result)
        return result

    def _sweep_streams(self, idle_s: float = 300.0) -> None:
        """Close streams abandoned by their consumer (client disconnect,
        dropped StreamingResponse) so generators don't leak for the
        replica's lifetime. Lazy sweep on registration — no timers."""
        now = time.monotonic()
        for sid in [s for s, (_, t) in self._streams.items()
                    if now - t > idle_s]:
            gen, _ = self._streams.pop(sid)
            try:
                close = getattr(gen, "close", None) or getattr(
                    gen, "aclose", None)
                if close is not None:
                    res = close()
                    if hasattr(res, "__await__"):
                        import asyncio

                        asyncio.ensure_future(res)
            except Exception:
                pass

    async def handle_request(self, args, kwargs):
        # Sweep abandoned streams from the request path too: a replica
        # whose LAST streaming consumer disconnected would otherwise
        # leak that generator until another streaming request arrives.
        if self._streams:
            self._sweep_streams()
        self._ongoing += 1
        self._total += 1
        start = time.perf_counter()
        ok = True
        try:
            fn = self.callable
            if not callable(fn):
                raise TypeError("deployment is not callable")
            return await self._invoke(fn, args, kwargs)
        except BaseException:
            ok = False
            raise
        finally:
            self._observe(start, 1, ok)
            self._ongoing -= 1

    async def handle_request_batch(self, items):
        """Coalesced entry: N requests in ONE actor RPC (the proxy's
        Nagle-style batching — on a host where the per-call actor hop is
        the serving bottleneck, coalescing divides it by the batch).
        Results are per-item isolated: ("ok", value) or ("err", repr).

        Async handlers run concurrently under asyncio.gather with full
        _invoke semantics. Sync handlers run in ONE executor task for
        the whole batch — a single thread hop instead of one per item
        (the per-item hop was the dominant serving cost on a contended
        host), with the event loop staying free for streams and async
        requests. Within-batch items of a sync handler are sequential;
        request_timeout_s bounds the whole batch on that path (a sync
        handler cannot be interrupted item-by-item anyway)."""
        import asyncio
        import inspect

        if self._streams:
            self._sweep_streams()
        self._ongoing += len(items)
        self._total += len(items)
        start = time.perf_counter()
        out = None
        try:
            fn = self.callable
            if callable(fn) and inspect.iscoroutinefunction(
                    self._resolve_target(fn)):
                async def one(args, kwargs):
                    try:
                        return ("ok", await self._invoke(fn, args,
                                                         kwargs))
                    except Exception as e:  # noqa: BLE001 — isolation
                        return ("err", repr(e))

                out = list(await asyncio.gather(
                    *(one(a, k) for a, k in items)))
                return out

            def run_all():
                out = []
                for a, k in items:
                    try:
                        if not callable(fn):
                            raise TypeError("deployment is not callable")
                        out.append(("ok", fn(*a, **k)))
                    except Exception as e:  # noqa: BLE001 — isolation
                        out.append(("err", repr(e)))
                return out

            loop = asyncio.get_running_loop()
            call = loop.run_in_executor(None, run_all)
            results = await (asyncio.wait_for(call, self._timeout)
                             if self._timeout else call)
            final = []
            for tag, val in results:
                if tag == "ok":
                    try:
                        if inspect.iscoroutine(val):
                            val = await (asyncio.wait_for(
                                val, self._timeout) if self._timeout
                                else val)
                        if inspect.isgenerator(val) or inspect.isasyncgen(
                                val):
                            val = self._register_stream(val)
                    except Exception as e:  # noqa: BLE001 — isolation
                        tag, val = "err", repr(e)
                final.append((tag, val))
            out = final
            return out
        finally:
            self._observe_batch(start, len(items), out)
            self._ongoing -= len(items)

    async def call_method(self, method, args, kwargs):
        self._ongoing += 1
        self._total += 1
        start = time.perf_counter()
        ok = True
        try:
            return await self._invoke(
                getattr(self.callable, method), args, kwargs)
        except BaseException:
            ok = False
            raise
        finally:
            self._observe(start, 1, ok)
            self._ongoing -= 1

    async def next_chunks(self, stream_id: int, max_n: int = 8):
        """Drain up to ``max_n`` items from a registered stream; returns
        (done, items). The stream is dropped when exhausted."""
        import inspect

        if self._streams:
            self._sweep_streams()
        entry = self._streams.get(stream_id)
        if entry is None:
            return True, []
        gen = entry[0]
        self._streams[stream_id] = (gen, time.monotonic())
        items = []
        try:
            if inspect.isasyncgen(gen):
                async for item in gen:
                    items.append(item)
                    if len(items) >= max_n:
                        return False, items
            else:
                for item in gen:
                    items.append(item)
                    if len(items) >= max_n:
                        return False, items
        finally:
            if len(items) < max_n:
                self._streams.pop(stream_id, None)
        return True, items

    def metrics(self):
        return {"ongoing": self._ongoing, "total": self._total}

    def reconfigure(self, user_config):
        if hasattr(self.callable, "reconfigure"):
            self.callable.reconfigure(user_config)
        return True


class ServeController:
    """Controller actor: owns deployment state, reconciles replicas.

    Reference: serve/controller.py — ``deploy`` (:330) +
    ``run_control_loop`` (:229). The control loop runs INSIDE the actor
    (``start_loop`` spawns it), so Serve keeps reconciling after driver
    handles are GC'd; routers learn of replica-set changes through the
    blocking ``listen_for_change`` long-poll (reference:
    long_poll.py:184 LongPollHost snapshot-ids), not interval polling.
    """

    def __init__(self):
        import threading

        self.deployments: Dict[str, DeploymentInfo] = {}
        self.replicas: Dict[str, List[Any]] = {}
        self._metrics: Dict[str, List[float]] = {}
        self._last_scale_up: Dict[str, float] = {}
        self._last_scale_down: Dict[str, float] = {}
        self._lock = threading.RLock()
        self._change = threading.Condition(self._lock)
        self._versions: Dict[str, int] = {}
        self._loop_stop = threading.Event()
        self._loop_thread = None

    def _bump_locked(self, name: str) -> None:
        self._versions[name] = self._versions.get(name, 0) + 1
        self._change.notify_all()

    # -- control loop (runs inside the actor process) -----------------------
    def start_loop(self, interval_s: float = 0.25) -> bool:
        import threading

        if self._loop_thread is not None:
            return False

        def loop():
            while not self._loop_stop.wait(interval_s):
                try:
                    self.reconcile()
                except Exception:
                    pass

        self._loop_thread = threading.Thread(
            target=loop, daemon=True, name="serve-control-loop")
        self._loop_thread.start()
        return True

    def stop_loop(self) -> bool:
        self._loop_stop.set()
        return True

    # -- deploy API ----------------------------------------------------------
    def deploy(self, info: DeploymentInfo) -> bool:
        with self._lock:
            existing = self.deployments.get(info.name)
            if existing is not None:
                info.version = existing.version + 1
            self.deployments[info.name] = info
            self._reconcile_deployment(info.name,
                                       redeploy=existing is not None)
        return True

    def delete_deployment(self, name: str) -> bool:
        with self._lock:
            info = self.deployments.pop(name, None)
            victims = self.replicas.pop(name, [])
            self._bump_locked(name)
        metrics = serve_metrics()
        if metrics is not None:
            metrics["replicas"].set(0.0, tags={"deployment": name})
        for r in victims:
            try:
                kill(r)
            except Exception:
                pass
        return info is not None

    # -- long-poll config push ----------------------------------------------
    def listen_for_change(self, name: str, known_version: int,
                          timeout_s: float = 30.0):
        """Block until the replica set of ``name`` changes past
        ``known_version`` (or timeout); returns (version, replicas).
        Reference: LongPollHost.listen_for_change — routers hold one of
        these calls open instead of polling on an interval."""
        deadline = time.monotonic() + timeout_s
        with self._change:
            while self._versions.get(name, 0) <= known_version:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._change.wait(remaining)
            return (self._versions.get(name, 0),
                    list(self.replicas.get(name, [])))

    def reconfigure_deployment(self, name: str, user_config) -> int:
        """Push a new user_config to every live replica in parallel;
        returns how many acknowledged (reference: controller.py
        deploy-with-user_config → replica reconfigure; the config-file
        ops path sets this per deployment). New replicas pick the config
        up at creation (_reconcile_deployment)."""
        with self._lock:
            info = self.deployments.get(name)
            if info is None:
                return -1
            info.user_config = user_config
            replicas = list(self.replicas.get(name, []))
        if not replicas:
            return 0
        from ..core import wait as _wait

        refs = [r.reconfigure.remote(user_config) for r in replicas]
        done, _pending = _wait(refs, num_returns=len(refs), timeout=30)
        return len(done)

    def list_deployments(self) -> Dict[str, dict]:
        with self._lock:
            return self._list_deployments_locked()

    def _list_deployments_locked(self) -> Dict[str, dict]:
        return {
            name: {
                "num_replicas": len(self.replicas.get(name, [])),
                "target": self._target_replicas(name),
                "route_prefix": info.route_prefix,
                "version": info.version,
            }
            for name, info in self.deployments.items()
        }

    def get_replicas(self, name: str) -> List[Any]:
        with self._lock:
            return list(self.replicas.get(name, []))

    def get_replica_snapshot(self, name: str):
        with self._lock:
            return (self._versions.get(name, 0),
                    list(self.replicas.get(name, [])))

    def get_deployment_names(self) -> List[str]:
        with self._lock:
            return list(self.deployments)

    # -- reconciliation ------------------------------------------------------
    def _target_replicas(self, name: str) -> int:
        info = self.deployments.get(name)
        if info is None:
            return 0
        if info.autoscaling is None:
            return info.num_replicas
        return self._autoscale_target(name, info)

    def _autoscale_target(self, name: str, info: DeploymentInfo) -> int:
        """Reference: autoscaling_policy.py:127 get_decision_num_replicas —
        target = ceil(total_ongoing / target_per_replica), clamped, with
        up/downscale delay."""
        cfg = info.autoscaling
        current = len(self.replicas.get(name, []))
        ongoing = self._collect_ongoing(name)
        desired = math.ceil(
            ongoing / max(cfg.target_num_ongoing_requests_per_replica, 1e-9)
        )
        desired = max(cfg.min_replicas, min(cfg.max_replicas, desired))
        now = time.monotonic()
        if desired > current:
            first = self._last_scale_up.setdefault(name, now)
            if now - first >= cfg.upscale_delay_s:
                self._last_scale_up.pop(name, None)
                return desired
            return current
        self._last_scale_up.pop(name, None)
        if desired < current:
            first = self._last_scale_down.setdefault(name, now)
            if now - first >= cfg.downscale_delay_s:
                self._last_scale_down.pop(name, None)
                return desired
            return current
        self._last_scale_down.pop(name, None)
        return current

    def _collect_ongoing(self, name: str) -> float:
        total = 0.0
        refs = []
        replicas = self.replicas.get(name, [])
        for r in replicas:
            refs.append(r.metrics.remote())
        if refs:
            ready, _ = wait(refs, num_returns=len(refs), timeout=1.0)
            for ref in ready:
                try:
                    total += get(ref)["ongoing"]
                except Exception:
                    pass
        return total

    def reconcile(self) -> Dict[str, int]:
        """One control-loop tick (reference: run_control_loop body)."""
        out = {}
        with self._lock:
            names = list(self.deployments)
        for name in names:
            with self._lock:
                if name not in self.deployments:
                    continue
                out[name] = self._reconcile_deployment(name)
        return out

    def _reconcile_deployment(self, name: str, redeploy: bool = False) -> int:
        info = self.deployments[name]
        current = self.replicas.setdefault(name, [])
        if redeploy:
            for r in current:
                try:
                    kill(r)
                except Exception:
                    pass
            current.clear()
        target = self._target_replicas(name)
        replica_cls = remote(_Replica)
        changed = redeploy
        while len(current) < target:
            changed = True
            opts = dict(info.ray_actor_options)
            actor = replica_cls.options(
                max_concurrency=max(2, info.max_concurrent_queries),
                **opts,
            ).remote(info.deployment_def, info.init_args, info.init_kwargs,
                     request_timeout_s=info.request_timeout_s,
                     user_config=info.user_config,
                     deployment_name=name)
            current.append(actor)
        while len(current) > target:
            victim = current.pop()
            changed = True
            try:
                kill(victim)
            except Exception:
                pass
        metrics = serve_metrics()
        if metrics is not None:
            # Runs in the controller process; telemetry ships it head-ward.
            metrics["replicas"].set(float(len(current)),
                                    tags={"deployment": name})
        if changed:
            self._bump_locked(name)
        return len(current)


class Router:
    """Client-side replica selection (reference: router.py ReplicaSet).

    Round-robin with ENFORCED per-replica in-flight caps: each assigned
    request registers a completion watcher (``core.on_ref_ready``) that
    releases the slot when the result lands, so a replica never holds
    more than ``max_concurrent_queries`` outstanding requests
    (router.py:62,221). Replica-set updates arrive through the
    controller's blocking ``listen_for_change`` long-poll held open by a
    background listener thread (long_poll.py:67 LongPollClient), not
    interval polling.
    """

    def __init__(self, controller, deployment_name: str,
                 max_concurrent_queries: int = 100):
        import threading

        self._controller = controller
        self._name = deployment_name
        self._max_cq = max_concurrent_queries
        self._replicas: List[Any] = []
        # Parallel to _replicas: cached actor-id keys, so the pick loop
        # never re-derives ``_actor_id.binary()`` per replica per
        # request (an O(replicas) allocation storm at 8 replicas that
        # helped INVERT handle throughput vs 1 replica).
        self._keys: List[bytes] = []
        self._version = -1
        self._rr = 0  # sticky pick: index of the previous replica
        self._slack = 16  # see _pick_slot_locked sticky-with-slack
        # keyed by replica actor id (stable across replica-set updates)
        self._inflight: Dict[bytes, int] = {}
        # Router-wide in-flight total -> rt_serve_queue_depth gauge.
        # DRIVER routers only: gauges keep producer tags through absorb,
        # so a nested replica-worker router shipping the same
        # {deployment} key would clobber the driver's live value with
        # its own (usually near-zero) count. The driver (proxy +
        # handles) is the authoritative ingress queue.
        from ..core.runtime import is_worker_process

        self._nq = 0
        self._metrics = None if is_worker_process() else serve_metrics()
        if self._metrics is not None:
            self._qd_key = (("deployment", deployment_name),)
        self._waiters = 0  # blocked assigners; gate for notify_all
        self._lock = threading.Lock()
        self._slot_free = threading.Condition(self._lock)
        self._stop = threading.Event()
        self._listener = threading.Thread(
            target=self._listen_loop, daemon=True,
            name=f"serve-router-{deployment_name}")
        self._listener.start()

    def _listen_loop(self):
        """Long-poll: one blocking listen_for_change call held open."""
        while not self._stop.is_set():
            try:
                version, replicas = get(
                    self._controller.listen_for_change.remote(
                        self._name, self._version),
                    timeout=45,
                )
                with self._slot_free:
                    if version != self._version:
                        self._version = version
                        self._set_replicas_locked(replicas)
                        self._slot_free.notify_all()
            except Exception:
                if self._stop.is_set():
                    return
                time.sleep(0.5)

    def _set_replicas_locked(self, replicas) -> None:
        self._replicas = replicas
        self._keys = [r._actor_id.binary() for r in replicas]

    def _ensure_replicas(self, timeout: float = 5.0) -> None:
        """First-use bootstrap: snapshot directly (the long-poll only
        reports CHANGES past our version)."""
        if self._replicas:
            return
        try:
            version, replicas = get(
                self._controller.get_replica_snapshot.remote(self._name),
                timeout=timeout,
            )
            with self._slot_free:
                if version >= self._version and replicas:
                    self._version = version
                    self._set_replicas_locked(replicas)
        except Exception:
            pass

    def stop(self):
        self._stop.set()
        # Give back this router's outstanding queue-depth contribution:
        # serve.shutdown() drops routers with requests still in flight,
        # and their _release callbacks may never run — without this the
        # deployment-wide total (_qd_totals) stays offset forever and a
        # restarted serve instance inherits a phantom queue depth.
        # Clearing _inflight makes any late _release a no-op (its clamp
        # sees 0), so the residual can't be subtracted twice.
        with self._slot_free:
            residual, self._nq = self._nq, 0
            self._inflight.clear()
        if residual and self._metrics is not None:
            _queue_depth_note(self._name, -residual,
                              self._metrics["queue_depth"], self._qd_key)

    def stats(self) -> Dict[str, Any]:
        """Router-local routing state (for tests/diagnostics)."""
        with self._slot_free:
            return {"replicas": len(self._replicas),
                    "sticky_index": self._rr,
                    "queue_depth": self._nq,
                    "inflight": dict(self._inflight)}

    def _note_inflight(self, delta: int) -> None:
        """Under self._slot_free: track this router's in-flight count
        and mirror the DEPLOYMENT-WIDE total (summed across routers via
        _queue_depth_note) into the gauge — interned key, so the added
        hot-path cost is two uncontended dict stores."""
        self._nq = max(0, self._nq + delta)
        if self._metrics is not None:
            _queue_depth_note(self._name, delta,
                              self._metrics["queue_depth"], self._qd_key)

    def assign(self, method: Optional[str], args, kwargs):
        return self.assign_with_replica(method, args, kwargs)[0]

    def _pick_slot_locked(self):
        """Under self._slot_free: least-loaded pick with a sticky tie
        break. Pure round-robin spreads consecutive requests across
        actors, defeating the core runtime's per-actor submission
        batching and bouncing worker processes in and out of the kernel
        run queue — on a single-core host that HALVED the handle path at
        8 replicas. Preferring the last-used replica while it is no more
        loaded than the least-loaded keeps one worker hot at low load,
        while genuine concurrency (inflight ties broken) still spreads
        by load exactly like the reference's availability-set routing
        (router.py:221). None when all are at capacity.

        REPLICA-LINEAR: the common case is O(1) — when the sticky
        replica's load is already within ``_slack`` of zero it beats or
        ties any scan result (best_load >= 0), so no scan runs and the
        pick cost no longer grows with the replica count. The full
        least-loaded scan (over cached keys) only runs once the hot
        replica is loaded beyond the slack — i.e. under saturation,
        where spreading is the point."""
        n = len(self._replicas)
        if n == 0:
            return None
        if self._rr >= n:
            self._rr = 0
        skey = self._keys[self._rr]
        sload = self._inflight.get(skey, 0)
        if sload < self._max_cq and sload <= self._slack:
            # Equivalent to the scan outcome: sload - best_load <= slack
            # holds for every possible best_load >= 0.
            self._inflight[skey] = sload + 1
            self._note_inflight(1)
            return self._replicas[self._rr], skey
        best = best_key = best_load = None
        for idx in range(n):
            key = self._keys[idx]
            load = self._inflight.get(key, 0)
            if load >= self._max_cq:
                continue
            if best_load is None or load < best_load:
                best, best_key, best_load = idx, key, load
        if best is None:
            return None
        # Sticky-with-slack: keep the previous replica while its load is
        # within `_slack` of the least loaded; spill beyond. Bursts stay
        # packed on one hot replica (per-actor submission batching +
        # worker cache locality), while sustained saturation still
        # spreads by load like the reference's availability-set routing.
        if self._rr != best:
            if sload < self._max_cq and sload - best_load <= self._slack:
                best, best_key, best_load = self._rr, skey, sload
            elif sload < self._max_cq:
                # Slack-overflow spill: route THIS call to the least
                # loaded but keep the anchor — moving it handed the
                # next whole burst to a cold replica (anchor ping-pong
                # was part of the 8-replica handle inversion). The
                # anchor only migrates when it is at hard capacity.
                self._inflight[best_key] = best_load + 1
                self._note_inflight(1)
                return self._replicas[best], best_key
        self._rr = best
        self._inflight[best_key] = best_load + 1
        self._note_inflight(1)
        return self._replicas[best], best_key

    def _submit(self, replica, key, method, args, kwargs):
        try:
            if method:
                ref = replica.call_method.remote(method, args, kwargs)
            else:
                ref = replica.handle_request.remote(args, kwargs)
        except Exception:
            self._release(key)
            raise

        from ..core import on_ref_ready

        on_ref_ready(ref, lambda k=key: self._release(k))
        return ref, replica

    def try_assign_with_replica(self, method: Optional[str], args,
                                kwargs):
        """Non-blocking assign: (ref, replica) or None when every
        replica is at capacity — lets the HTTP proxy submit inline on
        its event loop in the common unsaturated case instead of paying
        a thread-pool hop per request. STRICTLY non-blocking: an empty
        replica set returns None (the caller's off-loop slow path runs
        the bootstrap RPC) so a slow controller can never stall the
        proxy's event loop."""
        if not self._replicas:
            return None
        with self._slot_free:
            chosen = self._pick_slot_locked()
        if chosen is None:
            return None
        replica, key = chosen
        return self._submit(replica, key, method, args, kwargs)

    def assign_with_replica(self, method: Optional[str], args, kwargs):
        """Pick a replica with a free slot; block (condvar, woken by
        completions and replica-set updates) when all are at capacity.
        Returns (result_ref, replica_handle) — the replica is needed to
        drain streaming responses (``_Replica.next_chunks``)."""
        deadline = time.monotonic() + 30
        self._ensure_replicas()
        while True:
            with self._slot_free:
                chosen = self._pick_slot_locked()
                if chosen is None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        detail = (f" (all at max_concurrent_queries="
                                  f"{self._max_cq})"
                                  if self._replicas else "")
                        raise RuntimeError(
                            f"no replica available for "
                            f"{self._name!r}{detail}")
                    self._waiters += 1
                    try:
                        self._slot_free.wait(min(remaining, 1.0))
                    finally:
                        self._waiters -= 1
            if chosen is None:
                self._ensure_replicas()
                continue
            replica, key = chosen
            return self._submit(replica, key, method, args, kwargs)

    def try_assign_batch(self, items):
        """Assign a COALESCED batch to ONE replica in a single actor
        RPC. Takes as many items as the replica's free slots allow
        (>= 1). Returns (ref, replica, n_taken) or None when every
        replica is at capacity / the set is empty."""
        if not self._replicas:
            return None
        with self._slot_free:
            picked = self._pick_slot_locked()  # takes one slot
            if picked is None:
                return None
            replica, key = picked
            free = self._max_cq - self._inflight.get(key, 0)
            extra = min(len(items) - 1, max(free, 0))
            self._inflight[key] += extra
            self._note_inflight(extra)
            n = 1 + extra
        try:
            ref = replica.handle_request_batch.remote(list(items[:n]))
        except Exception:
            self._release(key, n)
            raise

        from ..core import on_ref_ready

        on_ref_ready(ref, lambda k=key, c=n: self._release(k, c))
        return ref, replica, n

    def assign_batch(self, items):
        """Blocking form of try_assign_batch (saturation path)."""
        deadline = time.monotonic() + 30
        self._ensure_replicas()
        while True:
            got = self.try_assign_batch(items)
            if got is not None:
                return got
            with self._slot_free:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise RuntimeError(
                        f"no replica available for {self._name!r}")
                self._waiters += 1
                try:
                    self._slot_free.wait(min(remaining, 1.0))
                finally:
                    self._waiters -= 1
            self._ensure_replicas()

    def _release(self, key: bytes, n: int = 1) -> None:
        with self._slot_free:
            c = self._inflight.get(key, 0)
            # Clamp ONCE and apply the same released amount to both the
            # per-replica map and the router/deployment totals, so a
            # spurious double-release can't make them diverge.
            released = n if n < c else c
            self._inflight[key] = c - released
            self._note_inflight(-released)
            if self._waiters:
                # Gate the wake: _release runs on EVERY request
                # completion, and an unconditional notify_all was a
                # futex storm with zero waiters in the common
                # unsaturated case.
                self._slot_free.notify_all()
