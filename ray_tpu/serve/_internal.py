"""Serve internals: controller, replica, router, autoscaling.

Reference analog (call stack SURVEY §3.5):
  - ``serve/controller.py:61,229,330`` — ServeController actor with a
    reconcile loop driving DeploymentState replica scaling
  - ``serve/_private/deployment_state.py:942,1248`` — target-vs-actual
    replica reconciliation
  - ``serve/_private/router.py:62,221`` — replica set + assignment honoring
    ``max_concurrent_queries``
  - ``serve/_private/autoscaling_policy.py:93,127`` — queue-metric-based
    replica target (the policy math carries over unchanged)
  - ``serve/_private/replica.py`` — replica actor wrapping the user
    callable.

TPU note: replicas hosting pjit-compiled models are plain actors here —
model placement/sharding happens inside the replica via ``parallel``.
"""

from __future__ import annotations

import math
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ..core import get, kill, remote, wait
from ..core.actor import ActorHandle
from ..core.exceptions import (
    ActorError,
    DeadlineExceededError,
    OverloadedError,
    WorkerCrashedError,
)
from ..observability import tracing

# -- first-class Serve metrics (reference: serve/_private/metrics_utils +
# the serve_* series of metric_defs.cc). Created lazily in whichever
# process first serves traffic: replica processes observe request
# counts/latency (shipped to the head by worker telemetry, which tags
# node/worker), the controller process sets the replica-count gauge, and
# driver-side routers set queue depth directly in the head registry.
_serve_metrics_cache: Optional[Dict[str, Any]] = None
_serve_metrics_lock = threading.Lock()


def serve_metrics() -> Optional[Dict[str, Any]]:
    """The serve metric family, or None with telemetry disabled."""
    global _serve_metrics_cache

    from ..core.config import config
    from ..observability.metrics import (
        Counter,
        Gauge,
        Histogram,
        get_or_create,
    )

    if not config().telemetry_enabled:
        return None
    with _serve_metrics_lock:
        if _serve_metrics_cache is None:
            # get_or_create: the telemetry absorber may have minted
            # these names first (controller/replica flushes land before
            # the driver's first Router) — reconstructing would REPLACE
            # the registered metric and drop the absorbed series.
            _serve_metrics_cache = {
                "requests": get_or_create(
                    Counter, "rt_serve_requests",
                    "Serve requests handled per deployment",
                    ("deployment", "result")),
                "latency": get_or_create(
                    Histogram, "rt_serve_request_latency_seconds",
                    "Replica-side request latency",
                    boundaries=[0.001, 0.005, 0.01, 0.05, 0.1, 0.5,
                                1.0, 5.0],
                    tag_keys=("deployment",)),
                "queue_depth": get_or_create(
                    Gauge, "rt_serve_queue_depth",
                    "Router in-flight requests per deployment",
                    ("deployment",)),
                "replicas": get_or_create(
                    Gauge, "rt_serve_replicas",
                    "Live replicas per deployment", ("deployment",)),
                "restarts": get_or_create(
                    Counter, "rt_serve_replica_restarts_total",
                    "Replicas replaced after failed health checks",
                    ("deployment",)),
                "retries": get_or_create(
                    Counter, "rt_serve_retries_total",
                    "Requests re-dispatched after replica death",
                    ("deployment", "reason")),
                "unhealthy": get_or_create(
                    Gauge, "rt_serve_unhealthy_replicas",
                    "Replicas currently failing health checks",
                    ("deployment",)),
                "deadline_exceeded": get_or_create(
                    Counter, "rt_serve_deadline_exceeded_total",
                    "Requests that exceeded their end-to-end deadline"),
            }
        return _serve_metrics_cache


# Deployment-wide in-flight totals shared by EVERY driver-side router
# of a deployment (the proxy and each handle own separate Routers): the
# queue-depth gauge must report their sum, not whichever router wrote
# last. One tiny process-wide lock; the heavy per-request coordination
# stays on each router's own condvar.
_qd_lock = threading.Lock()
_qd_totals: Dict[str, int] = {}

# Deployment-wide BLOCKED-waiter totals (cluster-wide admission): when a
# deployment sets max_pending, an assign that would queue past the bound
# is shed with a typed OverloadedError instead of joining the condvar
# wait. Shares _qd_lock — both are two-instruction critical sections.
_pending_totals: Dict[str, int] = {}


def _pending_note(name: str, delta: int) -> int:
    """Update (delta != 0) or read (delta == 0) the deployment's blocked
    assign count across every router in this process."""
    with _qd_lock:
        total = max(0, _pending_totals.get(name, 0) + delta)
        if delta:
            _pending_totals[name] = total
        return total


def _queue_depth_note(name: str, delta: int, gauge=None,
                      key=None) -> int:
    """Update the deployment total and (when given) mirror it into the
    gauge UNDER the same lock — a set outside it can interleave with
    another router's update and publish a stale value (e.g. nonzero at
    idle). The metric lock is a leaf, so nesting it here is safe."""
    with _qd_lock:
        total = max(0, _qd_totals.get(name, 0) + delta)
        _qd_totals[name] = total
        if gauge is not None:
            gauge.set_key(key, float(total))
    return total


def _session_rendezvous(session_id: str, keys: List[bytes]) -> int:
    """Rendezvous (highest-random-weight) hash of a session id over
    replica actor-id keys. Deterministic and order-independent, so
    EVERY router — and the controller choosing a drain migration
    target — maps a session to the same surviving replica without any
    coordination: after a drain or crash the re-pinned replica is
    exactly the one the sessions were migrated to."""
    import hashlib

    sid = session_id.encode()
    best_i = 0
    best_h = b""
    for i, k in enumerate(keys):
        h = hashlib.sha1(sid + k).digest()
        if h > best_h:
            best_i, best_h = i, h
    return best_i


class SessionLog:
    """Head-side bounded transcript log for stateful LLM sessions.

    The proxy appends (transcript, seed) after every successful
    session-tagged generation. When a session's pinned replica dies
    WITHOUT exporting (SIGKILL — no drain, no page migration), the
    re-pinned replica reconstructs the session by re-prefilling this
    transcript (``restore_session``): cheap when its radix prefix cache
    hits, correct always. Bounded two ways: whole sessions are evicted
    LRU past ``max_sessions``, and a transcript is capped at
    ``max_tokens`` (the resident prefix is what recovery needs; an
    over-long tail would re-prefill past max_seq anyway)."""

    def __init__(self, max_sessions: int = 512, max_tokens: int = 8192):
        from collections import OrderedDict

        self.max_sessions = max_sessions
        self.max_tokens = max_tokens
        self._entries: "Dict[tuple, dict]" = OrderedDict()
        self._lock = threading.Lock()

    def note(self, deployment: str, session_id: str, transcript,
             seed=None, temperature: float = 0.0) -> None:
        toks = [int(t) for t in transcript][: self.max_tokens]
        with self._lock:
            self._entries[(deployment, session_id)] = {
                "transcript": toks,
                "seed": None if seed is None else int(seed),
                "temperature": float(temperature),
                "t": time.monotonic(),
            }
            self._entries.move_to_end((deployment, session_id))
            while len(self._entries) > self.max_sessions:
                self._entries.popitem(last=False)

    def get(self, deployment: str, session_id: str) -> Optional[dict]:
        with self._lock:
            entry = self._entries.get((deployment, session_id))
            return None if entry is None else dict(entry)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


@dataclass
class AutoscalingConfig:
    """Reference: serve/config.py AutoscalingConfig."""

    min_replicas: int = 1
    max_replicas: int = 4
    target_num_ongoing_requests_per_replica: float = 2.0
    upscale_delay_s: float = 0.5
    downscale_delay_s: float = 2.0


@dataclass
class DeploymentInfo:
    name: str
    deployment_def: Any  # class or function (cloudpickleable)
    init_args: tuple = ()
    init_kwargs: dict = field(default_factory=dict)
    num_replicas: int = 1
    max_concurrent_queries: int = 100
    route_prefix: Optional[str] = None
    autoscaling: Optional[AutoscalingConfig] = None
    ray_actor_options: dict = field(default_factory=dict)
    version: int = 0
    request_timeout_s: Optional[float] = None
    user_config: Optional[dict] = None
    # -- fault tolerance / admission (ISSUE 18) --------------------------
    # End-to-end deadline per request (queueing + retries + handler);
    # None = no deadline beyond request_timeout_s per attempt.
    request_deadline_s: Optional[float] = None
    # Safe-retry budget for requests that die with the replica BEFORE
    # any response byte; 0 disables. Non-idempotent deployments fail
    # fast with the typed actor error instead of re-dispatching.
    max_request_retries: int = 2
    retry_backoff_s: float = 0.05
    idempotent: bool = True
    # Cluster-wide admission: bound on blocked (queued) assigns across
    # every router of this deployment, and how long a queued request may
    # wait for a slot before being shed as OverloadedError -> HTTP 503.
    max_pending: Optional[int] = None
    queue_timeout_s: Optional[float] = None
    # Controller liveness probes: period between probes, per-probe
    # timeout, and consecutive failures before the replica is evicted
    # and replaced. None period disables health checking.
    health_check_period_s: Optional[float] = 1.0
    health_check_timeout_s: float = 5.0
    health_check_failure_threshold: int = 3


def _err_payload(e: BaseException):
    """Per-item batch error payload. Errors are stringified for
    transport (arbitrary app exceptions may not pickle) EXCEPT the typed
    control-flow errors the proxy must isinstance-match — admission
    sheds (-> 503) and deadline expiry (-> 504) — which are
    known-picklable and travel as live exceptions."""
    if isinstance(e, (OverloadedError, DeadlineExceededError)):
        return e
    return repr(e)


class _Replica:
    """Replica actor body (reference: RayServeReplica).

    Request methods are ASYNC: the actor machinery runs every coroutine
    method on the replica's ONE persistent asyncio event loop (see
    ``core/worker_main.py`` async-actor support), so concurrent requests
    interleave at awaits instead of each spinning up a throwaway loop —
    the asyncio request plane of ``serve/_private/replica.py``. Streaming
    responses register a (async) generator under a stream id which the
    caller drains with ``next_chunks`` (chunked-pull streaming).
    """

    def __init__(self, deployment_def, init_args, init_kwargs,
                 request_timeout_s: Optional[float] = None,
                 user_config: Optional[dict] = None,
                 deployment_name: str = ""):
        import inspect

        if inspect.isclass(deployment_def):
            self.callable = deployment_def(*init_args, **init_kwargs)
        else:
            self.callable = deployment_def
        if user_config is not None:
            # Applied during construction, BEFORE the replica is
            # routable — a post-creation reconfigure RPC could race with
            # routed requests on a concurrent actor.
            self.reconfigure(user_config)
        self._ongoing = 0
        self._total = 0
        self._timeout = request_timeout_s
        self._streams: Dict[int, Any] = {}
        self._stream_counter = 0
        # Request counter + latency histogram, deployment-tagged; the
        # worker telemetry flusher ships them to the head registry. Tag
        # keys interned once — this runs per request.
        self._deployment = deployment_name
        self._metrics = serve_metrics()
        if self._metrics is not None:
            self._key_ok = (("deployment", deployment_name),
                            ("result", "ok"))
            self._key_err = (("deployment", deployment_name),
                             ("result", "error"))
            self._key_lat = (("deployment", deployment_name),)

    def _observe(self, start: float, n: int, ok: bool) -> None:
        if self._metrics is None:
            return
        elapsed = time.perf_counter() - start
        self._metrics["requests"].inc_key(
            self._key_ok if ok else self._key_err, n)
        self._metrics["latency"].observe_key(self._key_lat, elapsed,
                                             count=n)

    def _observe_batch(self, start: float, n: int, results) -> None:
        """Coalesced-entry accounting: ``results`` is the final
        ("ok"|"err", value) list, or None when the whole batch raised —
        per-item errors must land in result="error", not "ok"."""
        if self._metrics is None:
            return
        elapsed = time.perf_counter() - start
        n_err = (sum(1 for tag, _ in results if tag == "err")
                 if results is not None else n)
        if n - n_err:
            self._metrics["requests"].inc_key(self._key_ok, n - n_err)
        if n_err:
            self._metrics["requests"].inc_key(self._key_err, n_err)
        self._metrics["latency"].observe_key(self._key_lat, elapsed,
                                             count=n)

    @staticmethod
    def _resolve_target(fn):
        import inspect

        return fn.__call__ if not inspect.isfunction(fn) and not \
            inspect.ismethod(fn) and callable(fn) else fn

    def _register_stream(self, gen):
        """Register a generator result under a stream id (must run on
        the replica's event loop — _streams is loop-confined)."""
        self._sweep_streams()
        self._stream_counter += 1
        self._streams[self._stream_counter] = (gen, time.monotonic())
        return ("__rt_stream__", self._stream_counter)

    def _limit(self, timeout_s: Optional[float]) -> Optional[float]:
        """Effective per-attempt timeout: the deployment's
        request_timeout_s bounded by the request's remaining deadline
        (propagated proxy -> router -> replica). None = unbounded."""
        if timeout_s is None:
            return self._timeout
        if self._timeout is None:
            return timeout_s
        return min(self._timeout, timeout_s)

    async def _invoke(self, fn, args, kwargs,
                      timeout_s: Optional[float] = None):
        import asyncio
        import functools
        import inspect

        limit = self._limit(timeout_s)
        try:
            target = self._resolve_target(fn)
            if inspect.iscoroutinefunction(target):
                coro = fn(*args, **kwargs)
                result = await (asyncio.wait_for(coro, limit)
                                if limit else coro)
            else:
                # Sync handlers run off-loop so concurrent requests (e.g.
                # @serve.batch coalescing) aren't serialized behind the
                # replica's event loop.
                loop = asyncio.get_running_loop()
                call = loop.run_in_executor(
                    None, functools.partial(fn, *args, **kwargs))
                result = await (asyncio.wait_for(call, limit)
                                if limit else call)
                if inspect.iscoroutine(result):
                    result = await (asyncio.wait_for(result, limit)
                                    if limit else result)
        except asyncio.TimeoutError:
            raise DeadlineExceededError(
                f"request exceeded its deadline ({limit:.3f}s) in "
                f"deployment {self._deployment!r}") from None
        if inspect.isgenerator(result) or inspect.isasyncgen(result):
            return self._register_stream(result)
        return result

    def _sweep_streams(self, idle_s: float = 300.0) -> None:
        """Close streams abandoned by their consumer (client disconnect,
        dropped StreamingResponse) so generators don't leak for the
        replica's lifetime. Lazy sweep on registration — no timers."""
        now = time.monotonic()
        for sid in [s for s, (_, t) in self._streams.items()
                    if now - t > idle_s]:
            gen, _ = self._streams.pop(sid)
            try:
                close = getattr(gen, "close", None) or getattr(
                    gen, "aclose", None)
                if close is not None:
                    res = close()
                    if hasattr(res, "__await__"):
                        import asyncio

                        asyncio.ensure_future(res)
            except Exception:
                pass

    async def handle_request(self, args, kwargs,
                             timeout_s: Optional[float] = None,
                             trace_ctx: Optional[tuple] = None):
        # Sweep abandoned streams from the request path too: a replica
        # whose LAST streaming consumer disconnected would otherwise
        # leak that generator until another streaming request arrives.
        if self._streams:
            self._sweep_streams()
        self._ongoing += 1
        self._total += 1
        start = time.perf_counter()
        # ContextVar, not the thread-local span stack: this coroutine
        # interleaves with other requests on the replica's one event
        # loop, and the binding must follow THIS request across awaits
        # (nested .remote() calls and the LLM engine read it back).
        token = tracing.set_request_context(trace_ctx)
        t0 = time.time()
        ok = True
        try:
            fn = self.callable
            if not callable(fn):
                raise TypeError("deployment is not callable")
            return await self._invoke(fn, args, kwargs, timeout_s)
        except BaseException:
            ok = False
            raise
        finally:
            if trace_ctx is not None:
                tracing.record_span(
                    "replica.handle", trace_id=trace_ctx[0],
                    parent_id=trace_ctx[1], start_s=t0,
                    deployment=self._deployment,
                    **({} if ok else {"error": "handler raised"}))
            tracing.reset_request_context(token)
            self._observe(start, 1, ok)
            self._ongoing -= 1

    async def handle_request_batch(self, items,
                                   timeout_s: Optional[float] = None):
        """Coalesced entry: N requests in ONE actor RPC (the proxy's
        Nagle-style batching — on a host where the per-call actor hop is
        the serving bottleneck, coalescing divides it by the batch).
        Results are per-item isolated: ("ok", value) or ("err", repr).

        Async handlers run concurrently under asyncio.gather with full
        _invoke semantics. Sync handlers run in ONE executor task for
        the whole batch — a single thread hop instead of one per item
        (the per-item hop was the dominant serving cost on a contended
        host), with the event loop staying free for streams and async
        requests. Within-batch items of a sync handler are sequential;
        request_timeout_s bounds the whole batch on that path (a sync
        handler cannot be interrupted item-by-item anyway)."""
        import asyncio
        import inspect

        if self._streams:
            self._sweep_streams()
        # Items are (args, kwargs) or (args, kwargs, trace_ctx) — the
        # proxy ships per-request trace ctx as a third element; older
        # callers (tests, handle fan-out) still send pairs.
        items = [(it[0], it[1], it[2] if len(it) > 2 else None)
                 for it in items]
        self._ongoing += len(items)
        self._total += len(items)
        start = time.perf_counter()
        limit = self._limit(timeout_s)
        out = None
        try:
            fn = self.callable
            if callable(fn) and inspect.iscoroutinefunction(
                    self._resolve_target(fn)):
                async def one(args, kwargs, ctx):
                    # gather() wraps each coroutine in its own task with
                    # a COPY of the current context, so this binding is
                    # per-item even though all items share the loop.
                    token = tracing.set_request_context(ctx)
                    t0 = time.time()
                    err = None
                    try:
                        return ("ok", await self._invoke(fn, args,
                                                         kwargs,
                                                         timeout_s))
                    except Exception as e:  # noqa: BLE001 — isolation
                        err = type(e).__name__
                        return ("err", _err_payload(e))
                    finally:
                        if ctx is not None:
                            attrs = {"deployment": self._deployment}
                            if err:
                                attrs["error"] = err
                            tracing.record_span(
                                "replica.handle", trace_id=ctx[0],
                                parent_id=ctx[1], start_s=t0, **attrs)
                        tracing.reset_request_context(token)

                out = list(await asyncio.gather(
                    *(one(a, k, c) for a, k, c in items)))
                return out

            def run_all():
                out = []
                for a, k, ctx in items:
                    t0 = time.time()
                    try:
                        if not callable(fn):
                            raise TypeError("deployment is not callable")
                        # Sync handlers run on ONE executor thread, so
                        # the thread-local remote context is safe here.
                        with tracing.remote_context(ctx):
                            out.append(("ok", fn(*a, **k)))
                    except Exception as e:  # noqa: BLE001 — isolation
                        out.append(("err", _err_payload(e)))
                    if ctx is not None:
                        tracing.record_span(
                            "replica.handle", trace_id=ctx[0],
                            parent_id=ctx[1], start_s=t0,
                            deployment=self._deployment)
                return out

            loop = asyncio.get_running_loop()
            call = loop.run_in_executor(None, run_all)
            try:
                results = await (asyncio.wait_for(call, limit)
                                 if limit else call)
            except asyncio.TimeoutError:
                raise DeadlineExceededError(
                    f"batch exceeded its deadline ({limit:.3f}s) in "
                    f"deployment {self._deployment!r}") from None
            final = []
            for tag, val in results:
                if tag == "ok":
                    try:
                        if inspect.iscoroutine(val):
                            val = await (asyncio.wait_for(
                                val, limit) if limit
                                else val)
                        if inspect.isgenerator(val) or inspect.isasyncgen(
                                val):
                            val = self._register_stream(val)
                    except Exception as e:  # noqa: BLE001 — isolation
                        tag, val = "err", _err_payload(e)
                final.append((tag, val))
            out = final
            return out
        finally:
            self._observe_batch(start, len(items), out)
            self._ongoing -= len(items)

    async def call_method(self, method, args, kwargs,
                          timeout_s: Optional[float] = None,
                          trace_ctx: Optional[tuple] = None):
        self._ongoing += 1
        self._total += 1
        start = time.perf_counter()
        token = tracing.set_request_context(trace_ctx)
        ok = True
        try:
            return await self._invoke(
                getattr(self.callable, method), args, kwargs, timeout_s)
        except BaseException:
            ok = False
            raise
        finally:
            tracing.reset_request_context(token)
            self._observe(start, 1, ok)
            self._ongoing -= 1

    async def health_check(self) -> bool:
        """Controller liveness probe. A replica whose event loop is
        wedged (sync work on the loop, deadlocked handler) simply never
        answers — the controller counts the timeout. Deployments can add
        their own semantics via a ``check_health`` method (raise =
        unhealthy)."""
        fn = getattr(self.callable, "check_health", None)
        if fn is not None:
            res = fn()
            if hasattr(res, "__await__"):
                await res
        return True

    async def next_chunks(self, stream_id: int, max_n: int = 8):
        """Drain up to ``max_n`` items from a registered stream; returns
        (done, items). The stream is dropped when exhausted."""
        import inspect

        if self._streams:
            self._sweep_streams()
        entry = self._streams.get(stream_id)
        if entry is None:
            return True, []
        gen = entry[0]
        self._streams[stream_id] = (gen, time.monotonic())
        items = []
        try:
            if inspect.isasyncgen(gen):
                async for item in gen:
                    items.append(item)
                    if len(items) >= max_n:
                        return False, items
            else:
                for item in gen:
                    items.append(item)
                    if len(items) >= max_n:
                        return False, items
        finally:
            if len(items) < max_n:
                self._streams.pop(stream_id, None)
        return True, items

    def metrics(self):
        # "streams" lets the controller's drain verb wait for handed-off
        # streaming responses (no longer "ongoing") to finish before the
        # replica is terminated — killing earlier severs them mid-stream.
        return {"ongoing": self._ongoing, "total": self._total,
                "streams": len(self._streams)}

    def reconfigure(self, user_config):
        if hasattr(self.callable, "reconfigure"):
            self.callable.reconfigure(user_config)
        return True


class ServeController:
    """Controller actor: owns deployment state, reconciles replicas.

    Reference: serve/controller.py — ``deploy`` (:330) +
    ``run_control_loop`` (:229). The control loop runs INSIDE the actor
    (``start_loop`` spawns it), so Serve keeps reconciling after driver
    handles are GC'd; routers learn of replica-set changes through the
    blocking ``listen_for_change`` long-poll (reference:
    long_poll.py:184 LongPollHost snapshot-ids), not interval polling.
    """

    def __init__(self):
        import threading

        self.deployments: Dict[str, DeploymentInfo] = {}
        self.replicas: Dict[str, List[Any]] = {}
        # Per-deployment, per-replica (actor-id keyed) probe state:
        # {"probe": outstanding ref|None, "sent": ts, "fails": n,
        #  "ok": answered-at-least-once}. See _health_sweep_locked.
        self._health: Dict[str, Dict[bytes, dict]] = {}
        # Replicas removed from the routable set by drain() but still
        # alive finishing in-flight work; killed once quiescent.
        self._draining: Dict[str, List[Any]] = {}
        self._metrics: Dict[str, List[float]] = {}
        self._last_scale_up: Dict[str, float] = {}
        self._last_scale_down: Dict[str, float] = {}
        self._lock = threading.RLock()
        self._change = threading.Condition(self._lock)
        self._versions: Dict[str, int] = {}
        self._loop_stop = threading.Event()
        self._loop_thread = None

    def _bump_locked(self, name: str) -> None:
        self._versions[name] = self._versions.get(name, 0) + 1
        self._change.notify_all()

    # -- control loop (runs inside the actor process) -----------------------
    def start_loop(self, interval_s: float = 0.25) -> bool:
        import threading

        if self._loop_thread is not None:
            return False

        def loop():
            while not self._loop_stop.wait(interval_s):
                try:
                    self.reconcile()
                except Exception:
                    pass

        self._loop_thread = threading.Thread(
            target=loop, daemon=True, name="serve-control-loop")
        self._loop_thread.start()
        return True

    def stop_loop(self) -> bool:
        self._loop_stop.set()
        return True

    # -- deploy API ----------------------------------------------------------
    def deploy(self, info: DeploymentInfo) -> bool:
        with self._lock:
            existing = self.deployments.get(info.name)
            if existing is not None:
                info.version = existing.version + 1
            self.deployments[info.name] = info
            self._reconcile_deployment(info.name,
                                       redeploy=existing is not None)
        return True

    def delete_deployment(self, name: str) -> bool:
        with self._lock:
            info = self.deployments.pop(name, None)
            victims = self.replicas.pop(name, [])
            victims += self._draining.pop(name, [])
            self._health.pop(name, None)
            self._bump_locked(name)
        metrics = serve_metrics()
        if metrics is not None:
            metrics["replicas"].set(0.0, tags={"deployment": name})
        for r in victims:
            try:
                kill(r)
            except Exception:
                pass
        return info is not None

    # -- graceful drain (ISSUE 19) -------------------------------------------
    def drain(self, name: str, replica_actor_id: Optional[str] = None,
              timeout_s: float = 30.0, migrate: bool = True) -> dict:
        """Gracefully remove ONE replica: stop new assignments (routers
        learn on the next long-poll push; target-count reconciliation
        spawns the replacement), migrate resident LLM sessions to the
        surviving replicas they will re-pin to (same rendezvous hash
        the routers use), let in-flight requests AND handed-off streams
        finish, then terminate. Zero dropped requests, zero 503s
        attributable to the drain — the stateful counterpart to the
        health sweep's kill-and-replace."""
        t0 = time.monotonic()
        report: dict = {"deployment": name, "sessions_migrated": 0,
                        "migrate_errors": 0, "migrate_ms": [],
                        "sessions": [], "timed_out": False}
        with self._lock:
            current = self.replicas.get(name, [])
            victim = None
            if replica_actor_id is None:
                victim = current[0] if current else None
            else:
                for r in current:
                    if r._actor_id.hex() == replica_actor_id:
                        victim = r
                        break
            if victim is None:
                report["error"] = (f"no such replica in deployment "
                                   f"{name!r}")
                return report
            current.remove(victim)
            self._health.get(name, {}).pop(victim._actor_id.binary(),
                                           None)
            self._draining.setdefault(name, []).append(victim)
            report["replica"] = victim._actor_id.hex()
            self._bump_locked(name)
        # Let reconciliation register the replacement handle before
        # choosing migration targets: the rendezvous set must match
        # what routers will re-pin against (calls on a replica still
        # constructing queue in its mailbox, so import can proceed).
        target_wait = min(5.0, timeout_s / 2)
        while time.monotonic() - t0 < target_wait:
            with self._lock:
                info = self.deployments.get(name)
                have = len(self.replicas.get(name, []))
                want = self._target_replicas(name) if info else 0
            if have >= want or have == 0:
                break
            time.sleep(0.05)
        if migrate:
            self._migrate_sessions(name, victim, report,
                                   deadline=t0 + timeout_s)
        # Quiesce: both the request counter and handed-off streams must
        # reach zero on a few consecutive polls (a request may be
        # between router assignment and handle_request entry).
        zero_polls = 0
        while time.monotonic() - t0 < timeout_s:
            try:
                m = get(victim.metrics.remote(), timeout=5)
            except Exception:
                break  # already dead: nothing left to wait for
            if m.get("ongoing", 0) <= 0 and m.get("streams", 0) <= 0:
                zero_polls += 1
                if zero_polls >= 3:
                    break
            else:
                zero_polls = 0
            time.sleep(0.05)
        else:
            report["timed_out"] = True
        report["drained_ms"] = round((time.monotonic() - t0) * 1e3, 3)
        try:
            kill(victim)
        except Exception:
            pass
        with self._lock:
            lst = self._draining.get(name, [])
            if victim in lst:
                lst.remove(victim)
        return report

    def _migrate_sessions(self, name: str, victim, report: dict,
                          deadline: float) -> None:
        """Export every resident session from the draining replica and
        import each into the surviving replica its id rendezvous-hashes
        to. Deployments without session methods (anything that isn't an
        LLM server) drain without migration."""
        try:
            snaps = get(victim.call_method.remote("export_sessions",
                                                  (), {}),
                        timeout=max(5.0, deadline - time.monotonic()))
        except Exception as e:  # noqa: BLE001 — non-LLM deployment
            report["export_skipped"] = repr(e)[:200]
            return
        if not snaps:
            return
        with self._lock:
            targets = list(self.replicas.get(name, []))
        if not targets:
            report["migrate_errors"] = len(snaps)
            report["export_skipped"] = "no surviving replicas"
            return
        keys = [r._actor_id.binary() for r in targets]
        for snap in snaps:
            sid = snap.get("session_id")
            tgt = targets[_session_rendezvous(str(sid), keys)]
            t1 = time.monotonic()
            try:
                get(tgt.call_method.remote("import_session", (snap,),
                                           {}),
                    timeout=max(5.0, deadline - time.monotonic()))
                report["sessions_migrated"] += 1
                report["migrate_ms"].append(
                    round((time.monotonic() - t1) * 1e3, 3))
                report["sessions"].append(sid)
            except Exception as e:  # noqa: BLE001 — keep draining
                report["migrate_errors"] += 1
                report.setdefault("migrate_error_detail",
                                  repr(e)[:200])

    # -- long-poll config push ----------------------------------------------
    def listen_for_change(self, name: str, known_version: int,
                          timeout_s: float = 30.0):
        """Block until the replica set of ``name`` changes past
        ``known_version`` (or timeout); returns (version, replicas,
        router_cfg). Reference: LongPollHost.listen_for_change — routers
        hold one of these calls open instead of polling on an interval.
        router_cfg carries the deployment's retry/admission/deadline
        knobs so every config change reaches routers on the same push
        that delivers replica-set changes."""
        deadline = time.monotonic() + timeout_s
        with self._change:
            while self._versions.get(name, 0) <= known_version:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._change.wait(remaining)
            return (self._versions.get(name, 0),
                    list(self.replicas.get(name, [])),
                    self._router_cfg_locked(name))

    def _router_cfg_locked(self, name: str) -> dict:
        info = self.deployments.get(name)
        if info is None:
            return {}
        return {
            "max_request_retries": info.max_request_retries,
            "retry_backoff_s": info.retry_backoff_s,
            "idempotent": info.idempotent,
            "max_pending": info.max_pending,
            "queue_timeout_s": info.queue_timeout_s,
            "request_deadline_s": info.request_deadline_s,
        }

    def reconfigure_deployment(self, name: str, user_config) -> int:
        """Push a new user_config to every live replica in parallel;
        returns how many acknowledged (reference: controller.py
        deploy-with-user_config → replica reconfigure; the config-file
        ops path sets this per deployment). New replicas pick the config
        up at creation (_reconcile_deployment)."""
        with self._lock:
            info = self.deployments.get(name)
            if info is None:
                return -1
            info.user_config = user_config
            replicas = list(self.replicas.get(name, []))
        if not replicas:
            return 0
        from ..core import wait as _wait

        refs = [r.reconfigure.remote(user_config) for r in replicas]
        done, _pending = _wait(refs, num_returns=len(refs), timeout=30)
        return len(done)

    def list_deployments(self) -> Dict[str, dict]:
        with self._lock:
            return self._list_deployments_locked()

    def _list_deployments_locked(self) -> Dict[str, dict]:
        return {
            name: {
                "num_replicas": len(self.replicas.get(name, [])),
                "target": self._target_replicas(name),
                "route_prefix": info.route_prefix,
                "version": info.version,
            }
            for name, info in self.deployments.items()
        }

    def get_replicas(self, name: str) -> List[Any]:
        with self._lock:
            return list(self.replicas.get(name, []))

    def get_replica_snapshot(self, name: str):
        with self._lock:
            return (self._versions.get(name, 0),
                    list(self.replicas.get(name, [])),
                    self._router_cfg_locked(name))

    def get_deployment_names(self) -> List[str]:
        with self._lock:
            return list(self.deployments)

    # -- reconciliation ------------------------------------------------------
    def _target_replicas(self, name: str) -> int:
        info = self.deployments.get(name)
        if info is None:
            return 0
        if info.autoscaling is None:
            return info.num_replicas
        return self._autoscale_target(name, info)

    def _autoscale_target(self, name: str, info: DeploymentInfo) -> int:
        """Reference: autoscaling_policy.py:127 get_decision_num_replicas —
        target = ceil(total_ongoing / target_per_replica), clamped, with
        up/downscale delay."""
        cfg = info.autoscaling
        current = len(self.replicas.get(name, []))
        ongoing = self._collect_ongoing(name)
        desired = math.ceil(
            ongoing / max(cfg.target_num_ongoing_requests_per_replica, 1e-9)
        )
        desired = max(cfg.min_replicas, min(cfg.max_replicas, desired))
        now = time.monotonic()
        if desired > current:
            first = self._last_scale_up.setdefault(name, now)
            if now - first >= cfg.upscale_delay_s:
                self._last_scale_up.pop(name, None)
                return desired
            return current
        self._last_scale_up.pop(name, None)
        if desired < current:
            first = self._last_scale_down.setdefault(name, now)
            if now - first >= cfg.downscale_delay_s:
                self._last_scale_down.pop(name, None)
                return desired
            return current
        self._last_scale_down.pop(name, None)
        return current

    def _collect_ongoing(self, name: str) -> float:
        total = 0.0
        refs = []
        replicas = self.replicas.get(name, [])
        for r in replicas:
            refs.append(r.metrics.remote())
        if refs:
            ready, _ = wait(refs, num_returns=len(refs), timeout=1.0)
            for ref in ready:
                try:
                    total += get(ref)["ongoing"]
                except Exception:
                    pass
        return total

    def reconcile(self) -> Dict[str, int]:
        """One control-loop tick (reference: run_control_loop body)."""
        out = {}
        with self._lock:
            names = list(self.deployments)
        for name in names:
            with self._lock:
                if name not in self.deployments:
                    continue
                out[name] = self._reconcile_deployment(name)
        return out

    def _health_sweep_locked(self, name: str, info: DeploymentInfo,
                             current: List[Any]) -> bool:
        """Probe every replica's liveness; evict the ones past the
        failure threshold. Returns True when the replica set changed
        (the caller's target loop then creates replacements — target-
        count reconciliation, never in-place restart, so routers can't
        keep dispatching to a stale handle).

        Probe outcomes per replica (actor-id keyed state):
          - probe resolves OK          -> fails = 0, mark responsive
          - probe resolves with error  -> dead/raising: evict NOW (the
            runtime already knows the actor died; waiting out the
            threshold only extends the outage)
          - probe outstanding past health_check_timeout_s -> hung: count
            one failure, but ONLY once the replica has answered at least
            one probe — a replica still constructing (LLM warmup can
            compile for many seconds) must not be culled mid-warmup.
        """
        now = time.monotonic()
        hstate = self._health.setdefault(name, {})
        threshold = max(1, info.health_check_failure_threshold)
        live_keys = set()
        dead: List[Any] = []
        for r in current:
            key = r._actor_id.binary()
            live_keys.add(key)
            st = hstate.setdefault(key, {"probe": None, "sent": now,
                                         "fails": 0, "ok": False})
            probe = st["probe"]
            if probe is not None:
                ready, _ = wait([probe], num_returns=1, timeout=0)
                if ready:
                    st["probe"] = None
                    try:
                        get(ready[0])
                        st["fails"] = 0
                        st["ok"] = True
                    except Exception:
                        st["fails"] = threshold
                elif now - st["sent"] > info.health_check_timeout_s:
                    st["probe"] = None
                    if st["ok"]:
                        st["fails"] += 1
            if (st["probe"] is None and st["fails"] < threshold
                    and now - st["sent"] >= info.health_check_period_s):
                try:
                    st["probe"] = r.health_check.remote()
                    st["sent"] = now
                except Exception:
                    st["fails"] = threshold
            if st["fails"] >= threshold:
                dead.append((r, key))
        for key in [k for k in hstate if k not in live_keys]:
            hstate.pop(key)
        metrics = serve_metrics()
        if metrics is not None:
            metrics["unhealthy"].set(float(len(dead)),
                                     tags={"deployment": name})
        if not dead:
            return False
        for r, key in dead:
            current.remove(r)
            hstate.pop(key, None)
            try:
                kill(r)  # hung replicas hold a worker process hostage
            except Exception:
                pass
            if metrics is not None:
                metrics["restarts"].inc(1.0, tags={"deployment": name})
        return True

    def _reconcile_deployment(self, name: str, redeploy: bool = False) -> int:
        info = self.deployments[name]
        current = self.replicas.setdefault(name, [])
        if redeploy:
            for r in current:
                try:
                    kill(r)
                except Exception:
                    pass
            current.clear()
            self._health.pop(name, None)
        target = self._target_replicas(name)
        replica_cls = remote(_Replica)
        changed = redeploy
        if not redeploy and info.health_check_period_s is not None:
            changed = self._health_sweep_locked(name, info,
                                                current) or changed
        while len(current) < target:
            changed = True
            opts = dict(info.ray_actor_options)
            actor = replica_cls.options(
                max_concurrency=max(2, info.max_concurrent_queries),
                **opts,
            ).remote(info.deployment_def, info.init_args, info.init_kwargs,
                     request_timeout_s=info.request_timeout_s,
                     user_config=info.user_config,
                     deployment_name=name)
            current.append(actor)
        while len(current) > target:
            victim = current.pop()
            changed = True
            try:
                kill(victim)
            except Exception:
                pass
        metrics = serve_metrics()
        if metrics is not None:
            # Runs in the controller process; telemetry ships it head-ward.
            metrics["replicas"].set(float(len(current)),
                                    tags={"deployment": name})
        if changed:
            self._bump_locked(name)
        return len(current)


class Router:
    """Client-side replica selection (reference: router.py ReplicaSet).

    Round-robin with ENFORCED per-replica in-flight caps: each assigned
    request registers a completion watcher (``core.on_ref_ready``) that
    releases the slot when the result lands, so a replica never holds
    more than ``max_concurrent_queries`` outstanding requests
    (router.py:62,221). Replica-set updates arrive through the
    controller's blocking ``listen_for_change`` long-poll held open by a
    background listener thread (long_poll.py:67 LongPollClient), not
    interval polling.
    """

    def __init__(self, controller, deployment_name: str,
                 max_concurrent_queries: int = 100):
        import threading

        self._controller = controller
        self._name = deployment_name
        self._max_cq = max_concurrent_queries
        self._replicas: List[Any] = []
        # Parallel to _replicas: cached actor-id keys, so the pick loop
        # never re-derives ``_actor_id.binary()`` per replica per
        # request (an O(replicas) allocation storm at 8 replicas that
        # helped INVERT handle throughput vs 1 replica).
        self._keys: List[bytes] = []
        self._version = -1
        self._rr = 0  # sticky pick: index of the previous replica
        self._slack = 16  # see _pick_slot_locked sticky-with-slack
        # keyed by replica actor id (stable across replica-set updates)
        self._inflight: Dict[bytes, int] = {}
        # Router-wide in-flight total -> rt_serve_queue_depth gauge.
        # DRIVER routers only: gauges keep producer tags through absorb,
        # so a nested replica-worker router shipping the same
        # {deployment} key would clobber the driver's live value with
        # its own (usually near-zero) count. The driver (proxy +
        # handles) is the authoritative ingress queue.
        from ..core.runtime import is_worker_process

        self._nq = 0
        self._metrics = None if is_worker_process() else serve_metrics()
        if self._metrics is not None:
            self._qd_key = (("deployment", deployment_name),)
        # Deployment retry/admission/deadline knobs, pushed by the
        # controller on the same long-poll as replica-set changes.
        self._cfg: Dict[str, Any] = {}
        # oid-binary -> replica that ACTUALLY served a retried request
        # (bounded; see replica_for) — streaming consumers must drain
        # next_chunks from the replica that holds the stream, not the
        # dead one originally picked.
        self._retried_replica: Dict[bytes, Any] = {}
        # session id -> pinned replica key (sticky routing). Lazy
        # re-pin: a pin whose replica left the set is re-resolved with
        # the rendezvous hash on next use — the same hash the
        # controller's drain verb used to place the migrated sessions.
        self._sticky: Dict[str, bytes] = {}
        self._waiters = 0  # blocked assigners; gate for notify_all
        self._lock = threading.Lock()
        self._slot_free = threading.Condition(self._lock)
        self._stop = threading.Event()
        self._listener = threading.Thread(
            target=self._listen_loop, daemon=True,
            name=f"serve-router-{deployment_name}")
        self._listener.start()

    def _listen_loop(self):
        """Long-poll: one blocking listen_for_change call held open."""
        while not self._stop.is_set():
            try:
                version, replicas, cfg = get(
                    self._controller.listen_for_change.remote(
                        self._name, self._version),
                    timeout=45,
                )
                with self._slot_free:
                    self._cfg = cfg or {}
                    if version != self._version:
                        self._version = version
                        self._set_replicas_locked(replicas)
                        self._slot_free.notify_all()
            except Exception:
                if self._stop.is_set():
                    return
                time.sleep(0.5)

    def _set_replicas_locked(self, replicas) -> None:
        self._replicas = replicas
        self._keys = [r._actor_id.binary() for r in replicas]
        # Evicted-replica cleanup: in-flight counts keyed by a replica
        # that left the set would otherwise linger forever — its
        # requests fail (actor death) and their _release clamps to the
        # popped key's 0, so the router-wide total (_nq and the shared
        # queue-depth gauge) stays permanently offset: the phantom-
        # queue-depth leak. Give the residual back NOW; late _release
        # calls on the popped key no-op against the clamp.
        live = set(self._keys)
        for key in [k for k in self._inflight if k not in live]:
            residual = self._inflight.pop(key)
            if residual:
                self._note_inflight(-residual)
        if self._waiters:
            self._slot_free.notify_all()

    def _ensure_replicas(self, timeout: float = 5.0) -> None:
        """First-use bootstrap: snapshot directly (the long-poll only
        reports CHANGES past our version)."""
        if self._replicas:
            return
        try:
            version, replicas, cfg = get(
                self._controller.get_replica_snapshot.remote(self._name),
                timeout=timeout,
            )
            with self._slot_free:
                self._cfg = cfg or {}
                if version >= self._version and replicas:
                    self._version = version
                    self._set_replicas_locked(replicas)
        except Exception:
            pass

    def stop(self):
        self._stop.set()
        # Give back this router's outstanding queue-depth contribution:
        # serve.shutdown() drops routers with requests still in flight,
        # and their _release callbacks may never run — without this the
        # deployment-wide total (_qd_totals) stays offset forever and a
        # restarted serve instance inherits a phantom queue depth.
        # Clearing _inflight makes any late _release a no-op (its clamp
        # sees 0), so the residual can't be subtracted twice.
        with self._slot_free:
            residual, self._nq = self._nq, 0
            self._inflight.clear()
        if residual and self._metrics is not None:
            _queue_depth_note(self._name, -residual,
                              self._metrics["queue_depth"], self._qd_key)

    def stats(self) -> Dict[str, Any]:
        """Router-local routing state (for tests/diagnostics)."""
        with self._slot_free:
            return {"replicas": len(self._replicas),
                    "sticky_index": self._rr,
                    "queue_depth": self._nq,
                    "inflight": dict(self._inflight)}

    def _note_inflight(self, delta: int) -> None:
        """Under self._slot_free: track this router's in-flight count
        and mirror the DEPLOYMENT-WIDE total (summed across routers via
        _queue_depth_note) into the gauge — interned key, so the added
        hot-path cost is two uncontended dict stores."""
        self._nq = max(0, self._nq + delta)
        if self._metrics is not None:
            _queue_depth_note(self._name, delta,
                              self._metrics["queue_depth"], self._qd_key)

    def assign(self, method: Optional[str], args, kwargs):
        return self.assign_with_replica(method, args, kwargs)[0]

    def _pick_slot_locked(self, avoid: Optional[bytes] = None):
        """Under self._slot_free: least-loaded pick with a sticky tie
        break. Pure round-robin spreads consecutive requests across
        actors, defeating the core runtime's per-actor submission
        batching and bouncing worker processes in and out of the kernel
        run queue — on a single-core host that HALVED the handle path at
        8 replicas. Preferring the last-used replica while it is no more
        loaded than the least-loaded keeps one worker hot at low load,
        while genuine concurrency (inflight ties broken) still spreads
        by load exactly like the reference's availability-set routing
        (router.py:221). None when all are at capacity.

        REPLICA-LINEAR: the common case is O(1) — when the sticky
        replica's load is already within ``_slack`` of zero it beats or
        ties any scan result (best_load >= 0), so no scan runs and the
        pick cost no longer grows with the replica count. The full
        least-loaded scan (over cached keys) only runs once the hot
        replica is loaded beyond the slack — i.e. under saturation,
        where spreading is the point."""
        n = len(self._replicas)
        if n == 0:
            return None
        if avoid is not None and n > 1:
            # Retry re-dispatch: least-loaded scan SKIPPING the replica
            # that just failed the request. Soft exclusion — when every
            # other replica is at capacity we fall through to the
            # normal pick (retrying the suspect beats shedding).
            best = best_key = best_load = None
            for idx in range(n):
                key = self._keys[idx]
                if key == avoid:
                    continue
                load = self._inflight.get(key, 0)
                if load >= self._max_cq:
                    continue
                if best_load is None or load < best_load:
                    best, best_key, best_load = idx, key, load
            if best is not None:
                self._inflight[best_key] = best_load + 1
                self._note_inflight(1)
                return self._replicas[best], best_key
        if self._rr >= n:
            self._rr = 0
        skey = self._keys[self._rr]
        sload = self._inflight.get(skey, 0)
        if sload < self._max_cq and sload <= self._slack:
            # Equivalent to the scan outcome: sload - best_load <= slack
            # holds for every possible best_load >= 0.
            self._inflight[skey] = sload + 1
            self._note_inflight(1)
            return self._replicas[self._rr], skey
        best = best_key = best_load = None
        for idx in range(n):
            key = self._keys[idx]
            load = self._inflight.get(key, 0)
            if load >= self._max_cq:
                continue
            if best_load is None or load < best_load:
                best, best_key, best_load = idx, key, load
        if best is None:
            return None
        # Sticky-with-slack: keep the previous replica while its load is
        # within `_slack` of the least loaded; spill beyond. Bursts stay
        # packed on one hot replica (per-actor submission batching +
        # worker cache locality), while sustained saturation still
        # spreads by load like the reference's availability-set routing.
        if self._rr != best:
            if sload < self._max_cq and sload - best_load <= self._slack:
                best, best_key, best_load = self._rr, skey, sload
            elif sload < self._max_cq:
                # Slack-overflow spill: route THIS call to the least
                # loaded but keep the anchor — moving it handed the
                # next whole burst to a cold replica (anchor ping-pong
                # was part of the 8-replica handle inversion). The
                # anchor only migrates when it is at hard capacity.
                self._inflight[best_key] = best_load + 1
                self._note_inflight(1)
                return self._replicas[best], best_key
        self._rr = best
        self._inflight[best_key] = best_load + 1
        self._note_inflight(1)
        return self._replicas[best], best_key

    # -- deadlines / admission ----------------------------------------------
    def _deadlines(self, deadline: Optional[float]):
        """(request_deadline, queue_deadline): the end-to-end deadline
        (explicit per-request, else the deployment's request_deadline_s,
        else None) and how long this assign may wait for a slot — the
        deployment's queue_timeout_s (default 30s, the old hardcoded
        bound) clamped so queueing never outlives the deadline."""
        now = time.monotonic()
        if deadline is None:
            rd = self._cfg.get("request_deadline_s")
            deadline = now + rd if rd is not None else None
        qt = self._cfg.get("queue_timeout_s")
        queue_deadline = now + (qt if qt is not None else 30.0)
        if deadline is not None:
            queue_deadline = min(queue_deadline, deadline)
        return deadline, queue_deadline

    def _timeout_for(self, deadline: Optional[float]) -> Optional[float]:
        if deadline is None:
            return None
        return max(0.0, deadline - time.monotonic())

    def _admit_locked(self, queued: bool) -> bool:
        """First time an assign is about to block: check the
        deployment-wide pending bound and register the waiter. Raises
        OverloadedError when the queue is already full."""
        if queued:
            return True
        mp = self._cfg.get("max_pending")
        if mp is not None and _pending_note(self._name, 0) >= mp:
            raise OverloadedError(
                f"deployment {self._name!r} overloaded: pending queue "
                f"is full (max_pending={mp})")
        _pending_note(self._name, 1)
        return True

    def _count_retry(self, reason: str) -> None:
        if self._metrics is not None:
            self._metrics["retries"].inc(
                1.0, tags={"deployment": self._name, "reason": reason})

    def _count_deadline(self) -> None:
        if self._metrics is not None:
            self._metrics["deadline_exceeded"].inc(1.0)

    def _overloaded(self) -> OverloadedError:
        detail = (f" (all at max_concurrent_queries={self._max_cq})"
                  if self._replicas else "")
        return OverloadedError(
            f"deployment {self._name!r} overloaded: no replica "
            f"available{detail}")

    def _submit(self, replica, key, method, args, kwargs,
                deadline: Optional[float] = None,
                ctx: Optional[tuple] = None):
        timeout_s = self._timeout_for(deadline)
        try:
            # remote_context: the actor-submit span this .remote() opens
            # (actor.py) adopts the REQUEST's trace, not a fresh one —
            # the router runs on the proxy loop / executor threads where
            # no thread-local span is open. The ctx also rides as an
            # explicit arg so the replica can stamp its handler span and
            # bind the asyncio request context.
            with tracing.remote_context(ctx):
                if method:
                    ref = replica.call_method.remote(
                        method, args, kwargs, timeout_s, ctx)
                else:
                    ref = replica.handle_request.remote(
                        args, kwargs, timeout_s, ctx)
        except Exception:
            self._release(key)
            raise

        from ..core import on_ref_ready

        on_ref_ready(ref, lambda k=key: self._release(k))
        self._arm_retry(ref, key, ("unary", method, args, kwargs),
                        deadline)
        return ref, replica

    # -- safe retry (replica died before any response byte) -----------------
    def _arm_retry(self, ref, key, call, deadline: Optional[float],
                   slots: int = 1) -> None:
        """Register a one-shot failure interceptor on the request's
        return oid: if the replica dies before the result lands, the
        request is re-dispatched to a healthy replica while the caller
        keeps waiting on the ORIGINAL ref. Zero cost on the success
        path. Disabled for non-idempotent deployments (a duplicate side
        effect is worse than a typed error) and in worker processes
        (the interceptor needs the head runtime's object table)."""
        if self._cfg.get("max_request_retries", 0) <= 0:
            return
        if not self._cfg.get("idempotent", True):
            return
        from ..core.runtime import get_head_runtime

        rt = get_head_runtime()
        if rt is None:
            return
        ctx = {
            "call": call,
            "user_deadline": deadline,
            # Retry chains are always bounded, even with no user
            # deadline: a replacement replica that never comes up must
            # not park the caller forever.
            "deadline": (deadline if deadline is not None
                         else time.monotonic() + 60.0),
            "bad": key,
            "slots": slots,
        }
        rt.intercept_failure(
            ref.id, lambda err, o=ref.id, c=ctx: self._maybe_retry(
                o, c, err))

    def _maybe_retry(self, oid, ctx, error) -> bool:
        """Failure-interceptor body. Runs on whatever thread delivered
        the failure (possibly holding the runtime lock): decide and
        hand off, never block. True = we own completing the oid."""
        if not isinstance(error, (ActorError, WorkerCrashedError)):
            return False  # app exception: not retryable, fail normally
        if time.monotonic() >= ctx["deadline"]:
            return False
        threading.Thread(
            target=self._retry_loop, args=(oid, ctx, error),
            daemon=True, name=f"serve-retry-{self._name}").start()
        return True

    def _pick_for_retry(self, avoid: bytes, deadline: float):
        while True:
            with self._slot_free:
                chosen = self._pick_slot_locked(avoid=avoid)
                if chosen is not None:
                    return chosen
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                self._waiters += 1
                try:
                    self._slot_free.wait(min(remaining, 0.5))
                finally:
                    self._waiters -= 1
            self._ensure_replicas()

    def _retry_loop(self, oid, ctx, error) -> None:
        """Re-dispatch a dead request until it lands, the retry budget
        runs out, or the deadline passes. Owns the original oid's
        completion (fail_object / transfer_result)."""
        from ..core import on_ref_ready
        from ..core.runtime import get_head_runtime

        rt = get_head_runtime()
        budget = int(self._cfg.get("max_request_retries", 0))
        backoff0 = float(self._cfg.get("retry_backoff_s", 0.05))
        n_slots = int(ctx.get("slots", 1))
        avoid = ctx["bad"]
        last_err = error
        attempt = 0
        while True:
            attempt += 1
            if attempt > budget:
                rt.fail_object(oid, last_err)
                return
            self._count_retry("actor_died")
            delay = min(backoff0 * (2 ** (attempt - 1)), 1.0)
            if time.monotonic() + delay >= ctx["deadline"]:
                self._count_deadline()
                rt.fail_object(oid, DeadlineExceededError(
                    f"request to {self._name!r} exceeded its deadline "
                    f"while retrying after replica death"))
                return
            time.sleep(delay)
            picked = self._pick_for_retry(avoid, ctx["deadline"])
            if picked is None:
                self._count_deadline()
                rt.fail_object(oid, DeadlineExceededError(
                    f"request to {self._name!r} exceeded its deadline "
                    f"waiting for a healthy replica"))
                return
            replica, key = picked
            if n_slots > 1:
                with self._slot_free:
                    self._inflight[key] = (
                        self._inflight.get(key, 0) + n_slots - 1)
                    self._note_inflight(n_slots - 1)
            timeout_s = self._timeout_for(ctx["user_deadline"])
            kind = ctx["call"][0]
            try:
                if kind == "batch":
                    ref2 = replica.handle_request_batch.remote(
                        ctx["call"][1], timeout_s)
                elif ctx["call"][1]:
                    ref2 = replica.call_method.remote(
                        ctx["call"][1], ctx["call"][2], ctx["call"][3],
                        timeout_s)
                else:
                    ref2 = replica.handle_request.remote(
                        ctx["call"][2], ctx["call"][3], timeout_s)
            except Exception as e:  # noqa: BLE001
                self._release(key, n_slots)
                last_err, avoid = e, key
                continue
            on_ref_ready(ref2, lambda k=key, c=n_slots: self._release(
                k, c))
            done = threading.Event()
            rt.add_ready_watcher(ref2.id, done.set)
            remaining = ctx["deadline"] - time.monotonic()
            if not done.wait(timeout=max(remaining, 0.0)):
                self._count_deadline()
                rt.fail_object(oid, DeadlineExceededError(
                    f"request to {self._name!r} exceeded its deadline "
                    f"mid-retry"))
                return
            status, err = rt.object_status(ref2.id)
            if status == "ready":
                self._note_final_replica(oid, replica)
                rt.transfer_result(ref2.id, oid)
                return
            if isinstance(err, (ActorError, WorkerCrashedError)):
                last_err, avoid = err, key
                continue
            rt.fail_object(oid, err if err is not None else last_err)
            return

    def _note_final_replica(self, oid, replica) -> None:
        with self._slot_free:
            if len(self._retried_replica) > 256:
                self._retried_replica.clear()
            self._retried_replica[oid.binary()] = replica

    def replica_for(self, ref, default):
        """The replica that actually served ``ref`` — the original pick
        unless a safe retry moved the request (streaming consumers must
        drain next_chunks from the live replica holding the stream)."""
        with self._slot_free:
            return self._retried_replica.get(ref.id.binary(), default)

    def try_assign_with_replica(self, method: Optional[str], args,
                                kwargs, deadline: Optional[float] = None):
        """Non-blocking assign: (ref, replica) or None when every
        replica is at capacity — lets the HTTP proxy submit inline on
        its event loop in the common unsaturated case instead of paying
        a thread-pool hop per request. STRICTLY non-blocking: an empty
        replica set returns None (the caller's off-loop slow path runs
        the bootstrap RPC) so a slow controller can never stall the
        proxy's event loop."""
        if not self._replicas:
            return None
        if deadline is None:
            deadline, _ = self._deadlines(None)
        with self._slot_free:
            chosen = self._pick_slot_locked()
        if chosen is None:
            return None
        replica, key = chosen
        return self._submit(replica, key, method, args, kwargs, deadline)

    def assign_with_replica(self, method: Optional[str], args, kwargs,
                            deadline: Optional[float] = None):
        """Pick a replica with a free slot; block (condvar, woken by
        completions and replica-set updates) when all are at capacity.
        Returns (result_ref, replica_handle) — the replica is needed to
        drain streaming responses (``_Replica.next_chunks``).

        Queue-wait is bounded by the deployment's queue_timeout_s (and
        the request deadline); expiry sheds with a typed error —
        OverloadedError (-> 503) for queue timeout, DeadlineExceededError
        (-> 504) when the end-to-end deadline itself passed. max_pending
        bounds how many assigns may block deployment-wide."""
        # Bootstrap BEFORE resolving deadlines: on a fresh router the
        # deployment cfg (request_deadline_s etc.) arrives with the
        # first replica snapshot — resolving first would silently run
        # the request unbounded.
        self._ensure_replicas()
        deadline, queue_deadline = self._deadlines(deadline)
        queued = False
        try:
            while True:
                with self._slot_free:
                    chosen = self._pick_slot_locked()
                    if chosen is None:
                        now = time.monotonic()
                        if deadline is not None and now >= deadline:
                            self._count_deadline()
                            raise DeadlineExceededError(
                                f"request to {self._name!r} exceeded "
                                f"its deadline while queued")
                        if now >= queue_deadline:
                            raise self._overloaded()
                        queued = self._admit_locked(queued)
                        self._waiters += 1
                        try:
                            self._slot_free.wait(
                                min(queue_deadline - now, 1.0))
                        finally:
                            self._waiters -= 1
                if chosen is None:
                    self._ensure_replicas()
                    continue
                replica, key = chosen
                return self._submit(replica, key, method, args, kwargs,
                                    deadline)
        finally:
            if queued:
                _pending_note(self._name, -1)

    # -- sticky sessions (ISSUE 19) ------------------------------------------
    def _pick_session_locked(self, session_id: str):
        """Under self._slot_free: resolve the session's pinned replica
        (rendezvous hash on first use or after its replica left the
        set) and take one slot on it. Returns (replica, key, rerouted)
        or None when the pinned replica is at capacity — session
        affinity means we WAIT for its slot rather than spill the
        session's KV-cache locality to a cold replica."""
        n = len(self._replicas)
        if n == 0:
            return None
        key = self._sticky.get(session_id)
        rerouted = False
        if key is not None and key not in set(self._keys):
            rerouted = True  # pinned replica drained or crashed
            key = None
        if key is None:
            if len(self._sticky) > 4096:
                self._sticky.clear()
            key = self._keys[_session_rendezvous(session_id, self._keys)]
            self._sticky[session_id] = key
        idx = self._keys.index(key)
        load = self._inflight.get(key, 0)
        if load >= self._max_cq:
            return None
        self._inflight[key] = load + 1
        self._note_inflight(1)
        return self._replicas[idx], key, rerouted

    def acquire_session_slot(self, session_id: str,
                             deadline: Optional[float] = None):
        """Two-phase session assign, step 1: pin (or re-pin) the
        session's replica and reserve one slot on it, WITHOUT
        submitting. Returns (replica, key, rerouted, deadline). The
        caller restores crashed sessions on reroute before submitting
        with ``submit_on``; on failure in between it must give the slot
        back via ``release_slot``. Blocking/shedding semantics match
        assign_with_replica (typed 503/504)."""
        self._ensure_replicas()
        deadline, queue_deadline = self._deadlines(deadline)
        queued = False
        try:
            while True:
                with self._slot_free:
                    got = self._pick_session_locked(session_id)
                    if got is not None:
                        replica, key, rerouted = got
                        return replica, key, rerouted, deadline
                    now = time.monotonic()
                    if deadline is not None and now >= deadline:
                        self._count_deadline()
                        raise DeadlineExceededError(
                            f"session request to {self._name!r} "
                            f"exceeded its deadline while queued")
                    if now >= queue_deadline:
                        raise self._overloaded()
                    queued = self._admit_locked(queued)
                    self._waiters += 1
                    try:
                        self._slot_free.wait(
                            min(queue_deadline - now, 1.0))
                    finally:
                        self._waiters -= 1
                self._ensure_replicas()
        finally:
            if queued:
                _pending_note(self._name, -1)

    def submit_on(self, replica, key, method, args, kwargs,
                  deadline: Optional[float] = None,
                  ctx: Optional[tuple] = None):
        """Two-phase session assign, step 2: submit on the slot taken
        by acquire_session_slot. Rides _submit, so the safe-retry
        interceptor still re-dispatches if the pinned replica dies
        before any response byte (re-prefill recovery makes the retried
        request bit-for-bit correct on the survivor)."""
        return self._submit(replica, key, method, args, kwargs, deadline,
                            ctx)

    def release_slot(self, key: bytes) -> None:
        """Give back a slot reserved by acquire_session_slot that was
        never submitted (restore failed, caller bailed)."""
        self._release(key)

    def session_replica(self, session_id: str):
        """Diagnostics: the session's pinned replica key hex, or None."""
        with self._slot_free:
            key = self._sticky.get(session_id)
            return None if key is None else key.hex()

    def assign_session(self, method: Optional[str], args, kwargs,
                       session_id: str,
                       deadline: Optional[float] = None):
        """One-call sticky assign (handle path): acquire + submit.
        Returns (ref, replica, rerouted)."""
        replica, key, rerouted, deadline = self.acquire_session_slot(
            session_id, deadline)
        # _submit gives the slot back itself if the dispatch raises.
        ref, replica = self.submit_on(replica, key, method, args,
                                      kwargs, deadline)
        return ref, replica, rerouted

    def try_assign_batch(self, items, deadline: Optional[float] = None):
        """Assign a COALESCED batch to ONE replica in a single actor
        RPC. Takes as many items as the replica's free slots allow
        (>= 1). Returns (ref, replica, n_taken) or None when every
        replica is at capacity / the set is empty."""
        if not self._replicas:
            return None
        if deadline is None:
            deadline, _ = self._deadlines(None)
        with self._slot_free:
            picked = self._pick_slot_locked()  # takes one slot
            if picked is None:
                return None
            replica, key = picked
            free = self._max_cq - self._inflight.get(key, 0)
            extra = min(len(items) - 1, max(free, 0))
            self._inflight[key] += extra
            self._note_inflight(extra)
            n = 1 + extra
        taken = list(items[:n])
        try:
            ref = replica.handle_request_batch.remote(
                taken, self._timeout_for(deadline))
        except Exception:
            self._release(key, n)
            raise

        from ..core import on_ref_ready

        on_ref_ready(ref, lambda k=key, c=n: self._release(k, c))
        self._arm_retry(ref, key, ("batch", taken), deadline, slots=n)
        return ref, replica, n

    def assign_batch(self, items, deadline: Optional[float] = None):
        """Blocking form of try_assign_batch (saturation path)."""
        self._ensure_replicas()  # cfg before deadlines, as in assign
        deadline, queue_deadline = self._deadlines(deadline)
        queued = False
        try:
            while True:
                got = self.try_assign_batch(items, deadline)
                if got is not None:
                    return got
                with self._slot_free:
                    now = time.monotonic()
                    if deadline is not None and now >= deadline:
                        self._count_deadline()
                        raise DeadlineExceededError(
                            f"batch for {self._name!r} exceeded its "
                            f"deadline while queued")
                    if now >= queue_deadline:
                        raise self._overloaded()
                    queued = self._admit_locked(queued)
                    self._waiters += 1
                    try:
                        self._slot_free.wait(
                            min(queue_deadline - now, 1.0))
                    finally:
                        self._waiters -= 1
                self._ensure_replicas()
        finally:
            if queued:
                _pending_note(self._name, -1)

    def _release(self, key: bytes, n: int = 1) -> None:
        with self._slot_free:
            c = self._inflight.get(key, 0)
            # Clamp ONCE and apply the same released amount to both the
            # per-replica map and the router/deployment totals, so a
            # spurious double-release can't make them diverge.
            released = n if n < c else c
            self._inflight[key] = c - released
            self._note_inflight(-released)
            if self._waiters:
                # Gate the wake: _release runs on EVERY request
                # completion, and an unconditional notify_all was a
                # futex storm with zero waiters in the common
                # unsaturated case.
                self._slot_free.notify_all()
