"""Serve public API: deployments, handles, run/shutdown, HTTP proxy.

Reference analog: ``python/ray/serve/api.py`` + ``serve/deployment.py``
(@serve.deployment / .options / .bind) and ``serve/handle.py``
(DeploymentHandle). The HTTP proxy uses a stdlib threading HTTP server in
place of uvicorn/starlette (same per-node proxy role as
``http_proxy.py:189``).
"""

from __future__ import annotations

import asyncio
import functools
import json
import sys
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ..observability import tracing

from ..core import get, get_actor, kill, remote
from ..core.exceptions import (
    ActorError,
    DeadlineExceededError,
    OverloadedError,
    StreamInterruptedError,
    TaskError,
    WorkerCrashedError,
)
from ._internal import (
    AutoscalingConfig,
    DeploymentInfo,
    Router,
    ServeController,
    SessionLog,
    serve_metrics,
)

_CONTROLLER_NAME = "SERVE_CONTROLLER"
_state: Dict[str, Any] = {"controller": None, "http_server": None,
                          "routers": [], "http_addr": None}


def start(http_port: Optional[int] = None, http_host: Optional[str] = None,
          detached: bool = True) -> None:
    """Start the Serve instance: a DETACHED controller actor running its
    own control loop (reference: run_control_loop inside the
    ServeController actor, controller.py:229) + the HTTP proxy. Serve
    survives driver-side handle GC — only serve.shutdown() stops it."""
    explicit = http_port is not None or http_host is not None
    http_port = 8000 if http_port is None else http_port
    http_host = "127.0.0.1" if http_host is None else http_host
    if _state["controller"] is not None:
        current = _state.get("http_addr")
        if explicit and current is not None and \
                current != (http_host, http_port):
            import sys

            print(f"serve: already running with HTTP on "
                  f"{current[0]}:{current[1]}; requested "
                  f"{http_host}:{http_port} ignored — serve.shutdown() "
                  "first to change http_options", file=sys.stderr)
        return
    # Connect-to-existing first (reference: serve.context connects to a
    # running instance): inside a REPLICA process a deserialized
    # DeploymentHandle must reach the cluster's controller, not boot a
    # second Serve. A DRIVER adopting a detached controller (left by an
    # exited driver) still starts its own HTTP proxy — the previous
    # proxy died with its driver; worker processes never own a proxy.
    # Liveness-checked: right after a shutdown() the name can briefly
    # resolve to the still-dying controller — adopting a corpse would
    # hang every later RPC, so an unresponsive hit falls through to a
    # fresh create.
    from ..core.runtime import is_worker_process

    try:
        existing = get_actor(_CONTROLLER_NAME)
    except Exception:
        existing = None
    if existing is not None:
        try:
            get(existing.get_deployment_names.remote(), timeout=5)
            _state["controller"] = existing
            if not is_worker_process():
                _start_http_proxy(http_host, http_port)
            return
        except Exception:
            existing = None
    controller_cls = remote(ServeController)

    def _create():
        c = controller_cls.options(
            name=_CONTROLLER_NAME, max_concurrency=64,
            lifetime="detached" if detached else None,
        ).remote()
        get(c.start_loop.remote(), timeout=30)
        return c

    try:
        controller = _create()
    except ValueError:
        # Name taken: either we lost a create race to a HEALTHY
        # controller (adopt it), or the name still points at the corpse
        # the liveness probe rejected (kill it to free the name, then
        # create fresh — adopting the corpse would hang every RPC).
        owner = get_actor(_CONTROLLER_NAME)
        try:
            get(owner.get_deployment_names.remote(), timeout=5)
            controller = owner
        except Exception:
            try:
                kill(owner)
            except Exception:
                pass
            controller = _create()
    _state["controller"] = controller
    if not is_worker_process():
        _start_http_proxy(http_host, http_port)


def is_running() -> bool:
    """True when a Serve controller exists in THIS driver process —
    a read-only probe that never starts an instance."""
    return _state["controller"] is not None


def shutdown() -> None:
    controller = _state.get("controller")
    if controller is not None:
        try:
            get(controller.stop_loop.remote(), timeout=10)
        except Exception:
            pass
    for router in _state.get("routers", []):
        try:
            router.stop()
        except Exception:
            pass
    _state["routers"] = []
    server = _state.get("http_server")
    if server is not None:
        try:
            server.shutdown()
        except Exception:
            pass
        _state["http_server"] = None
    _state["http_addr"] = None
    controller = _state.get("controller")
    if controller is not None:
        try:
            for name in get(controller.get_deployment_names.remote(),
                            timeout=10):
                get(controller.delete_deployment.remote(name), timeout=10)
            kill(controller)
        except Exception:
            pass
        _state["controller"] = None


def _controller():
    if _state["controller"] is None:
        start()
    return _state["controller"]


def drain(deployment: str, replica: Optional[str] = None,
          timeout_s: float = 30.0) -> dict:
    """Gracefully drain one replica of ``deployment``: new requests stop
    routing to it, resident LLM sessions migrate (KV pages + transcript)
    to the surviving replicas they will re-pin to, in-flight requests
    and streams finish, then the replica is terminated and reconciled
    away. ``replica`` is an actor-id hex (first replica when None).
    Returns the controller's drain report (sessions migrated, per-
    session migration latency, total drain time)."""
    return get(_controller().drain.remote(deployment, replica, timeout_s),
               timeout=timeout_s + 90)


def _is_stream_marker(value) -> bool:
    return (isinstance(value, tuple) and len(value) == 2
            and value[0] == "__rt_stream__")


class StreamingResponse:
    """Iterator over a streaming deployment response (the replica holds
    the generator; chunks are pulled via ``_Replica.next_chunks``).
    Reference: Serve's ASGI StreamingResponse — here as chunked pull."""

    def __init__(self, replica, stream_id: int, chunk_size: int = 8):
        self._replica = replica
        self._stream_id = stream_id
        self._chunk = chunk_size
        self._buf: List[Any] = []
        self._done = False

    def __iter__(self):
        return self

    def __next__(self):
        if not self._buf:
            if self._done:
                raise StopIteration
            try:
                done, items = get(
                    self._replica.next_chunks.remote(
                        self._stream_id, self._chunk),
                    timeout=60,
                )
            except (ActorError, WorkerCrashedError) as e:
                # Replica died mid-stream. Chunks already handed out
                # can't be un-delivered, so a transparent retry could
                # duplicate output — fail fast with the typed error.
                raise StreamInterruptedError(
                    "streaming replica died after the stream started; "
                    "already-delivered chunks cannot be retried safely"
                ) from e
            self._done = done
            self._buf = list(items)
            if not self._buf:
                raise StopIteration
        return self._buf.pop(0)


class DeploymentHandle:
    """Python-side handle (reference: serve/handle.py ServeHandle).

    Pickles as (name, max_concurrent) only — the router (which holds
    actor handles and a controller reference) rebuilds lazily in the
    receiving process, so handles can ride deployment-graph init args
    into replicas."""

    def __init__(self, name: str, max_concurrent_queries: int = 100):
        self._name = name
        self._mcq = max_concurrent_queries
        self._router_obj = None
        self._router_lock = threading.Lock()

    @property
    def _router(self):
        # Locked: a handle shared across threads must build exactly ONE
        # router — each Router starts a long-poll listener thread, and a
        # first-use race would leak the loser's thread until shutdown.
        if self._router_obj is None:
            with self._router_lock:
                if self._router_obj is None:
                    router = Router(_controller(), self._name, self._mcq)
                    _state.setdefault("routers", []).append(router)
                    self._router_obj = router
        return self._router_obj

    def __reduce__(self):
        return (DeploymentHandle, (self._name, self._mcq))

    def remote(self, *args, **kwargs):
        return self._router.assign(None, args, kwargs)

    def stream(self, *args, **kwargs) -> StreamingResponse:
        """Call a streaming deployment (one returning a generator /
        async generator); returns an iterator over its chunks."""
        ref, replica = self._router.assign_with_replica(None, args, kwargs)
        value = get(ref, timeout=60)
        # A safe retry may have moved the request to another replica —
        # the stream must be drained from whichever actor holds it.
        replica = self._router.replica_for(ref, replica)
        if not _is_stream_marker(value):
            single = StreamingResponse(replica, -1)
            single._buf = [value]
            single._done = True
            return single
        return StreamingResponse(replica, value[1])

    def method(self, method_name: str) -> "DeploymentMethodHandle":
        return DeploymentMethodHandle(self, method_name)

    def session(self, session_id: str) -> "SessionHandle":
        """Sticky-session view of this handle: every ``.remote()`` call
        routes to the one replica the session id pins (rendezvous hash
        over the live replica set), keeping its KV-cache locality.
        HTTP clients get the same affinity via the ``x-serve-session``
        header."""
        return SessionHandle(self, session_id)

    def __getattr__(self, item):
        if item.startswith("_"):
            raise AttributeError(item)
        return DeploymentMethodHandle(self, item)


class SessionHandle:
    """Session-pinned caller (see DeploymentHandle.session)."""

    def __init__(self, handle: DeploymentHandle, session_id: str):
        self._handle = handle
        self._session_id = session_id

    def remote(self, *args, **kwargs):
        ref, _replica, _rerouted = self._handle._router.assign_session(
            None, args, kwargs, self._session_id)
        return ref

    def replica_key(self) -> Optional[str]:
        """Hex actor-id key the session is currently pinned to."""
        return self._handle._router.session_replica(self._session_id)


class DeploymentMethodHandle:
    def __init__(self, handle: DeploymentHandle, method: str):
        self._handle = handle
        self._method = method

    def remote(self, *args, **kwargs):
        return self._handle._router.assign(self._method, args, kwargs)


@dataclass
class Application:
    """A bound deployment graph node (reference: .bind() -> Application)."""

    deployment: "Deployment"
    args: tuple = ()
    kwargs: dict = field(default_factory=dict)


class Deployment:
    """Reference: serve/deployment.py Deployment."""

    def __init__(self, func_or_class, name: str, opts: Dict[str, Any]):
        self._def = func_or_class
        self.name = name
        self._opts = opts
        functools.update_wrapper(self, func_or_class, updated=[])

    def options(self, **overrides) -> "Deployment":
        opts = dict(self._opts)
        name = overrides.pop("name", self.name)
        opts.update(overrides)
        return Deployment(self._def, name, opts)

    def bind(self, *args, **kwargs) -> Application:
        return Application(self, args, kwargs)

    def deploy(self, *init_args, **init_kwargs) -> DeploymentHandle:
        o = self._opts
        autoscaling = o.get("autoscaling_config")
        if isinstance(autoscaling, dict):
            autoscaling = AutoscalingConfig(**autoscaling)
        info = DeploymentInfo(
            name=self.name,
            deployment_def=self._def,
            init_args=init_args,
            init_kwargs=init_kwargs,
            num_replicas=o.get("num_replicas", 1),
            max_concurrent_queries=o.get("max_concurrent_queries", 100),
            # `or`, not .get default: the decorator always stores the
            # key (value None), so a dict default would never fire.
            route_prefix=o.get("route_prefix") or f"/{self.name}",
            autoscaling=autoscaling,
            ray_actor_options=o.get("ray_actor_options") or {},
            request_timeout_s=o.get("request_timeout_s"),
            user_config=o.get("user_config"),
            request_deadline_s=o.get("request_deadline_s"),
            max_request_retries=o.get("max_request_retries", 2),
            retry_backoff_s=o.get("retry_backoff_s", 0.05),
            idempotent=o.get("idempotent", True),
            max_pending=o.get("max_pending"),
            queue_timeout_s=o.get("queue_timeout_s"),
            health_check_period_s=o.get("health_check_period_s", 1.0),
            health_check_timeout_s=o.get("health_check_timeout_s", 5.0),
            health_check_failure_threshold=o.get(
                "health_check_failure_threshold", 3),
        )
        get(_controller().deploy.remote(info), timeout=60)
        return DeploymentHandle(self.name, o.get("max_concurrent_queries",
                                                 100))

    def __call__(self, *a, **k):
        raise TypeError(
            f"Deployment {self.name!r} cannot be called directly; deploy it "
            f"with serve.run(dep.bind(...)) and use the handle."
        )


def deployment(_func_or_class=None, *, name: Optional[str] = None,
               num_replicas: int = 1, max_concurrent_queries: int = 100,
               route_prefix: Optional[str] = None,
               autoscaling_config=None,
               ray_actor_options: Optional[dict] = None,
               request_timeout_s: Optional[float] = None,
               request_deadline_s: Optional[float] = None,
               max_request_retries: int = 2,
               retry_backoff_s: float = 0.05,
               idempotent: bool = True,
               max_pending: Optional[int] = None,
               queue_timeout_s: Optional[float] = None,
               health_check_period_s: Optional[float] = 1.0,
               health_check_timeout_s: float = 5.0,
               health_check_failure_threshold: int = 3):
    """``@serve.deployment`` decorator (reference: serve/api.py).

    Fault-tolerance / admission knobs (ISSUE 18): ``request_deadline_s``
    bounds a request end-to-end (queueing + retries + handler; -> 504);
    ``max_request_retries``/``retry_backoff_s`` govern safe re-dispatch
    after replica death (disabled when ``idempotent=False``);
    ``max_pending``/``queue_timeout_s`` shed overload as typed 503s;
    ``health_check_*`` tune the controller's liveness probes (period
    None disables)."""

    def wrap(target):
        return Deployment(target, name or target.__name__, {
            "num_replicas": num_replicas,
            "max_concurrent_queries": max_concurrent_queries,
            "route_prefix": route_prefix,
            "autoscaling_config": autoscaling_config,
            "ray_actor_options": ray_actor_options or {},
            "request_timeout_s": request_timeout_s,
            "request_deadline_s": request_deadline_s,
            "max_request_retries": max_request_retries,
            "retry_backoff_s": retry_backoff_s,
            "idempotent": idempotent,
            "max_pending": max_pending,
            "queue_timeout_s": queue_timeout_s,
            "health_check_period_s": health_check_period_s,
            "health_check_timeout_s": health_check_timeout_s,
            "health_check_failure_threshold":
                health_check_failure_threshold,
        })

    if _func_or_class is not None:
        return wrap(_func_or_class)
    return wrap


def _resolve_graph(value, deployed: Dict[int, DeploymentHandle]):
    """Deployment-graph composition (reference: serve's DeploymentNode
    graphs — ``Ensemble.bind(ModelA.bind(), ModelB.bind())``): nested
    Applications inside bind args deploy first (DFS, deduped per bound
    node) and are replaced by their DeploymentHandles, so a parent
    deployment receives live handles to its children in __init__."""
    if isinstance(value, Application):
        key = id(value)
        if key not in deployed:
            deployed[key] = _deploy_app(value, deployed)
        return deployed[key]
    if isinstance(value, (list, tuple)):
        resolved = [_resolve_graph(v, deployed) for v in value]
        if all(a is b for a, b in zip(resolved, value)):
            return value  # untouched (incl. namedtuples/subclasses)
        if isinstance(value, tuple) and hasattr(value, "_fields"):
            return type(value)(*resolved)  # namedtuple: positional ctor
        return type(value)(resolved)
    if isinstance(value, dict):
        resolved = {k: _resolve_graph(v, deployed)
                    for k, v in value.items()}
        if all(resolved[k] is value[k] for k in resolved):
            return value  # untouched (incl. dict subclasses)
        out = value.copy()  # preserve subclass type + extra state
        out.update(resolved)
        return out
    return value


def _deploy_app(app: Application,
                deployed: Dict[int, DeploymentHandle]) -> DeploymentHandle:
    args = tuple(_resolve_graph(a, deployed) for a in app.args)
    kwargs = {k: _resolve_graph(v, deployed)
              for k, v in app.kwargs.items()}
    return app.deployment.deploy(*args, **kwargs)


def run(app: Application, *, name: str = "default",
        route_prefix: Optional[str] = None) -> DeploymentHandle:
    """Deploy a bound application — including any deployment graph
    nested in its bind args (reference: serve.run + deployment graphs).
    Returns the handle of the ROOT (ingress) deployment."""
    start()
    dep = app.deployment
    if route_prefix is not None:
        dep = dep.options(route_prefix=route_prefix)
    return _deploy_app(Application(dep, app.args, app.kwargs), {})


def get_deployment_handle(name: str) -> DeploymentHandle:
    return DeploymentHandle(name)


def list_deployments() -> Dict[str, dict]:
    return get(_controller().list_deployments.remote(), timeout=30)


def serve_status_snapshot() -> Dict[str, Any]:
    """Read-only Serve status for the dashboard's ``/api/serve``
    endpoint: deployment table (replicas/target/route/version) plus
    driver-side router state (queue depth per deployment). Never starts
    an instance; ``{"running": False}`` when Serve is down."""
    controller = _state.get("controller")
    if controller is None:
        return {"running": False, "deployments": {}}
    try:
        deployments = get(controller.list_deployments.remote(), timeout=5)
    except Exception as e:  # noqa: BLE001 — dashboard must not 500
        return {"running": True, "error": str(e), "deployments": {}}
    # Aggregate across routers: the proxy and each handle own SEPARATE
    # Routers for the same deployment — queue depths sum, and a
    # name-keyed overwrite would hide all but the last one's load.
    routers: Dict[str, dict] = {}
    for router in _state.get("routers", []):
        try:
            stats = router.stats()  # JSON-safe subset (the inflight
            entry = routers.setdefault(  # map is keyed by bytes)
                router._name,
                {"replicas": 0, "queue_depth": 0, "routers": 0})
            entry["replicas"] = max(entry["replicas"], stats["replicas"])
            entry["queue_depth"] += stats["queue_depth"]
            entry["routers"] += 1
        except Exception:  # noqa: BLE001
            continue
    http_addr = _state.get("http_addr")
    return {
        "running": True,
        "http": f"{http_addr[0]}:{http_addr[1]}" if http_addr else None,
        "deployments": deployments,
        "routers": routers,
    }


# -- HTTP proxy --------------------------------------------------------------

class _AsyncHTTPProxy:
    """Asyncio HTTP/1.1 proxy (role of ``http_proxy.py:189`` HTTPProxy —
    uvicorn replaced by an asyncio.start_server loop; stdlib only).

    One event loop serves every connection with keep-alive; replica
    results resolve through ``on_ref_ready`` callbacks bridged to the
    loop (never a parked thread per request). Streaming responses are
    written with chunked transfer encoding as chunks are pulled from the
    replica.
    """

    def __init__(self, host: str, port: int):
        import asyncio

        self._host = host
        self._port = port
        self._handles: Dict[str, DeploymentHandle] = {}
        # route_prefix -> deployment name (refreshed from the
        # controller on miss OR when stale; reference: the proxy's
        # route table pushed by the controller's LongestPrefixRouter —
        # pull-based here, so a TTL bounds how long a newly-deployed
        # longer prefix can be shadowed by a cached shorter one).
        self._routes: Dict[str, str] = {}
        self._routes_ts: float = 0.0
        self._routes_ttl_s: float = 5.0
        # Per-deployment request coalescers (Nagle-style): concurrent
        # requests that arrive while a replica RPC is in flight ride the
        # NEXT batch — one actor hop serves many requests, with zero
        # added latency for a lone request (batch of 1 goes immediately).
        self._pending: Dict[str, Any] = {}
        self._draining: set = set()
        # Crash-recovery transcript log for x-serve-session requests
        # (drain migrates pages; SIGKILL recovery re-prefills from here).
        self._session_log = SessionLog()
        self._loop = asyncio.new_event_loop()
        self._server = None
        self._started = threading.Event()
        self._ok = False
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="serve-http")
        self._thread.start()
        self._started.wait(timeout=10)

    def _run(self):
        import asyncio

        asyncio.set_event_loop(self._loop)

        async def boot():
            try:
                self._server = await asyncio.start_server(
                    self._serve_conn, self._host, self._port)
                self._ok = True
            except OSError:
                self._ok = False  # port busy; python handles still work
            self._started.set()

        self._loop.run_until_complete(boot())
        if self._ok:
            self._loop.run_forever()

    def shutdown(self):
        import asyncio

        if self._ok and self._loop.is_running():
            def _stop():
                if self._server is not None:
                    self._server.close()
                self._loop.stop()
            self._loop.call_soon_threadsafe(_stop)

    async def _aget(self, ref, timeout: float = 60.0):
        """Await an ObjectRef on the event loop: on_ref_ready bridges the
        completion callback; the final get() is then non-blocking."""
        import asyncio

        from ..core import on_ref_ready

        loop = self._loop
        fut = loop.create_future()

        def _done():
            if not fut.done():
                fut.set_result(None)

        on_ref_ready(ref, lambda: loop.call_soon_threadsafe(_done))
        await asyncio.wait_for(fut, timeout)
        return get(ref, timeout=5)

    @staticmethod
    def _request_id(headers: Optional[dict]) -> str:
        """The client's x-request-id when it is a sane header token
        (bounded length, url/log-safe charset); a fresh uuid otherwise."""
        rid = (headers or {}).get("x-request-id", "")
        if rid and len(rid) <= 128 and all(
                c.isalnum() or c in "._-" for c in rid):
            return rid
        return uuid.uuid4().hex

    async def _submit_coalesced(self, name: str, handle, args,
                                deadline: Optional[float] = None,
                                ctx: Optional[tuple] = None):
        """Queue one request on the deployment's coalescer and await its
        result. A drainer task per deployment pops whatever is pending
        (up to 16) into ONE replica RPC; batches form naturally from
        whatever arrives during the previous batch's round trip.

        Admission: when the deployment sets max_pending, a coalescer
        queue already at the bound sheds the request immediately with
        the typed OverloadedError (-> 503) instead of growing without
        limit under a traffic wave."""
        import asyncio
        from collections import deque

        q = self._pending.get(name)
        if q is None:
            q = self._pending[name] = deque()
        mp = handle._router._cfg.get("max_pending")
        if mp is not None and len(q) >= mp:
            raise OverloadedError(
                f"deployment {name!r} overloaded: proxy queue is full "
                f"(max_pending={mp})")
        fut = self._loop.create_future()
        q.append((args, fut, deadline, ctx))
        if name not in self._draining:
            self._draining.add(name)
            asyncio.ensure_future(self._drain_pending(name, handle))
        return await fut

    async def _submit_session(self, name: str, handle, args, sid: str,
                              deadline: Optional[float] = None,
                              ctx: Optional[tuple] = None):
        """Sticky-session submit path (x-serve-session): bypasses the
        coalescer — the slot is reserved on the session's PINNED
        replica first (two-phase), and when that pin had to move
        (pinned replica drained or crashed) the session is restored on
        the new replica from the head-side transcript log BEFORE the
        request runs. acquire_session_slot can block on the pinned
        replica's capacity, so it runs off-loop."""
        import asyncio

        router = handle._router
        loop = self._loop
        replica, key, rerouted, eff_deadline = await loop.run_in_executor(
            None, lambda: router.acquire_session_slot(sid, deadline))
        if rerouted:
            entry = self._session_log.get(name, sid)
            if entry is not None:
                try:
                    await self._aget(
                        replica.call_method.remote(
                            "restore_session",
                            (sid, entry["transcript"], entry["seed"],
                             entry.get("temperature", 0.0)), {}, None),
                        120)
                except Exception:
                    # Best-effort: a deployment without restore_session
                    # (or a failed re-prefill) still serves the request
                    # — the engine simply prefills cold.
                    pass
        # submit_on's _submit gives the slot back itself on a raise.
        assign_t0 = time.time()
        ref, _ = router.submit_on(replica, key, None, args, {},
                                  eff_deadline, ctx)
        if ctx is not None:
            tracing.record_span("router.assign", trace_id=ctx[0],
                                parent_id=ctx[1], start_s=assign_t0,
                                deployment=name, session=sid)
        timeout = 60.0
        if eff_deadline is not None:
            timeout = max(0.0, eff_deadline - time.monotonic()) + 2.0
        result = await self._aget(ref, timeout)
        replica = router.replica_for(ref, replica)
        return result, replica

    def _note_session(self, name: str, sid: str, payload,
                      result) -> None:
        """After a successful session-tagged generation: append the
        conversation state (prompt + produced tokens) to the bounded
        transcript log the crash path recovers from."""
        if not (isinstance(result, dict) and
                isinstance(result.get("tokens"), list) and
                isinstance(payload, dict) and
                isinstance(payload.get("prompt"), list)):
            return
        self._session_log.note(
            name, sid, list(payload["prompt"]) + list(result["tokens"]),
            payload.get("seed"), float(payload.get("temperature", 0.0)))

    async def _drain_pending(self, name: str, handle):
        import asyncio

        q = self._pending[name]
        try:
            while q:
                batch = []
                while q and len(batch) < 16:
                    batch.append(q.popleft())
                # 3-tuple items: the per-request trace ctx rides the
                # batch into the replica so handler-side spans (and any
                # nested .remote() the handler makes) join the trace.
                items = [(args, {}, ctx) for args, _, _, ctx in batch]
                # Tightest member deadline bounds the whole coalesced
                # RPC (deadlines within one deployment's batch are near-
                # uniform: all derive from the same request_deadline_s).
                dls = [d for _, _, d, _ in batch if d is not None]
                deadline = min(dls) if dls else None
                assign_t0 = time.time()
                try:
                    assigned = handle._router.try_assign_batch(
                        items, deadline)
                    if assigned is None:
                        # saturated / empty replica set: block off-loop
                        assigned = await self._loop.run_in_executor(
                            None, lambda it=items, dl=deadline:
                            handle._router.assign_batch(it, dl))
                except Exception as e:  # noqa: BLE001 — a dead replica
                    # must 500 the batch, never strand its futures (the
                    # drainer survives to serve later arrivals).
                    for _, fut, _, _ in batch:
                        if not fut.done():
                            fut.set_exception(e)
                    continue
                ref, replica, n = assigned
                if n < len(batch):
                    for entry in reversed(batch[n:]):
                        q.appendleft(entry)
                    batch = batch[:n]
                for _, _, _, ctx in batch:
                    if ctx is not None:
                        tracing.record_span(
                            "router.assign", trace_id=ctx[0],
                            parent_id=ctx[1], start_s=assign_t0,
                            deployment=name, batch=len(batch))
                # distribute concurrently; keep draining new arrivals
                asyncio.ensure_future(
                    self._distribute(ref, replica, batch, deadline))
        finally:
            self._draining.discard(name)

    async def _distribute(self, ref, replica, batch,
                          deadline: Optional[float] = None):
        import asyncio

        timeout = 60.0
        if deadline is not None:
            # +2s slack: the replica/router enforce the deadline with a
            # typed error; this watchdog only catches a replica that
            # stopped responding entirely, so a request can never hang.
            timeout = max(0.0, min(timeout,
                                   deadline - time.monotonic() + 2.0))
        try:
            results = await self._aget(ref, timeout)
        except asyncio.TimeoutError as e:
            err: Exception = (DeadlineExceededError(
                "request exceeded its deadline awaiting the replica")
                if deadline is not None else e)
            for _, fut, _, _ in batch:
                if not fut.done():
                    fut.set_exception(err)
            return
        except Exception as e:  # noqa: BLE001 — replica died mid-batch
            for _, fut, _, _ in batch:
                if not fut.done():
                    fut.set_exception(e)
            return
        for (_, fut, _, _), res in zip(batch, results):
            if fut.done():
                continue
            if res[0] == "err":
                # Typed control-flow errors travel as live exceptions
                # (isinstance-matched to 503/504); everything else is a
                # transport-safe repr string.
                err = (res[1] if isinstance(res[1], BaseException)
                       else RuntimeError(res[1]))
                fut.set_exception(err)
            else:
                fut.set_result((res[1], replica))

    async def _serve_conn(self, reader, writer):
        try:
            while True:
                req = await reader.readline()
                if not req:
                    return
                try:
                    method, target, _version = req.decode().split()
                except ValueError:
                    return
                headers = {}
                while True:
                    line = await reader.readline()
                    if line in (b"\r\n", b"\n", b""):
                        break
                    k, _, v = line.decode().partition(":")
                    headers[k.strip().lower()] = v.strip()
                length = int(headers.get("content-length", 0) or 0)
                body = await reader.readexactly(length) if length else b""
                keep = headers.get("connection", "keep-alive") != "close"
                keep = await self._route(writer, target, body, keep,
                                         headers) and keep
                await writer.drain()
                if not keep:
                    return
        except (ConnectionError, TimeoutError, EOFError,
                asyncio.IncompleteReadError):
            pass  # client went away
        except Exception:
            import traceback

            traceback.print_exc(file=sys.stderr)
        finally:
            try:
                writer.close()
            except Exception:
                pass

    def _write_simple(self, writer, status: int, payload: bytes,
                      keep: bool, rid: Optional[str] = None) -> None:
        conn = b"keep-alive" if keep else b"close"
        rid_hdr = (b"x-request-id: %s\r\n" % rid.encode("ascii")
                   if rid else b"")
        writer.write(
            b"HTTP/1.1 %d %s\r\nContent-Type: application/json\r\n"
            b"Content-Length: %d\r\n%sConnection: %s\r\n\r\n%s"
            % (status, b"OK" if status == 200 else b"ERR",
               len(payload), rid_hdr, conn, payload))

    def _resolve_route(self, path: str) -> Optional[str]:
        """Longest-prefix match of the request path against registered
        route prefixes (reference: LongestPrefixRouter.match_route)."""
        best = None
        for prefix, name in self._routes.items():
            if path == prefix or path.startswith(prefix.rstrip("/") + "/"):
                if best is None or len(prefix) > len(best[0]):
                    best = (prefix, name)
        return best[1] if best else None

    async def _route(self, writer, target: str, body: bytes,
                     keep: bool, headers: Optional[dict] = None) -> bool:
        """Handle one request. Returns False when the connection must be
        closed (e.g. a failure after a chunked response started — a 500
        cannot be written into the middle of a chunked body)."""
        # Normalized to no trailing slash; "/" itself stays routable
        # (a deployment may mount at route_prefix="/").
        path = "/" + target.split("?")[0].strip("/")
        payload = None
        if body:
            try:
                payload = json.loads(body)
            except json.JSONDecodeError:
                payload = body.decode("utf-8", "replace")
        # Per-request deadline: x-serve-deadline-s (seconds from now)
        # overrides the deployment's request_deadline_s; it flows
        # proxy -> router -> replica, so retries and queueing can never
        # extend total latency past what the client asked for.
        deadline = None
        hdr = (headers or {}).get("x-serve-deadline-s")
        if hdr:
            try:
                deadline = time.monotonic() + max(float(hdr), 0.0)
            except ValueError:
                pass
        # Request identity: honor a sane client-sent x-request-id (so
        # callers can pre-correlate their own logs) or mint one. It is
        # echoed on EVERY response, stamped into 5xx bodies, and doubles
        # as the trace id — `rt trace <x-request-id>` answers "where did
        # THIS request spend its time".
        rid = self._request_id(headers)
        t0 = time.time()
        root_span = (tracing.new_span_id()
                     if tracing.get_tracer().enabled else None)
        ctx = (rid, root_span) if root_span is not None else None
        name = None

        def _finish(status: int, error: Optional[str] = None) -> None:
            # Root span, recorded with explicit bounds: the proxy's
            # event loop interleaves requests on one thread, so a
            # context-managed span could not stay open across awaits.
            if root_span is None:
                return
            attrs: Dict[str, Any] = {"path": path, "status": status}
            if name:
                attrs["deployment"] = name
            if error:
                attrs["error"] = str(error)[:200]
            tracing.record_span("proxy.request", trace_id=rid,
                                span_id=root_span, start_s=t0, **attrs)

        try:
            import time as _time

            stale = (_time.monotonic() - self._routes_ts
                     > self._routes_ttl_s)
            name = None if stale else self._resolve_route(path)
            if name is None:
                # Miss or stale: refresh the route table from the
                # controller (covers custom route_prefix values, the
                # default /<name> routes, and newly-added longer
                # prefixes that would otherwise stay shadowed by a
                # cached shorter match).
                table = await self._aget(
                    _controller().list_deployments.remote(), 10)
                self._routes = {}
                for n, info in table.items():
                    prefix = info.get("route_prefix") or f"/{n}"
                    # Same normalization as request paths, so
                    # "/api/" matches GET /api.
                    prefix = "/" + prefix.strip("/")
                    self._routes[prefix] = n
                self._routes_ts = _time.monotonic()
                name = self._resolve_route(path)
            if name is None:
                self._write_simple(
                    writer, 404,
                    json.dumps(
                        {"error": f"no route matches {path}",
                         "request_id": rid}
                    ).encode(), keep, rid)
                _finish(404)
                return True
            handle = self._handles.get(name)
            if handle is None:
                handle = DeploymentHandle(name)
                self._handles[name] = handle
            sid = (headers or {}).get("x-serve-session")
            if sid:
                # Sticky session: tag the payload (the LLM server
                # records residency under this id) and take the pinned
                # two-phase path instead of the coalescer.
                if isinstance(payload, dict):
                    payload.setdefault("session", sid)
                args = () if payload is None else (payload,)
                result, replica = await self._submit_session(
                    name, handle, args, sid, deadline, ctx)
                self._note_session(name, sid, payload, result)
            else:
                args = () if payload is None else (payload,)
                result, replica = await self._submit_coalesced(
                    name, handle, args, deadline, ctx)
        except Exception as e:  # noqa: BLE001
            # No cache surgery here: an application-level 500 says
            # nothing about routes, and the TTL already bounds how long
            # a deleted deployment's route can linger. The handle stays
            # — its Router owns a live long-poll listener thread that
            # tracks replica-set changes itself; popping it per failing
            # request would leak one such thread each time.
            #
            # Typed error mapping: admission sheds (bounded pending
            # queue / queue timeout) surface as OverloadedError -> 503
            # ("back off and retry"), expired deadlines as
            # DeadlineExceededError -> 504 — both shared classes from
            # core.exceptions, isinstance-matched through the TaskError
            # wrapper the actor boundary adds around replica raises.
            root = e
            while isinstance(root, TaskError) and root.cause is not None:
                root = root.cause
            overloaded = isinstance(root, OverloadedError)
            deadline_exceeded = (not overloaded and
                                 isinstance(root, DeadlineExceededError))
            if deadline_exceeded and root is not e:
                # Replica-side deadline expiry: count here (router-side
                # raises already incremented the counter themselves).
                m = serve_metrics()
                if m is not None:
                    m["deadline_exceeded"].inc(1.0)
            try:
                # request_id in the error body: a 503/504 log line is
                # exactly the request you want to `rt trace` afterwards.
                body = {"error": str(e), "request_id": rid}
                status = 500
                if overloaded:
                    body["overloaded"] = True
                    status = 503
                elif deadline_exceeded:
                    body["deadline_exceeded"] = True
                    status = 504
                self._write_simple(
                    writer, status, json.dumps(body).encode(), keep, rid)
            except Exception:
                _finish(500, str(e))
                return False
            _finish(status, str(e))
            return True
        if _is_stream_marker(result):
            try:
                await self._write_stream(writer, replica, result[1], keep,
                                         rid)
            except Exception as e:
                # Mid-stream failure: the chunked body is unterminated —
                # drop the connection so framing can't desync.
                _finish(500, str(e))
                return False
            _finish(200)
            return True
        self._write_simple(writer, 200, json.dumps(result).encode(), keep,
                           rid)
        _finish(200)
        return True

    async def _write_stream(self, writer, replica, stream_id: int,
                            keep: bool, rid: Optional[str] = None) -> None:
        conn = b"keep-alive" if keep else b"close"
        rid_hdr = (b"x-request-id: %s\r\n" % rid.encode("ascii")
                   if rid else b"")
        writer.write(
            b"HTTP/1.1 200 OK\r\nContent-Type: application/json\r\n"
            b"Transfer-Encoding: chunked\r\n%sConnection: %s\r\n\r\n"
            % (rid_hdr, conn))
        done = False
        while not done:
            done, items = await self._aget(
                replica.next_chunks.remote(stream_id, 8), 60)
            for item in items:
                chunk = json.dumps(item).encode() + b"\n"
                writer.write(b"%x\r\n%s\r\n" % (len(chunk), chunk))
            await writer.drain()
        writer.write(b"0\r\n\r\n")


def _start_http_proxy(host: str, port: int) -> None:
    proxy = _AsyncHTTPProxy(host, port)
    if proxy._ok:
        _state["http_server"] = proxy
        # Recorded only on a successful bind: a failed proxy must not
        # make later start() calls claim HTTP is already being served.
        _state["http_addr"] = (host, port)


# -- batching ----------------------------------------------------------------

def batch(_func=None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """``@serve.batch``: coalesce concurrent calls into one batched call.

    Reference: ``serve/batching.py`` — the wrapped method receives a list
    of requests and must return a list of responses.
    """

    def wrap(fn):
        @functools.wraps(fn)
        def wrapper(self_or_first, *args):
            state = _batch_state_for(wrapper)
            return state.submit(fn, self_or_first, args)

        wrapper._batch_params = (max_batch_size, batch_wait_timeout_s)
        return wrapper

    if _func is not None:
        return wrap(_func)
    return wrap


class _BatchState:
    """Per-process batching state (created lazily in the replica, never at
    decoration time — locks aren't picklable)."""

    def __init__(self, max_batch_size: int, wait_timeout: float):
        self.max_batch_size = max_batch_size
        self.wait_timeout = wait_timeout
        self.lock = threading.Lock()
        self.cond = threading.Condition(self.lock)
        self.pending: List = []
        self.results: Dict[int, Any] = {}
        self.counter = 0

    def submit(self, fn, self_obj, args):
        with self.cond:
            my_id = self.counter
            self.counter += 1
            self.pending.append((my_id, self_obj, args))
            if len(self.pending) >= self.max_batch_size:
                self._flush_locked(fn)
            else:
                self.cond.wait(timeout=self.wait_timeout)
                if my_id not in self.results and self.pending:
                    self._flush_locked(fn)
            value = self.results.pop(my_id)
        if isinstance(value, Exception):
            raise value
        return value

    def _flush_locked(self, fn):
        items = list(self.pending)
        self.pending.clear()
        if not items:
            return
        self_obj = items[0][1]
        inputs = [it[2][0] if it[2] else None for it in items]
        try:
            outs = fn(self_obj, inputs)
            if len(outs) != len(inputs):
                raise ValueError("batch fn returned wrong length")
        except Exception as e:  # noqa: BLE001
            outs = [e] * len(inputs)
        for (rid, _, _), out in zip(items, outs):
            self.results[rid] = out
        self.cond.notify_all()


_batch_states: Dict[int, _BatchState] = {}
_batch_states_lock = threading.Lock()


def _batch_state_for(wrapper) -> _BatchState:
    key = id(wrapper)
    with _batch_states_lock:
        state = _batch_states.get(key)
        if state is None:
            size, timeout = wrapper._batch_params
            state = _BatchState(size, timeout)
            _batch_states[key] = state
        return state
