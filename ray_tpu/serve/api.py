"""Serve public API: deployments, handles, run/shutdown, HTTP proxy.

Reference analog: ``python/ray/serve/api.py`` + ``serve/deployment.py``
(@serve.deployment / .options / .bind) and ``serve/handle.py``
(DeploymentHandle). The HTTP proxy uses a stdlib threading HTTP server in
place of uvicorn/starlette (same per-node proxy role as
``http_proxy.py:189``).
"""

from __future__ import annotations

import functools
import json
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ..core import get, get_actor, kill, remote
from ._internal import (
    AutoscalingConfig,
    DeploymentInfo,
    Router,
    ServeController,
)

_CONTROLLER_NAME = "SERVE_CONTROLLER"
_state: Dict[str, Any] = {"controller": None, "http_server": None,
                          "routers": []}


def start(http_port: int = 8000, http_host: str = "127.0.0.1",
          detached: bool = True) -> None:
    """Start the Serve instance: a DETACHED controller actor running its
    own control loop (reference: run_control_loop inside the
    ServeController actor, controller.py:229) + the HTTP proxy. Serve
    survives driver-side handle GC — only serve.shutdown() stops it."""
    if _state["controller"] is not None:
        return
    controller_cls = remote(ServeController)
    controller = controller_cls.options(
        name=_CONTROLLER_NAME, max_concurrency=64,
        lifetime="detached" if detached else None,
    ).remote()
    get(controller.start_loop.remote(), timeout=30)
    _state["controller"] = controller
    _start_http_proxy(http_host, http_port)


def shutdown() -> None:
    controller = _state.get("controller")
    if controller is not None:
        try:
            get(controller.stop_loop.remote(), timeout=10)
        except Exception:
            pass
    for router in _state.get("routers", []):
        try:
            router.stop()
        except Exception:
            pass
    _state["routers"] = []
    server = _state.get("http_server")
    if server is not None:
        try:
            server.shutdown()
        except Exception:
            pass
        _state["http_server"] = None
    controller = _state.get("controller")
    if controller is not None:
        try:
            for name in get(controller.get_deployment_names.remote(),
                            timeout=10):
                get(controller.delete_deployment.remote(name), timeout=10)
            kill(controller)
        except Exception:
            pass
        _state["controller"] = None


def _controller():
    if _state["controller"] is None:
        start()
    return _state["controller"]


class DeploymentHandle:
    """Python-side handle (reference: serve/handle.py ServeHandle)."""

    def __init__(self, name: str, max_concurrent_queries: int = 100):
        self._name = name
        self._router = Router(_controller(), name, max_concurrent_queries)
        _state.setdefault("routers", []).append(self._router)

    def remote(self, *args, **kwargs):
        return self._router.assign(None, args, kwargs)

    def method(self, method_name: str) -> "DeploymentMethodHandle":
        return DeploymentMethodHandle(self, method_name)

    def __getattr__(self, item):
        if item.startswith("_"):
            raise AttributeError(item)
        return DeploymentMethodHandle(self, item)


class DeploymentMethodHandle:
    def __init__(self, handle: DeploymentHandle, method: str):
        self._handle = handle
        self._method = method

    def remote(self, *args, **kwargs):
        return self._handle._router.assign(self._method, args, kwargs)


@dataclass
class Application:
    """A bound deployment graph node (reference: .bind() -> Application)."""

    deployment: "Deployment"
    args: tuple = ()
    kwargs: dict = field(default_factory=dict)


class Deployment:
    """Reference: serve/deployment.py Deployment."""

    def __init__(self, func_or_class, name: str, opts: Dict[str, Any]):
        self._def = func_or_class
        self.name = name
        self._opts = opts
        functools.update_wrapper(self, func_or_class, updated=[])

    def options(self, **overrides) -> "Deployment":
        opts = dict(self._opts)
        name = overrides.pop("name", self.name)
        opts.update(overrides)
        return Deployment(self._def, name, opts)

    def bind(self, *args, **kwargs) -> Application:
        return Application(self, args, kwargs)

    def deploy(self, *init_args, **init_kwargs) -> DeploymentHandle:
        o = self._opts
        autoscaling = o.get("autoscaling_config")
        if isinstance(autoscaling, dict):
            autoscaling = AutoscalingConfig(**autoscaling)
        info = DeploymentInfo(
            name=self.name,
            deployment_def=self._def,
            init_args=init_args,
            init_kwargs=init_kwargs,
            num_replicas=o.get("num_replicas", 1),
            max_concurrent_queries=o.get("max_concurrent_queries", 100),
            route_prefix=o.get("route_prefix", f"/{self.name}"),
            autoscaling=autoscaling,
            ray_actor_options=o.get("ray_actor_options", {}),
        )
        get(_controller().deploy.remote(info), timeout=60)
        return DeploymentHandle(self.name, o.get("max_concurrent_queries",
                                                 100))

    def __call__(self, *a, **k):
        raise TypeError(
            f"Deployment {self.name!r} cannot be called directly; deploy it "
            f"with serve.run(dep.bind(...)) and use the handle."
        )


def deployment(_func_or_class=None, *, name: Optional[str] = None,
               num_replicas: int = 1, max_concurrent_queries: int = 100,
               route_prefix: Optional[str] = None,
               autoscaling_config=None,
               ray_actor_options: Optional[dict] = None):
    """``@serve.deployment`` decorator (reference: serve/api.py)."""

    def wrap(target):
        return Deployment(target, name or target.__name__, {
            "num_replicas": num_replicas,
            "max_concurrent_queries": max_concurrent_queries,
            "route_prefix": route_prefix,
            "autoscaling_config": autoscaling_config,
            "ray_actor_options": ray_actor_options or {},
        })

    if _func_or_class is not None:
        return wrap(_func_or_class)
    return wrap


def run(app: Application, *, name: str = "default",
        route_prefix: Optional[str] = None) -> DeploymentHandle:
    """Deploy a bound application (reference: serve.run)."""
    start()
    dep = app.deployment
    if route_prefix is not None:
        dep = dep.options(route_prefix=route_prefix)
    return dep.deploy(*app.args, **app.kwargs)


def get_deployment_handle(name: str) -> DeploymentHandle:
    return DeploymentHandle(name)


def list_deployments() -> Dict[str, dict]:
    return get(_controller().list_deployments.remote(), timeout=30)


# -- HTTP proxy --------------------------------------------------------------

def _start_http_proxy(host: str, port: int) -> None:
    """Threaded stdlib HTTP proxy (role of http_proxy.py HTTPProxy actor)."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    handles: Dict[str, DeploymentHandle] = {}

    class ProxyHandler(BaseHTTPRequestHandler):
        def log_message(self, *args):
            pass

        def _route(self):
            path = self.path.split("?")[0].strip("/")
            parts = path.split("/")
            name = parts[0] if parts and parts[0] else None
            if name is None:
                self.send_response(404)
                self.end_headers()
                self.wfile.write(b'{"error": "no deployment in path"}')
                return
            length = int(self.headers.get("Content-Length", 0) or 0)
            body = self.rfile.read(length) if length else b""
            payload = None
            if body:
                try:
                    payload = json.loads(body)
                except json.JSONDecodeError:
                    payload = body.decode("utf-8", "replace")
            try:
                handle = handles.get(name)
                if handle is None:
                    names = get(
                        _controller().get_deployment_names.remote(),
                        timeout=10,
                    )
                    if name not in names:
                        self.send_response(404)
                        self.end_headers()
                        self.wfile.write(
                            json.dumps({"error": f"unknown deployment "
                                                 f"{name}"}).encode())
                        return
                    handle = DeploymentHandle(name)
                    handles[name] = handle
                if payload is None:
                    ref = handle.remote()
                else:
                    ref = handle.remote(payload)
                result = get(ref, timeout=60)
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.end_headers()
                self.wfile.write(json.dumps(result).encode())
            except Exception as e:  # noqa: BLE001
                self.send_response(500)
                self.end_headers()
                self.wfile.write(json.dumps({"error": str(e)}).encode())

        do_GET = _route
        do_POST = _route

    try:
        server = ThreadingHTTPServer((host, port), ProxyHandler)
    except OSError:
        return  # port busy (another instance); python handles still work
    _state["http_server"] = server
    t = threading.Thread(target=server.serve_forever, daemon=True,
                         name="serve-http")
    t.start()


# -- batching ----------------------------------------------------------------

def batch(_func=None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """``@serve.batch``: coalesce concurrent calls into one batched call.

    Reference: ``serve/batching.py`` — the wrapped method receives a list
    of requests and must return a list of responses.
    """

    def wrap(fn):
        @functools.wraps(fn)
        def wrapper(self_or_first, *args):
            state = _batch_state_for(wrapper)
            return state.submit(fn, self_or_first, args)

        wrapper._batch_params = (max_batch_size, batch_wait_timeout_s)
        return wrapper

    if _func is not None:
        return wrap(_func)
    return wrap


class _BatchState:
    """Per-process batching state (created lazily in the replica, never at
    decoration time — locks aren't picklable)."""

    def __init__(self, max_batch_size: int, wait_timeout: float):
        self.max_batch_size = max_batch_size
        self.wait_timeout = wait_timeout
        self.lock = threading.Lock()
        self.cond = threading.Condition(self.lock)
        self.pending: List = []
        self.results: Dict[int, Any] = {}
        self.counter = 0

    def submit(self, fn, self_obj, args):
        with self.cond:
            my_id = self.counter
            self.counter += 1
            self.pending.append((my_id, self_obj, args))
            if len(self.pending) >= self.max_batch_size:
                self._flush_locked(fn)
            else:
                self.cond.wait(timeout=self.wait_timeout)
                if my_id not in self.results and self.pending:
                    self._flush_locked(fn)
            value = self.results.pop(my_id)
        if isinstance(value, Exception):
            raise value
        return value

    def _flush_locked(self, fn):
        items = list(self.pending)
        self.pending.clear()
        if not items:
            return
        self_obj = items[0][1]
        inputs = [it[2][0] if it[2] else None for it in items]
        try:
            outs = fn(self_obj, inputs)
            if len(outs) != len(inputs):
                raise ValueError("batch fn returned wrong length")
        except Exception as e:  # noqa: BLE001
            outs = [e] * len(inputs)
        for (rid, _, _), out in zip(items, outs):
            self.results[rid] = out
        self.cond.notify_all()


_batch_states: Dict[int, _BatchState] = {}
_batch_states_lock = threading.Lock()


def _batch_state_for(wrapper) -> _BatchState:
    key = id(wrapper)
    with _batch_states_lock:
        state = _batch_states.get(key)
        if state is None:
            size, timeout = wrapper._batch_params
            state = _BatchState(size, timeout)
            _batch_states[key] = state
        return state
