"""Serve library: model serving on actors.

Reference analog: ``python/ray/serve``.
"""

from ._internal import AutoscalingConfig, DeploymentInfo, ServeController
from .schema import (
    DeploymentSchema,
    ServeApplicationSchema,
    ServeDeploySchema,
)
from .api import (
    Application,
    Deployment,
    DeploymentHandle,
    batch,
    deployment,
    drain,
    get_deployment_handle,
    list_deployments,
    run,
    shutdown,
    start,
)

__all__ = [
    "Application", "AutoscalingConfig", "Deployment", "DeploymentHandle",
    "DeploymentInfo", "DeploymentSchema", "ServeApplicationSchema",
    "ServeController", "ServeDeploySchema", "batch", "deployment",
    "drain", "get_deployment_handle", "list_deployments", "run",
    "shutdown", "start",
]
