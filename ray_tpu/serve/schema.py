"""Declarative Serve config: schema + apply — the production ops path.

Reference analog: ``python/ray/serve/schema.py:227``
(``ServeApplicationSchema`` / ``ServeDeploySchema``) and the config-file
flow of ``serve deploy`` (``python/ray/serve/scripts.py:106,172``): a
YAML/JSON file names applications by import path, overrides per-
deployment options, and is idempotently applied to the running cluster.
"""

from __future__ import annotations

import importlib
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional

_DEPLOYMENT_FIELDS = (
    "num_replicas", "max_concurrent_queries", "route_prefix",
    "autoscaling_config", "ray_actor_options", "request_timeout_s",
    "request_deadline_s", "max_pending", "queue_timeout_s",
    "health_check_period_s",
)


@dataclass
class DeploymentSchema:
    """Per-deployment overrides (reference: schema.py DeploymentSchema)."""

    name: str
    num_replicas: Optional[int] = None
    max_concurrent_queries: Optional[int] = None
    route_prefix: Optional[str] = None
    autoscaling_config: Optional[Dict[str, Any]] = None
    ray_actor_options: Optional[Dict[str, Any]] = None
    request_timeout_s: Optional[float] = None
    # Fault tolerance / admission (ISSUE 18): end-to-end deadline,
    # bounded pending queue, queue-wait shed, health-probe period.
    request_deadline_s: Optional[float] = None
    max_pending: Optional[int] = None
    queue_timeout_s: Optional[float] = None
    health_check_period_s: Optional[float] = None
    user_config: Optional[Dict[str, Any]] = None

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "DeploymentSchema":
        if "name" not in d:
            raise ValueError("deployment entry requires a 'name'")
        unknown = set(d) - {f for f in cls.__dataclass_fields__}
        if unknown:
            raise ValueError(
                f"unknown deployment option(s) {sorted(unknown)} for "
                f"deployment {d.get('name')!r}")
        return cls(**d)

    def overrides(self) -> Dict[str, Any]:
        out = {}
        for f in _DEPLOYMENT_FIELDS:
            v = getattr(self, f)
            if v is not None:
                out[f] = v
        return out


@dataclass
class ServeApplicationSchema:
    """One application: an import path to a bound Application or a
    Deployment, plus per-deployment overrides (reference:
    schema.py:227 ServeApplicationSchema)."""

    import_path: str
    name: str = "default"
    route_prefix: Optional[str] = None
    args: Dict[str, Any] = field(default_factory=dict)
    deployments: List[DeploymentSchema] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ServeApplicationSchema":
        if "import_path" not in d:
            raise ValueError("application entry requires 'import_path'")
        if ":" not in d["import_path"]:
            raise ValueError(
                f"import_path {d['import_path']!r} must be "
                "'module.sub:attribute'")
        deployments = [DeploymentSchema.from_dict(x)
                       for x in d.get("deployments", [])]
        known = {"import_path", "name", "route_prefix", "args",
                 "deployments"}
        unknown = set(d) - known
        if unknown:
            raise ValueError(
                f"unknown application option(s) {sorted(unknown)}")
        return cls(
            import_path=d["import_path"], name=d.get("name", "default"),
            route_prefix=d.get("route_prefix"), args=d.get("args", {}),
            deployments=deployments)

    def to_dict(self) -> Dict[str, Any]:
        return asdict(self)


@dataclass
class HTTPOptionsSchema:
    host: str = "127.0.0.1"
    port: int = 8000

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "HTTPOptionsSchema":
        unknown = set(d) - {"host", "port"}
        if unknown:
            raise ValueError(f"unknown http option(s) {sorted(unknown)}")
        return cls(host=d.get("host", "127.0.0.1"),
                   port=int(d.get("port", 8000)))


@dataclass
class ServeDeploySchema:
    """The whole config file (reference: ServeDeploySchema)."""

    applications: List[ServeApplicationSchema]
    http_options: HTTPOptionsSchema = field(
        default_factory=HTTPOptionsSchema)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ServeDeploySchema":
        if "applications" not in d or not d["applications"]:
            raise ValueError("config requires a non-empty 'applications'")
        unknown = set(d) - {"applications", "http_options"}
        if unknown:
            raise ValueError(f"unknown top-level option(s) "
                             f"{sorted(unknown)}")
        return cls(
            applications=[ServeApplicationSchema.from_dict(a)
                          for a in d["applications"]],
            http_options=HTTPOptionsSchema.from_dict(
                d.get("http_options", {})),
        )

    @classmethod
    def from_file(cls, path: str) -> "ServeDeploySchema":
        import json

        with open(path) as f:
            text = f.read()
        if path.endswith((".yaml", ".yml")):
            import yaml

            data = yaml.safe_load(text)
        else:
            data = json.loads(text)
        if not isinstance(data, dict):
            raise ValueError(f"{path}: config must be a mapping")
        return cls.from_dict(data)

    def to_dict(self) -> Dict[str, Any]:
        return asdict(self)


def _import_target(import_path: str):
    module_name, _, attr = import_path.partition(":")
    module = importlib.import_module(module_name)
    target = module
    for part in attr.split("."):
        target = getattr(target, part)
    return target


def apply(schema: ServeDeploySchema) -> Dict[str, Any]:
    """Deploy every application in the schema to the running cluster
    (idempotent: re-applying updates deployments in place, the
    controller reconciles replicas). Returns a name -> route summary."""
    from . import api

    api.start(http_port=schema.http_options.port,
              http_host=schema.http_options.host)
    deployed: Dict[str, Any] = {}
    for app in schema.applications:
        target = _import_target(app.import_path)
        if isinstance(target, api.Application):
            application = target
        elif isinstance(target, api.Deployment):
            application = target.bind(**app.args)
        elif callable(target):  # app builder fn(args) -> Application
            application = target(app.args) if app.args else target()
            if not isinstance(application, api.Application):
                raise TypeError(
                    f"{app.import_path} returned "
                    f"{type(application).__name__}, expected Application")
        else:
            raise TypeError(
                f"{app.import_path} resolves to "
                f"{type(target).__name__}; expected an Application, "
                "Deployment, or builder function")
        dep = application.deployment
        overrides: Dict[str, Any] = {}
        user_config = None
        unmatched = []
        for dschema in app.deployments:
            if dschema.name == dep.name:
                overrides = dschema.overrides()
                user_config = dschema.user_config
            else:
                unmatched.append(dschema.name)
        if unmatched:
            # A typo'd name silently dropping overrides is the worst
            # config-file failure mode — reject it loudly.
            raise ValueError(
                f"application {app.name!r}: deployment override(s) "
                f"{unmatched} do not match the application's deployment "
                f"{dep.name!r}")
        if app.route_prefix is not None:
            overrides.setdefault("route_prefix", app.route_prefix)
        if user_config is not None:
            # Carried in DeploymentInfo so every replica applies it at
            # CONSTRUCTION, before becoming routable (a post-deploy
            # reconfigure RPC races with routed requests).
            overrides["user_config"] = user_config
        if overrides:
            dep = dep.options(**overrides)
        handle = dep.deploy(*application.args, **application.kwargs)
        deployed[app.name] = {
            "deployment": dep.name,
            "route_prefix": (dep._opts.get("route_prefix")
                             or f"/{dep.name}"),
        }
    return deployed


def status() -> Dict[str, Any]:
    """Serve status (reference: ``serve status``) — read-only: reports
    not-running instead of implicitly starting an instance (which would
    spawn a controller and bind the HTTP port as a side effect)."""
    from . import api

    if not api.is_running():
        return {"running": False, "deployments": {}}
    return {"running": True, "deployments": api.list_deployments()}
