"""Runtime environments: per-task/actor execution context, plugin-based.

Reference analog: ``python/ray/runtime_env/runtime_env.py`` (public
RuntimeEnv) + ``_private/runtime_env/plugin.py`` (RuntimeEnvPlugin /
RuntimeEnvPluginManager) + ``_private/runtime_env/{working_dir,
py_modules,pip,conda,container}.py`` + ``uri_cache.py``.

Architecture (mirrors the reference's agent-side plugin manager, applied
in-worker because workers here are generic processes, not per-env
processes):

- Each runtime_env field is owned by a :class:`RuntimeEnvPlugin` with
  ``validate`` / ``get_uri`` / ``create`` / ``modify_context`` /
  ``delete_uri`` hooks. Plugins run in ascending ``priority`` order
  (reference: RAY_RUNTIME_ENV_PRIORITY_FIELD_NAME ordering).
- ``create`` materializes cacheable resources keyed by URI; a process-
  wide :class:`URICache` tracks bytes and evicts least-recently-used
  materializations beyond its cap (reference: uri_cache.py).
- Custom plugins register via :func:`register_plugin` or the
  ``RT_RUNTIME_ENV_PLUGINS`` env var (comma-separated ``module:attr``
  import paths — reference: RAY_RUNTIME_ENV_PLUGINS_ENV_VAR).
- ``conda`` and ``container`` are *declared-but-gated*: this environment
  forbids network installs and has no container runtime, so their
  plugins validate the schema and raise actionable errors (or no-op when
  the named env is already active).
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import sys
import tempfile
from typing import Any, Callable, Dict, List, Optional


def _pip_env_key(pip: List[str], wheel_dir: str) -> str:
    """THE cache key for a pip materialization — single source for
    get_uri, site-path resolution, and materialize_pip_env."""
    return hashlib.sha1(json.dumps(
        [sorted(pip), os.path.abspath(wheel_dir)]).encode()
    ).hexdigest()[:16]


def _pip_site(key: str) -> str:
    return os.path.join(tempfile.gettempdir(), "rt_runtime_env", "pip",
                        key)


class RuntimeEnv(dict):
    """Validated runtime environment description.

    Validation is delegated per-field to the owning plugin
    (reference: RuntimeEnv.__init__ calls each plugin's validate)."""

    def __init__(self, env_vars: Optional[Dict[str, str]] = None,
                 working_dir: Optional[str] = None,
                 py_modules: Optional[List[str]] = None,
                 pip: Optional[List[str]] = None,
                 conda: Optional[Any] = None,
                 container: Optional[Dict] = None,
                 pip_wheel_dir: Optional[str] = None, **kwargs):
        known = set(_PLUGINS) | {"pip_wheel_dir"}
        unknown = set(kwargs) - known
        if unknown:
            raise ValueError(f"unknown runtime_env fields: {sorted(unknown)}"
                             f" (known: {sorted(known)})")
        super().__init__()
        fields = {"env_vars": env_vars, "working_dir": working_dir,
                  "py_modules": py_modules, "pip": pip, "conda": conda,
                  "container": container, **kwargs}
        if pip_wheel_dir:
            self["pip_wheel_dir"] = os.path.abspath(pip_wheel_dir)
        for name, value in fields.items():
            if value is None or value == [] or value == {}:
                continue
            plugin = _PLUGINS.get(name)
            if plugin is None:
                raise ValueError(f"no plugin registered for {name!r}")
            self[name] = plugin.validate(value, self)


class RuntimeEnvContext:
    """What plugins mutate; the worker applies + undoes it
    (reference: runtime_env/context.py RuntimeEnvContext — there it
    builds the worker command line; here workers are already running, so
    the context records process mutations and how to revert them)."""

    def __init__(self):
        self.env_vars: Dict[str, str] = {}
        self.sys_paths: List[str] = []
        self.working_dir: Optional[str] = None

    def apply(self) -> Dict[str, Any]:
        undo: Dict[str, Any] = {}
        try:
            if self.env_vars:
                undo["env_vars"] = {k: os.environ.get(k)
                                    for k in self.env_vars}
                os.environ.update(self.env_vars)
            if self.working_dir:
                undo["cwd"] = os.getcwd()
                os.chdir(self.working_dir)
            # Each path is inserted at 0 in plugin-priority order, so
            # LATER plugins end up in FRONT: pip-materialized packages
            # shadow py_modules, which shadow working_dir — a pinned pip
            # version must beat a stale copy in the working dir.
            for p in self.sys_paths:
                sys.path.insert(0, p)
            if self.sys_paths:
                undo["extra_paths"] = list(self.sys_paths)
        except Exception:
            # Half-applied process state is worse than no env: revert
            # whatever already mutated (the caller gets no undo info on
            # an exception path).
            restore_runtime_env(undo)
            raise
        return undo


class URICache:
    """LRU byte-capped cache of materialized resources
    (reference: _private/runtime_env/uri_cache.py)."""

    def __init__(self, max_total_bytes: int = 2 * 1024 ** 3):
        import threading

        self.max_total_bytes = max_total_bytes
        self._entries: Dict[str, int] = {}  # uri -> bytes (LRU order)
        self._deleters: Dict[str, Callable[[str], int]] = {}
        self._pins: Dict[str, int] = {}  # uri -> refcount
        # Concurrent actor executor threads apply runtime envs in the
        # same process; every mutation must hold this.
        self._lock = threading.RLock()

    @property
    def total_bytes(self) -> int:
        with self._lock:
            return sum(self._entries.values())

    def mark_used(self, uri: str) -> bool:
        with self._lock:
            if uri in self._entries:
                self._entries[uri] = self._entries.pop(uri)  # -> MRU
                return True
            return False

    def pin(self, uri: str) -> None:
        """A pinned URI is in use by an applied env; never evicted
        (reference: uri_cache marks added URIs 'in use'). Pin BEFORE
        add/mark_used so no eviction window exists."""
        with self._lock:
            self._pins[uri] = self._pins.get(uri, 0) + 1

    def unpin(self, uri: str) -> None:
        with self._lock:
            n = self._pins.get(uri, 0) - 1
            if n <= 0:
                self._pins.pop(uri, None)
            else:
                self._pins[uri] = n

    def add(self, uri: str, nbytes: int,
            deleter: Callable[[str], int]) -> None:
        with self._lock:
            self._entries.pop(uri, None)
            self._entries[uri] = nbytes
            self._deleters[uri] = deleter
            self._evict()

    def _evict(self) -> None:
        # Caller holds the lock.
        candidates = [u for u in self._entries if u not in self._pins]
        while sum(self._entries.values()) > self.max_total_bytes and len(
                candidates) > 0 and len(self._entries) > 1:
            uri = candidates.pop(0)  # least recently used, unpinned
            self._entries.pop(uri)
            deleter = self._deleters.pop(uri, None)
            if deleter:
                try:
                    deleter(uri)
                except OSError:
                    pass


_URI_CACHE = URICache()


class RuntimeEnvPlugin:
    """Base plugin (reference: plugin.py RuntimeEnvPlugin).

    ``validate(value, env)`` returns the canonicalized value (raises on
    bad input). ``get_uri`` names the cacheable resource (None = not
    cacheable). ``create(uri, env)`` materializes it and returns
    (path_or_none, bytes). ``modify_context`` records process mutations.
    ``delete_uri`` reclaims space, returning bytes freed.
    """

    name: str = ""
    priority: int = 10  # ascending execution order

    def validate(self, value: Any, env: Dict) -> Any:
        return value

    def get_uri(self, env: Dict) -> Optional[str]:
        return None

    def check_uri(self, uri: str) -> bool:
        """Is a cached URI's materialization still valid on disk?"""
        return True

    def create(self, uri: Optional[str], env: Dict) -> tuple:
        return None, 0

    def modify_context(self, uri: Optional[str], env: Dict,
                       ctx: RuntimeEnvContext) -> None:
        pass

    def delete_uri(self, uri: str) -> int:
        return 0


def _dir_bytes(path: str) -> int:
    total = 0
    for root, _dirs, files in os.walk(path):
        for f in files:
            try:
                total += os.path.getsize(os.path.join(root, f))
            except OSError:
                pass
    return total


def _rmtree_bytes(path: str) -> int:
    n = _dir_bytes(path)
    shutil.rmtree(path, ignore_errors=True)
    return n


class EnvVarsPlugin(RuntimeEnvPlugin):
    name = "env_vars"
    priority = 1

    def validate(self, value, env):
        if not isinstance(value, dict) or not all(
                isinstance(k, str) and isinstance(v, str)
                for k, v in value.items()):
            raise TypeError("env_vars must be Dict[str, str]")
        return dict(value)

    def modify_context(self, uri, env, ctx):
        ctx.env_vars.update(env.get("env_vars", {}))


class WorkingDirPlugin(RuntimeEnvPlugin):
    name = "working_dir"
    priority = 5

    def validate(self, value, env):
        if not os.path.isdir(value):
            raise ValueError(f"working_dir {value!r} not found")
        return os.path.abspath(value)

    def modify_context(self, uri, env, ctx):
        wd = env.get("working_dir")
        if wd:
            ctx.working_dir = wd
            ctx.sys_paths.append(wd)


class PyModulesPlugin(RuntimeEnvPlugin):
    name = "py_modules"
    priority = 6

    def validate(self, value, env):
        for m in value:
            if not os.path.exists(m):
                raise ValueError(f"py_module path {m!r} not found")
        return [os.path.abspath(m) for m in value]

    def modify_context(self, uri, env, ctx):
        for mod_path in env.get("py_modules", []):
            parent = (os.path.dirname(mod_path)
                      if os.path.isfile(mod_path) else mod_path)
            ctx.sys_paths.append(parent)


class PipPlugin(RuntimeEnvPlugin):
    """Offline pip materialization, URI-cached per (packages, wheel dir)
    hash (reference: _private/runtime_env/pip.py builds a venv per env
    hash; installs here are ``--no-index`` from a local wheel dir)."""

    name = "pip"
    priority = 7

    def validate(self, value, env):
        if not isinstance(value, (list, tuple)) or not all(
                isinstance(p, str) for p in value):
            raise TypeError("pip must be a list of requirement strings")
        return list(value)

    def _wheel_dir(self, env: Dict) -> Optional[str]:
        return env.get("pip_wheel_dir") or os.environ.get(
            "RT_RUNTIME_ENV_WHEEL_DIR")

    def get_uri(self, env: Dict) -> Optional[str]:
        pip = env.get("pip")
        wheel_dir = self._wheel_dir(env)
        if not pip or not wheel_dir:
            return None
        return f"pip://{_pip_env_key(pip, wheel_dir)}"

    def check_uri(self, uri: str) -> bool:
        # The cache is per-process but the site dir lives in shared
        # /tmp: another process (or a tmp cleaner) may have deleted it
        # since we cached the URI — verify before trusting the hit.
        return os.path.exists(os.path.join(self._site_for(uri),
                                           ".rt_ready"))

    def create(self, uri, env):
        pip = env.get("pip") or []
        wheel_dir = self._wheel_dir(env)
        if not pip:
            return None, 0
        if not wheel_dir:
            # NETWORK installs are forbidden here: without a local wheel
            # dir the packages must already import.
            for pkg in pip:
                name = pkg.split("==")[0].split(">=")[0].replace("-", "_")
                try:
                    __import__(name)
                except ImportError as e:
                    raise RuntimeError(
                        f"runtime_env pip package {pkg!r} unavailable; "
                        "installs are disabled — provide pip_wheel_dir "
                        "(or RT_RUNTIME_ENV_WHEEL_DIR) with local wheels"
                    ) from e
            return None, 0
        site = materialize_pip_env(pip, wheel_dir)
        return site, _dir_bytes(site)

    @staticmethod
    def _site_for(uri: str) -> str:
        return _pip_site(uri.split("://", 1)[1])

    def modify_context(self, uri, env, ctx):
        # The site path is a pure function of the URI, so the cached-hit
        # path (create skipped) resolves identically.
        if uri is not None:
            ctx.sys_paths.append(self._site_for(uri))

    def delete_uri(self, uri: str) -> int:
        target = self._site_for(uri)
        if os.path.isdir(target):
            return _rmtree_bytes(target)
        return 0


class CondaPlugin(RuntimeEnvPlugin):
    """Declared-but-gated (reference: _private/runtime_env/conda.py
    creates/caches conda envs and relaunches the worker inside them).
    Offline + single-interpreter here: a *named* env matching the
    currently-active one passes through; anything else raises with the
    reason."""

    name = "conda"
    priority = 4

    def validate(self, value, env):
        if not isinstance(value, (str, dict)):
            raise TypeError("conda must be an env name or a conda "
                            "environment.yml dict")
        if isinstance(value, dict) and "dependencies" not in value:
            raise ValueError("conda dict spec needs a 'dependencies' key")
        return value

    def create(self, uri, env):
        spec = env.get("conda")
        active = os.environ.get("CONDA_DEFAULT_ENV")
        if isinstance(spec, str) and spec == active:
            return None, 0  # already inside the requested env
        raise RuntimeError(
            f"conda runtime_env {spec!r} cannot be materialized: this "
            "deployment runs offline without a conda toolchain "
            f"(active env: {active or 'none'}). Name the already-active "
            "env, or use pip with a local wheel dir instead.")


class ContainerPlugin(RuntimeEnvPlugin):
    """Declared-but-gated (reference: _private/runtime_env/container.py
    wraps the worker command in ``podman run``). Validates the schema;
    raises unless a container runtime exists on the host."""

    name = "container"
    priority = 2

    def validate(self, value, env):
        if not isinstance(value, dict) or "image" not in value:
            raise ValueError(
                "container must be {'image': ..., 'run_options': [...]}")
        unknown = set(value) - {"image", "run_options", "worker_path"}
        if unknown:
            raise ValueError(f"unknown container fields {sorted(unknown)}")
        return dict(value)

    def create(self, uri, env):
        for runtime in ("podman", "docker"):
            if shutil.which(runtime):
                raise RuntimeError(
                    f"container runtime_env found {runtime!r}, but "
                    "per-worker container relaunch is not wired into "
                    "this deployment; run the whole node inside the "
                    "image instead")
        raise RuntimeError(
            "container runtime_env requires podman or docker on the "
            "host; neither is available in this environment")


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------

_PLUGINS: Dict[str, RuntimeEnvPlugin] = {}


def register_plugin(plugin: RuntimeEnvPlugin) -> None:
    """Register a custom plugin (reference: plugin.py
    RuntimeEnvPluginManager.load_plugins / RAY_RUNTIME_ENV_PLUGINS)."""
    if not plugin.name:
        raise ValueError("plugin needs a name")
    _PLUGINS[plugin.name] = plugin


for _p in (EnvVarsPlugin(), WorkingDirPlugin(), PyModulesPlugin(),
           PipPlugin(), CondaPlugin(), ContainerPlugin()):
    register_plugin(_p)


def _load_env_plugins() -> None:
    """Import plugins named in RT_RUNTIME_ENV_PLUGINS=module:attr,..."""
    spec = os.environ.get("RT_RUNTIME_ENV_PLUGINS")
    if not spec:
        return
    import importlib

    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        mod_name, _, attr = item.partition(":")
        obj = getattr(importlib.import_module(mod_name), attr)
        register_plugin(obj() if isinstance(obj, type) else obj)


_load_env_plugins()


def stage_working_dir(source: str, job_id_hex: str) -> str:
    """Copy the working dir into the session area (reference: packaging.py
    zips to GCS KV; single-host staging copies to a shared path)."""
    target = os.path.join(tempfile.gettempdir(), "rt_runtime_env",
                          job_id_hex, os.path.basename(source))
    if not os.path.exists(target):
        shutil.copytree(source, target)
    return target


def apply_runtime_env(env: Optional[Dict]) -> Dict[str, Any]:
    """Apply in the worker process before task execution: run every
    relevant plugin (ascending priority) — create with URI caching, then
    modify_context — and apply the assembled context. Returns undo info.
    """
    if not env:
        return {}
    ctx = RuntimeEnvContext()
    pinned: List[str] = []
    try:
        for plugin in sorted(_PLUGINS.values(), key=lambda p: p.priority):
            if plugin.name not in env:
                continue
            uri = plugin.get_uri(env)
            if uri is not None:
                # Pin FIRST (before add/mark_used): eviction must never
                # see this URI unpinned — not even in the window before
                # its own add() (whose _evict would otherwise delete the
                # just-created resource when everything else is pinned).
                _URI_CACHE.pin(uri)
                pinned.append(uri)
            hit = (uri is not None and _URI_CACHE.mark_used(uri)
                   and plugin.check_uri(uri))
            if not hit:
                _path, nbytes = plugin.create(uri, env)
                if uri is not None and nbytes:
                    _URI_CACHE.add(uri, nbytes, plugin.delete_uri)
            plugin.modify_context(uri, env, ctx)
        undo = ctx.apply()
    except Exception:
        # A later plugin (or the apply itself) failed: release pins
        # taken so far — the caller never receives undo info, so
        # restore_runtime_env can't.
        for uri in pinned:
            _URI_CACHE.unpin(uri)
        raise
    if pinned:
        undo["pinned_uris"] = pinned
    return undo


def materialize_pip_env(pip: List[str], wheel_dir: str) -> str:
    """Per-env-hash package materialization with caching (reference:
    ``_private/runtime_env/pip.py`` builds a venv per env hash; here a
    ``pip install --no-index --find-links=<local wheels> --target=<cache>``
    gives the same isolation contract fully OFFLINE). Concurrent workers
    race on a directory lock; the winner installs, the rest reuse."""
    import subprocess
    import time as time_mod

    target = _pip_site(_pip_env_key(pip, wheel_dir))
    marker = os.path.join(target, ".rt_ready")
    if os.path.exists(marker):
        return target
    lock_dir = target + ".lock"
    os.makedirs(os.path.dirname(target), exist_ok=True)
    deadline = time_mod.monotonic() + 120
    while True:
        try:
            os.mkdir(lock_dir)
            break
        except FileExistsError:
            if os.path.exists(marker):
                return target
            # Stale-lock recovery: the holder may have been killed mid
            # install (worker OOM kill, host crash) — steal locks older
            # than 300s; the new winner re-runs the install over any
            # partial target (pip --target overwrites safely).
            try:
                if time_mod.time() - os.path.getmtime(lock_dir) > 300:
                    os.rmdir(lock_dir)
                    continue
            except OSError:
                continue  # raced with the holder's cleanup
            if time_mod.monotonic() > deadline:
                raise TimeoutError(f"pip env lock stuck: {lock_dir}")
            time_mod.sleep(0.2)
    try:
        if not os.path.exists(marker):
            subprocess.run(
                [sys.executable, "-m", "pip", "install", "--quiet",
                 "--no-index", "--find-links", wheel_dir,
                 "--target", target] + list(pip),
                check=True, capture_output=True, timeout=300)
            with open(marker, "w") as f:
                f.write("ok")
    except subprocess.CalledProcessError as e:
        raise RuntimeError(
            f"offline pip install failed for {pip}: "
            f"{e.stderr.decode(errors='replace')[:500]}") from e
    finally:
        try:
            os.rmdir(lock_dir)
        except OSError:
            pass
    return target


def restore_runtime_env(undo: Dict[str, Any]) -> None:
    for uri in undo.get("pinned_uris", []):
        _URI_CACHE.unpin(uri)
    for k, v in (undo.get("env_vars") or {}).items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    if "cwd" in undo:
        os.chdir(undo["cwd"])
    for p in undo.get("extra_paths", []):
        if p in sys.path:
            sys.path.remove(p)
