"""Runtime environments: per-task/actor execution context.

Reference analog: ``python/ray/runtime_env/runtime_env.py`` (public
RuntimeEnv) + ``_private/runtime_env/{working_dir,py_modules,pip,conda}``.
Supported natively here: ``env_vars`` (applied in the worker before
execution), ``working_dir`` (staged to a per-job dir and chdir'd,
sys.path-prepended), ``py_modules`` (paths prepended to sys.path).
``pip``/``conda`` are declared-but-gated: this environment forbids
installs, so they validate and raise unless the packages already import.
"""

from __future__ import annotations

import os
import shutil
import sys
import tempfile
from typing import Any, Dict, List, Optional


class RuntimeEnv(dict):
    """Validated runtime environment description."""

    KNOWN = {"env_vars", "working_dir", "py_modules", "pip", "conda",
             "pip_wheel_dir"}

    def __init__(self, env_vars: Optional[Dict[str, str]] = None,
                 working_dir: Optional[str] = None,
                 py_modules: Optional[List[str]] = None,
                 pip: Optional[List[str]] = None,
                 conda: Optional[Any] = None,
                 pip_wheel_dir: Optional[str] = None, **kwargs):
        unknown = set(kwargs) - self.KNOWN
        if unknown:
            raise ValueError(f"unknown runtime_env fields: {sorted(unknown)}")
        super().__init__()
        if pip_wheel_dir:
            self["pip_wheel_dir"] = os.path.abspath(pip_wheel_dir)
        if env_vars:
            if not all(isinstance(k, str) and isinstance(v, str)
                       for k, v in env_vars.items()):
                raise TypeError("env_vars must be Dict[str, str]")
            self["env_vars"] = dict(env_vars)
        if working_dir:
            if not os.path.isdir(working_dir):
                raise ValueError(f"working_dir {working_dir!r} not found")
            self["working_dir"] = os.path.abspath(working_dir)
        if py_modules:
            for m in py_modules:
                if not os.path.exists(m):
                    raise ValueError(f"py_module path {m!r} not found")
            self["py_modules"] = [os.path.abspath(m) for m in py_modules]
        if pip:
            self["pip"] = list(pip)
        if conda:
            self["conda"] = conda


def stage_working_dir(source: str, job_id_hex: str) -> str:
    """Copy the working dir into the session area (reference: packaging.py
    zips to GCS KV; single-host staging copies to a shared path)."""
    target = os.path.join(tempfile.gettempdir(), "rt_runtime_env",
                          job_id_hex, os.path.basename(source))
    if not os.path.exists(target):
        shutil.copytree(source, target)
    return target


def apply_runtime_env(env: Optional[Dict]) -> Dict[str, Any]:
    """Apply in the worker process before task execution.

    Returns undo info (reference: the runtime-env agent materializes the
    env before worker start; here workers are generic and apply per-task).
    """
    if not env:
        return {}
    undo: Dict[str, Any] = {}
    env_vars = env.get("env_vars")
    if env_vars:
        undo["env_vars"] = {k: os.environ.get(k) for k in env_vars}
        os.environ.update(env_vars)
    working_dir = env.get("working_dir")
    if working_dir:
        undo["cwd"] = os.getcwd()
        os.chdir(working_dir)
        sys.path.insert(0, working_dir)
        undo["sys_path_entry"] = working_dir
    for mod_path in env.get("py_modules", []):
        parent = (os.path.dirname(mod_path)
                  if os.path.isfile(mod_path) else mod_path)
        sys.path.insert(0, parent)
        undo.setdefault("extra_paths", []).append(parent)
    pip_pkgs = env.get("pip") or []
    if pip_pkgs:
        wheel_dir = env.get("pip_wheel_dir") or os.environ.get(
            "RT_RUNTIME_ENV_WHEEL_DIR")
        if wheel_dir:
            site = materialize_pip_env(pip_pkgs, wheel_dir)
            sys.path.insert(0, site)
            undo.setdefault("extra_paths", []).append(site)
        else:
            # NETWORK installs are forbidden here: without a local wheel
            # dir the packages must already import.
            for pkg in pip_pkgs:
                name = pkg.split("==")[0].split(">=")[0].replace("-", "_")
                try:
                    __import__(name)
                except ImportError as e:
                    raise RuntimeError(
                        f"runtime_env pip package {pkg!r} unavailable; "
                        f"installs are disabled — provide pip_wheel_dir "
                        f"(or RT_RUNTIME_ENV_WHEEL_DIR) with local wheels"
                    ) from e
    return undo


def materialize_pip_env(pip: List[str], wheel_dir: str) -> str:
    """Per-env-hash package materialization with caching (reference:
    ``_private/runtime_env/pip.py`` builds a venv per env hash; here a
    ``pip install --no-index --find-links=<local wheels> --target=<cache>``
    gives the same isolation contract fully OFFLINE). Concurrent workers
    race on a directory lock; the winner installs, the rest reuse."""
    import hashlib
    import json as json_mod
    import subprocess
    import time as time_mod

    key = hashlib.sha1(json_mod.dumps(
        [sorted(pip), os.path.abspath(wheel_dir)]).encode()).hexdigest()[:16]
    target = os.path.join(tempfile.gettempdir(), "rt_runtime_env", "pip",
                          key)
    marker = os.path.join(target, ".rt_ready")
    if os.path.exists(marker):
        return target
    lock_dir = target + ".lock"
    os.makedirs(os.path.dirname(target), exist_ok=True)
    deadline = time_mod.monotonic() + 120
    while True:
        try:
            os.mkdir(lock_dir)
            break
        except FileExistsError:
            if os.path.exists(marker):
                return target
            # Stale-lock recovery: the holder may have been killed mid
            # install (worker OOM kill, host crash) — steal locks older
            # than 300s; the new winner re-runs the install over any
            # partial target (pip --target overwrites safely).
            try:
                if time_mod.time() - os.path.getmtime(lock_dir) > 300:
                    os.rmdir(lock_dir)
                    continue
            except OSError:
                continue  # raced with the holder's cleanup
            if time_mod.monotonic() > deadline:
                raise TimeoutError(f"pip env lock stuck: {lock_dir}")
            time_mod.sleep(0.2)
    try:
        if not os.path.exists(marker):
            subprocess.run(
                [sys.executable, "-m", "pip", "install", "--quiet",
                 "--no-index", "--find-links", wheel_dir,
                 "--target", target] + list(pip),
                check=True, capture_output=True, timeout=300)
            with open(marker, "w") as f:
                f.write("ok")
    except subprocess.CalledProcessError as e:
        raise RuntimeError(
            f"offline pip install failed for {pip}: "
            f"{e.stderr.decode(errors='replace')[:500]}") from e
    finally:
        try:
            os.rmdir(lock_dir)
        except OSError:
            pass
    return target


def restore_runtime_env(undo: Dict[str, Any]) -> None:
    for k, v in (undo.get("env_vars") or {}).items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    if "cwd" in undo:
        os.chdir(undo["cwd"])
    entry = undo.get("sys_path_entry")
    if entry and entry in sys.path:
        sys.path.remove(entry)
    for p in undo.get("extra_paths", []):
        if p in sys.path:
            sys.path.remove(p)
