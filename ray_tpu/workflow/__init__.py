"""Workflow: durable DAG execution with resume.

Reference analog: ``python/ray/workflow`` — ``workflow.run/run_async/
resume/resume_all/get_output/get_status`` (api.py:120-533); every DAG node
result persists to storage (``workflow_storage.py``) so a crashed or
interrupted workflow resumes from completed steps; the state machine of
``workflow_executor.py:32,72`` walks pending steps whose deps are done.
"""

from __future__ import annotations

import json
import os
import pickle
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from ..core import get
from ..dag import DAGNode, InputAttributeNode, InputNode


class WorkflowStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCESSFUL = "SUCCESSFUL"
    FAILED = "FAILED"
    RESUMABLE = "RESUMABLE"


_DEFAULT_STORAGE = os.path.join(
    os.environ.get("TMPDIR", "/tmp"), "rt_workflows"
)
_storage_root = [_DEFAULT_STORAGE]


def init(storage: Optional[str] = None) -> None:
    """Set the workflow storage root (reference: ray.init(storage=...))."""
    if storage:
        _storage_root[0] = storage


class WorkflowStorage:
    """Per-workflow step-result persistence (workflow_storage.py)."""

    def __init__(self, workflow_id: str):
        self.workflow_id = workflow_id
        self.dir = os.path.join(_storage_root[0], workflow_id)
        os.makedirs(os.path.join(self.dir, "steps"), exist_ok=True)

    def save_step(self, step_id: str, value: Any) -> None:
        path = os.path.join(self.dir, "steps", f"{step_id}.pkl")
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(value, f)
        os.replace(tmp, path)  # atomic commit

    def load_step(self, step_id: str):
        path = os.path.join(self.dir, "steps", f"{step_id}.pkl")
        if not os.path.exists(path):
            return None, False
        with open(path, "rb") as f:
            return pickle.load(f), True

    def save_meta(self, meta: Dict) -> None:
        with open(os.path.join(self.dir, "meta.json"), "w") as f:
            json.dump(meta, f)

    def load_meta(self) -> Optional[Dict]:
        path = os.path.join(self.dir, "meta.json")
        if not os.path.exists(path):
            return None
        with open(path) as f:
            return json.load(f)

    def save_dag(self, dag: DAGNode, input_value: Any) -> None:
        from ..core import serialization

        with open(os.path.join(self.dir, "dag.pkl"), "wb") as f:
            f.write(serialization.dumps((dag, input_value)))

    def load_dag(self):
        path = os.path.join(self.dir, "dag.pkl")
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            return pickle.loads(f.read())


def _step_key(node: DAGNode, index: int) -> str:
    # Stable step identity: topological position + node type/name.
    return f"{index:04d}_{type(node).__name__}"


def options(node: DAGNode, *, max_retries: int = 0,
            retry_delay_s: float = 0.1,
            timeout_s: Optional[float] = None,
            catch_exceptions: bool = False) -> DAGNode:
    """Attach per-step workflow options (reference:
    ``fn.options(**workflow.options(max_retries=..., catch_exceptions=
    ...))``): retries with delay, a step timeout, and exception
    capture — with ``catch_exceptions`` the step's persisted result is
    ``(value, None)`` on success / ``(None, exception)`` on failure."""
    node._wf_options = {
        "max_retries": max_retries, "retry_delay_s": retry_delay_s,
        "timeout_s": timeout_s, "catch_exceptions": catch_exceptions,
    }
    return node


class EventListener:
    """Reference: ``workflow/event_listener.py`` — poll_for_event blocks
    until the external event arrives; the event STEP persists its result
    like any step, so a resumed workflow does not re-wait."""

    def poll_for_event(self) -> Any:
        raise NotImplementedError


class _EventNode(DAGNode):
    def __init__(self, listener_factory, args, kwargs):
        super().__init__((), {})
        self._factory = listener_factory
        self._args = args
        self._kwargs = kwargs

    def _execute_one(self, resolved, input_value):
        listener = self._factory(*self._args, **self._kwargs)
        return listener.poll_for_event()


def wait_for_event(listener_factory, *args, **kwargs) -> DAGNode:
    """A DAG step that blocks on an external event (checkpointed)."""
    return _EventNode(listener_factory, args, kwargs)


def _log_event(storage: "WorkflowStorage", kind: str, **fields) -> None:
    path = os.path.join(storage.dir, "events.jsonl")
    entry = {"ts": time.time(), "event": kind, **fields}
    with open(path, "a") as f:
        f.write(json.dumps(entry) + "\n")


def get_events(workflow_id: str) -> List[Dict]:
    """The workflow's structured event log (step lifecycle + retries)."""
    path = os.path.join(WorkflowStorage(workflow_id).dir, "events.jsonl")
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def _run_step(storage, key, node, resolved, input_value):
    """One step with retries/timeout/catch_exceptions + continuation:
    a step RETURNING a DAGNode continues into that sub-workflow
    (reference: ``workflow.continuation`` + workflow_executor.py:32)."""
    opts = getattr(node, "_wf_options", None) or {}
    retries_left = int(opts.get("max_retries", 0))
    timeout_s = opts.get("timeout_s")
    catch = bool(opts.get("catch_exceptions", False))
    attempt = 0
    while True:
        attempt += 1
        _log_event(storage, "step_started", step=key, attempt=attempt)
        try:
            ref_or_val = node._execute_one(resolved, input_value)
            if hasattr(ref_or_val, "id"):
                value = get(ref_or_val, timeout=timeout_s)
            else:
                value = ref_or_val
            if isinstance(value, DAGNode):
                # Continuation: execute the returned DAG as a nested
                # sub-workflow rooted under this step's storage.
                sub_id = f"{storage.workflow_id}/sub_{key}"
                WorkflowStorage(sub_id).save_dag(value, input_value)
                value = _execute_workflow(sub_id, value, input_value)
            _log_event(storage, "step_finished", step=key,
                       attempt=attempt)
            return (value, None) if catch else value
        except Exception as e:  # noqa: BLE001
            _log_event(storage, "step_failed", step=key, attempt=attempt,
                       error=repr(e)[:200])
            if retries_left > 0:
                retries_left -= 1
                time.sleep(float(opts.get("retry_delay_s", 0.1)))
                continue
            if catch:
                return (None, e)
            raise


def _execute_workflow(workflow_id: str, dag: DAGNode, input_value: Any):
    """Walk the DAG, skipping steps whose results are already persisted.

    Reference: WorkflowExecutor.run_until_complete (workflow_executor.py:72).
    """
    storage = WorkflowStorage(workflow_id)
    storage.save_meta({"status": WorkflowStatus.RUNNING,
                       "start": time.time()})
    resolved: Dict[str, Any] = {}
    order = dag.topological()
    try:
        for i, node in enumerate(order):
            if isinstance(node, (InputNode, InputAttributeNode)):
                continue
            key = _step_key(node, i)
            cached, hit = storage.load_step(key)
            if hit:
                resolved[node._uuid] = cached
                continue
            value = _run_step(storage, key, node, resolved, input_value)
            storage.save_step(key, value)
            resolved[node._uuid] = value
        result = resolved[dag._uuid]
        storage.save_meta({"status": WorkflowStatus.SUCCESSFUL,
                           "end": time.time()})
        storage.save_step("__output__", result)
        return result
    except Exception as e:  # noqa: BLE001
        storage.save_meta({"status": WorkflowStatus.FAILED, "error": str(e)})
        raise


def run(dag: DAGNode, *, workflow_id: Optional[str] = None,
        workflow_input: Any = None):
    """Run to completion, persisting each step (api.py:120)."""
    import uuid as _uuid

    workflow_id = workflow_id or f"wf-{_uuid.uuid4().hex[:8]}"
    storage = WorkflowStorage(workflow_id)
    storage.save_dag(dag, workflow_input)
    return _execute_workflow(workflow_id, dag, workflow_input)


def run_async(dag: DAGNode, *, workflow_id: Optional[str] = None,
              workflow_input: Any = None):
    """Submit as a background task; returns an ObjectRef of the result."""
    import uuid as _uuid

    from ..core import remote

    workflow_id = workflow_id or f"wf-{_uuid.uuid4().hex[:8]}"
    WorkflowStorage(workflow_id).save_dag(dag, workflow_input)
    runner = remote(_execute_workflow)
    return runner.remote(workflow_id, dag, workflow_input)


def resume(workflow_id: str):
    """Re-run from persisted steps (api.py resume)."""
    storage = WorkflowStorage(workflow_id)
    loaded = storage.load_dag()
    if loaded is None:
        raise ValueError(f"unknown workflow {workflow_id!r}")
    dag, input_value = loaded
    return _execute_workflow(workflow_id, dag, input_value)


def resume_all() -> List[str]:
    out = []
    root = _storage_root[0]
    if not os.path.isdir(root):
        return out
    for wid in os.listdir(root):
        meta = WorkflowStorage(wid).load_meta()
        if meta and meta.get("status") in (WorkflowStatus.RUNNING,
                                           WorkflowStatus.FAILED,
                                           WorkflowStatus.RESUMABLE):
            resume(wid)
            out.append(wid)
    return out


def get_status(workflow_id: str) -> str:
    meta = WorkflowStorage(workflow_id).load_meta()
    if meta is None:
        raise ValueError(f"unknown workflow {workflow_id!r}")
    return meta["status"]


def get_output(workflow_id: str):
    value, hit = WorkflowStorage(workflow_id).load_step("__output__")
    if not hit:
        raise ValueError(f"workflow {workflow_id!r} has no output yet")
    return value


def list_all() -> List[Dict]:
    root = _storage_root[0]
    if not os.path.isdir(root):
        return []
    out = []
    for wid in sorted(os.listdir(root)):
        meta = WorkflowStorage(wid).load_meta() or {}
        out.append({"workflow_id": wid, "status": meta.get("status")})
    return out
