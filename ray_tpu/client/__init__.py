"""Remote-driver client ("Ray Client" equivalent).

Reference analog: ``python/ray/util/client/`` — a gRPC proxy that lets a
remote Python process drive a running cluster as if it were the driver
(``ray.init("ray://host:port")``); the server multiplexes many clients
onto the head runtime (``util/client/server/server.py``, architecture doc
``util/client/ARCHITECTURE.md``).

Here the wire is the same length-prefixed frame protocol as the native
control store; payloads are cloudpickle. Usage::

    # cluster side
    from ray_tpu.client import serve_forever  # or ClientServer
    server = ClientServer(runtime_already_initialized=True); server.start()

    # client side
    import ray_tpu.client as client
    session = client.connect("127.0.0.1:10001")
    ref = session.remote(lambda x: x + 1)(41)
    assert session.get(ref) == 42
"""

from .client import ClientActorHandle, ClientObjectRef, ClientSession, connect
from .server import ClientServer

__all__ = [
    "ClientActorHandle", "ClientObjectRef", "ClientServer", "ClientSession",
    "connect",
]
