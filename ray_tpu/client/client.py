"""Client-side session for driving a remote cluster.

Reference analog: ``python/ray/util/client/worker.py`` (the Worker that
proxies ``ray.*`` calls over the wire) and ``common.py``
(ClientObjectRef/ClientActorHandle/ClientRemoteFunc).
"""

from __future__ import annotations

import socket
import threading
import uuid
from typing import Any, List, Optional, Sequence, Tuple, Union

import cloudpickle

from .server import recv_msg, send_msg


class ClientError(Exception):
    pass


class ClientObjectRef:
    """Opaque handle to a server-side ObjectRef."""

    def __init__(self, hex_id: str, session: "ClientSession"):
        self._hex = hex_id
        self._session = session

    def hex(self) -> str:
        return self._hex

    def _wire(self) -> dict:
        return {"__client_ref__": True, "hex": self._hex}

    def __repr__(self):
        return f"ClientObjectRef({self._hex[:12]})"


class ClientRemoteFunction:
    def __init__(self, session: "ClientSession", fn, options: dict):
        self._session = session
        self._fn_id = uuid.uuid4().hex
        self._registered = False
        self._fn = fn
        self._options = options

    def _ensure_registered(self) -> None:
        if not self._registered:
            self._session._call({
                "op": "register_fn", "fn_id": self._fn_id,
                "fn": cloudpickle.dumps(self._fn),
                "options": self._options,
            })
            self._registered = True

    def remote(self, *args, **kwargs) -> ClientObjectRef:
        self._ensure_registered()
        reply = self._session._call({
            "op": "task", "fn_id": self._fn_id,
            "args": self._session._wire_args(args),
            "kwargs": self._session._wire_kwargs(kwargs),
        })
        return ClientObjectRef(reply["ref"], self._session)

    def options(self, **opts) -> "ClientRemoteFunction":
        merged = dict(self._options)
        merged.update(opts)
        return ClientRemoteFunction(self._session, self._fn, merged)


class ClientActorMethod:
    def __init__(self, handle: "ClientActorHandle", name: str):
        self._handle = handle
        self._name = name

    def remote(self, *args, **kwargs) -> ClientObjectRef:
        session = self._handle._session
        reply = session._call({
            "op": "actor_method", "actor_id": self._handle._actor_id,
            "method": self._name,
            "args": session._wire_args(args),
            "kwargs": session._wire_kwargs(kwargs),
        })
        return ClientObjectRef(reply["ref"], session)


class ClientActorHandle:
    def __init__(self, session: "ClientSession", actor_id: str):
        self._session = session
        self._actor_id = actor_id

    def __getattr__(self, name: str) -> ClientActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        return ClientActorMethod(self, name)


class ClientActorClass:
    def __init__(self, session: "ClientSession", cls, options: dict):
        self._session = session
        self._cls = cls
        self._options = options

    def remote(self, *args, **kwargs) -> ClientActorHandle:
        reply = self._session._call({
            "op": "actor_create", "cls": cloudpickle.dumps(self._cls),
            "options": self._options,
            "args": self._session._wire_args(args),
            "kwargs": self._session._wire_kwargs(kwargs),
        })
        return ClientActorHandle(self._session, reply["actor_id"])

    def options(self, **opts) -> "ClientActorClass":
        merged = dict(self._options)
        merged.update(opts)
        return ClientActorClass(self._session, self._cls, merged)


class ClientSession:
    """One connection to a ClientServer; thread-safe request/response."""

    def __init__(self, address: Union[str, Tuple[str, int]],
                 timeout: float = 30.0):
        if isinstance(address, str):
            host, _, port = address.partition(":")
            address = (host, int(port))
        self._sock = socket.create_connection(address, timeout=timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()
        self._call({"op": "ping"})

    # -- wire ------------------------------------------------------------
    def _call(self, req: dict) -> dict:
        with self._lock:
            send_msg(self._sock, req)
            reply = recv_msg(self._sock)
        if "error" in reply:
            raise reply["error"]
        return reply

    def _wire_args(self, args: Sequence[Any]) -> list:
        return [a._wire() if isinstance(a, ClientObjectRef) else a
                for a in args]

    def _wire_kwargs(self, kwargs: dict) -> dict:
        return {k: (v._wire() if isinstance(v, ClientObjectRef) else v)
                for k, v in kwargs.items()}

    # -- API mirror ------------------------------------------------------
    def put(self, value: Any) -> ClientObjectRef:
        reply = self._call({"op": "put", "value": value})
        return ClientObjectRef(reply["ref"], self)

    def get(self, refs, timeout: Optional[float] = None):
        single = isinstance(refs, ClientObjectRef)
        if single:
            refs = [refs]
        reply = self._call({"op": "get", "refs": [r.hex() for r in refs],
                            "timeout": timeout})
        values = reply["values"]
        return values[0] if single else values

    def wait(self, refs: List[ClientObjectRef], num_returns: int = 1,
             timeout: Optional[float] = None):
        reply = self._call({"op": "wait",
                            "refs": [r.hex() for r in refs],
                            "num_returns": num_returns,
                            "timeout": timeout})
        by_hex = {r.hex(): r for r in refs}
        return ([by_hex[h] for h in reply["ready"]],
                [by_hex[h] for h in reply["pending"]])

    def remote(self, fn_or_class=None, **options):
        """Mirror of ``rt.remote``: decorator for functions and classes."""
        def wrap(target):
            if isinstance(target, type):
                return ClientActorClass(self, target, options)
            return ClientRemoteFunction(self, target, options)

        if fn_or_class is None:
            return wrap
        return wrap(fn_or_class)

    def kill(self, actor: ClientActorHandle) -> None:
        self._call({"op": "kill_actor", "actor_id": actor._actor_id})

    def release(self, refs: List[ClientObjectRef]) -> None:
        self._call({"op": "release", "refs": [r.hex() for r in refs]})

    def cluster_info(self) -> dict:
        return self._call({"op": "cluster_info"})

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def connect(address: Union[str, Tuple[str, int]], **kwargs) -> ClientSession:
    """Reference: ``ray.init("ray://host:port")`` / ``ray.util.connect``."""
    return ClientSession(address, **kwargs)
