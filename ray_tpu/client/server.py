"""Client server: hosts remote drivers against the local runtime.

Reference analog: ``python/ray/util/client/server/server.py`` — the
RayletServicer holding per-client object/actor maps, translating proxied
calls into real core API calls; started by ``ray start`` as the "ray
client server" on port 10001.
"""

from __future__ import annotations

import socket
import struct
import threading
import traceback
from typing import Any, Dict, Optional, Tuple

import cloudpickle


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("client connection closed")
        buf += chunk
    return buf


def recv_msg(sock: socket.socket) -> Any:
    (n,) = struct.unpack("<I", _recv_exact(sock, 4))
    return cloudpickle.loads(_recv_exact(sock, n))


def send_msg(sock: socket.socket, obj: Any) -> None:
    payload = cloudpickle.dumps(obj)
    sock.sendall(struct.pack("<I", len(payload)) + payload)


class _ClientState:
    """Per-connection object/actor registries (reference: per-client
    state in RayletServicer; refs are released when the client drops)."""

    def __init__(self):
        self.object_refs: Dict[str, Any] = {}
        self.actor_handles: Dict[str, Any] = {}
        self.remote_fns: Dict[str, Any] = {}


class ClientServer:
    """Serves remote drivers over TCP; one thread per connection."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 init_kwargs: Optional[dict] = None):
        import ray_tpu as rt

        self._rt = rt
        if not rt.is_initialized():
            rt.init(**(init_kwargs or {}))
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(16)
        self.address: Tuple[str, int] = self._listener.getsockname()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True, name="rt-client-server")
        self._thread.start()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_one, args=(conn,),
                             daemon=True).start()

    def _serve_one(self, conn: socket.socket) -> None:
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        state = _ClientState()
        rt = self._rt
        try:
            while True:
                req = recv_msg(conn)
                try:
                    reply = self._dispatch(rt, state, req)
                except Exception as e:  # error travels to the client
                    reply = {"error": e,
                             "traceback": traceback.format_exc()}
                send_msg(conn, reply)
        except (ConnectionError, OSError, EOFError):
            pass
        finally:
            # Release this client's refs (reference: client disconnect
            # releases all per-client object/actor references).
            state.object_refs.clear()
            for handle in state.actor_handles.values():
                try:
                    rt.kill(handle)
                except Exception:
                    pass
            conn.close()

    def _dispatch(self, rt, state: _ClientState, req: dict) -> dict:
        op = req["op"]
        if op == "ping":
            return {"ok": True}
        if op == "put":
            ref = rt.put(req["value"])
            state.object_refs[ref.hex()] = ref
            return {"ref": ref.hex()}
        if op == "get":
            refs = [state.object_refs[h] for h in req["refs"]]
            out = rt.get(refs, timeout=req.get("timeout"))
            return {"values": out}
        if op == "wait":
            refs = [state.object_refs[h] for h in req["refs"]]
            ready, pending = rt.wait(
                refs, num_returns=req.get("num_returns", 1),
                timeout=req.get("timeout"))
            return {"ready": [r.hex() for r in ready],
                    "pending": [r.hex() for r in pending]}
        if op == "register_fn":
            fn = cloudpickle.loads(req["fn"])
            options = req.get("options") or {}
            remote_fn = rt.remote(**options)(fn) if options else rt.remote(fn)
            state.remote_fns[req["fn_id"]] = remote_fn
            return {"ok": True}
        if op == "task":
            remote_fn = state.remote_fns[req["fn_id"]]
            args, kwargs = self._resolve_args(state, req)
            ref = remote_fn.remote(*args, **kwargs)
            state.object_refs[ref.hex()] = ref
            return {"ref": ref.hex()}
        if op == "actor_create":
            cls = cloudpickle.loads(req["cls"])
            options = req.get("options") or {}
            remote_cls = (rt.remote(**options)(cls) if options
                          else rt.remote(cls))
            args, kwargs = self._resolve_args(state, req)
            handle = remote_cls.remote(*args, **kwargs)
            actor_key = handle._actor_id.hex()
            state.actor_handles[actor_key] = handle
            return {"actor_id": actor_key}
        if op == "actor_method":
            handle = state.actor_handles[req["actor_id"]]
            args, kwargs = self._resolve_args(state, req)
            ref = getattr(handle, req["method"]).remote(*args, **kwargs)
            state.object_refs[ref.hex()] = ref
            return {"ref": ref.hex()}
        if op == "kill_actor":
            handle = state.actor_handles.pop(req["actor_id"], None)
            if handle is not None:
                rt.kill(handle)
            return {"ok": True}
        if op == "release":
            for h in req["refs"]:
                state.object_refs.pop(h, None)
            return {"ok": True}
        if op == "cluster_info":
            return {"nodes": len(rt.nodes()),
                    "resources": rt.cluster_resources()}
        raise ValueError(f"unknown op {op!r}")

    def _resolve_args(self, state: _ClientState, req: dict):
        """Client-side ObjectRef placeholders -> server-side refs."""

        def resolve(v):
            if isinstance(v, dict) and v.get("__client_ref__"):
                return state.object_refs[v["hex"]]
            return v

        args = [resolve(a) for a in req.get("args", ())]
        kwargs = {k: resolve(v) for k, v in req.get("kwargs", {}).items()}
        return args, kwargs

    def stop(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
