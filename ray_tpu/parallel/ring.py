"""Ring attention: sequence/context parallelism over a mesh axis.

Absent from the reference (SURVEY §5.7: "no ring attention, context/sequence
parallelism anywhere") — designed fresh for TPU: the sequence dim is sharded
over the ``sp`` mesh axis; K/V shards rotate around the ring via
``jax.lax.ppermute`` (compiled to ICI neighbor exchanges) while each device
accumulates attention for its local Q shard with the online-softmax merge,
so peak memory is O(S/n) per device and communication overlaps compute.

Layout: q/k/v ``[batch, heads, seq, head_dim]`` with ``seq`` sharded. Use
inside ``shard_map`` (see :func:`ring_attention` for the sharded wrapper).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from .collective import axis_size

_NEG_INF = -1e30


def ring_attention_local(q, k, v, axis_name: str = "sp",
                         causal: bool = True,
                         scale: Optional[float] = None):
    """Per-shard ring attention body (call inside shard_map).

    q/k/v: local shards [B, H, S_local, D]; sequence is sharded over
    ``axis_name`` in rank order (shard r holds positions
    [r*S_local, (r+1)*S_local)).
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    n = axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    b, h, s_local, d = q.shape

    qf = q.astype(jnp.float32) * scale
    q_pos = rank * s_local + jnp.arange(s_local)  # global Q positions

    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, step_idx):
        m, l, acc, k_cur, v_cur = carry
        src = (rank - step_idx) % n  # whose K/V shard we hold this step
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, k_cur.astype(jnp.float32),
                       preferred_element_type=jnp.float32)
        if causal:
            k_pos = src * s_local + jnp.arange(s_local)
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        # Guard fully-masked rows at step 0 edge cases: keep m finite once
        # any step contributed; exp(-inf - -inf) avoided via where.
        p = jnp.exp(s - m_new)
        p = jnp.where(s <= _NEG_INF / 2, 0.0, p)
        alpha = jnp.exp(jnp.maximum(m - m_new, -80.0))
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_cur.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        # Rotate K/V around the ring (ICI neighbor exchange); overlapped
        # with the next step's compute by XLA's async collective scheduling.
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        return (m_new, l_new, acc_new, k_next, v_next), None

    m0 = jnp.full((b, h, s_local, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s_local, 1), jnp.float32)
    acc0 = jnp.zeros((b, h, s_local, d), jnp.float32)
    (m, l, acc, _, _), _ = jax.lax.scan(
        step, (m0, l0, acc0, k, v), jnp.arange(n)
    )
    safe_l = jnp.where(l == 0, 1.0, l)
    return (acc / safe_l).astype(q.dtype)


def ring_flash_attention_local(q, k, v, axis_name: str = "sp",
                               causal: bool = True,
                               scale: Optional[float] = None,
                               block_impl: str = "auto"):
    """Ring attention whose per-step block compute is the FLASH kernel
    (``ops.attention``): each step runs one flash forward of the local Q
    shard against the K/V shard currently held, and partial outputs
    merge across steps through their log-sum-exp — mathematically the
    same online-softmax as :func:`ring_attention_local`, but the inner
    S_local x S_local work runs on the fused pallas block instead of a
    materialized fp32 score matrix. Forward-only (serving / long-context
    inference); training through ring attention uses the autodiff-able
    einsum body above.

    Three block modes per step under causal masking: the diagonal step
    (src == rank) is plain causal flash; earlier shards (src < rank)
    attend fully; later shards are skipped via lax.switch with an
    lse of -1e30 so the merge weight is exactly 0.
    """
    from ..ops.attention import attention_with_lse

    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    n = axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    b, h, s_local, d = q.shape
    perm = [(i, (i + 1) % n) for i in range(n)]

    def diag_step(kv):
        k_cur, v_cur = kv
        return attention_with_lse(q, k_cur, v_cur, causal=True,
                                  scale=scale, impl=block_impl)

    def full_step(kv):
        k_cur, v_cur = kv
        return attention_with_lse(q, k_cur, v_cur, causal=False,
                                  scale=scale, impl=block_impl)

    def skip_step(kv):
        return (jnp.zeros((b, h, s_local, d), q.dtype),
                jnp.full((b, h, s_local), _NEG_INF, jnp.float32))

    def step(carry, step_idx):
        out, lse, k_cur, v_cur = carry
        src = (rank - step_idx) % n
        if causal:
            branch = jnp.where(src == rank, 0,
                               jnp.where(src < rank, 1, 2))
        else:
            branch = jnp.ones((), jnp.int32)
        o_i, lse_i = jax.lax.switch(
            branch, [diag_step, full_step, skip_step], (k_cur, v_cur))
        new_lse = jnp.logaddexp(lse, lse_i)
        w_old = jnp.exp(lse - new_lse)[..., None]
        w_new = jnp.exp(lse_i - new_lse)[..., None]
        out = out * w_old + o_i.astype(jnp.float32) * w_new
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        return (out, new_lse, k_next, v_next), None

    out0 = jnp.zeros((b, h, s_local, d), jnp.float32)
    lse0 = jnp.full((b, h, s_local), _NEG_INF, jnp.float32)
    (out, _, _, _), _ = jax.lax.scan(
        step, (out0, lse0, k, v), jnp.arange(n))
    return out.astype(q.dtype)


def ring_attention(q, k, v, mesh: Mesh, axis_name: str = "sp",
                   causal: bool = True,
                   batch_axes=("dp", "fsdp"), heads_axis="tp",
                   impl: str = "einsum"):
    """Sharded entry point: shard_map-wraps the ring body.

    q/k/v: global arrays [B, H, S, D]; S must divide by the sp axis
    size. ``impl='flash'`` uses the fused flash block per step
    (forward-only); ``'einsum'`` is the autodiff-able training body.
    """
    from .sharding import smap

    body = (ring_flash_attention_local if impl == "flash"
            else ring_attention_local)
    spec = P(batch_axes, heads_axis, axis_name, None)
    fn = smap(
        functools.partial(body, axis_name=axis_name, causal=causal),
        mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )
    return fn(q, k, v)
