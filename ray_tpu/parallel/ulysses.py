"""Ulysses-style sequence parallelism: all_to_all head/sequence swap.

Absent from the reference (SURVEY §2.4/§5.7). DeepSpeed-Ulysses pattern,
TPU-native: activations arrive sequence-sharded [B, H, S/n, D]; an
``all_to_all`` over the ``sp`` axis re-shards to head-sharded [B, H/n, S, D]
so each device runs *full-sequence* attention for a subset of heads; a
second all_to_all restores sequence sharding. On TPU the all_to_alls ride
ICI; compute per device is identical to tensor-parallel attention.

Requires heads % sp == 0 (use ring attention otherwise).
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.attention import attention as _attention
from .collective import axis_size


def ulysses_attention_local(q, k, v, axis_name: str = "sp",
                            causal: bool = True, impl: str = "auto"):
    """Per-shard body (inside shard_map). q/k/v: [B, H, S_local, D]."""
    n = axis_size(axis_name)

    def seq_to_heads(x):
        # [B, H, S/n, D] -> [B, H/n, S, D]: split heads dim, concat seq dim.
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    def heads_to_seq(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    oh = _attention(qh, kh, vh, causal=causal, impl=impl)
    return heads_to_seq(oh)


def ulysses_attention(q, k, v, mesh: Mesh, axis_name: str = "sp",
                      causal: bool = True, impl: str = "auto",
                      batch_axes=("dp", "fsdp"), heads_axis="tp"):
    """Sharded entry point for [B, H, S, D] global arrays."""
    from .sharding import smap

    spec = P(batch_axes, heads_axis, axis_name, None)
    fn = smap(
        functools.partial(ulysses_attention_local, axis_name=axis_name,
                          causal=causal, impl=impl),
        mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )
    return fn(q, k, v)
