"""Pipeline parallelism: GPipe-style microbatch pipelining over a mesh axis.

Absent from the reference (SURVEY §2.4: "Pipeline parallelism: absent").
TPU-native design: pipeline stages live on ranks of the ``pp`` mesh axis
(stage parameters sharded over that axis); microbatch activations advance
stage-to-stage via ``jax.lax.ppermute`` — a neighbor ICI transfer — inside
one compiled program, so the whole schedule (fill, steady state, drain) is
a single ``lax.scan`` with no host round-trips.

Schedule: plain GPipe (fill + steady + drain = M + N - 1 ticks for M
microbatches on N stages). Bubble fraction (N-1)/(M+N-1); choose M >= 4N.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from .collective import axis_size


def pipeline_apply_local(stage_fn: Callable, stage_params: Any, microbatches,
                         axis_name: str = "pp"):
    """Run the pipeline from inside shard_map.

    Args:
      stage_fn: ``(params, x) -> y`` — one stage's computation. Every rank
        runs the same code with its own ``stage_params`` shard.
      stage_params: this rank's stage parameters (leading ``stage`` dim
        already consumed by shard_map).
      microbatches: [M, micro_batch, ...] — identical on every rank (the
        first stage reads them; other ranks ignore the injected values).

    Returns [M, micro_batch, ...] outputs, valid on the LAST rank and
    broadcast to all ranks (so the caller's out_spec can be replicated).
    """
    n = axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    m = microbatches.shape[0]
    total_ticks = m + n - 1
    fwd_perm = [(i, (i + 1) % n) for i in range(n)]

    x0 = jnp.zeros_like(microbatches[0])
    outputs0 = jnp.zeros((m,) + tuple(x0.shape), microbatches.dtype)

    def tick(carry, t):
        incoming, outputs = carry
        # Stage 0 injects microbatch t (while t < m); other stages consume
        # what arrived from the left neighbor.
        feed_idx = jnp.minimum(t, m - 1)
        injected = jnp.where(rank == 0, microbatches[feed_idx], incoming)
        y = stage_fn(stage_params, injected)
        # Last stage commits microbatch (t - n + 1) once it exists.
        out_idx = t - (n - 1)
        valid = (rank == n - 1) & (out_idx >= 0)
        outputs = jax.lax.cond(
            valid,
            lambda o: jax.lax.dynamic_update_index_in_dim(
                o, y.astype(o.dtype), jnp.maximum(out_idx, 0), 0),
            lambda o: o,
            outputs,
        )
        # Advance activations one stage to the right (ICI neighbor hop).
        nxt = jax.lax.ppermute(y, axis_name, fwd_perm)
        return (nxt, outputs), None

    (_, outputs), _ = jax.lax.scan(tick, (x0, outputs0),
                                   jnp.arange(total_ticks))
    # Broadcast final outputs from the last stage to all ranks so callers
    # can treat the result as replicated over pp.
    mask = (rank == n - 1).astype(outputs.dtype)
    return jax.lax.psum(outputs * mask, axis_name)


def pipeline_apply(stage_fn: Callable, stacked_params: Any, microbatches,
                   mesh: Mesh, axis_name: str = "pp",
                   params_spec=None, data_spec=None):
    """Sharded entry: stage-shard ``stacked_params`` (leading dim = stage)
    over ``axis_name`` and run the pipeline."""
    from .sharding import smap

    if params_spec is None:
        params_spec = jax.tree.map(lambda _: P(axis_name), stacked_params)
    if data_spec is None:
        data_spec = P()

    def body(params, mb):
        params = jax.tree.map(lambda p: p[0], params)  # drop stage dim
        return pipeline_apply_local(stage_fn, params, mb, axis_name)

    fn = smap(body, mesh, in_specs=(params_spec, data_spec),
              out_specs=data_spec)
    return fn(stacked_params, microbatches)


def num_microbatches_for(batch: int, pp: int, target_bubble: float = 0.2) -> int:
    """Pick M so the GPipe bubble (N-1)/(M+N-1) is below target."""
    if pp <= 1:
        return 1
    m = max(1, int((pp - 1) * (1 - target_bubble) / target_bubble))
    while batch % m != 0 and m > 1:
        m -= 1
    return m
