"""Device meshes and mesh claims — topology as a first-class resource.

The reference schedules scalar resources (``{"GPU": n}``,
``src/ray/common/scheduling_resources.h``); TPU pods are structured — chips
wired in an ICI torus, hosts owning fixed chip subsets, slices joined over
DCN. This module makes that structure schedulable:

  - :class:`MeshSpec` — named parallelism axes (dp/fsdp/tp/pp/sp/ep) with
    sizes, mapped onto physical devices in ICI-friendly order.
  - :class:`MeshClaim` — a scheduler reservation of a contiguous subslice
    ("give me a 4x2 mesh"), the PG-bundle analog for device topology
    (reference analog: placement-group bundles,
    ``util/placement_group.py:128``).

Axis convention (outer → inner, DCN-slowest to ICI-fastest):
  ``dp``   data parallel (gradient allreduce; can ride DCN across slices)
  ``fsdp`` fully-sharded data parallel (param/optimizer sharding, ICI)
  ``pp``   pipeline stages (point-to-point ppermute)
  ``sp``   sequence/context parallel (ring attention / Ulysses)
  ``tp``   tensor parallel (innermost: highest-bandwidth ICI axis)
  ``ep``   expert parallel (MoE all_to_all; aliases onto tp or sp ranks)
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

AXIS_ORDER = ("dp", "fsdp", "pp", "sp", "tp", "ep")


@dataclass(frozen=True)
class MeshSpec:
    """Logical parallelism layout, independent of physical devices."""

    dp: int = 1
    fsdp: int = 1
    pp: int = 1
    sp: int = 1
    tp: int = 1
    ep: int = 1

    def axis_sizes(self) -> Dict[str, int]:
        return {a: getattr(self, a) for a in AXIS_ORDER}

    @property
    def num_devices(self) -> int:
        n = 1
        for a in AXIS_ORDER:
            n *= getattr(self, a)
        return n

    def active_axes(self) -> List[str]:
        return [a for a in AXIS_ORDER if getattr(self, a) > 1]

    @classmethod
    def for_devices(cls, n: int, tp: int = 1, sp: int = 1, pp: int = 1,
                    fsdp: Optional[int] = None, ep: int = 1) -> "MeshSpec":
        """Fill the dp (or fsdp) axis with whatever devices remain."""
        inner = tp * sp * pp * ep if ep > 1 else tp * sp * pp
        if n % inner != 0:
            raise ValueError(f"{n} devices not divisible by tp*sp*pp={inner}")
        rest = n // inner
        if fsdp is None:
            return cls(dp=rest, tp=tp, sp=sp, pp=pp, ep=ep)
        if rest % fsdp != 0:
            raise ValueError(f"remaining {rest} not divisible by fsdp={fsdp}")
        return cls(dp=rest // fsdp, fsdp=fsdp, tp=tp, sp=sp, pp=pp, ep=ep)

    def build(self, devices: Optional[Sequence] = None) -> "jax.sharding.Mesh":
        """Materialize a ``jax.sharding.Mesh``.

        Device order: JAX's device list for a TPU slice enumerates chips in
        topology order, so reshaping into (dp, fsdp, pp, sp, tp, ep) puts
        the innermost (tp) axis on physically adjacent chips — the
        highest-bandwidth ICI links — and dp outermost where DCN hops are
        tolerable. For finer control pass an explicitly ordered ``devices``.
        """
        import jax
        from jax.sharding import Mesh

        if devices is None:
            devices = jax.devices()
        n = self.num_devices
        if len(devices) < n:
            raise ValueError(
                f"MeshSpec needs {n} devices; only {len(devices)} available"
            )
        dev_array = np.asarray(devices[:n], dtype=object).reshape(
            tuple(getattr(self, a) for a in AXIS_ORDER)
        )
        return Mesh(dev_array, AXIS_ORDER)

    def describe(self) -> str:
        parts = [f"{a}={getattr(self, a)}" for a in self.active_axes()]
        return "x".join(parts) if parts else "single-device"


@dataclass
class MeshClaim:
    """A reservation of device topology, schedulable like a PG bundle.

    The autoscaler/scheduler resolve a claim against node topology labels
    (``NodeInfo.topology``): a claim for 8 chips as (2, 4) must land on
    hosts whose chips are ICI-contiguous. On a single host this degrades to
    "k local chips".
    """

    spec: MeshSpec
    slice_type: Optional[str] = None  # e.g. "v5e-8"; None = any
    multislice: bool = False  # allow spanning DCN-linked slices (dp axis only)
    name: str = ""

    def chips(self) -> int:
        return self.spec.num_devices

    def to_bundles(self, chips_per_host: int) -> List[Dict[str, float]]:
        """Lower to placement-group bundles of TPU chips per host."""
        total = self.chips()
        n_hosts = max(1, math.ceil(total / chips_per_host))
        per_host = min(total, chips_per_host)
        return [{"TPU": float(per_host)} for _ in range(n_hosts)]


def local_mesh(tp: int = 1, sp: int = 1, **kwargs) -> "jax.sharding.Mesh":
    """Mesh over this process's devices (tests: the 8 virtual CPU devices)."""
    import jax

    n = len(jax.devices())
    spec = MeshSpec.for_devices(n, tp=tp, sp=sp, **kwargs)
    return spec.build()


def single_device_mesh() -> "jax.sharding.Mesh":
    return MeshSpec().build()
